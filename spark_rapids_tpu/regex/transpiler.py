"""Regex transpiler: Java-regex subset -> byte DFA, executed on TPU.

Reference analog: com/nvidia/spark/rapids/RegexParser.scala (~2,200 LoC):
the reference parses Java regexes and transpiles to the cuDF regex dialect,
rejecting unsupported patterns at plan time so those expressions fall back
to CPU.  TPU redesign: there is no regex VM to target, and a backtracking
matcher is hostile to XLA — so supported patterns compile to a **DFA table**
(Thompson NFA -> subset construction) and matching is a single
`lax.scan` over the padded char matrix: per step one gather into the
(states x 256) table, fully vectorized across rows.  Patterns that cannot
compile (backrefs, lookaround, lazy/possessive quantifiers, word
boundaries, huge counted repetitions, non-ASCII) raise RegexUnsupported at
plan time -> the overrides layer tags the expression CPU-only, exactly the
reference's transpiler-reject path.

Byte-level semantics: ASCII patterns over UTF-8 bytes.  Since supported
patterns are ASCII-only, byte-wise matching agrees with Java's char-wise
matching on any input (UTF-8 continuation bytes >= 0x80 never collide with
ASCII classes).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

MAX_NFA_STATES = 2000
MAX_DFA_STATES = 256


class RegexUnsupported(Exception):
    """Pattern cannot run on TPU; plan-time fallback signal."""


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RLit:           # one byte-class
    mask: np.ndarray  # (256,) bool


@dataclasses.dataclass
class RSeq:
    parts: List


@dataclasses.dataclass
class RAlt:
    options: List


@dataclasses.dataclass
class RRep:           # {lo, hi} repetition; hi=None -> unbounded
    node: object
    lo: int
    hi: Optional[int]


def _ascii_mask(*ranges) -> np.ndarray:
    m = np.zeros(256, np.bool_)
    for lo, hi in ranges:
        m[lo:hi + 1] = True
    return m


_ASCII = _ascii_mask((0, 127))
_DIGIT = _ascii_mask((ord("0"), ord("9")))
_WORD = _ascii_mask((ord("0"), ord("9")), (ord("a"), ord("z")),
                    (ord("A"), ord("Z")), (ord("_"), ord("_")))
_SPACE = np.zeros(256, np.bool_)
for _c in " \t\n\x0b\f\r":
    _SPACE[ord(_c)] = True

# ASCII-positive classes stay plain byte classes; complements must also
# match multi-byte UTF-8 characters (Java matches per CHAR, we per byte)
_ESCAPE_CLASSES = {"d": _DIGIT, "w": _WORD, "s": _SPACE}
_COMPLEMENT_CLASSES = {"D": _DIGIT, "W": _WORD, "S": _SPACE}


def _utf8_multibyte_node():
    """One non-ASCII UTF-8 character: lead byte + continuation bytes.
    This is how a byte DFA counts CHARACTERS like Java does."""
    cont = RLit(_ascii_mask((0x80, 0xBF)))
    two = RSeq([RLit(_ascii_mask((0xC2, 0xDF))), cont])
    three = RSeq([RLit(_ascii_mask((0xE0, 0xEF))), cont, cont])
    four = RSeq([RLit(_ascii_mask((0xF0, 0xF4))), cont, cont, cont])
    return RAlt([two, three, four])


def _char_node(ascii_mask: np.ndarray, include_non_ascii: bool):
    """A one-CHARACTER matcher: ASCII byte class, plus (for complements /
    any-char) every multi-byte UTF-8 character."""
    lit = RLit(ascii_mask & _ASCII)
    if not include_non_ascii:
        return lit
    return RAlt([lit, _utf8_multibyte_node()])


def _dot_node():
    m = _ASCII.copy()
    m[ord("\n")] = False
    m[ord("\r")] = False  # Java `.` excludes line terminators
    return _char_node(m, include_non_ascii=True)
_ESCAPE_LITERALS = {"n": "\n", "t": "\t", "r": "\r", "f": "\f", "a": "\x07",
                    "e": "\x1b", "0": "\0"}


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.depth = 0          # group nesting depth
        self.top_alt = False    # pattern has a `|` at depth 0

    def error(self, why: str):
        raise RegexUnsupported(f"regex {self.p!r}: {why} (at {self.i})")

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        c = self.p[self.i]
        self.i += 1
        return c

    # -- grammar ------------------------------------------------------------
    def parse(self):
        """-> (node, anchored_start, anchored_end)"""
        anchored_start = False
        if self.peek() == "^":
            self.next()
            anchored_start = True
        node = self.alternation()
        if anchored_start and self.top_alt:
            # Java precedence binds a leading `^` to the FIRST branch only
            # (`^a|b` == `(^a)|b`); the DFA anchor flag is whole-pattern, so
            # compiling this would silently anchor every branch.  Reject at
            # plan time -> CPU fallback, like mid-pattern `^`/`$`.
            # (`^(a|b)` is fine: the alternation is inside a group.)
            self.error("`^` binds to the first alternation branch only")
        anchored_end = False
        # `$` only meaningful at the very end (deeper `$`s are rejected in
        # atom())
        if self.i != len(self.p):
            self.error("unexpected trailing input")
        if isinstance(node, RSeq) and node.parts and node.parts[-1] == "$":
            node.parts.pop()
            anchored_end = True
        elif node == "$":
            node = RSeq([])
            anchored_end = True
        return node, anchored_start, anchored_end

    def alternation(self):
        opts = [self.sequence()]
        while self.peek() == "|":
            self.next()
            opts.append(self.sequence())
        if len(opts) > 1 and self.depth == 0:
            self.top_alt = True
        return opts[0] if len(opts) == 1 else RAlt(opts)

    def sequence(self):
        parts = []
        while self.peek() is not None and self.peek() not in "|)":
            parts.append(self.quantified())
        if len(parts) == 1:
            return parts[0]
        return RSeq(parts)

    def quantified(self):
        atom = self.atom()
        c = self.peek()
        if c not in ("*", "+", "?", "{"):
            return atom
        if atom == "$":
            self.error("quantifier on `$` anchor")
        if c == "{":
            lo, hi = self.counted()
        else:
            self.next()
            lo, hi = {"*": (0, None), "+": (1, None), "?": (0, 1)}[c]
        nxt = self.peek()
        if nxt in ("?", "+"):
            self.error("lazy/possessive quantifiers are not supported")
        if nxt in ("*", "{") or (nxt == "?"):
            self.error("double quantifier")
        return RRep(atom, lo, hi)

    def counted(self) -> Tuple[int, Optional[int]]:
        assert self.next() == "{"
        body = ""
        while self.peek() is not None and self.peek() != "}":
            body += self.next()
        if self.peek() != "}":
            self.error("unterminated {")
        self.next()
        try:
            if "," not in body:
                lo = hi = int(body)
            else:
                l, h = body.split(",", 1)
                lo = int(l)
                hi = int(h) if h.strip() else None
        except ValueError:
            self.error(f"bad counted repetition {{{body}}}")
        if lo < 0 or (hi is not None and hi < 0):
            self.error(f"negative repetition bound {{{body}}}")
        if lo > 100 or (hi is not None and hi > 100):
            raise RegexUnsupported(
                f"counted repetition {{{body}}} too large for DFA expansion")
        if hi is not None and hi < lo:
            self.error("{m,n} with n < m")
        return lo, hi

    def atom(self):
        c = self.next()
        if c == "(":
            if self.peek() == "?":
                self.next()
                if self.peek() == ":":
                    self.next()
                else:
                    self.error("lookaround / named groups are not supported")
            self.depth += 1
            node = self.alternation()
            self.depth -= 1
            if self.peek() != ")":
                self.error("unterminated group")
            self.next()
            return node
        if c == "[":
            return self.char_class()
        if c == ".":
            return _dot_node()
        if c == "\\":
            return self.escape()
        if c == "$":
            # legal only at the very end / end of alternation branch
            if self.peek() not in (None, "|", ")"):
                self.error("`$` mid-pattern is not supported")
            return "$"
        if c == "^":
            self.error("`^` mid-pattern is not supported")
        if c in "*+?{":
            self.error(f"dangling quantifier {c!r}")
        if ord(c) > 127:
            raise RegexUnsupported(f"non-ASCII literal {c!r}")
        m = np.zeros(256, np.bool_)
        m[ord(c)] = True
        return RLit(m)

    def escape(self):
        c = self.peek()
        if c is None:
            self.error("trailing backslash")
        self.next()
        if c in _ESCAPE_CLASSES:
            return RLit(_ESCAPE_CLASSES[c].copy())
        if c in _COMPLEMENT_CLASSES:
            base = _COMPLEMENT_CLASSES[c]
            return _char_node(~base & _ASCII, include_non_ascii=True)
        if c in _ESCAPE_LITERALS:
            m = np.zeros(256, np.bool_)
            m[ord(_ESCAPE_LITERALS[c])] = True
            return RLit(m)
        if c in ("b", "B", "A", "Z", "z", "G"):
            raise RegexUnsupported(f"\\{c} boundary matchers not supported")
        if c.isdigit():
            raise RegexUnsupported("backreferences are not supported")
        if c.isalpha():
            raise RegexUnsupported(f"escape \\{c} not supported")
        m = np.zeros(256, np.bool_)
        m[ord(c)] = True
        return RLit(m)

    def char_class(self):
        negate = False
        if self.peek() == "^":
            self.next()
            negate = True
        mask = np.zeros(256, np.bool_)
        non_ascii = False  # class also matches multi-byte UTF-8 chars
        first = True
        while True:
            c = self.peek()
            if c is None:
                self.error("unterminated character class")
            if c == "]" and not first:
                self.next()
                break
            first = False
            self.next()
            if c == "\\":
                e = self.peek()
                if e in _ESCAPE_CLASSES:
                    self.next()
                    mask |= _ESCAPE_CLASSES[e]
                    continue
                if e in _COMPLEMENT_CLASSES:
                    self.next()
                    mask |= ~_COMPLEMENT_CLASSES[e] & _ASCII
                    non_ascii = True
                    continue
                sub = self.escape()
                if not isinstance(sub, RLit):
                    self.error("unsupported escape in character class")
                lo_ch = int(np.argmax(sub.mask))
            else:
                if ord(c) > 127:
                    raise RegexUnsupported(f"non-ASCII literal {c!r}")
                lo_ch = ord(c)
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self.next()
                hi_c = self.next()
                if hi_c == "\\":
                    hi_sub = self.escape()
                    if not isinstance(hi_sub, RLit):
                        self.error("unsupported escape in character class")
                    hi_ch = int(np.argmax(hi_sub.mask))
                else:
                    hi_ch = ord(hi_c)
                if hi_ch < lo_ch:
                    self.error("bad character range")
                mask[lo_ch:hi_ch + 1] = True
            else:
                mask[lo_ch] = True
        if negate:
            # Java [^...] matches any CHAR not listed — including every
            # non-ASCII character, realized as the multi-byte alternation
            mask = ~mask & _ASCII
            non_ascii = not non_ascii
        return _char_node(mask, include_non_ascii=non_ascii)


# ---------------------------------------------------------------------------
# NFA (Thompson construction)
# ---------------------------------------------------------------------------

class _NFA:
    def __init__(self):
        self.eps: List[List[int]] = []      # eps[s] -> targets
        self.trans: List[Tuple[int, np.ndarray, int]] = []  # (src, mask, dst)

    def new_state(self) -> int:
        if len(self.eps) >= MAX_NFA_STATES:
            raise RegexUnsupported("pattern too large (NFA state cap)")
        self.eps.append([])
        return len(self.eps) - 1

    def build(self, node) -> Tuple[int, int]:
        """-> (start, accept) fragment."""
        if node == "$":
            raise RegexUnsupported("`$` in unsupported position")
        if isinstance(node, RLit):
            s, a = self.new_state(), self.new_state()
            self.trans.append((s, node.mask, a))
            return s, a
        if isinstance(node, RSeq):
            s = a = self.new_state()
            for part in node.parts:
                ps, pa = self.build(part)
                self.eps[a].append(ps)
                a = pa
            return s, a
        if isinstance(node, RAlt):
            s, a = self.new_state(), self.new_state()
            for opt in node.options:
                os_, oa = self.build(opt)
                self.eps[s].append(os_)
                self.eps[oa].append(a)
            return s, a
        if isinstance(node, RRep):
            s = a = self.new_state()
            for _ in range(node.lo):
                ps, pa = self.build(node.node)
                self.eps[a].append(ps)
                a = pa
            if node.hi is None:
                ls, la = self.build(node.node)
                self.eps[a].append(ls)
                self.eps[la].append(a)  # loop
            else:
                end = self.new_state()
                self.eps[a].append(end)
                for _ in range(node.hi - node.lo):
                    ps, pa = self.build(node.node)
                    self.eps[a].append(ps)
                    self.eps[pa].append(end)
                    a = pa
                a = end
            return s, a
        raise AssertionError(f"unknown node {node}")


def _closure(states: frozenset, eps) -> frozenset:
    seen = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for t in eps[s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


# ---------------------------------------------------------------------------
# Compile: pattern -> DFA table
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CompiledRegex:
    table: np.ndarray    # (n_states, 256) int32
    accept: np.ndarray   # (n_states,) bool
    n_states: int


def compile_regex(pattern: str, full_match: bool = False) -> CompiledRegex:
    """Compile for RLike (find-anywhere) or full-match semantics.

    find semantics = implicit `.*` on each un-anchored side; the trailing
    `.*` is realized by making accept states absorbing.
    """
    node, anch_start, anch_end = _Parser(pattern).parse()
    if full_match:
        anch_start = anch_end = True
    elif anch_end:
        # Java (and Python) `$` also matches just before a FINAL line
        # terminator: "a$" finds a match in "a\n" / "a\r\n" / "a\r"
        nl = np.zeros(256, np.bool_)
        nl[ord("\n")] = True
        cr = np.zeros(256, np.bool_)
        cr[ord("\r")] = True
        term = RAlt([RSeq([RLit(cr), RLit(nl)]), RLit(nl), RLit(cr)])
        node = RSeq([node, RRep(term, 0, 1)])
    nfa = _NFA()
    start, accept = nfa.build(node)
    if not anch_start:
        # self-loop on any byte at a new start state feeding the fragment
        s0 = nfa.new_state()
        nfa.trans.append((s0, np.ones(256, np.bool_), s0))
        nfa.eps[s0].append(start)
        start = s0
    # byte equivalence classes to keep subset construction fast
    tmasks = [m for (_, m, _) in nfa.trans]
    if tmasks:
        sig = np.stack(tmasks, axis=0)          # (T, 256)
        _, classes = np.unique(sig, axis=1, return_inverse=True)
    else:
        classes = np.zeros(256, np.int64)
    n_classes = int(classes.max()) + 1
    class_rep = [int(np.argmax(classes == k)) for k in range(n_classes)]

    d0 = _closure(frozenset([start]), nfa.eps)
    dfa_states = {d0: 0}
    order = [d0]
    table_c = []  # per state: per class target
    accepting = []
    i = 0
    while i < len(order):
        S = order[i]
        i += 1
        is_acc = accept in S
        accepting.append(is_acc)
        row = []
        for k in range(n_classes):
            b = class_rep[k]
            if is_acc and not anch_end:
                row.append(-1)  # absorbing accept, patched below
                continue
            tgt = frozenset(
                d for (src, m, d) in nfa.trans if src in S and m[b])
            tgt = _closure(tgt, nfa.eps)
            if not tgt:
                row.append(-2)  # dead
                continue
            if tgt not in dfa_states:
                if len(dfa_states) >= MAX_DFA_STATES:
                    raise RegexUnsupported(
                        "pattern too complex (DFA state cap)")
                dfa_states[tgt] = len(order)
                order.append(tgt)
            row.append(dfa_states[tgt])
        table_c.append(row)
    n = len(order)
    dead = n           # explicit dead state (self-loop, non-accepting)
    absorb = n + 1     # absorbing accept state
    table = np.zeros((n + 2, 256), np.int32)
    acc = np.zeros(n + 2, np.bool_)
    acc[absorb] = True
    table[dead, :] = dead
    table[absorb, :] = absorb
    for si, row in enumerate(table_c):
        acc[si] = accepting[si]
        for k, t in enumerate(row):
            bs = classes == k
            if t == -1:
                table[si, bs] = absorb
            elif t == -2:
                table[si, bs] = dead
            else:
                table[si, bs] = t
    return CompiledRegex(table=table, accept=acc, n_states=n + 2)


def like_to_regex(pattern: str, escape: str = "\\") -> str:
    """SQL LIKE pattern -> regex (full-match), honoring the escape char.

    Spark only permits the escape char before '%', '_' or itself
    (StringUtils.escapeLikeRegex); anything else is an invalid pattern."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == escape:
            if i + 1 >= len(pattern):
                raise ValueError(
                    f"the LIKE pattern {pattern!r} ends with the escape "
                    f"character")
            nxt = pattern[i + 1]
            if nxt not in ("%", "_", escape):
                raise ValueError(
                    f"the LIKE pattern {pattern!r} has an invalid escape "
                    f"sequence {escape + nxt!r}")
            out.append("\\" + nxt if nxt in ".^$*+?()[]{}|\\" else nxt)
            i += 2
            continue
        if c == "%":
            out.append(r"[\s\S]*")  # Spark LIKE wildcards span newlines
        elif c == "_":
            out.append(r"[\s\S]")
        elif c in ".^$*+?()[]{}|\\":
            out.append("\\" + c)
        else:
            out.append(c)
        i += 1
    return "".join(out)
