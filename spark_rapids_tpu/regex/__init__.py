from spark_rapids_tpu.regex.transpiler import (  # noqa: F401
    CompiledRegex,
    RegexUnsupported,
    compile_regex,
    like_to_regex,
)
