"""shard_map version compatibility (ISSUE 10).

The mesh/ICI layers were written against the jax>=0.8 surface
(``jax.shard_map`` with the ``check_vma`` kwarg).  Older jax ships the
same primitive at ``jax.experimental.shard_map.shard_map`` with the
kwarg spelled ``check_rep`` — on such builds every mesh stage died at
trace time with ``unexpected keyword argument 'check_vma'``, which is
exactly what held the whole MULTICHIP suite red.  This shim resolves
the import once and translates the kwarg, so call sites keep the
modern spelling.
"""
from __future__ import annotations

import inspect

try:  # jax>=0.8
    from jax import shard_map as _shard_map  # type: ignore
except ImportError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    _PARAMS = set(inspect.signature(_shard_map).parameters)
except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
    _PARAMS = {"check_vma"}

if "check_vma" in _PARAMS:
    _CHECK_KW = "check_vma"
elif "check_rep" in _PARAMS:
    _CHECK_KW = "check_rep"
else:  # pragma: no cover - future jax dropped the knob entirely
    _CHECK_KW = None


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=None,
              **kwargs):
    """Drop-in ``shard_map`` accepting the modern ``check_vma`` name on
    every jax this repo runs against."""
    if check_vma is not None and _CHECK_KW is not None:
        kwargs[_CHECK_KW] = check_vma
    if f is None:  # decorator usage
        return lambda fn: _shard_map(fn, mesh=mesh, in_specs=in_specs,
                                     out_specs=out_specs, **kwargs)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
