"""Cross-slice (DCN-analog) hierarchical repartition — two-level mesh.

Reference analog: the reference's shuffle spans executors on different
NODES — UCX within a host, TCP/IB across hosts (SURVEY.md §2.7, §5.8).
The TPU counterpart is a two-level ``jax.sharding.Mesh``:

    Mesh(devices.reshape(n_host, n_ici), ("host", "ici"))

where the inner axis rides ICI (intra-slice links) and the outer axis
models the slice-to-slice fabric (DCN).  XLA lowers a collective over
each axis to that axis's interconnect, so laying the routing out
hierarchically keeps the heavy traffic on ICI and sends each row over
DCN at most once.

Protocol (hierarchical all-to-all, the standard two-phase route):

  phase 1 (ICI):  every row moves WITHIN its slice to the local device
                  index it will occupy at the destination —
                  ``dev = hash(key) %% n_ici``.  All traffic stays on
                  intra-slice links.
  phase 2 (DCN):  an all-to-all over the "host" axis per device column
                  delivers each row to its destination slice —
                  ``host = (hash(key) // n_ici) %% n_host``.  Each row
                  crosses DCN exactly once, and the n_ici device columns
                  exchange independently (the DCN fan-in per link is
                  n_host-1, matching the reference's inter-node shuffle
                  fan).

Single-process containers cannot present multiple slices, so this module
is exercised by the driver dryrun over a virtual n_host x n_ici CPU mesh
(``dryrun_multichip``) — the same code lowers unchanged on real
multi-slice topologies where jax.devices() spans slices.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu.parallel.compat import shard_map


def make_mesh2(n_host: int, n_ici: int,
               devices: Optional[list] = None) -> Mesh:
    """Two-level mesh: outer "host" axis (DCN analog) x inner "ici"
    axis (intra-slice)."""
    devs = devices or jax.devices()
    need = n_host * n_ici
    if len(devs) < need:
        raise ValueError(
            f"need {need} devices for a {n_host}x{n_ici} mesh, "
            f"have {len(devs)}")
    return Mesh(np.array(devs[:need]).reshape(n_host, n_ici),
                ("host", "ici"))


def cross_slice_all_to_all_columns(cols, row_valid, pid,
                                   n_host: int, n_ici: int,
                                   host_axis: str = "host",
                                   ici_axis: str = "ici"):
    """Whole-batch hierarchical routing (ISSUE 10): generalizes
    :func:`cross_slice_repartition`'s (keys, values) pair to ANY list of
    ``DeviceColumn`` (flat / string / array layouts — everything
    ``ici_all_to_all_columns`` carries).  Row i moves to global
    partition ``pid[i] in [0, n_host*n_ici)``, living on device
    ``(pid // n_ici, pid %% n_ici)``:

      phase 1 (ICI):  all-to-all over the inner axis to the
                      destination's LOCAL device index, the destination
                      host id riding along as one extra int32 column;
      phase 2 (DCN):  all-to-all over the host axis delivers each row
                      to its destination slice — each row crosses the
                      slice-to-slice fabric exactly once.

    Returns (received columns, received-row mask).  Must run inside a
    shard_map over a 2-level (host x ici) mesh."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu.parallel.mesh import ici_all_to_all_columns

    tgt_dev = (pid % n_ici).astype(jnp.int32)
    tgt_host = (pid // n_ici).astype(jnp.int32)
    carry = DeviceColumn(T.INT, row_valid, data=tgt_host)
    r1, ok1 = ici_all_to_all_columns(list(cols) + [carry], row_valid,
                                     tgt_dev, n_ici, ici_axis)
    r2, ok2 = ici_all_to_all_columns(
        list(r1[:-1]), ok1, r1[-1].data.astype(jnp.int32), n_host,
        host_axis)
    return r2, ok2


def cross_slice_repartition(mesh: Mesh):
    """Jittable hierarchical repartition of (keys, values, row_valid):
    returns (keys, values, received-mask) laid out so that partition
    ``p = hash(key) %% (n_host*n_ici)`` lives on device
    ``(p // n_ici, p %% n_ici)`` of the mesh."""
    from spark_rapids_tpu.parallel.mesh import (_local_hash_partition_ids,
                                                ici_all_to_all_columns)
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu import types as T

    n_host, n_ici = (int(mesh.shape["host"]), int(mesh.shape["ici"]))

    def per_device(keys, vals, valid):
        pid = _local_hash_partition_ids(keys, valid, n_host * n_ici)
        tgt_dev = pid % n_ici
        tgt_host = pid // n_ici
        cols = [DeviceColumn(T.LONG, valid, data=keys),
                DeviceColumn(T.LONG, valid, data=vals),
                DeviceColumn(T.LONG, valid,
                             data=tgt_host.astype(jnp.int64))]
        # phase 1: intra-slice (ICI) — move to the destination's local
        # device index, carrying the host id along
        r1, ok1 = ici_all_to_all_columns(cols, valid, tgt_dev, n_ici,
                                         "ici")
        # phase 2: cross-slice (DCN) — per device column, deliver to the
        # destination slice
        r2, ok2 = ici_all_to_all_columns(
            list(r1[:2]), ok1, r1[2].data.astype(jnp.int32), n_host,
            "host")
        return r2[0].data, r2[1].data, ok2

    return shard_map(
        per_device, mesh=mesh,
        in_specs=(P(("host", "ici")), P(("host", "ici")),
                  P(("host", "ici"))),
        out_specs=(P(("host", "ici")), P(("host", "ici")),
                   P(("host", "ici"))),
        check_vma=False)


def dryrun_cross_slice(n_host: int = 2, n_ici: int = 4,
                       rows_per_dev: int = 64) -> dict:
    """Route a random table over the 2-level mesh and verify against the
    host-side reference partitioning.  Returns routing evidence for the
    driver artifact."""
    from spark_rapids_tpu.ops.hashing import spark_partition_ids
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu import types as T

    mesh = make_mesh2(n_host, n_ici)
    n_dev = n_host * n_ici
    n = rows_per_dev * n_dev
    rng = np.random.default_rng(5)
    keys = jnp.asarray(rng.integers(0, 1 << 40, n), jnp.int64)
    vals = jnp.asarray(rng.integers(0, 1 << 30, n), jnp.int64)
    valid = jnp.asarray(rng.random(n) < 0.9)

    spec = NamedSharding(mesh, P(("host", "ici")))
    args = [jax.device_put(x, spec) for x in (keys, vals, valid)]
    rk, rv, rok = jax.jit(cross_slice_repartition(mesh))(*args)
    rk, rv, rok = (np.asarray(rk), np.asarray(rv), np.asarray(rok))

    # host-side reference: partition id of each VALID row
    kcol = DeviceColumn(T.LONG, valid, data=keys)
    pid = np.asarray(jnp.where(
        valid, spark_partition_ids([kcol], n_dev), -1))
    per_dev_cap = rk.shape[0] // n_dev
    got_rows = 0
    for p in range(n_dev):
        sl = slice(p * per_dev_cap, (p + 1) * per_dev_cap)
        got = sorted(zip(rk[sl][rok[sl]].tolist(),
                         rv[sl][rok[sl]].tolist()))
        want_mask = pid == p
        want = sorted(zip(np.asarray(keys)[want_mask].tolist(),
                          np.asarray(vals)[want_mask].tolist()))
        assert got == want, (
            f"cross-slice partition {p}: {len(got)} rows vs "
            f"expected {len(want)}")
        got_rows += len(got)
    assert got_rows == int(np.asarray(valid).sum())
    return {"mesh": f"{n_host}x{n_ici}", "rows_routed": got_rows,
            "protocol": "ICI phase (local device index) then DCN phase "
                        "(host axis all-to-all), one DCN hop per row"}
