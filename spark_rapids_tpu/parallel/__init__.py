from spark_rapids_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    distributed_agg_step,
    distributed_shuffle_agg_step,
)
