"""Multi-chip execution over a jax.sharding.Mesh — the ICI shuffle backend.

Reference analog (SURVEY.md §2.7, §5.8): the reference's distributed story is
(a) Spark netty shuffle with multithreaded GPU (de)serialization and (b) a
UCX peer-to-peer transport for device-direct transfers over NVLink/RDMA,
with driver-coordinated peer discovery.

TPU-first replacement: there is no peer-to-peer pull — the pod slice IS the
interconnect.  Shuffle mode "ICI" keeps batches device-resident and
repartitions them with a single XLA all-to-all across the mesh; broadcast is
an all-gather; global aggregation merges with psum-style collectives.  The
Spark-task-async vs SPMD-collective impedance mismatch (SURVEY.md §7 hard
part #1) is resolved by epoching: each shuffle exchange is one collective
step over the whole mesh, scheduled when all upstream partitions of the
stage are ready (the exchange is already a full barrier in Spark semantics,
so this loses no generality).

Parallelism mapping (the framework's DP/TP equivalent, SURVEY.md §2.9):
  * rows are data-parallel across the mesh axis ("dp");
  * repartitioning (hash/range) is the collective (all_to_all);
  * broadcast joins replicate the build side (all_gather);
  * within-chip parallelism is XLA's vectorization (VPU/MXU).

Everything here is built with shard_map so the per-device program is the
same single-chip code path operating on local shards.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu.parallel.compat import shard_map


def make_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


# ---------------------------------------------------------------------------
# Collective building blocks
# ---------------------------------------------------------------------------

def _local_hash_partition_ids(key_data, valid, n_parts: int):
    """Spark-compatible murmur3 pmod partition ids for an int64 key column."""
    from spark_rapids_tpu.ops.hashing import _hash_long, _fmix

    h = _hash_long(jnp.uint32(42), key_data.astype(jnp.int64).view(jnp.uint64)
                   if key_data.dtype == jnp.int64
                   else key_data.astype(jnp.int64).astype(jnp.uint64))
    h = jnp.where(valid, h.astype(jnp.int32), 42)
    p = h % jnp.int32(n_parts)
    return jnp.where(p < 0, p + n_parts, p)


def ici_all_to_all(values: jax.Array, validity: jax.Array,
                   target_dev: jax.Array, n_dev: int, axis: str):
    """Device-resident shuffle of one value column inside shard_map.

    Each device owns `cap` rows; row i goes to device target_dev[i].
    Dense quota scheme: each device reserves cap slots per peer.

    ragged_all_to_all: measured-and-deferred (VERDICT r2 next #2).  The
    dense quota moves up to n_dev x the ragged byte volume, BUT its send
    shapes are static — one compiled program regardless of skew — while
    jax.lax.ragged_all_to_all needs per-epoch group sizes on device and,
    on this jax build, lowers through a path that recompiles when the
    offset metadata layout changes; on a compile-tunnel platform (~20-60s
    per compile) one extra compile costs more than hundreds of padded
    epochs.  Revisit when targeting real multi-chip slices where ICI
    bytes, not compiles, dominate.  Returns (values, validity) of the
    rows received.
    """
    cap = values.shape[0]
    # stable sort rows by target device so each peer's rows are contiguous
    perm = jax.lax.sort(
        (jnp.where(validity, target_dev, n_dev).astype(jnp.int32),
         jnp.arange(cap, dtype=jnp.int32)), num_keys=1, is_stable=True)[-1]
    v_s = values[perm]
    ok_s = validity[perm]
    tgt_s = jnp.where(ok_s, target_dev[perm], n_dev)
    # slot each row into its peer bucket [peer * cap + rank_within_peer]
    is_start = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                tgt_s[1:] != tgt_s[:-1]])
    pos = jnp.arange(cap, dtype=jnp.int32)
    seg_start = jnp.where(is_start, pos, 0)
    seg_start = jax.lax.associative_scan(jnp.maximum, seg_start)
    rank = pos - seg_start
    slot = tgt_s * cap + rank
    send_vals = jnp.zeros((n_dev * cap,), values.dtype).at[slot].set(
        v_s, mode="drop")
    send_ok = jnp.zeros((n_dev * cap,), jnp.bool_).at[slot].set(
        ok_s & (tgt_s < n_dev), mode="drop")
    send_vals = send_vals.reshape(n_dev, cap)
    send_ok = send_ok.reshape(n_dev, cap)
    recv_vals = jax.lax.all_to_all(send_vals, axis, 0, 0, tiled=False)
    recv_ok = jax.lax.all_to_all(send_ok, axis, 0, 0, tiled=False)
    return recv_vals.reshape(-1), recv_ok.reshape(-1)


def _slot_plan(validity: jax.Array, target_dev: jax.Array, n_dev: int):
    """Shared slotting for a multi-column all-to-all: returns (perm, slot,
    ok_send) placing row i of the sorted order at dense quota slot
    [peer * cap + rank]."""
    cap = validity.shape[0]
    perm = jax.lax.sort(
        (jnp.where(validity, target_dev, n_dev).astype(jnp.int32),
         jnp.arange(cap, dtype=jnp.int32)), num_keys=1, is_stable=True)[-1]
    ok_s = validity[perm]
    tgt_s = jnp.where(ok_s, target_dev[perm], n_dev)
    is_start = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                tgt_s[1:] != tgt_s[:-1]])
    pos = jnp.arange(cap, dtype=jnp.int32)
    seg_start = jnp.where(is_start, pos, 0)
    seg_start = jax.lax.associative_scan(jnp.maximum, seg_start)
    slot = tgt_s * cap + (pos - seg_start)
    return perm, slot, ok_s & (tgt_s < n_dev)


def _a2a_array(arr: jax.Array, perm, slot, n_dev: int, axis: str):
    """Route one array (any trailing shape) through the dense-quota
    all-to-all using a precomputed slot plan."""
    cap = perm.shape[0]
    sorted_ = arr[perm]
    send = jnp.zeros((n_dev * cap,) + arr.shape[1:], arr.dtype
                     ).at[slot].set(sorted_, mode="drop")
    send = send.reshape((n_dev, cap) + arr.shape[1:])
    recv = jax.lax.all_to_all(send, axis, 0, 0, tiled=False)
    return recv.reshape((n_dev * cap,) + arr.shape[1:])


def ici_all_to_all_columns(cols, row_valid: jax.Array,
                           target_dev: jax.Array, n_dev: int, axis: str):
    """Device-resident shuffle of a whole batch (list of DeviceColumn)
    inside shard_map: every array (validity/data/chars/lengths) of every
    column rides the same all-to-all routing plan.

    Returns (received columns, received-row mask).  Dense quota layout:
    each device reserves cap slots per peer, so the received capacity is
    n_dev * cap (ragged all-to-all is the planned upgrade —
    jax.lax.ragged_all_to_all where available)."""
    from spark_rapids_tpu.columnar.column import DeviceColumn

    perm, slot, ok_send = _slot_plan(row_valid, target_dev, n_dev)
    cap = row_valid.shape[0]
    # ok_send is already in sorted order; scatter it through the slot plan
    sent_ok = jnp.zeros((n_dev * cap,), jnp.bool_).at[slot].set(
        ok_send, mode="drop").reshape(n_dev, cap)
    rok = jax.lax.all_to_all(sent_ok, axis, 0, 0, tiled=False).reshape(-1)
    out = []
    for c in cols:
        validity = _a2a_array(c.validity, perm, slot, n_dev, axis)
        if c.is_string:
            chars = _a2a_array(c.chars, perm, slot, n_dev, axis)
            lengths = _a2a_array(c.lengths, perm, slot, n_dev, axis)
            out.append(DeviceColumn(c.dtype, validity & rok, chars=chars,
                                    lengths=lengths))
        elif c.is_array:
            data = _a2a_array(c.data, perm, slot, n_dev, axis)
            lengths = _a2a_array(c.lengths, perm, slot, n_dev, axis)
            ev = _a2a_array(c.elem_valid, perm, slot, n_dev, axis)
            out.append(DeviceColumn(c.dtype, validity & rok, data=data,
                                    lengths=lengths, elem_valid=ev))
        else:
            data = _a2a_array(c.data, perm, slot, n_dev, axis)
            out.append(DeviceColumn(c.dtype, validity & rok, data=data))
    return out, rok


# ---------------------------------------------------------------------------
# Demonstration steps (used by tests and the driver's dryrun_multichip)
# ---------------------------------------------------------------------------

def distributed_agg_step(mesh: Mesh, axis: str = "dp"):
    """Global (no keys) filtered aggregation: local partial + psum merge.

    The multi-chip TPC-H Q6 shape: scan shards rows across the mesh,
    each chip filters+multiplies+sums its shard, one psum merges."""

    def step(price, discount, quantity, shipdate, valid):
        lo = jnp.int32(8766)   # 1994-01-01 in days
        hi = jnp.int32(9131)   # 1995-01-01
        keep = (valid
                & (shipdate >= lo) & (shipdate < hi)
                & (discount >= 5) & (discount <= 7)
                & (quantity < 24 * 100))
        contrib = jnp.where(keep, price * discount, 0).astype(jnp.int64)
        local = jnp.sum(contrib)
        total = jax.lax.psum(local, axis)
        count = jax.lax.psum(jnp.sum(keep.astype(jnp.int64)), axis)
        return total, count

    return shard_map(step, mesh=mesh,
                     in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
                     out_specs=(P(), P()))


def distributed_shuffle_agg_step(mesh: Mesh, axis: str = "dp"):
    """Grouped aggregation with an ICI all-to-all repartition:
    local partial agg -> hash all-to-all by key -> local final agg.

    This is the full distributed pipeline of the framework: the exchange in
    HashAggregate(partial) -> Exchange(hash) -> HashAggregate(final) runs as
    one collective instead of a disk/netty shuffle."""
    n_dev = mesh.devices.size

    def step(keys, vals, valid):
        cap = keys.shape[0]
        # ---- local partial aggregate (sort-based) ----
        kw = jnp.where(valid, keys, jnp.int64(2**62))
        perm = jax.lax.sort((kw, jnp.arange(cap, dtype=jnp.int32)),
                            num_keys=1, is_stable=True)[-1]
        ks = kw[perm]
        vs = jnp.where(valid, vals, 0)[perm]
        ok = valid[perm]
        change = jnp.concatenate([jnp.ones(1, jnp.bool_), ks[1:] != ks[:-1]])
        seg = jnp.cumsum(change.astype(jnp.int32)) - 1
        seg = jnp.where(ok, seg, cap - 1)
        psum_ = jax.ops.segment_sum(vs, seg, num_segments=cap)
        first = jax.ops.segment_min(
            jnp.where(ok, jnp.arange(cap, dtype=jnp.int32), cap), seg,
            num_segments=cap)
        gkeys = ks[jnp.clip(first, 0, cap - 1)]
        gvalid = first < cap
        # ---- ICI all-to-all repartition by key hash ----
        tgt = _local_hash_partition_ids(gkeys, gvalid, n_dev)
        rk, rok = ici_all_to_all(gkeys, gvalid, tgt, n_dev, axis)
        rv, _ = ici_all_to_all(psum_, gvalid, tgt, n_dev, axis)
        # ---- local final aggregate over received partials ----
        rcap = rk.shape[0]
        rkw = jnp.where(rok, rk, jnp.int64(2**62))
        perm2 = jax.lax.sort((rkw, jnp.arange(rcap, dtype=jnp.int32)),
                             num_keys=1, is_stable=True)[-1]
        ks2 = rkw[perm2]
        vs2 = jnp.where(rok, rv, 0)[perm2]
        ok2 = rok[perm2]
        change2 = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                   ks2[1:] != ks2[:-1]])
        seg2 = jnp.cumsum(change2.astype(jnp.int32)) - 1
        seg2 = jnp.where(ok2, seg2, rcap - 1)
        fsum = jax.ops.segment_sum(vs2, seg2, num_segments=rcap)
        f2 = jax.ops.segment_min(
            jnp.where(ok2, jnp.arange(rcap, dtype=jnp.int32), rcap), seg2,
            num_segments=rcap)
        fkeys = ks2[jnp.clip(f2, 0, rcap - 1)]
        fvalid = (f2 < rcap) & (fkeys < 2**62)
        return fkeys, fsum, fvalid

    return shard_map(step, mesh=mesh,
                     in_specs=(P(axis), P(axis), P(axis)),
                     out_specs=(P(axis), P(axis), P(axis)))


def broadcast_build_side(mesh: Mesh, axis: str = "dp"):
    """Broadcast-join build replication: all_gather of the local build shard
    (GpuBroadcastExchangeExec on ICI)."""

    def step(build_keys, build_vals):
        bk = jax.lax.all_gather(build_keys, axis, tiled=True)
        bv = jax.lax.all_gather(build_vals, axis, tiled=True)
        return bk, bv

    return shard_map(step, mesh=mesh, in_specs=(P(axis), P(axis)),
                     out_specs=(P(None), P(None)), check_vma=False)
