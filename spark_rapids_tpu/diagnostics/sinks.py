"""Diagnostics sinks: the JSONL structured event log and the
Chrome-trace/Perfetto exporter.

Reference analog: the Spark event log (what spark-rapids-tools profiles
offline) and NVTX/XProf timelines (SURVEY.md §5.1/§5.5).  Both sinks are
pure functions of a finished :class:`QueryDiagnostics`:

* :func:`write_event_log` — one ``query-<id>.jsonl`` per query, written
  to a temp file then ``os.replace``-d (atomic per-query flush: a killed
  process never leaves a half-written log), with oldest-first rotation
  bounded by ``spark.rapids.tpu.diagnostics.eventLog.maxFiles``.

* :func:`chrome_trace` / :func:`write_chrome_trace` — the Chrome trace
  event format (``chrome://tracing`` / Perfetto ``ui.perfetto.dev``).
  Each operator gets its own track (tid) named by plan path; its lifetime
  renders as a B/E span pair and the launches / syncs / compiles / cache
  and resilience events it attributed nest inside as X / instant events.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List

from spark_rapids_tpu.diagnostics.recorder import QueryDiagnostics


# ---------------------------------------------------------------------------
# JSONL event log
# ---------------------------------------------------------------------------

def event_log_lines(diag: QueryDiagnostics) -> List[str]:
    """Header first, then events ordered by ts_ns (stable), query_end
    last by construction (it carries the final timestamp)."""
    lines = [json.dumps(diag.header(), default=str)]
    with diag._lock:
        events = sorted(diag.events,
                        key=lambda e: (e.get("ts_ns", 0)))
    for e in events:
        lines.append(json.dumps(e, default=str))
    return lines


def write_event_log(diag: QueryDiagnostics, directory: str,
                    max_files: int = 64) -> str:
    """Atomically write ``<directory>/query-<id>.jsonl`` and rotate."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"query-{diag.query_id}.jsonl")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(event_log_lines(diag)) + "\n")
    os.replace(tmp, path)
    diag.event_log_path = path
    _rotate(directory, "query-", ".jsonl", max_files)
    return path


def _rotate(directory: str, prefix: str, suffix: str,
            max_files: int) -> None:
    if max_files <= 0:
        return
    try:
        names = [n for n in os.listdir(directory)
                 if n.startswith(prefix) and n.endswith(suffix)]
        if len(names) <= max_files:
            return
        # query ids embed a ms timestamp + sequence, so name order is
        # creation order — no mtime stat storm needed
        for n in sorted(names)[:len(names) - max_files]:
            try:
                os.unlink(os.path.join(directory, n))
            except OSError:
                pass
    except OSError:
        pass


# ---------------------------------------------------------------------------
# Chrome trace / Perfetto
# ---------------------------------------------------------------------------

def trace_pid(query_id: str) -> int:
    """Stable per-QUERY trace pid: concurrent queries' traces merge into
    one Perfetto timeline as separate process groups instead of
    interleaving on pid 0 (ISSUE 8 satellite)."""
    import zlib

    return (zlib.crc32(query_id.encode("utf-8")) & 0x3FFFFFFF) or 1


def worker_trace_pid(worker_id: str) -> int:
    """Stable per-WORKER trace pid (ISSUE 15), disjoint from the query
    pid space (high bit set): a merged cross-process trace renders the
    driver and every worker as distinct Perfetto process groups."""
    import zlib

    return 0x40000000 | (zlib.crc32(
        worker_id.encode("utf-8")) & 0x3FFFFFFF)


def chrome_trace(diag: QueryDiagnostics) -> Dict[str, Any]:
    """Build the Chrome trace-event dict for one finished query."""
    pid = trace_pid(diag.query_id)
    tids: Dict[str, int] = {}
    trace: List[Dict[str, Any]] = []
    seq = [0]

    def emit(ev):
        seq[0] += 1
        ev["_seq"] = seq[0]
        trace.append(ev)

    emit({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
          "ts": 0, "args": {"name": f"query {diag.query_id}"}})
    stats = diag.operator_stats()
    for i, st in enumerate(stats):
        tids[st.path] = i
        label = f"{st.path or 'query'} {st.name}" if st.path else "(query)"
        emit({"ph": "M", "name": "thread_name", "pid": pid, "tid": i,
              "ts": 0, "args": {"name": label}})
    # operator lifetime spans (B/E pairs, one per op that ever ran)
    for st in stats:
        if st.t_first_ns is None:
            continue
        tid = tids[st.path]
        args = {"path": st.path, "batches": st.batches, "rows": st.rows,
                "wall_ms": round(st.wall_ns / 1e6, 3)}
        if st.counters:
            args["counters"] = {k: v for k, v in sorted(st.counters.items())}
        emit({"ph": "B", "name": st.name, "pid": pid, "tid": tid,
              "ts": st.t_first_ns / 1e3, "args": args})
        emit({"ph": "E", "name": st.name, "pid": pid, "tid": tid,
              "ts": st.t_last_ns / 1e3})
    # point/duration events nested on their operator's track
    with diag._lock:
        events = list(diag.events)
    # worker processes (ISSUE 15): each worker that contributed merged
    # `worker_span` events renders as its OWN process group, pid from
    # worker_trace_pid, timestamps already clock-offset-aligned onto
    # the driver timeline by record_worker_spans
    worker_pids: Dict[str, int] = {}
    for e in events:
        if e.get("ev") != "worker_span":
            continue
        wid = e.get("worker_id", "?")
        if wid not in worker_pids:
            wpid = worker_trace_pid(wid)
            worker_pids[wid] = wpid
            emit({"ph": "M", "name": "process_name", "pid": wpid,
                  "tid": 0, "ts": 0, "args": {"name": f"worker {wid}"}})
            emit({"ph": "M", "name": "thread_name", "pid": wpid,
                  "tid": 0, "ts": 0, "args": {"name": "store"}})
    for e in events:
        ev = e.get("ev")
        tid = tids.get(e.get("op") or "", tids.get("", 0))
        ts_us = e.get("ts_ns", 0) / 1e3
        if ev == "worker_span":
            wpid = worker_pids[e.get("worker_id", "?")]
            emit({"ph": "X", "name": f"worker:{e.get('kind', '?')}",
                  "pid": wpid, "tid": 0, "ts": ts_us,
                  "dur": e.get("dur_ns", 0) / 1e3,
                  "args": {"trace": e.get("trace", ""),
                           "span": e.get("span", ""),
                           "exch": e.get("exch", -1),
                           "pid": e.get("pid", -1),
                           "seq": e.get("seq", -1),
                           "bytes": e.get("bytes", 0)}})
        elif ev == "launch":
            emit({"ph": "X", "name": "launch", "pid": pid, "tid": tid,
                  "ts": ts_us, "dur": e["dur_ns"] / 1e3,
                  "args": {"compiled": e["compiled"]}})
        elif ev == "compile":
            emit({"ph": "X", "name": f"compile:{e['mode']}", "pid": pid,
                  "tid": tid, "ts": ts_us, "dur": e["dur_ns"] / 1e3,
                  "args": {"label": e.get("label", "")}})
        elif ev == "sync":
            emit({"ph": "X", "name": f"sync:{e['kind']}", "pid": pid,
                  "tid": tid, "ts": ts_us, "dur": e["dur_ns"] / 1e3,
                  "args": {"bytes": e.get("bytes", 0)}})
        elif ev == "cache":
            emit({"ph": "i", "s": "t",
                  "name": "cache_hit" if e["hit"] else "cache_miss",
                  "pid": pid, "tid": tid, "ts": ts_us,
                  "args": {"label": e.get("label", "")}})
        elif ev == "resilience":
            emit({"ph": "i", "s": "t", "name": f"resilience:{e['kind']}",
                  "pid": pid, "tid": tid, "ts": ts_us,
                  "args": {"op": e.get("op_name", ""),
                           "detail": e.get("detail", "")}})
        elif ev == "cost_model":
            emit({"ph": "i", "s": "p", "name": "cost_model",
                  "pid": pid, "tid": tid, "ts": ts_us,
                  "args": {"hits": e.get("hits", 0),
                           "misses": e.get("misses", 0),
                           "predicted_wall_ms": round(
                               e.get("predicted_wall_ns", 0) / 1e6, 3),
                           "actual_wall_ms": round(
                               e.get("actual_wall_ns", 0) / 1e6, 3)}})
    # monotonic ts; B sorts before its E at equal ts via emission order,
    # and nested X events never straddle their operator's B/E interval
    trace.sort(key=lambda ev: (ev["ts"], ev["_seq"]))
    for ev in trace:
        del ev["_seq"]
    return {"traceEvents": trace, "displayTimeUnit": "ms",
            "otherData": {"query_id": diag.query_id,
                          "trace_id": diag.trace_id,
                          "metrics_level": diag.metrics_level}}


def write_chrome_trace(diag: QueryDiagnostics, directory: str,
                       max_files: int = 64) -> str:
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"query-{diag.query_id}.trace.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        # default=str: a stray non-native-JSON value (numpy scalar in a
        # rows/bytes field) must degrade to a string, not fail the query
        json.dump(chrome_trace(diag), f, default=str)
    os.replace(tmp, path)
    diag.trace_path = path
    _rotate(directory, "query-", ".trace.json", max_files)
    return path
