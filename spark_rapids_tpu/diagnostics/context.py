"""Diagnostics context — the ONLY module the hot paths import.

Two pieces of ambient state:

* ``RECORDER`` — the process-wide active :class:`QueryDiagnostics`
  recorder (or None).  It is deliberately a plain module attribute, not a
  contextvar: counter bumps can come from helper threads the engine owns
  (the multithreaded shuffle writer/reader pool, the AOT compile pool),
  and a contextvar would silently lose their deltas — then the event
  log's per-operator sums could never reconcile with the process-global
  ``perfcounters.since()`` deltas.  One recorder may be active at a time;
  a concurrent ``collect()`` simply runs unrecorded (see
  ``diagnostics.query_scope``).

* ``CURRENT_OP`` — the contextvar-scoped "current operator" (a plan-node
  path string like ``"0.1.0"``).  Each exec operator's batch pull sets it
  for exactly the duration of its ``next()`` (exec/base._diag), so the
  innermost operator actually doing the work wins attribution; events
  fired from threads without an operator context attribute to ``""``
  (the query-level bucket).

Disabled-path contract (ISSUE 3): every instrumentation site performs
exactly ONE ambient check — ``if CTX.RECORDER is None: return`` (or the
equivalent inline test) — before doing any other Python work.  Tests
assert this by profiling the disabled path (tests/test_diagnostics.py).
"""
from __future__ import annotations

from contextvars import ContextVar
from typing import Optional

# the active QueryDiagnostics recorder; None = diagnostics disabled.
# Read lock-free from hot paths; written only by diagnostics.query_scope
# under _RECORDER_LOCK.
RECORDER = None

CURRENT_OP: "ContextVar[Optional[str]]" = ContextVar(
    "srt_diagnostics_current_op", default=None)


def active():
    """The active recorder or None (one ambient check)."""
    return RECORDER
