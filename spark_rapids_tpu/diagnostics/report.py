"""Offline analysis over diagnostics event logs + the explain("analyze")
renderer.

Reference analog: the spark-rapids-tools profiler, which turns Spark
event logs into tuning reports (SURVEY.md L8).  Everything here is pure
functions over parsed JSONL dicts so ``tools/profile_report.py`` and the
tests share one implementation.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple


class QueryProfile:
    """One parsed query log."""

    __slots__ = ("path", "query_id", "trace_id", "started_at",
                 "metrics_level",
                 "plan", "operators", "events", "totals", "wall_ns",
                 "status", "parse_errors", "events_dropped")

    def __init__(self):
        self.path = ""
        self.query_id = ""
        self.trace_id = ""
        self.started_at = 0.0
        self.metrics_level = ""
        self.plan: List[Dict[str, Any]] = []
        self.operators: List[Dict[str, Any]] = []
        self.events: List[Dict[str, Any]] = []
        self.totals: Dict[str, int] = {}
        self.wall_ns = 0
        self.status = ""
        # data-quality flags (ISSUE 8 satellite): malformed/truncated
        # JSONL lines skipped while parsing this file (a query killed
        # mid-write leaves a torn trailing line), and the recorder-side
        # in-memory overflow count from query_end — either nonzero means
        # this query's aggregates are incomplete
        self.parse_errors = 0
        self.events_dropped = 0

    @property
    def incomplete(self) -> bool:
        return self.parse_errors > 0 or self.events_dropped > 0

    @property
    def plan_signature(self) -> str:
        """Stable per-plan key for diffing runs of the same query across
        two logs (operator names in path order)."""
        return "|".join(f"{n['path']}:{n['name']}" for n in self.plan)


def load_query_log(path: str) -> QueryProfile:
    """Parse one query log, tolerating torn lines: a query killed
    mid-write (SIGKILL between the sink's write and rename never
    happens, but a NON-atomic copy/tail of a live log does get truncated)
    must yield whatever parsed instead of raising — skipped lines are
    counted into ``parse_errors`` and the report flags the query's
    aggregates as incomplete."""
    qp = QueryProfile()
    qp.path = path
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
                if not isinstance(e, dict):
                    raise ValueError("not an event object")
            except ValueError:
                qp.parse_errors += 1
                continue
            ev = e.get("ev")
            if ev == "query_start":
                qp.query_id = e.get("query_id", "")
                qp.trace_id = e.get("trace_id", "")
                qp.started_at = e.get("started_at", 0.0)
                qp.metrics_level = e.get("metrics_level", "")
                qp.plan = e.get("plan", [])
            elif ev == "operator":
                qp.operators.append(e)
            elif ev == "query_end":
                qp.totals = e.get("counters", {})
                qp.wall_ns = e.get("wall_ns", 0)
                qp.status = e.get("status", "")
                qp.events_dropped = int(e.get("events_dropped", 0) or 0)
            else:
                qp.events.append(e)
    return qp


def expand_log_paths(paths: List[str]) -> List[str]:
    """Files pass through; directories glob their query-*.jsonl."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, n) for n in os.listdir(p)
                if n.startswith("query-") and n.endswith(".jsonl")))
        else:
            out.append(p)
    return out


def load_logs(paths: List[str]) -> List[QueryProfile]:
    return attach_worker_spans(
        [load_query_log(p) for p in expand_log_paths(paths)])


def attach_worker_spans(
        profiles: List[QueryProfile]) -> List[QueryProfile]:
    """Multi-process event logs (ISSUE 15): a file with no
    ``query_start`` whose events are worker spans (a worker-ring dump,
    a chaos harness timeline) is not a query — its spans attach to the
    loaded query whose trace id they carry, instead of surfacing as an
    anonymous empty profile (the old behavior: dropped as unknown
    operators).  Spans naming no loaded trace stay behind on the
    anonymous profile so nothing is silently discarded."""
    by_trace = {qp.trace_id: qp for qp in profiles
                if qp.query_id and qp.trace_id}
    out = []
    for qp in profiles:
        if qp.query_id or not qp.events:
            out.append(qp)
            continue
        orphans = []
        for e in qp.events:
            owner = by_trace.get(e.get("trace")) \
                if e.get("ev") == "worker_span" else None
            if owner is not None:
                owner.events.append(e)
            else:
                orphans.append(e)
        if orphans or qp.parse_errors:
            qp.events = orphans
            out.append(qp)
    return out


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

def top_operators(profiles: List[QueryProfile], by: str = "wall_ns",
                  n: int = 10) -> List[Tuple[str, Dict[str, float]]]:
    """Aggregate operator summaries across queries by operator name.
    ``by``: 'wall_ns' or any counter key (e.g. 'host_syncs',
    'bytes_d2h', 'programs_launched')."""
    agg: Dict[str, Dict[str, float]] = {}
    for qp in profiles:
        for op in qp.operators:
            name = op.get("name", "?")
            a = agg.setdefault(name, {"wall_ns": 0.0, "self_wall_ns": 0.0,
                                      "batches": 0.0,
                                      "rows": 0.0, "queries": 0.0})
            a["wall_ns"] += op.get("wall_ns", 0)
            # logs predating self_wall_ns fall back to inclusive wall
            a["self_wall_ns"] += op.get("self_wall_ns",
                                        op.get("wall_ns", 0))
            a["batches"] += op.get("batches", 0)
            a["rows"] += op.get("rows", 0)
            a["queries"] += 1
            for k, v in (op.get("counters") or {}).items():
                a[k] = a.get(k, 0.0) + v
    ranked = sorted(agg.items(), key=lambda kv: -kv[1].get(by, 0.0))
    return [(name, a) for name, a in ranked if a.get(by, 0.0) > 0][:n]


def totals_summary(profiles: List[QueryProfile]) -> Dict[str, float]:
    tot: Dict[str, float] = {}
    for qp in profiles:
        for k, v in qp.totals.items():
            tot[k] = tot.get(k, 0.0) + v
        tot["wall_ns"] = tot.get("wall_ns", 0.0) + qp.wall_ns
    tot["queries"] = float(len(profiles))
    hits = tot.get("compile_cache_hits", 0.0)
    misses = tot.get("compile_cache_misses", 0.0)
    tot["compile_cache_hit_rate"] = (
        hits / (hits + misses) if hits + misses else 0.0)
    return tot


_RESILIENCE_KEYS = ("transient_retries", "oom_restarts",
                    "runtime_fallbacks", "breaker_trips",
                    "breaker_plan_fallbacks", "query_fallbacks")


def resilience_summary(profiles: List[QueryProfile]) -> Dict[str, Any]:
    counts = {k: 0 for k in _RESILIENCE_KEYS}
    by_kind: Dict[str, int] = {}
    for qp in profiles:
        for k in _RESILIENCE_KEYS:
            counts[k] += int(qp.totals.get(k, 0))
        for e in qp.events:
            if e.get("ev") == "resilience":
                kk = f"{e.get('kind')}@{e.get('op_name')}"
                by_kind[kk] = by_kind.get(kk, 0) + 1
    return {"counters": counts, "events": by_kind}


def stalls_summary(profiles: List[QueryProfile]) -> Dict[str, Any]:
    """Aggregate ``query_stall`` events (ISSUE 12): which operators
    queries wedge in, how often, and for how long — the offline
    companion of the live stall detector.  Fed by
    ``tools/profile_report.py --stalls``."""
    by_op: Dict[str, Dict[str, float]] = {}
    events: List[Dict[str, Any]] = []
    queries = set()
    for qp in profiles:
        for e in qp.events:
            if e.get("ev") != "query_stall":
                continue
            name = e.get("name") or "(no in-flight operator)"
            a = by_op.setdefault(name, {"stalls": 0.0, "stalled_ms": 0.0})
            a["stalls"] += 1
            a["stalled_ms"] += float(e.get("stalled_ms", 0) or 0)
            queries.add(qp.query_id or qp.path)
            events.append({"query": qp.query_id,
                           "op": name,
                           "path": e.get("path", ""),
                           "stalled_ms": float(e.get("stalled_ms", 0)
                                               or 0),
                           "detail": e.get("detail", "")})
    return {"total_stalls": len(events),
            "queries_with_stalls": len(queries),
            "by_operator": dict(sorted(
                by_op.items(), key=lambda kv: -kv[1]["stalled_ms"])),
            "events": events}


def render_stalls(summary: Dict[str, Any]) -> str:
    out = [f"== stalls: {summary['total_stalls']} query_stall event"
           f"{'' if summary['total_stalls'] == 1 else 's'} across "
           f"{summary['queries_with_stalls']} quer"
           f"{'y' if summary['queries_with_stalls'] == 1 else 'ies'} =="]
    for name, a in summary["by_operator"].items():
        out.append(f"  {name:<34} {int(a['stalls']):3d} stall"
                   f"{'' if a['stalls'] == 1 else 's'}  "
                   f"{a['stalled_ms']:9.1f}ms stalled")
    for e in summary["events"]:
        out.append(f"    {e['query']}: {e['stalled_ms']:.0f}ms in "
                   f"{e['op']}" + (f" at {e['path']}" if e["path"]
                                   else ""))
    return "\n".join(out)


def workers_summary(profiles: List[QueryProfile]) -> Dict[str, Any]:
    """Aggregate cluster-observability events (ISSUE 15): worker spans
    grouped by worker and by owning query (trace id), plus each
    worker's last federated counter snapshot — the offline companion
    of the live per-worker labeled series."""
    by_worker: Dict[str, Dict[str, Any]] = {}
    queries = set()
    for qp in profiles:
        for e in qp.events:
            ev = e.get("ev")
            if ev == "worker_span":
                wid = e.get("worker_id", "?")
                a = by_worker.setdefault(wid, {
                    "spans": 0, "bytes": 0, "wall_ns": 0,
                    "by_kind": {}, "queries": set(), "counters": {}})
                a["spans"] += 1
                a["bytes"] += int(e.get("bytes", 0) or 0)
                a["wall_ns"] += int(e.get("dur_ns", 0) or 0)
                kind = e.get("kind", "?")
                a["by_kind"][kind] = a["by_kind"].get(kind, 0) + 1
                a["queries"].add(qp.query_id or e.get("trace", "?"))
                queries.add(qp.query_id or qp.path)
            elif ev == "worker_telemetry":
                wid = e.get("worker_id", "?")
                a = by_worker.setdefault(wid, {
                    "spans": 0, "bytes": 0, "wall_ns": 0,
                    "by_kind": {}, "queries": set(), "counters": {}})
                a["counters"] = e.get("counters") or {}
                a["queries"].add(qp.query_id or qp.path)
    workers = {}
    for wid, a in sorted(by_worker.items()):
        workers[wid] = {
            "spans": a["spans"], "bytes": a["bytes"],
            "wall_ns": a["wall_ns"],
            "by_kind": dict(sorted(a["by_kind"].items())),
            "queries": sorted(a["queries"]),
            "counters": a["counters"]}
    return {"workers": workers,
            "total_spans": sum(a["spans"] for a in workers.values()),
            "queries_with_workers": len(queries)}


def render_workers(summary: Dict[str, Any]) -> str:
    out = [f"== distributed workers: {len(summary['workers'])} worker"
           f"{'' if len(summary['workers']) == 1 else 's'}, "
           f"{summary['total_spans']} span"
           f"{'' if summary['total_spans'] == 1 else 's'} across "
           f"{summary['queries_with_workers']} quer"
           f"{'y' if summary['queries_with_workers'] == 1 else 'ies'} =="]
    for wid, a in summary["workers"].items():
        kinds = ", ".join(f"{k}={v}" for k, v in a["by_kind"].items())
        out.append(f"  {wid:<12} {a['spans']:5d} spans  "
                   f"{_fmt_bytes(a['bytes']):>10}  "
                   f"{a['wall_ns'] / 1e6:8.1f}ms  [{kinds}]  "
                   f"({len(a['queries'])} quer"
                   f"{'y' if len(a['queries']) == 1 else 'ies'})")
        c = a["counters"]
        if c:
            out.append(
                f"    counters: puts={c.get('store_puts', 0)} "
                f"redrive={c.get('store_redrive_puts', 0)} "
                f"fetches={c.get('store_fetches', 0)} "
                f"served={_fmt_bytes(c.get('store_bytes_served', 0))} "
                f"overflow={_fmt_bytes(c.get('store_overflow_bytes', 0))}")
    return "\n".join(out)


def bills_summary(profiles: List[QueryProfile]) -> Dict[str, Any]:
    """Aggregate ``resource_bill`` + ``regression`` events (ISSUE 18):
    queries ranked by device-byte-seconds (the per-tenant quota number)
    and spill traffic, with any sentinel verdicts attached — fed by
    ``tools/profile_report.py --bills``."""
    bills: List[Dict[str, Any]] = []
    regressions: List[Dict[str, Any]] = []
    for qp in profiles:
        reg = None
        for e in qp.events:
            if e.get("ev") == "regression":
                reg = e
                regressions.append({
                    "query": e.get("query_id") or qp.query_id,
                    "dimension": e.get("dimension", ""),
                    "ratio": float(e.get("ratio", 0) or 0),
                    "op": f"{e.get('op_path', '')}:{e.get('op_name', '')}",
                    "detail": e.get("detail", "")})
        for e in qp.events:
            if e.get("ev") != "resource_bill":
                continue
            sp = e.get("spill") or {}
            bills.append({
                "query": e.get("query_id") or qp.query_id,
                "signature": e.get("signature", ""),
                "wall_ns": int(e.get("wall_ns", 0) or 0),
                "device_peak_bytes":
                    int(e.get("device_peak_bytes", 0) or 0),
                "device_byte_seconds":
                    float(e.get("device_byte_seconds", 0) or 0),
                "spilled_bytes": int(sp.get("host_bytes", 0) or 0)
                + int(sp.get("disk_bytes", 0) or 0),
                "restored_bytes": int(sp.get("restore_bytes", 0) or 0),
                "residual_bytes": int(e.get("residual_bytes", 0) or 0),
                "partitions": e.get("partitions") or {},
                "regression": (reg.get("dimension") if reg is not None
                               else None)})
    bills.sort(key=lambda b: b["device_byte_seconds"], reverse=True)
    return {"bills": bills,
            "queries_with_bills": len(bills),
            "total_device_byte_seconds": round(
                sum(b["device_byte_seconds"] for b in bills), 6),
            "total_spilled_bytes":
                sum(b["spilled_bytes"] for b in bills),
            "regressions": regressions}


def render_bills(summary: Dict[str, Any]) -> str:
    n = summary["queries_with_bills"]
    out = [f"== resource bills: {n} quer{'y' if n == 1 else 'ies'}, "
           f"{summary['total_device_byte_seconds']:.1f} device-byte-"
           f"seconds, {_fmt_bytes(summary['total_spilled_bytes'])} "
           f"spilled =="]
    for b in summary["bills"]:
        flag = f"  REGRESSED[{b['regression']}]" if b["regression"] \
            else ""
        out.append(
            f"  {b['query']:<24} {b['device_byte_seconds']:12.1f} B*s  "
            f"peak {_fmt_bytes(b['device_peak_bytes']):>10}  "
            f"spilled {_fmt_bytes(b['spilled_bytes']):>10}  "
            f"wall {b['wall_ns'] / 1e6:8.1f}ms{flag}")
        if b["partitions"]:
            hot = sorted(
                b["partitions"].items(),
                key=lambda kv: kv[1].get("spill_bytes", 0)
                + kv[1].get("restore_bytes", 0), reverse=True)[:4]
            parts = ", ".join(
                f"p{pid}={_fmt_bytes(d.get('spill_bytes', 0) + d.get('restore_bytes', 0))}"
                for pid, d in hot)
            out.append(f"    hot partitions: {parts}")
        if b["residual_bytes"]:
            out.append(f"    RESIDUAL {_fmt_bytes(b['residual_bytes'])}"
                       f" charged but never released")
    for r in summary["regressions"]:
        out.append(f"  regression: {r['query']} {r['dimension']} "
                   f"x{r['ratio']:.2f} worst op {r['op']}")
    return "\n".join(out)


def diff_profiles(base: List[QueryProfile],
                  new: List[QueryProfile]) -> List[Dict[str, Any]]:
    """Per-query regression diff: match queries by plan signature (falls
    back to position for unmatched), compare wall + key counters."""
    base_by_sig: Dict[str, List[QueryProfile]] = {}
    for qp in base:
        base_by_sig.setdefault(qp.plan_signature, []).append(qp)
    # signature matches first (they never conflict with each other), so
    # the positional fallback cannot consume a baseline a later query
    # matches exactly — a consumed baseline is never diffed twice
    matches: Dict[int, Optional[QueryProfile]] = {}
    consumed = set()
    for i, qp in enumerate(new):
        pool = base_by_sig.get(qp.plan_signature)
        if pool:
            m = pool.pop(0)
            matches[i] = m
            consumed.add(id(m))
    for i, qp in enumerate(new):
        if i not in matches:
            m = base[i] if i < len(base) else None
            matches[i] = m if m is not None and id(m) not in consumed \
                else None
    rows = []
    for i, qp in enumerate(new):
        match = matches[i]
        if match is None:
            rows.append({"query": qp.query_id, "matched": None})
            continue
        row = {"query": qp.query_id, "matched": match.query_id,
               "wall_ms": qp.wall_ns / 1e6,
               "base_wall_ms": match.wall_ns / 1e6,
               "wall_delta_pct": _pct(match.wall_ns, qp.wall_ns)}
        for k in ("programs_launched", "host_syncs", "bytes_d2h",
                  "compiles", "compile_cache_misses"):
            b, v = match.totals.get(k, 0), qp.totals.get(k, 0)
            row[k] = v
            row[f"base_{k}"] = b
            row[f"{k}_delta"] = v - b
        rows.append(row)
    return rows


def _pct(base, new) -> float:
    return 0.0 if not base else round((new - base) * 100.0 / base, 2)


# ---------------------------------------------------------------------------
# report rendering (text)
# ---------------------------------------------------------------------------

def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TB"


def data_quality_warnings(profiles: List[QueryProfile]) -> List[str]:
    """Header warnings for incomplete inputs: queries whose in-memory
    event list overflowed (events_dropped > 0 — their aggregates are
    lower bounds) and files with skipped malformed/truncated lines."""
    out = []
    dropped = [qp for qp in profiles if qp.events_dropped > 0]
    if dropped:
        ids = ", ".join((qp.query_id or qp.path) for qp in dropped[:5])
        more = "" if len(dropped) <= 5 else f" (+{len(dropped) - 5} more)"
        out.append(
            f"WARNING: {len(dropped)} quer"
            f"{'y' if len(dropped) == 1 else 'ies'} dropped events "
            f"in-memory — aggregates incomplete: {ids}{more}")
    torn = sum(qp.parse_errors for qp in profiles)
    if torn:
        files = sum(1 for qp in profiles if qp.parse_errors)
        out.append(
            f"WARNING: skipped {torn} malformed/truncated line"
            f"{'' if torn == 1 else 's'} across {files} file"
            f"{'' if files == 1 else 's'} (query killed mid-write?) — "
            f"affected aggregates incomplete")
    return out


def render_report(profiles: List[QueryProfile], top_n: int = 10) -> str:
    out = []
    tot = totals_summary(profiles)
    out.append(f"== profile report: {len(profiles)} quer"
               f"{'y' if len(profiles) == 1 else 'ies'} ==")
    out.extend(data_quality_warnings(profiles))
    out.append(
        f"total wall {tot.get('wall_ns', 0) / 1e9:.3f}s | launches "
        f"{int(tot.get('programs_launched', 0))} | host syncs "
        f"{int(tot.get('host_syncs', 0))} | D2H "
        f"{_fmt_bytes(tot.get('bytes_d2h', 0))} | H2D "
        f"{_fmt_bytes(tot.get('bytes_h2d', 0))}")
    out.append(
        f"compile cache: {int(tot.get('compile_cache_hits', 0))} hits / "
        f"{int(tot.get('compile_cache_misses', 0))} misses "
        f"(hit rate {tot['compile_cache_hit_rate'] * 100:.1f}%) | "
        f"inline compile wall "
        f"{tot.get('compile_wall_ns', 0) / 1e9:.3f}s | aot compiles "
        f"{int(tot.get('aot_compiles', 0))}")

    res = resilience_summary(profiles)
    if any(res["counters"].values()):
        parts = [f"{k}={v}" for k, v in res["counters"].items() if v]
        out.append("resilience: " + ", ".join(parts))
        for kk, v in sorted(res["events"].items()):
            out.append(f"  {kk}: x{v}")
    else:
        out.append("resilience: clean (no retries/fallbacks/trips)")

    # distributed workers (ISSUE 15): merged worker spans grouped by
    # trace id under their owning queries
    ws = workers_summary(profiles)
    if ws["workers"]:
        out.append("")
        out.append(render_workers(ws))

    def section(title, by, fmt):
        ranked = top_operators(profiles, by=by, n=top_n)
        if not ranked:
            return
        out.append("")
        out.append(f"-- top operators by {title} --")
        for name, a in ranked:
            out.append(f"  {name:<34} {fmt(a)}")

    section("self wall time", "self_wall_ns",
            lambda a: f"{a['self_wall_ns'] / 1e9:9.3f}s self "
                      f"({a['wall_ns'] / 1e9:.3f}s incl, "
                      f"{int(a['batches'])} batches, "
                      f"{int(a['rows'])} rows)")
    section("host syncs", "host_syncs",
            lambda a: f"{int(a.get('host_syncs', 0)):6d} syncs  "
                      f"({int(a.get('programs_launched', 0))} launches)")
    section("D2H bytes", "bytes_d2h",
            lambda a: f"{_fmt_bytes(a.get('bytes_d2h', 0)):>10}  "
                      f"({int(a.get('host_syncs', 0))} syncs)")
    section("launches", "programs_launched",
            lambda a: f"{int(a.get('programs_launched', 0)):6d} launches "
                      f"({int(a.get('compiles', 0))} compiles)")
    return "\n".join(out)


def render_diff(base: List[QueryProfile],
                new: List[QueryProfile]) -> str:
    rows = diff_profiles(base, new)
    out = [f"== regression diff: {len(base)} base vs {len(new)} new =="]
    for r in rows:
        if r.get("matched") is None:
            out.append(f"  {r['query']}: no baseline match")
            continue
        out.append(
            f"  {r['query']} vs {r['matched']}: wall "
            f"{r['base_wall_ms']:.1f} -> {r['wall_ms']:.1f}ms "
            f"({r['wall_delta_pct']:+.1f}%) | launches "
            f"{r['base_programs_launched']} -> {r['programs_launched']} | "
            f"syncs {r['base_host_syncs']} -> {r['host_syncs']} | D2H "
            f"{_fmt_bytes(r['base_bytes_d2h'])} -> "
            f"{_fmt_bytes(r['bytes_d2h'])}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# explain("analyze") rendering — in-process, over the live recorder
# ---------------------------------------------------------------------------

def analyze_tree(root, diag, meta=None,
                 metrics_level: str = "MODERATE") -> str:
    """Re-print the exec tree annotated with each node's metrics, counter
    deltas, compile-cache hits, and fallback status after execution (the
    AdaptiveSparkPlan `explain("analyze")` analog)."""
    from spark_rapids_tpu.diagnostics.recorder import _LEVELS
    from spark_rapids_tpu.exec.base import TpuExec

    max_rank = _LEVELS.get(str(metrics_level).upper(), 1)
    lines = []
    matched = [0]
    if diag is None:
        lines.append("(diagnostics were not enabled for the last "
                     "execution; set spark.rapids.tpu.diagnostics."
                     "enabled=true for counter deltas — showing operator "
                     "metrics only)")

    def annotate(node, indent):
        st = None
        if diag is not None \
                and getattr(node, "_diag_qid", None) == diag.query_id:
            st = diag.ops.get(getattr(node, "_diag_path", None))
        parts = []
        # with a matching recorder, render ITS per-query metric deltas
        # (recorder.finish computed them from the registration baseline);
        # raw TpuMetric values are cumulative across collects of a cached
        # plan and would mix windows with the per-query counters below
        if st is not None:
            metric_items = sorted(st.metrics.items())
        else:
            metric_items = sorted((n, m.value)
                                  for n, m in node.metrics.items())
        for name, value in metric_items:
            if not value:
                continue
            m = node.metrics.get(name)
            if m is not None and _LEVELS.get(m.level, 1) > max_rank:
                continue
            if name.endswith(("Time", "time")):
                parts.append(f"{name}={value / 1e6:.1f}ms")
            else:
                parts.append(f"{name}={value}")
        if st is not None:
            matched[0] += 1
            if st.wall_ns:
                parts.insert(0, f"wall={st.wall_ns / 1e6:.1f}ms")
            for k in ("programs_launched", "host_syncs", "bytes_d2h",
                      "bytes_h2d", "compiles", "compile_cache_hits",
                      "compile_cache_misses"):
                v = st.counters.get(k, 0)
                if v:
                    parts.append(f"{k}={v}")
            if st.fallback:
                parts.append("fallback=CPU(runtime)")
        s = "  " * indent + node.describe()
        if parts:
            s += "  [" + ", ".join(parts) + "]"
        lines.append(s)
        for c in node.children:
            if isinstance(c, TpuExec):
                annotate(c, indent + 1)
            elif hasattr(c, "pretty"):
                lines.append(c.pretty(indent + 1))

    annotate(root, 0)
    if diag is not None and matched[0] == 0:
        # the plan was re-planned since the recorded run (breaker
        # generation tick, conf change): the live tree no longer carries
        # the recorder's paths.  Render the recorder-side operator table
        # instead of silently dropping the run's stats.
        ran = [st for st in diag.operator_stats()
               if st.path and (st.batches or st.counters)]
        if ran:
            lines.append("(plan was re-planned since the recorded run; "
                         "recorder-side operator stats:)")
            for st in ran:
                parts = [f"wall={st.wall_ns / 1e6:.1f}ms",
                         f"batches={st.batches}", f"rows={st.rows}"]
                parts += [f"{k}={v}"
                          for k, v in sorted(st.counters.items()) if v]
                lines.append(f"  {st.path} {st.describe}  ["
                             + ", ".join(parts) + "]")
    if diag is not None:
        qb = diag.ops.get("")
        if qb is not None and qb.counters:
            parts = [f"{k}={v}" for k, v in sorted(qb.counters.items())
                     if v]
            lines.append("(query-level, unattributed)  ["
                         + ", ".join(parts) + "]")
        lines.append(f"query: wall={diag.wall_ns / 1e6:.1f}ms "
                     f"status={diag.status} "
                     f"events={diag.n_events or len(diag.events)}"
                     + (f" eventLog={diag.event_log_path}"
                        if diag.event_log_path else ""))
    if meta is not None:
        fb = meta.explain(only_fallback=True)
        if fb:
            lines.append("Fallback reasons:")
            lines.append(fb)
    return "\n".join(lines)
