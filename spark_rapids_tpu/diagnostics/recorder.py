"""QueryDiagnostics — the per-query span/event recorder.

Reference analog: GpuTaskMetrics + the Spark event log (SURVEY.md §5.5):
the reference surfaces per-operator metrics in the SQL UI and writes an
event log the spark-rapids-tools profiler mines offline.  Here one
recorder is active per query (installed by ``diagnostics.query_scope``
around ``DataFrame.collect``); every instrumented site — jit launches
(``perfcounters.tpu_jit``), logical host syncs (``sync_event`` and the
scalar dunders), compile-cache hits/misses (``compilecache.registry``),
inline/AOT compiles, and resilience events (``resilience/domain.py``) —
records an event tagged with the contextvar-scoped current operator, and
every perf-counter bump is attributed to that operator's delta bucket.

The invariant the event log is built around: for any counter key, the
per-operator deltas (including the ``""`` query-level bucket for work no
operator claimed — plan-time compiles, background pool work, shuffle
helper threads) sum EXACTLY to the process-global ``perfcounters.since``
delta over the recorder's window.  tests/test_diagnostics.py pins this.

Event levels honor ``spark.rapids.sql.metrics.level``:

* ESSENTIAL — operator summaries, resilience events, query_start/end.
* MODERATE  — + launches, logical host syncs, compiles, cache hits/misses.
* DEBUG     — + one span per operator batch pull (``op_batch``).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu.diagnostics import context as CTX

ESSENTIAL, MODERATE, DEBUG = 0, 1, 2
_LEVELS = {"ESSENTIAL": ESSENTIAL, "MODERATE": MODERATE, "DEBUG": DEBUG}

# Event schema (golden — tests/test_diagnostics.py validates recorded
# logs against it and docs/diagnostics.md must document every type).
# Every event also carries: ev, ts_ns, op (the attributed operator path,
# "" when no operator context was active).
EVENT_SCHEMA: Dict[str, List[str]] = {
    "query_start": ["query_id", "trace_id", "started_at",
                    "metrics_level", "plan"],
    "launch": ["dur_ns", "compiled"],
    "compile": ["mode", "dur_ns", "label"],
    "sync": ["kind", "dur_ns", "bytes"],
    "cache": ["hit", "label"],
    "resilience": ["kind", "op_name", "detail"],
    "lifecycle": ["kind", "detail", "dur_ns"],
    "io_fault": ["kind", "path", "fmt", "detail"],
    "scan_prefetch": ["depth", "batches", "overlapped_bytes", "stall_ns"],
    "ici_shuffle": ["stage", "n_dev", "rows", "bytes", "dur_ns"],
    "governor": ["action", "state", "prev", "pressure", "detail"],
    "distributed": ["kind", "worker_id", "detail", "n_workers",
                    "n_partitions"],
    "worker_telemetry": ["worker_id", "blocks", "bytes", "mem_used",
                         "counters"],
    "recovery": ["kind", "fp", "detail", "n"],
    "worker_span": ["worker_id", "kind", "trace", "span", "exch",
                    "pid", "seq", "bytes", "dur_ns"],
    "query_stall": ["query_id", "path", "name", "stalled_ms", "detail"],
    "progress": ["query_id", "pct", "eta_ns", "stalls", "background"],
    "op_batch": ["path", "batch", "rows", "dur_ns"],
    "operator": ["path", "name", "describe", "op_class", "fp", "wall_ns",
                 "self_wall_ns", "batches", "rows", "counters", "metrics",
                 "fallback"],
    "cost_model": ["hits", "misses", "predicted_wall_ns",
                   "actual_wall_ns", "matched_actual_wall_ns"],
    "resource_bill": ["query_id", "signature", "wall_ns",
                      "device_peak_bytes", "device_byte_seconds",
                      "device_bytes_charged", "device_bytes_released",
                      "residual_bytes", "persistent_bytes", "spill",
                      "partitions", "background_wall_ns", "worker_bytes",
                      "counters"],
    "regression": ["query_id", "signature", "dimension", "observed",
                   "baseline", "ratio", "z", "op_path", "op_name",
                   "detail"],
    "query_end": ["wall_ns", "status", "counters"],
}

_QUERY_SEQ = [0]
_SEQ_LOCK = threading.Lock()


def next_query_id() -> str:
    with _SEQ_LOCK:
        _QUERY_SEQ[0] += 1
        seq = _QUERY_SEQ[0]
    return f"{int(time.time() * 1000):013d}-{os.getpid()}-{seq:04d}"


class _OpStat:
    """Per-operator accumulation: inclusive wall, batch/row counts, and
    the counter deltas attributed while this operator was current."""

    __slots__ = ("path", "name", "describe", "wall_ns", "batches", "rows",
                 "t_first_ns", "t_last_ns", "counters", "metrics",
                 "fallback", "cal_op", "cal_fp")

    def __init__(self, path: str, name: str, describe: str):
        self.path = path
        self.name = name
        self.describe = describe
        self.wall_ns = 0
        self.batches = 0
        self.rows = 0
        self.t_first_ns: Optional[int] = None
        self.t_last_ns: Optional[int] = None
        self.counters: Dict[str, int] = {}
        self.metrics: Dict[str, int] = {}
        self.fallback = False
        # calibration identity (ISSUE 8): the breaker/tagging plan key —
        # (plan-node class, expr fingerprint) — so the operator summary
        # event carries the key the profiling store and the plan-time
        # cost model match on; None when the exec has no plan twin
        self.cal_op: Optional[str] = None
        self.cal_fp: Optional[str] = None


def _cal_key_of(node):
    """The exec's (plan class, expr fingerprint) via its plan twin —
    cached on the exec by resilience.domain, so this is a dict hit on
    every collect after the first."""
    try:
        from spark_rapids_tpu.resilience.domain import _breaker_key_of

        return _breaker_key_of(node)
    except Exception:
        return None


class QueryDiagnostics:
    """One query's diagnostics: spans, events, per-operator counter
    deltas.  Thread-safe; installed as ``diagnostics.context.RECORDER``
    for the duration of the query by ``diagnostics.query_scope``."""

    def __init__(self, query_id: str, metrics_level: str = "MODERATE",
                 plan_text: str = "", max_events: int = 200_000,
                 trace_id: str = ""):
        self._lock = threading.Lock()
        self.query_id = query_id
        # the cluster-wide trace id (ISSUE 15): adopted from the
        # lifecycle QueryContext by query_scope, stamped on every TKD1
        # frame, and the key worker-side spans merge back under
        self.trace_id = trace_id
        self.max_events = int(max_events)
        self.dropped_events = 0
        self.level = _LEVELS.get(str(metrics_level).upper(), MODERATE)
        self.metrics_level = str(metrics_level).upper()
        self.plan_text = plan_text
        self.started_at = time.time()
        self._t0 = time.perf_counter_ns()
        self.events: List[Dict[str, Any]] = []
        self.ops: Dict[str, _OpStat] = {"": _OpStat("", "(query)", "(query)")}
        self._op_order: List[str] = [""]
        self._extra_seq = 0
        # TpuMetric values are CUMULATIVE across collects of a cached
        # plan (the Spark-UI semantics metrics_report documents); this
        # log is per-query, so baselines captured at registration turn
        # them into per-query deltas at finish()
        self._metric_base: Dict[str, Dict[str, int]] = {}
        self.snap0 = PC.snapshot()
        self.total: Dict[str, int] = {}
        self.wall_ns = 0
        self.status = "running"
        self.closed = False
        self.event_log_path: Optional[str] = None
        self.trace_path: Optional[str] = None
        self.n_events = 0          # final count, survives the post-flush
                                   # drop of the in-memory events list

    # -- time ----------------------------------------------------------
    def _now(self) -> int:
        return time.perf_counter_ns() - self._t0

    # -- plan registration ---------------------------------------------
    def register_root(self, root) -> None:
        """Assign a plan-node path ("0", "0.1", ...) to every TpuExec in
        the tree and create its stat bucket.  Idempotent per recorder;
        overwrites stale paths a previous query's recorder left behind."""
        from spark_rapids_tpu.exec.base import TpuExec

        def walk(node, path):
            node._diag_path = path
            node._diag_qid = self.query_id
            cal = _cal_key_of(node)
            with self._lock:
                if path not in self.ops:
                    self.ops[path] = _OpStat(path, node.node_name,
                                             node.describe())
                    self._op_order.append(path)
                if cal is not None:
                    self.ops[path].cal_op, self.ops[path].cal_fp = cal
                self._metric_base[path] = {
                    m.name: m.value for m in node.metrics.values()}
            for i, c in enumerate(node.children):
                if isinstance(c, TpuExec):
                    walk(c, f"{path}.{i}")

        walk(root, "0")

    def _register_runtime_op(self, op) -> str:
        """An exec created after planning (adaptive re-plan, runtime CPU
        fallback shim) registers lazily under a ``+N`` path."""
        cal = _cal_key_of(op)
        with self._lock:
            self._extra_seq += 1
            path = f"+{self._extra_seq}"
            self.ops[path] = _OpStat(path, op.node_name, op.describe())
            if cal is not None:
                self.ops[path].cal_op, self.ops[path].cal_fp = cal
            self._op_order.append(path)
            self._metric_base[path] = {
                m.name: m.value for m in op.metrics.values()}
        op._diag_path = path
        op._diag_qid = self.query_id
        return path

    # -- operator span driving (called from exec/base._diag) -----------
    def begin_op(self, op):
        """Returns (path, token, t0) — or None when ``op`` belongs to a
        DIFFERENT query's registered tree (a concurrent collect whose
        query_scope lost the one-recorder slot): its spans/counters must
        not corrupt this recorder's log, so it runs unrecorded.  (A
        never-diagnosed concurrent tree carries no ownership stamp and
        still lands here as a ``+N`` op — the one-recorder-per-process
        design's residual ambiguity.)"""
        qid = getattr(op, "_diag_qid", None)
        if qid is not None and qid != self.query_id:
            return None
        path = getattr(op, "_diag_path", None)
        if path is None or path not in self.ops:
            path = self._register_runtime_op(op)
        token = CTX.CURRENT_OP.set(path)
        return path, token, self._now()

    def end_op(self, path: str, token, t0_ns: int,
               rows: Optional[int]) -> None:
        CTX.CURRENT_OP.reset(token)
        t1 = self._now()
        dur = t1 - t0_ns
        with self._lock:
            if self.closed:
                return
            st = self.ops.get(path)
            if st is None:       # another query's stale path (see attribute)
                return
            st.wall_ns += dur
            if st.t_first_ns is None:
                st.t_first_ns = t0_ns
            st.t_last_ns = t1
            if rows is not None:
                batch_idx = st.batches
                st.batches += 1
                st.rows += rows
                if self.level >= DEBUG:
                    self._append_event_locked({
                        "ev": "op_batch", "ts_ns": t0_ns, "op": path,
                        "path": path, "batch": batch_idx, "rows": rows,
                        "dur_ns": dur})

    # -- counter attribution (called from perfcounters.bump) -----------
    def attribute(self, key: str, n: int) -> None:
        path = CTX.CURRENT_OP.get() or ""
        with self._lock:
            if self.closed:
                return
            # a path this recorder never registered (a thread still
            # carrying another query's CURRENT_OP token) lands in the
            # query-level bucket instead of KeyError-ing the hot path
            st = self.ops.get(path) or self.ops[""]
            c = st.counters
            c[key] = c.get(key, 0) + n

    def _attr_many(self, path: str, deltas) -> None:
        st = self.ops.get(path) or self.ops[""]
        c = st.counters
        for key, n in deltas:
            c[key] = c.get(key, 0) + n

    def _append_event_locked(self, e) -> None:
        """Caller holds self._lock (the ``_locked`` suffix is the
        caller-holds-lock contract tpulint's lockset rules recognize).
        The in-memory list is bounded (a
        launch-per-row pathological query must not hold GBs of event
        dicts until flush); overflow counts into ``events_dropped`` on
        query_end instead of growing without limit."""
        if len(self.events) >= self.max_events:
            self.dropped_events += 1
            return
        self.events.append(e)

    def _event(self, min_level: int, ev: str, **fields) -> None:
        if self.level < min_level:
            return
        e = {"ev": ev, "ts_ns": self._now(),
             "op": CTX.CURRENT_OP.get() or ""}
        e.update(fields)
        with self._lock:
            if not self.closed:
                self._append_event_locked(e)

    # -- instrumentation entry points ----------------------------------
    def launch(self, dur_ns: int, compiled: int) -> None:
        """One jitted program dispatch (perfcounters._CountingJit).
        Mirrors the counter writes the jit wrapper just made so the
        per-operator sums reconcile exactly with the globals."""
        path = CTX.CURRENT_OP.get() or ""
        deltas = [("programs_launched", 1), ("launch_wall_ns", dur_ns)]
        if compiled:
            deltas += [("compiles", compiled), ("compile_wall_ns", dur_ns)]
        with self._lock:
            if self.closed:
                return
            self._attr_many(path, deltas)
            if self.level >= MODERATE:
                ts = self._now()
                self._append_event_locked({
                    "ev": "launch", "ts_ns": ts - dur_ns, "op": path,
                    "dur_ns": dur_ns, "compiled": int(compiled)})
                if compiled:
                    self._append_event_locked({
                        "ev": "compile", "ts_ns": ts - dur_ns, "op": path,
                        "mode": "inline", "dur_ns": dur_ns, "label": ""})

    def d2h(self, nbytes: int, counted_sync: bool) -> None:
        """One device->host materialization (ArrayImpl dunder patch)."""
        path = CTX.CURRENT_OP.get() or ""
        deltas = [("bytes_d2h", nbytes)]
        if counted_sync:
            deltas.append(("host_syncs", 1))
        with self._lock:
            if self.closed:
                return
            self._attr_many(path, deltas)
            if counted_sync and self.level >= MODERATE:
                self._append_event_locked({
                    "ev": "sync", "ts_ns": self._now(), "op": path,
                    "kind": "scalar", "dur_ns": 0, "bytes": int(nbytes)})

    def sync_batched(self, dur_ns: int) -> None:
        """One LOGICAL batched round trip (perfcounters.sync_event exit;
        the host_syncs counter was attributed at entry via bump).
        Back-dated to the sync's START like launch events, so the trace
        span occupies the interval the round trip actually covered."""
        if self.level < MODERATE:
            return
        e = {"ev": "sync", "ts_ns": self._now() - dur_ns,
             "op": CTX.CURRENT_OP.get() or "", "kind": "batched",
             "dur_ns": dur_ns, "bytes": 0}
        with self._lock:
            if not self.closed:
                self._append_event_locked(e)

    def cache_event(self, hit: bool, label: str) -> None:
        """Compile-registry hit/miss (counter attributed via bump)."""
        self._event(MODERATE, "cache", hit=bool(hit), label=label or "")

    def aot_compile(self, label: str, dur_ns: int) -> None:
        """One background-pool AOT compile (counters via bump, which the
        pool thread attributes to the query-level bucket)."""
        self._event(MODERATE, "compile", mode="aot", dur_ns=dur_ns,
                    label=label or "")

    def resilience(self, kind: str, op_name: str, detail: str = "") -> None:
        """A fault-domain event: transient_retry, oom_restart,
        runtime_fallback, breaker_trip, or query_fallback."""
        self._event(ESSENTIAL, "resilience", kind=kind, op_name=op_name,
                    detail=str(detail)[:500])

    def io_fault(self, kind: str, path: str, fmt: str = "",
                 detail: str = "") -> None:
        """A per-file scan fault tolerated away (ISSUE 5): kind is the
        quarantine class (corrupt, truncated, missing, schema_mismatch)."""
        self._event(ESSENTIAL, "io_fault", kind=kind, path=path,
                    fmt=fmt or "", detail=str(detail)[:500])

    def lifecycle(self, kind: str, detail: str = "",
                  dur_ns: int = 0) -> None:
        """A query-lifecycle event (ISSUE 4): ``admitted`` (dur_ns = the
        admission queue wait), ``cancelled``, ``deadline_trip``, or
        ``rejected``."""
        self._event(ESSENTIAL, "lifecycle", kind=kind,
                    detail=str(detail)[:500], dur_ns=int(dur_ns))

    def governor(self, action: str, state: str, prev: str = "",
                 pressure: float = 0.0, detail: str = "") -> None:
        """An overload-governor event (ISSUE 13): ``transition`` (the
        pressure state machine moved; ``prev`` names the old state) or
        ``preempt_pause`` (this query took a cooperative pause-and-
        spill at a batch-pull boundary)."""
        self._event(ESSENTIAL, "governor", action=action, state=state,
                    prev=prev, pressure=float(pressure),
                    detail=str(detail)[:500])

    def distributed(self, kind: str, worker_id: str, detail: str,
                    n_workers: int, n_partitions: int) -> None:
        """A cross-host tier event (ISSUE 14): ``worker_joined`` /
        ``worker_quarantined`` / ``worker_probed`` / ``worker_left`` /
        ``worker_lost`` (membership + liveness, with the live-worker
        and placed-partition counts at the time) or
        ``partition_replayed`` (one reduce partition re-driven from
        the producer-side spilled queues after a loss)."""
        self._event(ESSENTIAL, "distributed", kind=kind,
                    worker_id=str(worker_id),
                    detail=str(detail)[:500],
                    n_workers=int(n_workers),
                    n_partitions=int(n_partitions))

    def recovery(self, kind: str, fp: str, detail: str,
                 n: int = 0) -> None:
        """A crash-recovery event (ISSUE 16, docs/recovery.md):
        ``stage_committed`` (one exchange's materialized output became
        durable — local checkpoint renamed or distributed lease
        journaled), ``stage_recovered`` (a committed stage served
        instead of re-executing; ``n`` counts partitions),
        ``checkpoint_discarded`` (a damaged/expired artifact degraded
        to full re-execution), or ``query_resumed`` (this query
        adopted at least one prior-incarnation stage)."""
        self._event(ESSENTIAL, "recovery", kind=kind, fp=str(fp),
                    detail=str(detail)[:500], n=int(n))

    def worker_telemetry(self, worker_id: str, blocks: int, bytes_: int,
                         mem_used: int, counters: Dict[str, int]) -> None:
        """One federated heartbeat payload from a worker (ISSUE 15):
        its store occupancy + cumulative worker-local counters at
        receipt time — the per-query record of what the cluster's
        workers were doing while this query ran."""
        self._event(MODERATE, "worker_telemetry",
                    worker_id=str(worker_id), blocks=int(blocks),
                    bytes=int(bytes_), mem_used=int(mem_used),
                    counters=dict(counters))

    def record_worker_spans(self, views: List[Dict]) -> int:
        """Merge worker-side span events (ISSUE 15) into this FINISHED
        query's log: each view is one worker's federated telemetry
        (``Coordinator.collect_trace`` shape — ring already filtered to
        this query's trace id, plus the handshake clock offset).  Ring
        timestamps are worker wall-clock; alignment onto the driver
        timeline is ``(ts_wall + offset - started_at)`` clamped into
        the query window.  Runs after ``finish()`` closed the window
        (like ``record_cost_model``) and keeps query_end last.  Returns
        the number of spans merged."""
        events = []
        for view in views:
            wid = str(view.get("worker_id", "?"))
            off = float(view.get("clock_offset_s") or 0.0)
            for e in view.get("ring", ()):
                ts_ns = int(((float(e.get("ts_wall", 0.0)) + off)
                             - self.started_at) * 1e9)
                events.append({
                    "ev": "worker_span",
                    "ts_ns": max(min(ts_ns, self.wall_ns), 0),
                    "op": e.get("span", "") or "",
                    "worker_id": wid,
                    "kind": e.get("kind", "?"),
                    "trace": e.get("trace", ""),
                    "span": e.get("span", "") or "",
                    "exch": int(e.get("exch", -1)),
                    "pid": int(e.get("pid", -1)),
                    "seq": int(e.get("seq", -1)),
                    "bytes": int(e.get("bytes", 0)),
                    "dur_ns": int(e.get("dur_ns", 0))})
        if not events:
            return 0
        with self._lock:
            # honor the in-memory bound like every other event: a
            # many-worker merge must not blow past max_events just
            # because it lands after finish() (overflow counts into
            # events_dropped, same as _append_event_locked)
            room = max(self.max_events - len(self.events), 0)
            if len(events) > room:
                self.dropped_events += len(events) - room
                events = events[:room]
            at = len(self.events)
            if self.events and self.events[-1].get("ev") == "query_end":
                at -= 1
                # finish() already stamped events_dropped into the
                # trailing query_end — keep the flushed log's count true
                self.events[-1]["events_dropped"] = self.dropped_events
            if not events:
                return 0
            self.events[at:at] = events
            self.n_events = len(self.events)
        return len(events)

    def query_stall(self, query_id: str, path: str, name: str,
                    stalled_ms: float, detail: str = "") -> None:
        """The watchdog's stall scan found no operator advance for
        progress.stallMs (ISSUE 12): names the stuck operator — the
        innermost in-flight batch pull — not just thread stacks."""
        self._event(ESSENTIAL, "query_stall", query_id=query_id,
                    path=path, name=name,
                    stalled_ms=round(float(stalled_ms), 1),
                    detail=str(detail)[:500])

    def progress_summary(self, query_id: str, pct, eta_ns, stalls: int,
                         background: Dict[str, Dict[str, int]]) -> None:
        """The query's final live-progress record (ISSUE 12): overall
        percent at finish, last ETA, stall episodes, and the background
        wall (AOT/prefetch/shuffle pools) attributed to this query."""
        self._event(ESSENTIAL, "progress", query_id=query_id, pct=pct,
                    eta_ns=eta_ns, stalls=int(stalls),
                    background=background)

    def scan_prefetch(self, depth: int, batches: int,
                      overlapped_bytes: int, stall_ns: int) -> None:
        """One scan's H2D prefetch-ring summary (ISSUE 6): how many
        batches the ring produced, how many uploaded bytes fully
        overlapped query compute, and how long the consumer stalled
        waiting on an in-flight prefetch — profile_report derives
        overlap efficiency from these."""
        self._event(MODERATE, "scan_prefetch", depth=int(depth),
                    batches=int(batches),
                    overlapped_bytes=int(overlapped_bytes),
                    stall_ns=int(stall_ns))

    def ici_shuffle(self, stage: str, n_dev: int, rows: int,
                    bytes_: int, dur_ns: int) -> None:
        """One ICI collective-exchange epoch (ISSUE 10): which mesh
        stage ran it, how many devices participated, and the rows/bytes
        exchanged device-to-device (zero host traffic on this path)."""
        self._event(MODERATE, "ici_shuffle", stage=stage, n_dev=int(n_dev),
                    rows=int(rows), bytes=int(bytes_), dur_ns=int(dur_ns))

    # -- finalization --------------------------------------------------
    def finish(self, root=None, status: str = "ok") -> None:
        """Close the window: snapshot the global deltas, harvest each
        registered operator's TpuMetrics, and append the operator
        summaries + query_end events."""
        from spark_rapids_tpu.exec.base import TpuExec

        if self.closed:
            return
        self.wall_ns = self._now()
        self.status = status
        # Snapshot the globals and stop attribution ATOMICALLY: counter
        # writes hold PC._LOCK across (global increment + attribution),
        # so every bump — including one from an AOT pool thread racing
        # the end of collect() — lands either fully inside the window or
        # fully outside; the per-operator sums stay exactly equal to the
        # global deltas.  Lock order everywhere: PC._LOCK -> self._lock.
        with PC._LOCK:
            cur = dict(PC.COUNTERS)
            with self._lock:
                self.closed = True
        self.total = {k: cur[k] - self.snap0.get(k, 0) for k in cur}
        if root is not None:
            def walk(node):
                path = getattr(node, "_diag_path", None)
                st = self.ops.get(path)
                if st is not None \
                        and getattr(node, "_diag_qid", None) == self.query_id:
                    base = self._metric_base.get(path, {})
                    st.metrics = {
                        m.name: m.value - base.get(m.name, 0)
                        for m in node.metrics.values()
                        if m.value - base.get(m.name, 0)}
                    st.fallback = bool(st.metrics.get("runtimeFallbacks"))
                for c in node.children:
                    if isinstance(c, TpuExec):
                        walk(c)

            walk(root)
        with self._lock:
            # exclusive (self) wall: an operator's pull span contains all
            # descendant pulls, so ranking by inclusive wall would just
            # rank by plan depth — subtract the DIRECT children's wall
            child_wall: Dict[str, int] = {}
            for path, st in self.ops.items():
                dot = path.rfind(".")
                if dot > 0:
                    parent = path[:dot]
                    child_wall[parent] = child_wall.get(parent, 0) \
                        + st.wall_ns
            for path in self._op_order:
                st = self.ops[path]
                if path == "" and not st.counters:
                    continue
                self.events.append({
                    "ev": "operator", "ts_ns": self.wall_ns, "op": path,
                    "path": path, "name": st.name,
                    "describe": st.describe,
                    "op_class": st.cal_op, "fp": st.cal_fp,
                    "wall_ns": st.wall_ns,
                    "self_wall_ns": max(
                        st.wall_ns - child_wall.get(path, 0), 0),
                    "batches": st.batches, "rows": st.rows,
                    "counters": dict(st.counters),
                    "metrics": dict(st.metrics),
                    "fallback": st.fallback,
                    "t_first_ns": st.t_first_ns, "t_last_ns": st.t_last_ns})
            self.events.append({
                "ev": "query_end", "ts_ns": self.wall_ns, "op": "",
                "wall_ns": self.wall_ns, "status": status,
                "events_dropped": self.dropped_events,
                "counters": dict(self.total)})
            self.n_events = len(self.events)

    def record_cost_model(self, hits: int, misses: int,
                          predicted_wall_ns: int, actual_wall_ns: int,
                          matched_actual_wall_ns: int) -> None:
        """The per-query predicted-vs-actual record (ISSUE 8).  The
        profiling finish hook runs after ``finish()`` closed the window
        but before the sinks flush, so this appends past the closed
        flag — inserted BEFORE the trailing query_end to keep the
        query_end-last log invariant."""
        e = {"ev": "cost_model", "ts_ns": self.wall_ns, "op": "",
             "hits": int(hits), "misses": int(misses),
             "predicted_wall_ns": int(predicted_wall_ns),
             "actual_wall_ns": int(actual_wall_ns),
             "matched_actual_wall_ns": int(matched_actual_wall_ns)}
        with self._lock:
            if self.events and self.events[-1].get("ev") == "query_end":
                self.events.insert(len(self.events) - 1, e)
            else:
                self.events.append(e)
            self.n_events = len(self.events)

    def _append_post_finish(self, e: Dict[str, Any]) -> None:
        """Insert a finish-hook event BEFORE the trailing query_end
        (same pattern as record_cost_model: the hooks run after
        ``finish()`` closed the window, before the sinks flush)."""
        with self._lock:
            if self.events and self.events[-1].get("ev") == "query_end":
                self.events.insert(len(self.events) - 1, e)
            else:
                self.events.append(e)
            self.n_events = len(self.events)

    def record_resource_bill(self, **fields: Any) -> None:
        """The per-query resource bill (ISSUE 18): the ledger joined
        with the window's counter deltas, progress background wall, and
        federated worker bytes — appended by the accounting finish
        hook."""
        self._append_post_finish(
            {"ev": "resource_bill", "ts_ns": self.wall_ns, "op": "",
             **fields})

    def record_regression(self, **fields: Any) -> None:
        """A sentinel-flagged excursion past this plan signature's
        baseline (ISSUE 18) — at most one per query, worst dimension."""
        self._append_post_finish(
            {"ev": "regression", "ts_ns": self.wall_ns, "op": "",
             **fields})

    def header(self) -> Dict[str, Any]:
        return {
            "ev": "query_start", "ts_ns": 0, "op": "",
            "query_id": self.query_id, "trace_id": self.trace_id,
            "started_at": self.started_at,
            "metrics_level": self.metrics_level,
            "plan": [{"path": p, "name": self.ops[p].name,
                      "describe": self.ops[p].describe}
                     for p in self._op_order if p != ""],
        }

    def operator_stats(self) -> List[_OpStat]:
        with self._lock:
            return [self.ops[p] for p in self._op_order]
