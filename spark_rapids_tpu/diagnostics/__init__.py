"""Query diagnostics layer (ISSUE 3): per-operator spans, a structured
JSONL event log, a Chrome-trace/Perfetto exporter, and offline report
tooling.

Reference analog: the reference plugin's telemetry stack — GpuExec
metrics in the SQL UI (``spark.rapids.sql.metrics.level``),
GpuTaskMetrics per task, and the spark-rapids-tools profiler over event
logs (SURVEY.md §5.5, L8).  On a tunnel-relayed TPU the *counts*
(launches, host syncs, D2H bytes) are the portable truth about engine
quality, so the recorder's core invariant is exact counter attribution:
per-operator deltas (+ the query-level bucket) sum to the process-global
``perfcounters.since()`` deltas over the query window.

This ``__init__`` is deliberately lazy: the hot paths import only
``diagnostics.context`` (one ambient check on the disabled path), and
everything heavier loads on first enabled query.

Layout:
  context.py   — the active-recorder slot + contextvar current operator
  recorder.py  — QueryDiagnostics (spans, events, attribution)
  sinks.py     — JSONL event log + Chrome-trace/Perfetto export
  report.py    — offline aggregation (tools/profile_report.py) and
                 explain("analyze") rendering
"""
from __future__ import annotations

import sys
import threading
from typing import Optional

_SCOPE_LOCK = threading.Lock()
_WARNED = [False]


def _stamp_unrecorded(root, keep_qid=None) -> None:
    """Mark a tree that is about to execute WITHOUT a recorder while some
    OTHER query's recorder is (or may become) active: ``begin_op`` sees
    the foreign ownership stamp and runs the span unrecorded, instead of
    lazily registering the tree as ``+N`` runtime ops and interleaving a
    concurrent query's spans into the active query's log/trace (ISSUE 8
    satellite: per-query span trees must not interleave).

    ``keep_qid``: nodes already stamped with the ACTIVE recorder's query
    id are left untouched — two threads collecting the SAME DataFrame
    share one cached exec tree, and the losing collect must not evict
    the winner's registration (that would silently truncate the
    recorded query's attribution mid-flight)."""
    from spark_rapids_tpu.exec.base import TpuExec

    def walk(node):
        if not (keep_qid is not None
                and getattr(node, "_diag_qid", None) == keep_qid):
            node._diag_qid = "(unrecorded)"
            node._diag_path = None
        for c in node.children:
            if isinstance(c, TpuExec):
                walk(c)

    walk(root)


class query_scope:
    """Context manager installing a QueryDiagnostics recorder around one
    query execution (used by ``DataFrame.collect``).  Yields the recorder
    or None when diagnostics are disabled — or when another query's
    recorder is already active (one recorder per process; the concurrent
    query runs unrecorded rather than corrupting the first's log).

    ``on_finish`` (optional): called with the finished recorder after
    ``finish()`` computed the operator summaries but BEFORE the sinks
    flush (so it may still append, e.g. the profiling layer's
    ``cost_model`` record) — its failures never fail the query."""

    def __init__(self, conf, root, plan_text: str = "", on_finish=None):
        self._conf = conf
        self._root = root
        self._plan_text = plan_text
        self._on_finish = on_finish
        self.diag = None

    def __enter__(self):
        from spark_rapids_tpu.config import (
            DIAGNOSTICS_ENABLED,
            DIAGNOSTICS_MAX_EVENTS,
            METRICS_LEVEL,
        )
        from spark_rapids_tpu.diagnostics import context as CTX

        if not self._conf.get(DIAGNOSTICS_ENABLED):
            # another session's recorder is live: this undiagnosed
            # query's spans must not land in its log as +N ops.  Only
            # then — the disabled-path contract stays one conf read +
            # one ambient check per collect (a recorder installed AFTER
            # this check can still briefly absorb spans; the common
            # overlap, recorder-first, is covered)
            rec = CTX.RECORDER
            if rec is not None:
                _stamp_unrecorded(self._root, keep_qid=rec.query_id)
            return None
        with _SCOPE_LOCK:
            if CTX.RECORDER is not None:
                if not _WARNED[0]:
                    _WARNED[0] = True
                    print("spark_rapids_tpu.diagnostics: a recorder is "
                          "already active; concurrent query runs "
                          "unrecorded", file=sys.stderr)
                # under _SCOPE_LOCK the active recorder cannot change:
                # keep_qid exactly protects a concurrently-recorded
                # collect of the SAME DataFrame's shared exec tree
                _stamp_unrecorded(self._root,
                                  keep_qid=CTX.RECORDER.query_id)
                return None
            from spark_rapids_tpu.diagnostics.recorder import (
                QueryDiagnostics,
                next_query_id,
            )

            # adopt the lifecycle-minted cluster trace id (ISSUE 15) so
            # the event-log header, the TKD1 frame stamps, and the
            # worker-span merge below all share one key
            from spark_rapids_tpu.lifecycle.context import current

            ctx = current()
            diag = QueryDiagnostics(
                next_query_id(),
                metrics_level=self._conf.get(METRICS_LEVEL),
                plan_text=self._plan_text,
                max_events=int(self._conf.get(DIAGNOSTICS_MAX_EVENTS)),
                trace_id=getattr(ctx, "trace_id", "") if ctx is not None
                else "")
            diag.register_root(self._root)
            # install + baseline snapshot atomically under the counter
            # lock (counter writes attribute under the same lock), so no
            # bump can land in the global window without also reaching
            # the recorder — the exact-sum invariant's other half; see
            # QueryDiagnostics.finish
            from spark_rapids_tpu import perfcounters as PC

            with PC._LOCK:
                diag.snap0 = dict(PC.COUNTERS)
                CTX.RECORDER = diag
            self.diag = diag
        return diag

    def __exit__(self, exc_type, exc, tb):
        if self.diag is None:
            return False
        from spark_rapids_tpu.diagnostics import context as CTX

        try:
            self.diag.finish(self._root,
                             status="ok" if exc_type is None else
                             f"error:{getattr(exc_type, '__name__', '?')}")
        finally:
            with _SCOPE_LOCK:
                if CTX.RECORDER is self.diag:
                    CTX.RECORDER = None
        if self._on_finish is not None:
            try:
                self._on_finish(self.diag)
            except Exception as e:
                print("spark_rapids_tpu.diagnostics: finish hook "
                      f"failed: {e}", file=sys.stderr)
        self._merge_worker_spans()
        self._write_sinks()
        return False

    def _merge_worker_spans(self) -> None:
        """Fold worker-side spans for this query's trace id into the
        finished log (ISSUE 15) so the event log and Chrome trace are
        the MERGED cross-process record.  The coordinator is peeked via
        sys.modules — the in-process path (distributed never imported
        or never built) makes zero calls into distributed modules, the
        cProfile pin in tests/test_cluster_observability.py holds this.
        ALIVE workers are DUMPed live first so the merge does not stop
        at the last heartbeat; failures never fail the query."""
        dist_mod = sys.modules.get("spark_rapids_tpu.distributed")
        coord = getattr(dist_mod, "_coordinator", None) \
            if dist_mod is not None else None
        if coord is None or not self.diag.trace_id \
                or not getattr(coord, "trace_enabled", False):
            return
        if not self.diag.total.get("dist_blocks_shipped"):
            return   # this query never touched the worker tier
        try:
            views = coord.collect_trace(self.diag.trace_id,
                                        pull_live=True)
            merged = self.diag.record_worker_spans(views)
            if merged:
                from spark_rapids_tpu import perfcounters as PC

                PC.bump_unattributed("dist_worker_spans_merged", merged)
        except Exception as e:   # observability must never fail a query
            print("spark_rapids_tpu.diagnostics: worker-span merge "
                  f"failed: {e}", file=sys.stderr)

    def _write_sinks(self) -> None:
        """Atomic per-query flush of the configured sinks; sink I/O
        failures never fail the query."""
        from spark_rapids_tpu.config import (
            DIAGNOSTICS_EVENT_LOG_DIR,
            DIAGNOSTICS_MAX_FILES,
            DIAGNOSTICS_TRACE_DIR,
        )

        max_files = int(self._conf.get(DIAGNOSTICS_MAX_FILES))
        log_dir = self._conf.get(DIAGNOSTICS_EVENT_LOG_DIR)
        trace_dir = self._conf.get(DIAGNOSTICS_TRACE_DIR)
        try:
            if log_dir:
                from spark_rapids_tpu.diagnostics.sinks import write_event_log

                write_event_log(self.diag, log_dir, max_files)
            if trace_dir:
                from spark_rapids_tpu.diagnostics.sinks import (
                    write_chrome_trace,
                )

                write_chrome_trace(self.diag, trace_dir, max_files)
        except Exception as e:   # a sink failure must never fail the query
            print(f"spark_rapids_tpu.diagnostics: sink write failed: {e}",
                  file=sys.stderr)
            return
        if self.diag.event_log_path or self.diag.trace_path:
            # the flushed file is now the authoritative copy; dropping
            # the in-memory duplicate keeps a bench sweep's retained
            # _last_diag recorders from pinning up to maxEvents dicts
            # each (explain("analyze") reads ops/n_events, not events)
            with self.diag._lock:
                self.diag.events = []
