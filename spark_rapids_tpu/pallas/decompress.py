"""Device snappy decompression — ship compressed bytes, expand at HBM
bandwidth.

Reference analog: "GPU Acceleration of SQL Analytics on Compressed Data"
(arXiv:2506.10092) and cuDF's gpuinflate/snappy device decompressors: the
winning trade on a bandwidth-starved host->device link is to transfer the
SMALLEST representation (the compressed page) and let the accelerator do
the byte movement.  On this platform the link tops out near 5-40 MB/s
(BENCH_r05), so every decoded byte shipped is ~25x more expensive than a
compressed one.

TPU adaptation (the same host-parses-structure / device-moves-bytes split
as pallas/decode.py): a snappy stream is a sequence of ops — literal runs
(bytes sit verbatim in the compressed buffer) and copies (back-references
into the output, including overlapping RLE-style copies).  The host walks
the TAG BYTES only (O(#ops) — literal payloads are skipped
arithmetically, never touched) and ships three int32 op arrays alongside
the raw compressed bytes.  The device resolves every output byte's
ULTIMATE literal source with pointer doubling:

    pass 0:  S[p] = comp offset        (p inside a literal op)
             S[p] = p - dist           (p inside a copy op)
    pass k:  S[p] = S[S[p]] where unresolved

Each pass is one vectorized gather over the output; back-reference
chains halve every pass, so ceil(log2(page)) + 1 passes resolve any
stream — including dist-1 RLE chains — with no sequential walk and no
host-side byte movement.  A final gather pulls the bytes from the
compressed buffer.  Stock XLA ops (searchsorted + gathers), one jitted
program per pow2 shape bucket (same rationale as decode._unpack_call).

When compressed bytes + op descriptors would cross the link heavier
than what the decoded-transfer path ships (incompressible pages),
:class:`TooFragmented` routes the caller there instead — bad trades
cost a fallback, never a wrong byte.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_SENTINEL = np.int32(2**31 - 1)


class TooFragmented(Exception):
    """Shipping this page compressed would cross the link heavier than
    the decoded path — the caller ships it decoded (transport cost
    only; correctness is identical either way)."""


def _parse_ops(data: bytes) -> Tuple[int, List[Tuple[int, int, int, int]]]:
    """Structural walk of a raw snappy block: (usize, ops).

    Each op is ``(kind, out_off, length, arg)`` with kind 0 = literal
    (arg = byte offset of the payload inside ``data``) and kind 1 = copy
    (arg = back-reference distance).  O(#ops) host work — literal
    payloads are skipped by length arithmetic, never touched."""
    n = len(data)
    pos = 0
    usize = 0
    shift = 0
    while True:
        if pos >= n:
            raise ValueError("malformed snappy varint")
        b = data[pos]
        pos += 1
        usize |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    ops: List[List[int]] = []
    out = 0

    def push(kind: int, o: int, length: int, arg: int) -> None:
        # coalesce: snappy splits a long match into 64-byte copies at
        # the SAME distance, and long literals into 60-byte runs with
        # adjacent payloads — merged they keep identical per-byte
        # semantics (out[p] = out[p - d] / comp payload) and the op
        # arrays ship ~100x smaller for structured pages
        if ops:
            k0, o0, l0, a0 = ops[-1]
            if k0 == kind and o0 + l0 == o and (
                    (kind == 1 and a0 == arg)
                    or (kind == 0 and a0 + l0 == arg)):
                ops[-1][2] = l0 + length
                return
        ops.append([kind, o, length, arg])

    while pos < n and out < usize:
        tag = data[pos]
        pos += 1
        t = tag & 3
        if t == 0:
            ln = tag >> 2
            if ln >= 60:
                nb = ln - 59
                if pos + nb > n:
                    raise ValueError("malformed snappy literal length")
                ln = int.from_bytes(data[pos:pos + nb], "little")
                pos += nb
            length = ln + 1
            if pos + length > n:
                raise ValueError("malformed snappy literal")
            push(0, out, length, pos)
            pos += length
        else:
            if t == 1:
                length = ((tag >> 2) & 0x7) + 4
                if pos + 1 > n:
                    raise ValueError("malformed snappy copy")
                dist = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif t == 2:
                length = (tag >> 2) + 1
                if pos + 2 > n:
                    raise ValueError("malformed snappy copy")
                dist = int.from_bytes(data[pos:pos + 2], "little")
                pos += 2
            else:
                length = (tag >> 2) + 1
                if pos + 4 > n:
                    raise ValueError("malformed snappy copy")
                dist = int.from_bytes(data[pos:pos + 4], "little")
                pos += 4
            if dist <= 0 or dist > out:
                raise ValueError("malformed snappy copy offset")
            push(1, out, length, dist)
        out += length
    if out != usize:
        raise ValueError("snappy length mismatch")
    return usize, [tuple(op) for op in ops]


def _p2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


_GATHER_JITS: Dict[Tuple[int, int, int], object] = {}


def _gather_fn(out_cap: int, comp_cap: int, op_cap: int):
    key = (out_cap, comp_cap, op_cap)
    fn = _GATHER_JITS.get(key)
    if fn is None:
        from spark_rapids_tpu.perfcounters import tpu_jit

        # chains halve each pass: log2(out_cap)+1 passes resolve any
        # back-reference chain the page can hold
        npasses = max(out_cap - 1, 1).bit_length() + 1

        def gather(comp, op_out, op_src, op_lit):
            p = jnp.arange(out_cap, dtype=jnp.int32)
            j = jnp.searchsorted(op_out, p, side="right") - 1
            j = jnp.clip(j, 0, op_cap - 1)
            rel = p - op_out[j]
            # resolved sources encode as -(comp offset) - 1; unresolved
            # stay as an earlier OUTPUT position (the copy's source)
            s = jnp.where(op_lit[j] > 0,
                          -(op_src[j] + rel) - 1,
                          p - op_src[j])
            for _ in range(npasses):
                hop = s[jnp.clip(s, 0, out_cap - 1)]
                s = jnp.where(s >= 0, hop, s)
            src = -s - 1
            return comp[jnp.clip(src, 0, comp_cap - 1)]

        fn = _GATHER_JITS[key] = tpu_jit(gather)
    return fn


def snappy_to_device(data: bytes, decoded_cost: int = 0) -> jax.Array:
    """Raw snappy block -> decompressed (usize,) uint8 DEVICE array.

    Only the compressed bytes + 12 B/op descriptor arrays cross the
    link (``bytes_h2d`` counts them; ``bytes_h2d_logical`` counts the
    decoded size).  ``decoded_cost`` is what the DECODED-transfer path
    would ship for this page (value payload + expanded def levels;
    defaults to the decompressed size): when the compressed
    representation is heavier, :class:`TooFragmented` routes the caller
    there.  Raises ValueError on malformed input."""
    from spark_rapids_tpu import perfcounters as PC

    usize, ops = _parse_ops(data)
    if usize == 0:
        return jnp.zeros(0, jnp.uint8)
    ship = len(data) + 12 * len(ops)
    if ship >= max(decoded_cost, usize):
        raise TooFragmented(
            f"compressed transfer larger than decoded ({ship} vs "
            f"{max(decoded_cost, usize)})")
    n_ops = len(ops)
    op_out = np.fromiter((o[1] for o in ops), np.int32, n_ops)
    op_src = np.fromiter((o[3] for o in ops), np.int32, n_ops)
    op_lit = np.fromiter((1 - o[0] for o in ops), np.int32, n_ops)
    comp_np = np.frombuffer(data, np.uint8)
    PC.count_h2d(comp_np.nbytes + 12 * n_ops, logical=usize)
    PC.bump("pages_device_decompressed")
    # exact-size uploads, device-side pow2 padding: padding bytes must
    # never cross the link (they would defeat the compressed transfer)
    import time as _time

    t0 = _time.perf_counter_ns()
    out_cap, comp_cap, op_cap = _p2(usize), _p2(len(data)), _p2(n_ops)
    comp = jnp.asarray(comp_np)
    o_np = jnp.asarray(op_out)
    s_np = jnp.asarray(op_src)
    lt_np = jnp.asarray(op_lit)
    PC.bump("scan_transfer_ns", _time.perf_counter_ns() - t0)
    comp = jnp.zeros(comp_cap, jnp.uint8).at[:len(data)].set(comp)
    o = jnp.full(op_cap, _SENTINEL, jnp.int32).at[:n_ops].set(o_np)
    s = jnp.zeros(op_cap, jnp.int32).at[:n_ops].set(s_np)
    lt = jnp.ones(op_cap, jnp.int32).at[:n_ops].set(lt_np)
    out = _gather_fn(out_cap, comp_cap, op_cap)(comp, o, s, lt)
    return out[:usize]


def raw_to_device(data: bytes) -> jax.Array:
    """UNCOMPRESSED page region -> (n,) uint8 device array (the identity
    twin of :func:`snappy_to_device`; same accounting contract)."""
    import time as _time

    from spark_rapids_tpu import perfcounters as PC

    buf = np.frombuffer(data, np.uint8)
    PC.count_h2d(buf.nbytes)
    t0 = _time.perf_counter_ns()
    out = jnp.asarray(buf)
    PC.bump("scan_transfer_ns", _time.perf_counter_ns() - t0)
    return out


def decompress_to_host(data: bytes) -> bytes:
    """Host (numpy) reference for the device gather (tests + docs): the
    same op stream executed sequentially."""
    usize, ops = _parse_ops(data)
    out = np.zeros(usize, np.uint8)
    comp = np.frombuffer(data, np.uint8)
    for kind, o, length, arg in ops:
        if kind == 0:
            out[o:o + length] = comp[arg:arg + length]
        elif arg >= length:
            out[o:o + length] = out[o - arg:o - arg + length]
        else:
            reps = -(-length // arg)
            out[o:o + length] = np.tile(out[o - arg:o], reps)[:length]
    return out.tobytes()
