"""Pallas Parquet decode kernels — the cuDF decode-kernel analog.

Reference analog: cuDF's parquet device decode (SURVEY.md §2.10 item 9:
"dictionary/RLE/bit-pack decode are TPU-feasible"; §3.4's
``Table.readParquet`` hot path).

Device layout insight: parquet's bit-packed runs repeat every 8 values
(8*bw bits = bw bytes), so reshaping the payload to (groups, bw) makes
every output's byte indices/shifts STATIC — the kernel is pure vector
shifts/ors over 8-wide lanes, no gathers, exactly what the VPU wants.
``unpack_bitpacked`` runs as a Pallas kernel on TPU (interpret mode
elsewhere); run expansion + dictionary gather compose around it with
stock XLA ops.

Supported bit widths: 1..24 (u32 windows never straddle more than 4
bytes); wider dictionary indices fall back to the host decode.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

MAX_BIT_WIDTH = 24
_TILE = 512


def _unpack_body(bytes_ref, out_ref, *, bw: int):
    # bytes arrive pre-widened to u32: Mosaic's u8 lane indexing miscompiles
    # on this platform (observed: silent zero lanes at bw=13)
    b = bytes_ref[...]  # (tile, 128) uint32; cols >= bw are 0
    cols = []
    mask = jnp.uint32((1 << bw) - 1)
    for i in range(8):
        lo_bit = i * bw
        b0 = lo_bit // 8
        sh = lo_bit % 8
        nb = (bw + sh + 7) // 8
        acc = jnp.zeros_like(b[:, 0])
        for k in range(nb):
            if b0 + k < bw:
                # multiply-add, not shift-or: Mosaic miscompiles chained
                # u32 shift-or accumulation here (silent dropped byte at
                # e.g. bw=11/13); byte lanes are disjoint so + == |
                acc = acc + b[:, b0 + k] * jnp.uint32(1 << (8 * k))
        cols.append((acc >> jnp.uint32(sh)) & mask)
    out = jnp.stack(cols, axis=1)
    pad = out_ref.shape[1] - out.shape[1]
    out_ref[...] = jnp.pad(out, ((0, 0), (0, pad)))


def _use_real_pallas() -> bool:
    return jax.default_backend() == "tpu"


_LANES = 128


_UNPACK_JITS: dict = {}


def _unpack_call(padded: jax.Array, bw: int, groups: int) -> jax.Array:
    from jax.experimental import pallas as pl

    tiles = (groups + _TILE - 1) // _TILE
    # pow2 tile ladder: each (tiles, bw) pair is one Pallas compilation;
    # unbucketed page sizes would trigger a compile per page (fatal over
    # the axon compile tunnel at ~20s each)
    p2 = 1
    while p2 < tiles:
        p2 <<= 1
    tiles = p2
    pad_groups = tiles * _TILE
    # Mosaic rejects the i64 grid scalars jax_enable_x64 produces; the
    # kernel itself is pure u8/u32, so trace it in an x64-free scope.
    # (jax.experimental.enable_x64 — the top-level jax.enable_x64 alias
    # was removed in jax 0.4.x, which made every device decode fail and
    # fall back to the host path.)
    # Blocks pad the byte dimension to the 128-lane register width —
    # narrower last dims hit Mosaic relayout hazards (observed: silent
    # wrong lanes at bw=13).
    from jax.experimental import enable_x64 as _x64_scope

    with _x64_scope(False):
        mat = jnp.zeros((pad_groups, _LANES), jnp.uint32)
        mat = mat.at[:groups, :bw].set(
            padded.reshape(groups, bw).astype(jnp.uint32))
        # one JITTED program per (tiles, bw) bucket: the bare pallas_call
        # re-traced (and in interpret mode re-interpreted) on EVERY run
        # of every page — a multi-run page paid seconds of pure Python
        # re-tracing per scan (ISSUE 6: the scan path is now hot enough
        # to see it)
        fn = _UNPACK_JITS.get((tiles, bw))
        if fn is None:
            from spark_rapids_tpu.perfcounters import tpu_jit

            fn = _UNPACK_JITS[(tiles, bw)] = tpu_jit(pl.pallas_call(
                partial(_unpack_body, bw=bw),
                out_shape=jax.ShapeDtypeStruct((pad_groups, _LANES),
                                               jnp.uint32),
                grid=(tiles,),
                in_specs=[pl.BlockSpec((_TILE, _LANES), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((_TILE, _LANES), lambda i: (i, 0)),
                interpret=not _use_real_pallas(),
            ))
        return fn(mat)[:, :8]


def unpack_bitpacked(payload: np.ndarray, bw: int,
                     count: int) -> jax.Array:
    """LSB-first parquet bit-packed payload -> (count,) uint32 on device."""
    if bw == 0:
        return jnp.zeros(count, jnp.uint32)
    groups = (count + 7) // 8
    need = groups * bw
    buf = np.zeros(need, np.uint8)
    buf[:min(len(payload), need)] = payload[:need]
    from spark_rapids_tpu.perfcounters import count_h2d

    count_h2d(buf.nbytes)
    out = _unpack_call(jnp.asarray(buf), bw, groups)
    return out.reshape(-1)[:count]


def unpack_bitpacked_dev(payload: jax.Array, bw: int,
                         count: int) -> jax.Array:
    """Device-resident twin of :func:`unpack_bitpacked`: the payload is
    already in HBM (the compressed-transfer path decompressed it there),
    so no bytes cross the link here."""
    if bw == 0:
        return jnp.zeros(count, jnp.uint32)
    groups = (count + 7) // 8
    need = groups * bw
    n = int(payload.shape[0])
    if n < need:
        payload = jnp.concatenate(
            [payload, jnp.zeros(need - n, jnp.uint8)])
    elif n > need:
        payload = payload[:need]
    out = _unpack_call(payload, bw, groups)
    return out.reshape(-1)[:count]


def expand_runs_host(runs, buf: bytes, total: int,
                     bw: int) -> np.ndarray:
    """Host (numpy) run expansion — for the tiny definition-level streams,
    where per-run device dispatch over the tunnel would dominate (values
    still decode on device)."""
    out = np.zeros(total, np.uint32)
    got = 0
    for r in runs:
        take = min(r.count, total - got)
        if take <= 0:
            break
        if r.is_packed:
            payload = np.frombuffer(buf, np.uint8, count=r.nbytes,
                                    offset=r.byte_off)
            if bw == 0:
                # bw=0 (all-dictionary single-entry stream): zero-width
                # packed values are all index 0 — mirror the device
                # path's uint32 zeros instead of dividing by zero below
                vals = np.zeros(take, np.uint32)
            elif bw == 1:
                vals = np.unpackbits(payload, bitorder="little")[:take]
            else:
                bits = np.unpackbits(payload, bitorder="little")
                usable = (len(bits) // bw) * bw
                vals = (bits[:usable].reshape(-1, bw).astype(np.uint32)
                        * (1 << np.arange(bw, dtype=np.uint32))).sum(
                    axis=1)[:take]
            out[got:got + take] = vals
        else:
            out[got:got + take] = r.value
        got += take
    return out


def expand_runs(runs, buf: bytes, total: int, bw: int) -> jax.Array:
    """RLE/bit-packed hybrid runs -> (total,) uint32 (device).

    Run headers were host-parsed (io/parquet_native.split_hybrid_runs);
    payload bytes expand on device.  ``bw`` is the stream's bit width
    (1 for definition levels, index_bit_width for dictionary indices)."""
    parts: List[jax.Array] = []
    got = 0
    for r in runs:
        take = min(r.count, total - got)
        if take <= 0:
            break
        if r.is_packed:
            payload = np.frombuffer(buf, np.uint8, count=r.nbytes,
                                    offset=r.byte_off)
            parts.append(unpack_bitpacked(payload, bw, take))
        else:
            parts.append(jnp.full(take, np.uint32(r.value), jnp.uint32))
        got += take
    if not parts:
        return jnp.zeros(total, jnp.uint32)
    out = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
    if out.shape[0] < total:
        out = jnp.concatenate(
            [out, jnp.zeros(total - out.shape[0], jnp.uint32)])
    return out[:total]


def expand_runs_dev(runs, dev_buf: jax.Array, base_off: int, total: int,
                    bw: int) -> jax.Array:
    """Device-resident twin of :func:`expand_runs`: payload bytes live in
    ``dev_buf`` (a device-decompressed page region) at ``base_off`` plus
    each run's host-parsed ``byte_off`` — no link bytes, the expansion
    consumes HBM-resident slices directly."""
    parts: List[jax.Array] = []
    got = 0
    for r in runs:
        take = min(r.count, total - got)
        if take <= 0:
            break
        if r.is_packed:
            lo = base_off + r.byte_off
            parts.append(unpack_bitpacked_dev(
                dev_buf[lo:lo + r.nbytes], bw, take))
        else:
            parts.append(jnp.full(take, np.uint32(r.value), jnp.uint32))
        got += take
    if not parts:
        return jnp.zeros(total, jnp.uint32)
    out = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
    if out.shape[0] < total:
        out = jnp.concatenate(
            [out, jnp.zeros(total - out.shape[0], jnp.uint32)])
    return out[:total]
