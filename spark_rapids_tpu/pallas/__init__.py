"""Pallas TPU kernels (SURVEY.md §2.10 L0) — device decode et al."""
