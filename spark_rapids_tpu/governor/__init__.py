"""Overload governor (ISSUE 13): graceful degradation under sustained
memory and queue pressure — the robustness prerequisite of the
always-on serving tier (ROADMAP north star).

Before this package, a saturated device pool plus a deep admission
queue produced hard ``deviceOom`` retry storms, deadline cascades, and
blunt queue-full ``QueryRejected``s.  The governor fuses the signals
the repo already produces — HBM-pool occupancy (memory/spill.py),
admission queue depth (lifecycle/admission.py), the watchdog
active-query table, the telemetry rolling p95, and PR 8 cost-model
predicted walls — into an EWMA-smoothed GREEN/YELLOW/RED state machine
with separate up/down hysteresis thresholds, and each state drives
concrete degradation:

* YELLOW — shrink batch-size goals (coalesce targets, exchange drain
  chunks) and exchange partition budgets to ``degradeBatchFraction``,
  stop scan-prefetch run-ahead, defer background AOT compiles.
* RED — additionally: deadline-aware load shedding at admission (a
  structured ``QueryRejected`` carrying ``queue_depth`` /
  ``retry_after_ms`` / ``pressure_state``), LRU eviction of the
  hot-table cache, and cooperative pause-and-spill preemption of the
  newest-admitted running query at its next batch-pull boundary — the
  pool drains without cancelling anyone.

  context.py — the ambient slot (ONE attribute read on hot paths)
  core.py    — OverloadGovernor: signal fusion, hysteresis, actions

Observability: ``governor_transitions`` / ``queries_shed`` /
``preempt_pauses`` / ``degraded_batches`` counters, ``governor_state``
/ ``governor_pressure`` sampler gauges, the ``governor`` diagnostics
event, flight-ring ``governor`` events, and a post-mortem bundle on
every entry into RED.  Chaos/stress drivers: ``tools/run_chaos.py
--pressure`` and ``tools/run_stress.py --overload``
(docs/overload.md).
"""
from __future__ import annotations

import threading
from typing import Optional

from spark_rapids_tpu.governor import context as CTX
from spark_rapids_tpu.governor.core import (
    GREEN,
    RED,
    YELLOW,
    OverloadGovernor,
)

_LOCK = threading.Lock()


def ensure_governor(conf) -> Optional["OverloadGovernor"]:
    """Idempotent process-global start (called by TpuSession.__init__):
    the FIRST enabling conf builds the governor; later sessions reuse
    it.  Returns None when the conf leaves the governor disabled (the
    default) — the ambient slot stays None and every instrumented site
    skips on one attribute read."""
    from spark_rapids_tpu.config import GOVERNOR_ENABLED

    if not conf.get(GOVERNOR_ENABLED):
        return None
    with _LOCK:
        if CTX.GOVERNOR is None:
            CTX.GOVERNOR = OverloadGovernor(conf)
        return CTX.GOVERNOR


def get_governor() -> Optional["OverloadGovernor"]:
    return CTX.GOVERNOR


def shutdown_governor() -> None:
    """Clear the ambient slot (tests / process teardown); the next
    enabling TpuSession rebuilds."""
    with _LOCK:
        CTX.GOVERNOR = None


__all__ = [
    "GREEN", "YELLOW", "RED", "OverloadGovernor",
    "ensure_governor", "get_governor", "shutdown_governor",
]
