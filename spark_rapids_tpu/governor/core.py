"""OverloadGovernor — the EWMA-smoothed pressure state machine and the
degradation actions each state drives.

Reference analog: the resource-governance layer Theseus
(arXiv:2508.05029) argues accelerated SQL platforms win or lose on, and
the load-shedding discipline a serving-shaped deployment needs
("Accelerating Presto with GPUs", arXiv:2606.24647): a saturated device
pool plus a deep admission queue must produce *controlled degradation*
— smaller working sets, paused speculation, deadline-aware shedding,
cooperative preemption — never hard-OOM retry storms or deadline
cascades.

Signals (all peek-only — a governor consult can never CREATE a spill
framework, admission controller, or telemetry hub):

* HBM-pool occupancy: ``SpillFramework.device_used`` / ``pool_bytes``.
* Admission queue depth: ``peek_admission()`` queued / maxQueueDepth.
* Rolling p95 vs the armed SLO target (telemetry hub, when present).
* Cost-model backlog: summed PR 8 predicted walls of admitted queries
  vs ``governor.backlogTargetMs`` (0 disables the component).
* The watchdog active-query table feeds preemption targeting (newest
  admitted = least sunk cost) and the transition detail.
* Fleet tail latency (ISSUE 20): ``Coordinator.fleet_pressure()`` —
  the DEGRADED fraction of the worker fleet, or how far the worst
  per-worker latency EWMA sits past ``slowFactor`` x the median; a
  gray worker stretches every exchange drain, so admission feels it.

The fused raw pressure is the MAX of the components (overload is a
max-bottleneck phenomenon: a full queue with an empty pool is still
overload), EWMA-smoothed under ``governor.ewmaAlpha``.  The state
machine uses separate up/down thresholds (yellowUp > yellowDown,
redUp > redDown) so an oscillating signal inside the hysteresis band
produces no transitions — pinned by tests/test_governor.py.

Locking discipline: all mutable state is guarded by ``self._lock``;
raw-signal reads and every outward call (spill, evict, post-mortem,
flight events) happen OUTSIDE the lock, so the only inter-lock edge is
<caller's lock> -> governor lock and the lock-order detector sees no
cycle.
"""
from __future__ import annotations

import sys
import threading
import time
from typing import Dict, Optional, Tuple

GREEN = "GREEN"
YELLOW = "YELLOW"
RED = "RED"

_STATE_LEVEL = {GREEN: 0, YELLOW: 1, RED: 2}


class OverloadGovernor:
    """Process-global pressure state machine + degradation ladder."""

    def __init__(self, conf):
        from spark_rapids_tpu.config import (
            GOVERNOR_BACKLOG_TARGET_MS,
            GOVERNOR_DEGRADE_FRACTION,
            GOVERNOR_EWMA_ALPHA,
            GOVERNOR_HOT_CACHE_EVICT_FRACTION,
            GOVERNOR_MAX_PAUSE_MS,
            GOVERNOR_RED_DOWN,
            GOVERNOR_RED_UP,
            GOVERNOR_SHED_MIN_RETRY_MS,
            GOVERNOR_UPDATE_PERIOD_MS,
            GOVERNOR_YELLOW_DOWN,
            GOVERNOR_YELLOW_UP,
            TELEMETRY_SLO_TARGET_P95_MS,
        )

        self._lock = threading.Lock()
        self._period_ns = int(
            max(float(conf.get(GOVERNOR_UPDATE_PERIOD_MS)), 1.0) * 1e6)
        self._alpha = min(max(float(conf.get(GOVERNOR_EWMA_ALPHA)), 0.01),
                          1.0)
        self._yellow_up = float(conf.get(GOVERNOR_YELLOW_UP))
        self._yellow_down = float(conf.get(GOVERNOR_YELLOW_DOWN))
        self._red_up = float(conf.get(GOVERNOR_RED_UP))
        self._red_down = float(conf.get(GOVERNOR_RED_DOWN))
        self._degrade_fraction = min(max(
            float(conf.get(GOVERNOR_DEGRADE_FRACTION)), 0.05), 1.0)
        self._max_pause_ms = int(conf.get(GOVERNOR_MAX_PAUSE_MS))
        self._shed_min_retry_ms = int(conf.get(GOVERNOR_SHED_MIN_RETRY_MS))
        self._evict_fraction = min(max(
            float(conf.get(GOVERNOR_HOT_CACHE_EVICT_FRACTION)), 0.0), 1.0)
        self._backlog_target_ms = int(conf.get(GOVERNOR_BACKLOG_TARGET_MS))
        self._slo_target_ms = float(conf.get(TELEMETRY_SLO_TARGET_P95_MS))
        # mutable state (all under self._lock)
        self._state = GREEN
        self._ewma = 0.0
        self._raw = 0.0
        self._next_update_ns = 0
        self._transitions = 0
        self._preempt_qid: Optional[str] = None
        self._pausing_qid: Optional[str] = None
        self._predicted_ns: Dict[str, int] = {}
        self._wall_ewma_ms = 0.0
        # test hook: a callable returning the raw pressure, bypassing
        # the live signal peeks (unit tests drive the state machine
        # with synthetic oscillations)
        self._signal_override = None

    # -- introspection ---------------------------------------------------
    @property
    def state(self) -> str:
        return self._state

    @property
    def pressure(self) -> float:
        return self._ewma

    @property
    def transitions(self) -> int:
        return self._transitions

    def gauges(self) -> Dict[str, float]:
        """Telemetry-sampler gauges (one update first, so the sampled
        state is at most updatePeriodMs stale)."""
        self.maybe_update()
        with self._lock:
            return {"governor_state": float(_STATE_LEVEL[self._state]),
                    "governor_pressure": round(self._ewma, 4)}

    # -- test hook -------------------------------------------------------
    def set_signal_override(self, fn) -> None:
        """Replace the live signal peeks with ``fn() -> float`` (None
        restores); also resets the update throttle so a test can step
        the machine deterministically."""
        with self._lock:
            self._signal_override = fn
            self._next_update_ns = 0

    # -- signal fusion ---------------------------------------------------
    def _raw_pressure(self) -> Tuple[float, Dict[str, float]]:
        """The fused raw pressure and its components.  Peek-only and
        LOCK-FREE: called before taking self._lock (the component reads
        take other modules' locks)."""
        override = self._signal_override
        if override is not None:
            v = float(override())
            return v, {"override": v}
        comp: Dict[str, float] = {}
        from spark_rapids_tpu.memory.spill import peek_spill_framework

        fw = peek_spill_framework()
        if fw is not None and fw.pool_bytes:
            comp["memory"] = fw.device_used / float(fw.pool_bytes)
        from spark_rapids_tpu.lifecycle.admission import peek_admission

        limit = 1
        ctl = peek_admission()
        if ctl is not None:
            st = ctl.stats()
            limit = max(int(st["limit"]), 1)
            comp["queue"] = st["queued"] / float(max(st["max_queue"], 1))
        from spark_rapids_tpu.telemetry import context as TEL

        hub = TEL.HUB
        if hub is not None and self._slo_target_ms > 0:
            comp["latency"] = hub.slo.p95_ms() / self._slo_target_ms
        if self._backlog_target_ms > 0:
            with self._lock:
                pred_ns = sum(self._predicted_ns.values())
            comp["backlog"] = (pred_ns / 1e6) / (
                self._backlog_target_ms * float(limit))
        from spark_rapids_tpu import distributed as _D

        coord = _D.peek_coordinator()
        if coord is not None:
            # fleet tail latency (ISSUE 20): degraded workers and a
            # worst-vs-median latency EWMA outlier are overload the
            # driver-side signals cannot see — a gray worker stretches
            # every exchange drain, so admission should feel it
            fleet = coord.fleet_pressure()
            if fleet > 0.0:
                comp["fleet"] = fleet
        return (max(comp.values()) if comp else 0.0), comp

    # -- the update step -------------------------------------------------
    def maybe_update(self, now_ns: Optional[int] = None) -> str:
        """Recompute pressure and step the state machine, at most once
        per updatePeriodMs; returns the (possibly unchanged) state.
        Safe from any thread and from inside other modules' locks."""
        now = now_ns if now_ns is not None else time.monotonic_ns()
        if now < self._next_update_ns:          # cheap unlocked fast path
            return self._state
        raw, comp = self._raw_pressure()
        prev = new = None
        with self._lock:
            if now < self._next_update_ns:      # another thread updated
                return self._state
            self._next_update_ns = now + self._period_ns
            self._raw = raw
            self._ewma = (self._alpha * raw
                          + (1.0 - self._alpha) * self._ewma)
            prev = self._state
            new = self._next_state_locked(self._ewma)
            if new != prev:
                self._state = new
                self._transitions += 1
                if _STATE_LEVEL[new] < _STATE_LEVEL[RED]:
                    # leaving RED lifts any still-armed preemption
                    self._preempt_qid = None
            ewma = self._ewma
        if new != prev:
            self._on_transition(prev, new, ewma, comp)
        return new

    def _next_state_locked(self, ewma: float) -> str:
        s = self._state
        if s == GREEN:
            if ewma >= self._red_up:
                return RED
            if ewma >= self._yellow_up:
                return YELLOW
        elif s == YELLOW:
            if ewma >= self._red_up:
                return RED
            if ewma <= self._yellow_down:
                return GREEN
        else:  # RED
            if ewma <= self._red_down:
                return GREEN if ewma <= self._yellow_down else YELLOW
        return s

    def _on_transition(self, prev: str, new: str, ewma: float,
                       comp: Dict[str, float]) -> None:
        """Everything a state change drives — runs OUTSIDE the governor
        lock (post-mortems, eviction, and events call other modules)."""
        from spark_rapids_tpu import perfcounters as PC

        PC.bump("governor_transitions")
        detail = ", ".join(f"{k}={v:.2f}" for k, v in sorted(comp.items()))
        from spark_rapids_tpu.diagnostics import context as DIAG

        rec = DIAG.RECORDER
        if rec is not None:
            rec.governor("transition", new, prev=prev,
                         pressure=round(ewma, 4), detail=detail)
        from spark_rapids_tpu.telemetry import context as TEL

        hub = TEL.HUB
        if hub is not None:
            hub.record_event("governor", state=new, prev=prev,
                             pressure=round(ewma, 4), detail=detail)
        if new == RED:
            self._enter_red(ewma, detail, hub)

    def _enter_red(self, ewma: float, detail: str, hub) -> None:
        """RED-entry actions: flight-recorder post-mortem, hot-table
        -cache eviction, and arming pause-and-spill preemption."""
        if hub is not None:
            try:
                hub.postmortem(
                    "governor_red",
                    detail=f"pressure {ewma:.3f} ({detail})")
            # tpulint: disable=cancel-swallow (telemetry isolation: a
            # post-mortem failure must not break the pressure update)
            except Exception:
                pass
        from spark_rapids_tpu.io.hot_cache import peek_hot_cache

        hc = peek_hot_cache()
        if hc is not None and self._evict_fraction > 0:
            try:
                keep = int(hc.stats()["bytes"]
                           * (1.0 - self._evict_fraction))
                hc.evict_to_bytes(keep)
            # tpulint: disable=cancel-swallow (best-effort ballast drop;
            # eviction failure must not break the pressure update)
            except Exception:
                pass
        # serving result-fragment cache (ISSUE 19): same RED ladder,
        # same fraction.  sys.modules peek, not an import — a process
        # that never enabled serving must make zero serving-module calls
        srv = sys.modules.get("spark_rapids_tpu.serving.context")
        rc = getattr(srv, "RESULT_CACHE", None) if srv is not None else None
        if rc is not None and self._evict_fraction > 0:
            try:
                keep = int(rc.stats()["bytes"]
                           * (1.0 - self._evict_fraction))
                rc.evict_to_bytes(keep)
            # tpulint: disable=cancel-swallow (best-effort ballast drop;
            # eviction failure must not break the pressure update)
            except Exception:
                pass
        self.request_preempt()

    # -- degradation: batch goals / budgets (YELLOW and up) --------------
    def degraded_goal(self, goal_bytes: int) -> int:
        """The (possibly shrunk) batch-size goal for the current
        pressure state; counts one ``degraded_batches`` per shrink.
        The 64KiB floor never RAISES a goal already configured below
        it — degradation shrinks or leaves alone, only."""
        if self.maybe_update() == GREEN:
            return goal_bytes
        from spark_rapids_tpu import perfcounters as PC

        PC.bump("degraded_batches")
        return min(goal_bytes,
                   max(int(goal_bytes * self._degrade_fraction), 1 << 16))

    def degraded_partition_target(self, target_bytes: int) -> int:
        """The (possibly shrunk) exchange partition budget — plan-time
        twin of :meth:`degraded_goal` (no per-batch counter)."""
        if self.maybe_update() == GREEN:
            return target_bytes
        return min(target_bytes,
                   max(int(target_bytes * self._degrade_fraction), 1 << 16))

    def pause_background(self) -> bool:
        """True when speculative background work (scan prefetch
        run-ahead, AOT compile submission) should pause: any non-GREEN
        state — speculation spends exactly the memory and device time
        pressure needs back."""
        return self.maybe_update() != GREEN

    # -- RED: deadline-aware admission shedding --------------------------
    def shed_admission(self, ctx, running: int, limit: int,
                       queued: int,
                       running_by: Optional[dict] = None) -> Optional[int]:
        """Consulted by the admission gate for a query about to queue:
        returns the ``retry_after_ms`` hint when the query should be
        shed (RED, carries a deadline, and predicted wall + predicted
        queue wait cannot meet it), else None (queue normally).  Never
        sheds deadline-less queries — they can afford to wait.

        ISSUE 19: with the serving tier's fair-share scheduler
        installed the decision is tenant-aware FIRST — the most-starved
        tenant's queries are never shed (not even by the deadline
        predictor), and a tenant at/over its running quota sheds
        immediately, deadline or not (``running_by`` is the admission
        gate's per-tenant running snapshot)."""
        if self.maybe_update() != RED:
            return None
        from spark_rapids_tpu.lifecycle import admission as _adm

        sched = _adm.SCHEDULER
        tenant = getattr(ctx, "tenant", "") or ""
        if sched is not None and tenant:
            by = running_by or {}
            decision = sched.shed_decision(tenant, by, by.keys())
            if decision == "never":
                return None
            if decision == "shed":
                from spark_rapids_tpu import perfcounters as PC

                PC.bump("tenant_sheds")
                return self.retry_after_ms(queued, limit)
        if ctx.deadline_ns is None:
            return None
        remaining_ms = (ctx.deadline_ns - time.monotonic_ns()) / 1e6
        wall_ms, wait_ms = self._predict_ms(queued, limit)
        if wall_ms <= 0.0:
            # no latency history yet: shed only the already-hopeless
            wall_ms = 0.0
        if wait_ms + wall_ms <= remaining_ms:
            return None
        return self.retry_after_ms(queued, limit)

    def retry_after_ms(self, queued: int, limit: int) -> int:
        """The client-backoff hint: the predicted time for the current
        queue to drain one slot, floored at shedMinRetryMs."""
        _wall, wait_ms = self._predict_ms(queued, limit)
        return int(max(wait_ms, float(self._shed_min_retry_ms)))

    def _predict_ms(self, queued: int, limit: int) -> Tuple[float, float]:
        """(predicted wall of one query, predicted queue wait) in ms:
        the rolling p95 when the telemetry hub has one, else the
        governor's own wall EWMA."""
        wall_ms = 0.0
        from spark_rapids_tpu.telemetry import context as TEL

        hub = TEL.HUB
        if hub is not None:
            wall_ms = hub.slo.p95_ms()
        if wall_ms <= 0.0:
            wall_ms = self._wall_ewma_ms
        wait_ms = queued * wall_ms / float(max(limit, 1))
        return wall_ms, wait_ms

    # -- lifecycle feed --------------------------------------------------
    def note_query_end(self, query_id: str, wall_ns: int) -> None:
        """query_lifecycle exit hook: feeds the wall EWMA the shed
        predictor falls back on, and clears the query's predicted-wall
        backlog entry.  An armed preemption TARGET that finished on its
        own re-arms the slot against the next-newest query — a stale
        dead-query id must not disable pause-and-spill for the rest of
        a RED episode."""
        ms = wall_ns / 1e6
        rearm = False
        with self._lock:
            self._predicted_ns.pop(query_id, None)
            self._wall_ewma_ms = (0.3 * ms + 0.7 * self._wall_ewma_ms
                                  if self._wall_ewma_ms else ms)
            if self._preempt_qid == query_id:
                self._preempt_qid = None
                rearm = self._state == RED
        if rearm:
            # the finished query already left the watchdog registry, so
            # this targets the next-newest running query (if any)
            self.request_preempt()

    def note_predicted_wall(self, query_id: str, wall_ns: int) -> None:
        """Cost-model hook (ISSUE 8 join): an admitted query's predicted
        wall joins the backlog signal until its query_lifecycle exits."""
        with self._lock:
            self._predicted_ns[query_id] = int(wall_ns)

    # -- RED: cooperative pause-and-spill preemption ---------------------
    def request_preempt(self, exclude_qid: Optional[str] = None) -> bool:
        """Arm a pause-and-spill of the newest-admitted running query
        (largest admission_seq = least sunk cost), excluding
        ``exclude_qid`` (an OOM-retrying query must not preempt
        itself).  The target pauses at its next batch-pull boundary —
        it is never cancelled.  False when no eligible target exists."""
        from spark_rapids_tpu.lifecycle import watchdog as _wd

        cands = [c for c in _wd.active_queries()
                 if c.query_id != exclude_qid and not c.token.cancelled]
        if not cands:
            return False
        from spark_rapids_tpu.lifecycle import admission as _adm

        sched = _adm.SCHEDULER
        if sched is not None:
            # tenant-aware (ISSUE 19): pause the MOST OVER-SHARE
            # tenant's query (highest normalized usage; admission order
            # breaks ties toward the newest) — the fair-share twin of
            # "shed the over-quota tenant first"
            target = max(cands, key=lambda c: (
                sched.normalized_usage(getattr(c, "tenant", "") or ""),
                c.admission_seq))
        else:
            target = max(cands, key=lambda c: c.admission_seq)
        with self._lock:
            if self._pausing_qid == target.query_id:
                return True          # already pausing
            self._preempt_qid = target.query_id
        if sched is not None:
            from spark_rapids_tpu import perfcounters as PC

            PC.bump("tenant_preempts")
        return True

    def preempt_for_oom(self, exclude_qid: Optional[str] = None) -> bool:
        """memory/retry.py's RED path: arm a preemption pass (and spill
        whatever is already unpinned) INSTEAD of immediately halving
        the batch — the pool drains from someone else's working set
        before this query shrinks its own."""
        armed = self.request_preempt(exclude_qid=exclude_qid)
        from spark_rapids_tpu.memory.spill import peek_spill_framework

        fw = peek_spill_framework()
        if fw is not None:
            fw.spill_device_pressure()
        return armed

    def batch_pull_checkpoint(self) -> None:
        """The exec/base per-pull hook: one rate-limited pressure
        update, plus — when THIS query is the armed preemption target —
        the cooperative pause-and-spill."""
        now = time.monotonic_ns()
        if now >= self._next_update_ns:
            self.maybe_update(now)
        if self._preempt_qid is None:           # one unlocked read
            return
        from spark_rapids_tpu.lifecycle.context import current

        ctx = current()
        if ctx is None or ctx.query_id != self._preempt_qid:
            return
        self._pause_and_spill(ctx)

    def _pause_and_spill(self, ctx) -> None:
        """The pause itself: claim the armed target (compare-and-clear
        under the lock so concurrent pulls of the same query pause
        once), spill the pool, then wait — cancellably — until pressure
        leaves RED or maxPauseMs passes, and resume."""
        with self._lock:
            if self._preempt_qid != ctx.query_id:
                return                            # lost the claim
            self._preempt_qid = None
            self._pausing_qid = ctx.query_id
        try:
            from spark_rapids_tpu import perfcounters as PC

            PC.bump("preempt_pauses")
            from spark_rapids_tpu.memory.spill import peek_spill_framework

            fw = peek_spill_framework()
            spilled = fw.spill_device_pressure() if fw is not None else 0
            from spark_rapids_tpu.diagnostics import context as DIAG

            rec = DIAG.RECORDER
            if rec is not None:
                rec.governor(
                    "preempt_pause", self._state,
                    pressure=round(self._ewma, 4),
                    detail=f"{ctx.query_id} paused, {spilled}B spilled")
            deadline = time.monotonic() + self._max_pause_ms / 1000.0
            while time.monotonic() < deadline:
                # a tripped CancelToken raises from here — the pause is
                # a blocking site like any other (PROPAGATE class)
                ctx.token.sleep_or_raise(0.02)
                if self.maybe_update() != RED:
                    break
        finally:
            with self._lock:
                self._pausing_qid = None
