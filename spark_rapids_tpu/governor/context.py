"""Governor context — the ONLY governor module instrumented sites read.

``GOVERNOR`` is the process-wide active :class:`~spark_rapids_tpu.
governor.core.OverloadGovernor` (or None).  Like ``telemetry.context.
HUB`` and ``diagnostics.context.RECORDER`` it is a plain module
attribute, not a contextvar: overload is a property of the *process*
(one HBM pool, one admission queue), and degradation decisions must be
visible from engine-owned helper threads (the telemetry sampler, the
scan prefetch ring, the AOT pool) that a contextvar would silently
drop.

Disabled-path contract (mirrors the diagnostics/telemetry/progress
contracts, pinned by tests/test_governor.py): every instrumented site
performs exactly ONE ambient check — ``if CTX.GOVERNOR is None: skip``
— before doing any other governor work, so the
``spark.rapids.tpu.governor.enabled=false`` path costs an attribute
read and ZERO calls into governor modules (cProfile-pinned).
"""
from __future__ import annotations

# the active OverloadGovernor; None = governor off (the default).  Read
# lock-free from instrumented sites; written only by
# governor.ensure_governor / governor.shutdown_governor under the
# module lock in governor/__init__.py.
GOVERNOR = None


def active():
    """The active governor or None (one ambient check)."""
    return GOVERNOR
