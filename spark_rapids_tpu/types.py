"""Spark SQL type system for the TPU accelerator.

Mirrors the set of types the reference plugin supports
(reference: com/nvidia/spark/rapids/TypeSig.scala — the type-signature
checking machinery; and org.apache.spark.sql.types).  Each TPU-side column
maps a Spark SQL type onto a device storage dtype:

  BooleanType    -> bool_
  ByteType       -> int8       ShortType -> int16
  IntegerType    -> int32      LongType  -> int64
  FloatType      -> float32    DoubleType-> float64 (x64 enabled on TPU host)
  DateType       -> int32 (days since epoch, Spark-compatible)
  TimestampType  -> int64 (microseconds since epoch, UTC)
  StringType     -> uint8 padded char matrix + int32 lengths (see columnar/)
  DecimalType    -> int32/int64 unscaled value for precision<=18;
                    precision>18 (decimal128) stored as two int64 limbs.
  NullType       -> all-null marker column

TypeSig — the per-rule declaration of which types an expression/exec supports
— is reproduced here because it is the backbone of the reference's tagging
layer: every TpuOverrides rule declares its TypeSig and the meta layer
tags nodes with willNotWorkOnTpu when actual types fall outside it.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


class DataType:
    """Base of the Spark-style SQL type lattice."""

    #: class-level simple name, e.g. "int"
    simpleString: str = "?"

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))

    def __repr__(self):
        return self.simpleString

    @property
    def is_numeric(self) -> bool:
        return isinstance(self, NumericType)

    @property
    def is_integral(self) -> bool:
        return isinstance(self, IntegralType)

    @property
    def is_floating(self) -> bool:
        return isinstance(self, FractionalType) and not isinstance(self, DecimalType)

    def default_size(self) -> int:
        return 8


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


class BooleanType(DataType):
    simpleString = "boolean"

    def default_size(self):
        return 1


class ByteType(IntegralType):
    simpleString = "tinyint"

    def default_size(self):
        return 1


class ShortType(IntegralType):
    simpleString = "smallint"

    def default_size(self):
        return 2


class IntegerType(IntegralType):
    simpleString = "int"

    def default_size(self):
        return 4


class LongType(IntegralType):
    simpleString = "bigint"

    def default_size(self):
        return 8


class FloatType(FractionalType):
    simpleString = "float"

    def default_size(self):
        return 4


class DoubleType(FractionalType):
    simpleString = "double"

    def default_size(self):
        return 8


class StringType(DataType):
    simpleString = "string"

    def default_size(self):
        return 20


class BinaryType(DataType):
    simpleString = "binary"

    def default_size(self):
        return 20


class DateType(DataType):
    simpleString = "date"

    def default_size(self):
        return 4


class TimestampType(DataType):
    simpleString = "timestamp"

    def default_size(self):
        return 8


class NullType(DataType):
    simpleString = "void"

    def default_size(self):
        return 1


class DecimalType(FractionalType):
    """Spark decimal(precision, scale); stored as unscaled integer.

    Reference analog: GpuDecimalMultiply / decimal_utils.cu operate on
    32/64/128-bit unscaled representations chosen by precision; we do the
    same (SURVEY.md §2.5 Arithmetic/decimal row).
    """

    MAX_INT_DIGITS = 9          # fits int32
    MAX_LONG_DIGITS = 18        # fits int64
    MAX_PRECISION = 38

    def __init__(self, precision: int = 10, scale: int = 0):
        if not (0 < precision <= self.MAX_PRECISION):
            raise ValueError(f"precision {precision} out of range")
        if not (0 <= scale <= precision):
            raise ValueError(f"scale {scale} out of range for precision {precision}")
        self.precision = precision
        self.scale = scale

    @property
    def simpleString(self):  # type: ignore[override]
        return f"decimal({self.precision},{self.scale})"

    def __eq__(self, other):
        return (
            isinstance(other, DecimalType)
            and other.precision == self.precision
            and other.scale == self.scale
        )

    def __hash__(self):
        return hash((DecimalType, self.precision, self.scale))

    def default_size(self):
        return 8 if self.precision <= self.MAX_LONG_DIGITS else 16

    @property
    def is_128(self) -> bool:
        return self.precision > self.MAX_LONG_DIGITS


@dataclasses.dataclass(frozen=True)
class StructField:
    name: str
    dataType: DataType
    nullable: bool = True


class StructType(DataType):
    def __init__(self, fields):
        self.fields = list(fields)

    @property
    def simpleString(self):  # type: ignore[override]
        inner = ",".join(f"{f.name}:{f.dataType.simpleString}" for f in self.fields)
        return f"struct<{inner}>"

    def __eq__(self, other):
        return isinstance(other, StructType) and other.fields == self.fields

    def __hash__(self):
        return hash((StructType, tuple(self.fields)))

    def field_names(self):
        return [f.name for f in self.fields]

    def __iter__(self):
        return iter(self.fields)

    def __len__(self):
        return len(self.fields)


class ArrayType(DataType):
    def __init__(self, elementType: DataType, containsNull: bool = True):
        self.elementType = elementType
        self.containsNull = containsNull

    @property
    def simpleString(self):  # type: ignore[override]
        return f"array<{self.elementType.simpleString}>"

    def __eq__(self, other):
        return (
            isinstance(other, ArrayType)
            and other.elementType == self.elementType
            and other.containsNull == self.containsNull
        )

    def __hash__(self):
        return hash((ArrayType, self.elementType, self.containsNull))


class MapType(DataType):
    def __init__(self, keyType: DataType, valueType: DataType,
                 valueContainsNull: bool = True):
        self.keyType = keyType
        self.valueType = valueType
        self.valueContainsNull = valueContainsNull

    @property
    def simpleString(self):  # type: ignore[override]
        return f"map<{self.keyType.simpleString},{self.valueType.simpleString}>"

    def __eq__(self, other):
        return (
            isinstance(other, MapType)
            and other.keyType == self.keyType
            and other.valueType == self.valueType
        )

    def __hash__(self):
        return hash((MapType, self.keyType, self.valueType))


# Singletons, Spark-style.
BOOLEAN = BooleanType()
BYTE = ByteType()
SHORT = ShortType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
STRING = StringType()
BINARY = BinaryType()
DATE = DateType()
TIMESTAMP = TimestampType()
NULL = NullType()

_NUMPY_STORAGE = {
    BooleanType: np.bool_,
    ByteType: np.int8,
    ShortType: np.int16,
    IntegerType: np.int32,
    LongType: np.int64,
    FloatType: np.float32,
    DoubleType: np.float64,
    DateType: np.int32,
    TimestampType: np.int64,
}


def storage_dtype(dt: DataType) -> np.dtype:
    """numpy/jnp storage dtype for a (non-string) SQL type."""
    if isinstance(dt, DecimalType):
        return np.dtype(np.int64)  # <=18 digits; 128-bit handled as limb pairs
    t = _NUMPY_STORAGE.get(type(dt))
    if t is None:
        raise TypeError(f"no flat storage dtype for {dt}")
    return np.dtype(t)


_PROMOTE_ORDER = [ByteType, ShortType, IntegerType, LongType, FloatType, DoubleType]


def numeric_promote(a: DataType, b: DataType) -> DataType:
    """Spark's findTightestCommonType for flat numeric types."""
    if a == b:
        return a
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        raise TypeError("decimal promotion handled by DecimalPrecision rules")
    ia = _PROMOTE_ORDER.index(type(a))
    ib = _PROMOTE_ORDER.index(type(b))
    return _PROMOTE_ORDER[max(ia, ib)]()


# ---------------------------------------------------------------------------
# TypeSig — which SQL types a rule supports (reference: TypeSig.scala).
# ---------------------------------------------------------------------------

class TypeSig:
    """A set of supported type *kinds*, with optional notes.

    The reference encodes this as a bitmask + per-type notes and uses it both
    for plan tagging and for the generated supported_ops.md docs; we keep the
    same shape so the docs generator (docs/gen_supported_ops.py) can walk it.
    """

    def __init__(self, kinds: frozenset, max_decimal_precision: int = DecimalType.MAX_PRECISION,
                 notes: Optional[dict] = None):
        self.kinds = frozenset(kinds)
        self.max_decimal_precision = max_decimal_precision
        self.notes = dict(notes or {})

    @staticmethod
    def none() -> "TypeSig":
        return TypeSig(frozenset())

    def __add__(self, other: "TypeSig") -> "TypeSig":
        notes = dict(self.notes)
        notes.update(other.notes)
        return TypeSig(self.kinds | other.kinds,
                       max(self.max_decimal_precision, other.max_decimal_precision),
                       notes)

    def with_max_decimal(self, p: int) -> "TypeSig":
        return TypeSig(self.kinds, p, self.notes)

    def with_note(self, kind: type, note: str) -> "TypeSig":
        notes = dict(self.notes)
        notes[kind] = note
        return TypeSig(self.kinds, self.max_decimal_precision, notes)

    def supports(self, dt: DataType) -> bool:
        if isinstance(dt, DecimalType):
            return DecimalType in self.kinds and dt.precision <= self.max_decimal_precision
        if isinstance(dt, StructType):
            return StructType in self.kinds and all(self.supports(f.dataType) for f in dt.fields)
        if isinstance(dt, ArrayType):
            return ArrayType in self.kinds and self.supports(dt.elementType)
        if isinstance(dt, MapType):
            return (MapType in self.kinds and self.supports(dt.keyType)
                    and self.supports(dt.valueType))
        return type(dt) in self.kinds

    def reason_not_supported(self, dt: DataType) -> str:
        note = self.notes.get(type(dt))
        base = f"{dt.simpleString} is not supported"
        return f"{base} ({note})" if note else base


def _sig(*kinds) -> TypeSig:
    return TypeSig(frozenset(kinds))


BOOLEAN_SIG = _sig(BooleanType)
INTEGRAL_SIG = _sig(ByteType, ShortType, IntegerType, LongType)
FP_SIG = _sig(FloatType, DoubleType)
DECIMAL_64_SIG = TypeSig(frozenset({DecimalType}), DecimalType.MAX_LONG_DIGITS)
DECIMAL_128_SIG = TypeSig(frozenset({DecimalType}), DecimalType.MAX_PRECISION)
STRING_SIG = _sig(StringType)
BINARY_SIG = _sig(BinaryType)
DATETIME_SIG = _sig(DateType, TimestampType)
NULL_SIG = _sig(NullType)

numeric = INTEGRAL_SIG + FP_SIG + DECIMAL_64_SIG
integral = INTEGRAL_SIG
gpu_numeric = numeric  # alias kept for parity grep-ability with the reference
commonTypes = BOOLEAN_SIG + numeric + STRING_SIG + DATETIME_SIG
all_basic = commonTypes + NULL_SIG + BINARY_SIG + DECIMAL_128_SIG
nested = _sig(StructType, ArrayType, MapType)
everything = all_basic + nested
