"""DeltaTable command surface: read, write, DELETE, UPDATE, MERGE,
OPTIMIZE [ZORDER BY].

Reference analog: delta-lake/delta-2xx GpuDeltaLog consumers —
GpuDeleteCommand, GpuUpdateCommand, GpuMergeIntoCommand (low-shuffle
merge), GpuOptimizeExecutor with Z-ORDER (SURVEY.md §2.8).

TPU designs:
  * DELETE/UPDATE rewrite only files that CONTAIN matches (a per-file
    filter probe — the reference's file-pruning pass), committing
    add+remove pairs in one optimistic transaction.
  * MERGE runs as engine joins: matched updates/deletes resolve per target
    file; unmatched inserts append — all columnar on device.
  * OPTIMIZE ZORDER sorts on interleaved bit planes (ops/zorder.py, the
    zorder.cu analog) and rewrites files.
"""
from __future__ import annotations

import os
import uuid
from typing import Dict, List, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.delta.log import DeltaLog, Snapshot
from spark_rapids_tpu.expr.base import Expression

_CHUNK_ROWS = 1 << 20


def _write_parquet_file(table_path: str, arrow_tbl) -> Dict:
    import pyarrow.parquet as pq

    name = f"part-{uuid.uuid4().hex}.snappy.parquet"
    full = os.path.join(table_path, name)
    pq.write_table(arrow_tbl, full, compression="snappy")
    return {"path": name, "size": os.path.getsize(full)}


def _df_to_arrow(df):
    """Collect a DataFrame (through the TPU plan) into one arrow table."""
    import pyarrow as pa

    from spark_rapids_tpu.exec.transitions import TpuColumnarToRowExec

    root, _ = df._planned()
    from spark_rapids_tpu.exec.base import TpuExec

    if isinstance(root, TpuExec):
        host = TpuColumnarToRowExec(root).collect_host()
    else:
        from spark_rapids_tpu.cpu.oracle import execute_cpu_plan

        cols, n = execute_cpu_plan(root, ansi=False)
        host = [c.to_host() for c in cols]
    names = df.schema.field_names()
    return pa.table({n: h.to_arrow() for n, h in zip(names, host)})


class DeltaTable:
    """deltaTable = DeltaTable.for_path(session, path)."""

    def __init__(self, session, path: str):
        self.session = session
        self.path = path
        self.log = DeltaLog(path)

    @staticmethod
    def for_path(session, path: str) -> "DeltaTable":
        DeltaLog(path).snapshot()  # validate
        return DeltaTable(session, path)

    @staticmethod
    def create(session, path: str, df, mode: str = "error",
               partition_by: Optional[List[str]] = None) -> "DeltaTable":
        write_delta(df, path, mode=mode, partition_by=partition_by)
        return DeltaTable(session, path)

    # -- read -----------------------------------------------------------
    def to_df(self):
        return read_delta(self.session, self.path)

    def history(self) -> List[int]:
        return list(range(self.log.latest_version() + 1))

    # -- commands -------------------------------------------------------
    def _scan_file(self, add):
        """One data file -> DataFrame."""
        snap = self.log.snapshot()
        return self.session.read.schema(snap.schema).parquet(
            os.path.join(self.path, add.path))

    def delete(self, condition: Expression) -> int:
        """DELETE WHERE condition; returns #files rewritten."""
        from spark_rapids_tpu.expr.predicates import Not

        snap = self.log.snapshot()
        actions = []
        rewritten = 0
        for add in snap.files:
            df = self._scan_file(add)
            n_match = df.filter(condition).count()
            if n_match == 0:
                continue  # file untouched (the pruning pass)
            keep = df.filter(Not(condition))
            kept_rows = keep.count()
            actions.append(DeltaLog.remove_action(add.path))
            if kept_rows:
                tbl = _df_to_arrow(keep)
                info = _write_parquet_file(self.path, tbl)
                actions.append(DeltaLog.add_action(info["path"],
                                                   info["size"]))
            rewritten += 1
        if actions:
            self.log.commit(actions)
        return rewritten

    def update(self, condition: Expression,
               assignments: Dict[str, Expression]) -> int:
        """UPDATE SET col=expr WHERE condition; returns #files rewritten."""
        from spark_rapids_tpu.expr.base import AttributeReference
        from spark_rapids_tpu.expr.conditional import If

        snap = self.log.snapshot()
        actions = []
        rewritten = 0
        for add in snap.files:
            df = self._scan_file(add)
            if df.filter(condition).count() == 0:
                continue
            # project: updated value where cond else original
            exprs = []
            for f in snap.schema.fields:
                if f.name in assignments:
                    exprs.append(
                        If(condition, assignments[f.name],
                           AttributeReference(f.name)).alias(f.name))
                else:
                    exprs.append(AttributeReference(f.name))
            out = df.select(*exprs)
            tbl = _df_to_arrow(out)
            info = _write_parquet_file(self.path, tbl)
            actions.append(DeltaLog.remove_action(add.path))
            actions.append(DeltaLog.add_action(info["path"], info["size"]))
            rewritten += 1
        if actions:
            self.log.commit(actions)
        return rewritten

    def merge(self, source, on: List[str],
              when_matched_update: Optional[Dict[str, Expression]] = None,
              when_matched_delete: bool = False,
              when_not_matched_insert: bool = True) -> dict:
        """MERGE INTO target USING source ON target.k == source.k.

        Supported clause shapes (the common upsert patterns):
          * matched -> update assignments OR delete
          * not matched -> insert source row
        Executes as engine joins (the low-shuffle-merge idea: matched
        rows resolve against the existing files; inserts append)."""
        from spark_rapids_tpu.expr.base import AttributeReference

        snap = self.log.snapshot()
        target = self.to_df()
        actions = []
        stats = {"files_rewritten": 0, "rows_inserted": 0}
        schema_names = snap.schema.field_names()
        # 1. per-file rewrite for matched rows
        if when_matched_update or when_matched_delete:
            for add in snap.files:
                fdf = self._scan_file(add)
                matched = fdf.join(source, on=on, how="left_semi")
                if matched.count() == 0:
                    continue
                if when_matched_delete:
                    out = fdf.join(source, on=on, how="left_anti")
                else:
                    # update matched rows from source values; target fields
                    # bind by ORDINAL (an inner join repeats the key names
                    # on both sides), update expressions resolve by name
                    # against the joined schema (source columns must be
                    # uniquely named apart from the keys)
                    from spark_rapids_tpu.expr.base import BoundReference

                    joined = fdf.join(source, on=on, how="inner")
                    upd_exprs = []
                    for fi, f in enumerate(snap.schema.fields):
                        if f.name in when_matched_update:
                            upd_exprs.append(
                                when_matched_update[f.name].alias(f.name))
                        else:
                            upd_exprs.append(
                                BoundReference(fi, f.dataType, f.nullable,
                                               name=f.name).alias(f.name))
                    updated = joined.select(*upd_exprs)
                    untouched = fdf.join(source, on=on, how="left_anti")
                    out = untouched.union(updated)
                tbl = _df_to_arrow(out)
                actions.append(DeltaLog.remove_action(add.path))
                if tbl.num_rows:
                    info = _write_parquet_file(self.path, tbl)
                    actions.append(DeltaLog.add_action(info["path"],
                                                       info["size"]))
                stats["files_rewritten"] += 1
        # 2. inserts: source rows with no target match
        if when_not_matched_insert:
            inserts = source.join(target, on=on, how="left_anti").select(
                *[AttributeReference(n) for n in schema_names])
            tbl = _df_to_arrow(inserts)
            if tbl.num_rows:
                info = _write_parquet_file(self.path, tbl)
                actions.append(DeltaLog.add_action(info["path"],
                                                   info["size"]))
                stats["rows_inserted"] = tbl.num_rows
        if actions:
            self.log.commit(actions)
        return stats

    def optimize(self, zorder_by: Optional[List[str]] = None) -> dict:
        """OPTIMIZE [ZORDER BY cols]: compact all files into one (or a
        z-ordered rewrite) — GpuOptimizeExecutor analog."""
        snap = self.log.snapshot()
        df = self.to_df()
        if zorder_by:
            df = _zorder_sort(df, zorder_by)
        tbl = _df_to_arrow(df)
        actions = [DeltaLog.remove_action(a.path) for a in snap.files]
        if tbl.num_rows:
            info = _write_parquet_file(self.path, tbl)
            actions.append(DeltaLog.add_action(info["path"], info["size"]))
        self.log.commit(actions)
        return {"files_removed": len(snap.files),
                "files_added": 1 if tbl.num_rows else 0}

    def vacuum(self) -> int:
        """Remove data files no longer referenced by the latest snapshot."""
        snap = self.log.snapshot()
        live = {a.path for a in snap.files}
        removed = 0
        for name in os.listdir(self.path):
            if name.endswith(".parquet") and name not in live \
                    and not name.startswith("_"):
                os.unlink(os.path.join(self.path, name))
                removed += 1
        return removed


def _zorder_sort(df, zorder_by: List[str]):
    """Sort rows by interleaved z-order key (device kernel)."""
    import numpy as np

    import jax

    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import HostColumn
    from spark_rapids_tpu.ops.zorder import interleave_bits

    # materialize once, compute keys on device, argsort, rebuild
    rows = df.collect()
    schema = df.schema
    names = schema.field_names()
    data = {n: [r[i] for r in rows] for i, n in enumerate(names)}
    batch = ColumnarBatch.from_host_columns(
        [HostColumn.from_pylist(data[n], f.dataType)
         for n, f in zip(names, schema.fields)], names)
    key_cols = [batch.columns[names.index(z)] for z in zorder_by]
    words = interleave_bits(key_cols)
    n = batch.num_rows
    keys = tuple(words) + (jax.numpy.arange(batch.capacity),)
    sorted_keys = jax.lax.sort(keys, num_keys=len(words))
    perm = np.asarray(sorted_keys[-1])
    # padding rows carry zero keys and sort first; drop them
    perm = perm[np.isin(perm, np.arange(n))][:n] if batch.capacity != n \
        else perm
    order = [int(i) for i in perm if i < n]
    reordered = {nm: [data[nm][i] for i in order] for nm in names}
    return df.session.create_dataframe(reordered, schema)


# ---------------------------------------------------------------------------
# read/write entry points (wired into session.read / DataFrameWriter)
# ---------------------------------------------------------------------------

def read_delta(session, path: str, version: Optional[int] = None):
    log = DeltaLog(path)
    snap = log.snapshot(version)
    paths = snap.file_paths(path)
    if not paths:
        return session.create_dataframe(
            {f.name: [] for f in snap.schema.fields}, snap.schema)
    if any(f.deletionVector for f in snap.files):
        return _read_with_deletion_vectors(session, path, snap)
    return session.read.schema(snap.schema).parquet(*paths)


def _read_with_deletion_vectors(session, path: str, snap):
    """Merge-on-read: drop each file's DV-marked row indices while
    assembling the scan (io/mor.py, shared with Iceberg position
    deletes)."""
    from spark_rapids_tpu.delta.dv import read_dv_indices
    from spark_rapids_tpu.io.mor import read_parquet_minus_rows

    files = []
    for af in snap.files:
        gone = (read_dv_indices(path, af.deletionVector)
                if af.deletionVector else None)
        files.append((os.path.join(path, af.path), gone))
    return read_parquet_minus_rows(session, files, snap.schema)


def write_delta(df, path: str, mode: str = "error",
                partition_by: Optional[List[str]] = None) -> int:
    """Write a DataFrame as a Delta commit; returns the new version."""
    log = DeltaLog(path)
    existing = log.latest_version()
    if existing >= 0 and mode == "error":
        raise FileExistsError(f"delta table already exists at {path}")
    if existing >= 0 and mode == "ignore":
        return existing
    os.makedirs(path, exist_ok=True)
    actions: List[dict] = []
    if existing < 0:
        actions.append(DeltaLog.protocol_action())
        actions.append(log.metadata_action(df.schema, partition_by or []))
    elif mode == "overwrite":
        snap = log.snapshot()
        actions.append(log.metadata_action(df.schema, partition_by or [],
                                           snap.metadata_id))
        actions.extend(DeltaLog.remove_action(a.path) for a in snap.files)
    tbl = _df_to_arrow(df)
    if tbl.num_rows:
        info = _write_parquet_file(path, tbl)
        actions.append(DeltaLog.add_action(info["path"], info["size"]))
    return log.commit(actions)
