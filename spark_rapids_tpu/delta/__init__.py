"""Delta Lake support (SURVEY.md §2.8) — log, table commands, Z-ORDER."""
from spark_rapids_tpu.delta.log import DeltaLog, Snapshot  # noqa: F401
from spark_rapids_tpu.delta.table import (  # noqa: F401
    DeltaTable,
    read_delta,
    write_delta,
)
