"""Delta deletion vectors — RoaringBitmapArray decode + Z85 paths.

Reference analog: the reference's Delta modules read deletion vectors so
DML on DV-enabled tables stays on the GPU (SURVEY.md §2.8 "deletion
vectors").  A deletion vector marks deleted ROW INDICES of one data file:

  deletionVector: {storageType: 'u'|'i'|'p', pathOrInlineDv, offset?,
                   sizeInBytes, cardinality}

  * 'i': pathOrInlineDv is the Z85-encoded serialized bitmap itself
  * 'u': pathOrInlineDv is [optional random prefix]<20-char Z85 UUID>;
         the bytes live in <table>/[prefix/]deletion_vector_<uuid>.bin at
         ``offset`` (int32 big-endian size, then the bitmap, then CRC32)
  * 'p': an absolute path to such a .bin file

The serialized form is Delta's *portable* RoaringBitmapArray: little-
endian magic 1681511377, int64 bitmap count, then per 32-bit roaring
bitmap an int32 key plus the standard roaring serialization (array /
bitmap / run containers — RoaringFormatSpec).  Absolute row index =
key << 32 | container value.
"""
from __future__ import annotations

import os
import struct
import uuid as _uuid
from typing import List, Optional

_MAGIC = 1681511377
_SERIAL_COOKIE_NO_RUN = 12346
_SERIAL_COOKIE = 12347

_Z85_CHARS = ("0123456789abcdefghijklmnopqrstuvwxyz"
              "ABCDEFGHIJKLMNOPQRSTUVWXYZ.-:+=^!/*?&<>()[]{}@%$#")
_Z85_INDEX = {c: i for i, c in enumerate(_Z85_CHARS)}


def z85_decode(s: str) -> bytes:
    if len(s) % 5:
        raise ValueError("z85 length must be a multiple of 5")
    out = bytearray()
    for i in range(0, len(s), 5):
        v = 0
        for ch in s[i:i + 5]:
            v = v * 85 + _Z85_INDEX[ch]
        out += v.to_bytes(4, "big")
    return bytes(out)


def z85_encode(b: bytes) -> str:
    if len(b) % 4:
        raise ValueError("z85 input must be a multiple of 4 bytes")
    out = []
    for i in range(0, len(b), 4):
        v = int.from_bytes(b[i:i + 4], "big")
        chunk = []
        for _ in range(5):
            v, r = divmod(v, 85)
            chunk.append(_Z85_CHARS[r])
        out.extend(reversed(chunk))
    return "".join(out)


def _decode_roaring32(buf: bytes, off: int):
    """One standard 32-bit roaring bitmap at ``off`` -> (values, new off)."""
    cookie = struct.unpack_from("<I", buf, off)[0]
    vals: List[int] = []
    if (cookie & 0xFFFF) == _SERIAL_COOKIE:
        n = (cookie >> 16) + 1
        off += 4
        run_flags = buf[off: off + (n + 7) // 8]
        off += (n + 7) // 8
        has_offsets = n >= 4
    elif cookie == _SERIAL_COOKIE_NO_RUN:
        n = struct.unpack_from("<I", buf, off + 4)[0]
        off += 8
        run_flags = b"\x00" * ((n + 7) // 8)
        has_offsets = True
    else:
        raise ValueError(f"bad roaring cookie {cookie}")
    keys = []
    cards = []
    for i in range(n):
        k, c = struct.unpack_from("<HH", buf, off)
        off += 4
        keys.append(k)
        cards.append(c + 1)
    if has_offsets:
        off += 4 * n  # container offsets (we read sequentially)
    for i in range(n):
        base = keys[i] << 16
        is_run = (run_flags[i // 8] >> (i % 8)) & 1
        if is_run:
            nruns = struct.unpack_from("<H", buf, off)[0]
            off += 2
            for _ in range(nruns):
                start, length = struct.unpack_from("<HH", buf, off)
                off += 4
                vals.extend(base + v
                            for v in range(start, start + length + 1))
        elif cards[i] > 4096:  # bitmap container: 8 KiB bitset
            words = struct.unpack_from("<1024Q", buf, off)
            off += 8192
            for wi, w in enumerate(words):
                while w:
                    b = w & -w
                    vals.append(base + (wi << 6) + b.bit_length() - 1)
                    w ^= b
        else:  # array container
            arr = struct.unpack_from(f"<{cards[i]}H", buf, off)
            off += 2 * cards[i]
            vals.extend(base + v for v in arr)
    return vals, off


def decode_roaring_array(buf: bytes) -> List[int]:
    """Delta portable RoaringBitmapArray -> sorted absolute row indices."""
    magic = struct.unpack_from("<i", buf, 0)[0]
    if magic != _MAGIC:
        raise ValueError(f"bad deletion vector magic {magic}")
    nmaps = struct.unpack_from("<q", buf, 4)[0]
    off = 12
    out: List[int] = []
    for _ in range(nmaps):
        key = struct.unpack_from("<i", buf, off)[0]
        off += 4
        vals, off = _decode_roaring32(buf, off)
        out.extend((key << 32) | v for v in vals)
    return sorted(out)


def encode_roaring_array(indices) -> bytes:
    """Serialize row indices as a portable RoaringBitmapArray (array
    containers only) — used by the DV writer and tests."""
    by_key = {}
    for idx in sorted(set(int(i) for i in indices)):
        by_key.setdefault(idx >> 32, []).append(idx & 0xFFFFFFFF)
    out = bytearray(struct.pack("<iq", _MAGIC, len(by_key)))
    for key in sorted(by_key):
        vals = by_key[key]
        containers = {}
        for v in vals:
            containers.setdefault(v >> 16, []).append(v & 0xFFFF)
        out += struct.pack("<i", key)
        n = len(containers)
        out += struct.pack("<II", _SERIAL_COOKIE_NO_RUN, n)
        for k in sorted(containers):
            out += struct.pack("<HH", k, len(containers[k]) - 1)
        # offsets (array containers <=4096 values; bitmap containers
        # above — the spec's mandatory container choice)
        sizes = [8192 if len(containers[k]) > 4096
                 else 2 * len(containers[k]) for k in sorted(containers)]
        base = len(out) + 4 * n
        pos = 0
        for sz in sizes:
            out += struct.pack("<I", base + pos)
            pos += sz
        for k in sorted(containers):
            vals = sorted(containers[k])
            if len(vals) > 4096:
                words = [0] * 1024
                for v in vals:
                    words[v >> 6] |= 1 << (v & 63)
                out += struct.pack("<1024Q", *words)
            else:
                out += struct.pack(f"<{len(vals)}H", *vals)
    return bytes(out)


def read_dv_indices(table_path: str, dv: dict) -> List[int]:
    """deletionVector action dict -> sorted deleted row indices."""
    st = dv.get("storageType", "u")
    body = dv["pathOrInlineDv"]
    if st == "i":
        return decode_roaring_array(z85_decode(body))
    if st == "p":
        path = body
        prefix = ""
    else:  # 'u': [random prefix]<20-char z85 uuid>
        enc = body[-20:]
        prefix = body[:-20]
        u = _uuid.UUID(bytes=z85_decode(enc))
        path = os.path.join(table_path, prefix,
                            f"deletion_vector_{u}.bin")
    with open(path, "rb") as f:
        data = f.read()
    off = int(dv.get("offset", 1))
    size = struct.unpack_from(">i", data, off)[0]
    return decode_roaring_array(data[off + 4: off + 4 + size])


def write_dv_file(table_path: str, indices) -> dict:
    """Write a deletion-vector .bin and return its action dict."""
    import zlib

    payload = encode_roaring_array(indices)
    u = _uuid.uuid4()
    name = f"deletion_vector_{u}.bin"
    blob = (b"\x01" + struct.pack(">i", len(payload)) + payload
            + struct.pack(">I", zlib.crc32(payload)))
    with open(os.path.join(table_path, name), "wb") as f:
        f.write(blob)
    return {"storageType": "u", "pathOrInlineDv": z85_encode(u.bytes),
            "offset": 1, "sizeInBytes": len(payload),
            "cardinality": len(set(int(i) for i in indices))}
