"""Delta transaction log — the GpuDeltaLog analog.

Reference analog: delta-lake/common GpuDeltaLog + GpuOptimisticTransaction
(SURVEY.md §2.8): the reference wraps Delta's log replay and commits
GPU-written files through Delta's optimistic protocol.  This module
implements the open Delta log format directly (the subset the engine
needs): JSON commit files under ``_delta_log/``, protocol/metaData/add/
remove actions, parquet checkpoints + ``_last_checkpoint``, and optimistic
concurrency via atomic create (O_EXCL) with retry.

Interoperability: the files written here follow the public Delta spec
(https://github.com/delta-io/delta PROTOCOL.md) at reader/writer version 1,
so delta-rs / Spark can read these tables (no deletion vectors, no column
mapping).  Checkpoints use a PRIVATE simplified layout under the private
``_tpu_checkpoint.json`` pointer (never ``_last_checkpoint``), so foreign
readers replay the spec-compliant JSON commits and stay compatible.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
import uuid
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import types as T

LOG_DIR = "_delta_log"


# ---------------------------------------------------------------------------
# Schema <-> Spark schema JSON
# ---------------------------------------------------------------------------

_TO_JSON = {
    T.BooleanType: "boolean", T.ByteType: "byte", T.ShortType: "short",
    T.IntegerType: "integer", T.LongType: "long", T.FloatType: "float",
    T.DoubleType: "double", T.StringType: "string", T.DateType: "date",
    T.TimestampType: "timestamp", T.BinaryType: "binary",
}

_FROM_JSON = {v: k for k, v in _TO_JSON.items()}


def _type_to_json(dt: T.DataType):
    if isinstance(dt, T.DecimalType):
        return f"decimal({dt.precision},{dt.scale})"
    if isinstance(dt, T.ArrayType):
        return {"type": "array", "elementType": _type_to_json(dt.elementType),
                "containsNull": dt.containsNull}
    if isinstance(dt, T.StructType):
        return schema_to_json(dt)
    return _TO_JSON[type(dt)]


def _type_from_json(j):
    if isinstance(j, dict):
        if j.get("type") == "array":
            return T.ArrayType(_type_from_json(j["elementType"]),
                               j.get("containsNull", True))
        if j.get("type") == "struct":
            return schema_from_json(j)
        raise ValueError(f"unsupported delta type {j!r}")
    if j.startswith("decimal("):
        p, s = j[8:-1].split(",")
        return T.DecimalType(int(p), int(s))
    return _FROM_JSON[j]()


def schema_to_json(schema: T.StructType) -> dict:
    return {"type": "struct", "fields": [
        {"name": f.name, "type": _type_to_json(f.dataType),
         "nullable": f.nullable, "metadata": {}} for f in schema.fields]}


def schema_from_json(j: dict) -> T.StructType:
    return T.StructType([
        T.StructField(f["name"], _type_from_json(f["type"]),
                      f.get("nullable", True)) for f in j["fields"]])


# ---------------------------------------------------------------------------
# Snapshot
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class AddFile:
    path: str
    partitionValues: Dict[str, str]
    size: int
    modificationTime: int
    dataChange: bool = True
    stats: Optional[str] = None
    deletionVector: Optional[dict] = None  # delta/dv.py decodes these


@dataclasses.dataclass
class Snapshot:
    version: int
    schema: T.StructType
    files: List[AddFile]
    partition_columns: List[str]
    metadata_id: str

    def file_paths(self, table_path: str) -> List[str]:
        return [os.path.join(table_path, f.path) for f in self.files]


class DeltaLog:
    """Log replay + optimistic commits for one table path."""

    CHECKPOINT_INTERVAL = 10

    def __init__(self, table_path: str):
        self.table_path = table_path
        self.log_path = os.path.join(table_path, LOG_DIR)

    # -- replay ---------------------------------------------------------
    def _commit_file(self, version: int) -> str:
        return os.path.join(self.log_path, f"{version:020d}.json")

    def _checkpoint_file(self, version: int) -> str:
        return os.path.join(self.log_path,
                            f"{version:020d}.tpu-checkpoint.parquet")

    def latest_version(self) -> int:
        if not os.path.isdir(self.log_path):
            return -1
        best = -1
        for name in os.listdir(self.log_path):
            if name.endswith(".json") and name[:20].isdigit():
                best = max(best, int(name[:20]))
        return best

    def _last_checkpoint_version(self) -> int:
        p = os.path.join(self.log_path, "_tpu_checkpoint.json")
        if not os.path.isfile(p):
            return -1
        try:
            with open(p) as f:
                return int(json.load(f)["version"])
        except (ValueError, KeyError, OSError):
            return -1

    def snapshot(self, version: Optional[int] = None) -> Snapshot:
        latest = self.latest_version()
        if latest < 0:
            raise FileNotFoundError(
                f"{self.table_path} is not a Delta table (no {LOG_DIR})")
        version = latest if version is None else version
        files: Dict[str, AddFile] = {}
        schema = None
        part_cols: List[str] = []
        meta_id = ""
        start = 0
        ckpt = self._last_checkpoint_version()
        if 0 <= ckpt <= version and os.path.isfile(
                self._checkpoint_file(ckpt)):
            for action in self._read_checkpoint(ckpt):
                schema, part_cols, meta_id = self._apply(
                    action, files, schema, part_cols, meta_id)
            start = ckpt + 1
        for v in range(start, version + 1):
            p = self._commit_file(v)
            if not os.path.isfile(p):
                continue
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        schema, part_cols, meta_id = self._apply(
                            json.loads(line), files, schema, part_cols,
                            meta_id)
        if schema is None:
            raise ValueError(f"{self.table_path}: no metaData action found")
        return Snapshot(version, schema, list(files.values()), part_cols,
                        meta_id)

    @staticmethod
    def _apply(action, files, schema, part_cols, meta_id):
        if "metaData" in action:
            md = action["metaData"]
            schema = schema_from_json(json.loads(md["schemaString"]))
            part_cols = md.get("partitionColumns", [])
            meta_id = md.get("id", "")
        elif "add" in action:
            a = action["add"]
            files[a["path"]] = AddFile(
                a["path"], a.get("partitionValues", {}),
                a.get("size", 0), a.get("modificationTime", 0),
                a.get("dataChange", True), a.get("stats"),
                a.get("deletionVector"))
        elif "remove" in action:
            files.pop(action["remove"]["path"], None)
        return schema, part_cols, meta_id

    # -- commit ---------------------------------------------------------
    def commit(self, actions: List[dict], attempts: int = 20) -> int:
        """Optimistic commit: next version via atomic O_EXCL create; a
        concurrent writer winning the race surfaces as FileExistsError and
        we re-read + retry (the reference delegates this loop to Delta's
        OptimisticTransaction)."""
        os.makedirs(self.log_path, exist_ok=True)
        for _ in range(attempts):
            version = self.latest_version() + 1
            path = self._commit_file(version)
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                time.sleep(0.01)
                continue
            with os.fdopen(fd, "w") as f:
                for a in actions:
                    f.write(json.dumps(a) + "\n")
            if version > 0 and version % self.CHECKPOINT_INTERVAL == 0:
                self._write_checkpoint(version)
            return version
        raise RuntimeError(
            f"could not commit to {self.log_path} after {attempts} tries")

    def metadata_action(self, schema: T.StructType,
                        partition_columns: List[str],
                        meta_id: Optional[str] = None) -> dict:
        return {"metaData": {
            "id": meta_id or str(uuid.uuid4()),
            "format": {"provider": "parquet", "options": {}},
            "schemaString": json.dumps(schema_to_json(schema)),
            "partitionColumns": partition_columns,
            "configuration": {},
            "createdTime": int(time.time() * 1000),
        }}

    @staticmethod
    def protocol_action() -> dict:
        return {"protocol": {"minReaderVersion": 1, "minWriterVersion": 1}}

    @staticmethod
    def add_action(rel_path: str, size: int,
                   partition_values: Optional[Dict[str, str]] = None,
                   stats: Optional[str] = None) -> dict:
        return {"add": {
            "path": rel_path, "partitionValues": partition_values or {},
            "size": size, "modificationTime": int(time.time() * 1000),
            "dataChange": True, **({"stats": stats} if stats else {})}}

    @staticmethod
    def remove_action(rel_path: str) -> dict:
        return {"remove": {"path": rel_path,
                           "deletionTimestamp": int(time.time() * 1000),
                           "dataChange": True}}

    # -- checkpoints ----------------------------------------------------
    def _write_checkpoint(self, version: int):
        import pyarrow as pa
        import pyarrow.parquet as pq

        snap = self.snapshot(version)
        rows = []
        rows.append({"kind": "protocol",
                     "json": json.dumps(self.protocol_action())})
        rows.append({"kind": "metaData", "json": json.dumps(
            self.metadata_action(snap.schema, snap.partition_columns,
                                 snap.metadata_id))})
        for f in snap.files:
            rows.append({"kind": "add", "json": json.dumps(
                {"add": dataclasses.asdict(f)})})
        tbl = pa.table({"kind": [r["kind"] for r in rows],
                        "json": [r["json"] for r in rows]})
        pq.write_table(tbl, self._checkpoint_file(version))
        with open(os.path.join(self.log_path,
                               "_tpu_checkpoint.json"), "w") as f:
            json.dump({"version": version, "size": len(rows)}, f)

    def _read_checkpoint(self, version: int):
        import pyarrow.parquet as pq

        from spark_rapids_tpu.io.faults import file_context

        # log metadata: never tolerated away, attributed only (ISSUE 5)
        path = self._checkpoint_file(version)
        with file_context(path, "parquet", "delta-checkpoint"):
            tbl = pq.read_table(path)
        for j in tbl.column("json").to_pylist():
            yield json.loads(j)
