"""ColumnarBatch — a set of equal-row-count device columns.

Reference analog: Spark's ColumnarBatch holding GpuColumnVectors
(GpuColumnVector.from(Table) etc.).  Batches here carry:

  * columns: DeviceColumn pytrees (padded to a shared row capacity)
  * num_rows: the logical row count (host int — known when the batch is
    materialized; device-resident fused programs carry it as a scalar)
  * schema: StructType naming the columns

Batches are immutable; operators build new ones.  Registered as a pytree so a
whole fused plan-stage can be jitted over batches directly.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import (
    DEFAULT_ROW_BUCKETS,
    DeviceColumn,
    HostColumn,
    round_up_bucket,
)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class ColumnarBatch:
    columns: List[DeviceColumn]
    num_rows: int
    schema: T.StructType

    def tree_flatten(self):
        return tuple(self.columns), (self.num_rows, self.schema)

    @classmethod
    def tree_unflatten(cls, aux, children):
        num_rows, schema = aux
        return cls(list(children), num_rows, schema)

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else 0

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.columns)

    def column(self, i: int) -> DeviceColumn:
        return self.columns[i]

    def column_by_name(self, name: str) -> DeviceColumn:
        for f, c in zip(self.schema.fields, self.columns):
            if f.name == name:
                return c
        raise KeyError(name)

    @property
    def row_mask(self) -> jax.Array:
        """(capacity,) bool — True for logical rows, False for padding."""
        return jnp.arange(self.capacity) < self.num_rows

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_host_columns(cols: Sequence[HostColumn], names: Sequence[str],
                          row_buckets=DEFAULT_ROW_BUCKETS) -> "ColumnarBatch":
        from spark_rapids_tpu.columnar.column import _np_tree_bytes
        from spark_rapids_tpu.perfcounters import count_h2d

        n = cols[0].num_rows if cols else 0
        cap = round_up_bucket(max(n, 1), row_buckets)
        # pad every column on host, then ONE device_put over the whole
        # column list: per-buffer uploads pay a dispatch round trip per
        # array, and a scan batch has 2-5 buffers per column (ISSUE 6
        # satellite — fold the batch into a single multi-array transfer)
        padded = [DeviceColumn._padded_host(c, capacity=cap)
                  for c in cols]
        count_h2d(_np_tree_bytes(padded),
                  logical=sum(c.nbytes() for c in cols))
        dcols = list(jax.device_put(padded))
        schema = T.StructType(
            [T.StructField(nm, c.dtype) for nm, c in zip(names, cols)])
        return ColumnarBatch(dcols, n, schema)

    @staticmethod
    def from_pydict(data: dict, schema: T.StructType,
                    row_buckets=DEFAULT_ROW_BUCKETS) -> "ColumnarBatch":
        cols = [HostColumn.from_pylist(data[f.name], f.dataType)
                for f in schema.fields]
        return ColumnarBatch.from_host_columns(
            cols, [f.name for f in schema.fields], row_buckets)

    def shrink_to_fit(self) -> "ColumnarBatch":
        """Compact to the row bucket of ``num_rows`` in ONE jitted program.

        A grouped aggregate / window / filter keeps its input's capacity, so
        a 600-group result can sit in 2M-row padded buffers; transferring
        that to host (collect, spill, shuffle wire) pays the full padded
        size.  One extra launch here cuts the transfer by the cap ratio —
        the single biggest lever on a latency/bandwidth-constrained link
        (VERDICT r3: qa/qb/qc spent seconds moving >95% padding)."""
        out_cap = round_up_bucket(max(self.num_rows, 1), DEFAULT_ROW_BUCKETS)
        if out_cap >= self.capacity:
            return self
        cols = _shrink_cols(out_cap, tuple(self.columns))
        return ColumnarBatch(list(cols), self.num_rows, self.schema)

    def to_host_columns(
            self, max_shrink_waste_bytes: int = 0) -> List[HostColumn]:
        # one device_get for the whole batch: per-array np.asarray would pay
        # a device round trip PER BUFFER (tunnel latency dominates small
        # transfers); shrink first so padding never crosses the link
        import jax

        shrunk = self
        out_cap = round_up_bucket(max(self.num_rows, 1), DEFAULT_ROW_BUCKETS)
        if out_cap < self.capacity:
            # shrink elision (docs/whole_plan_fusion.md): the shrink is a
            # whole extra program launch; when the padding it would strip
            # is under the caller's waste budget, transferring the padded
            # buffers is cheaper than compiling + launching the compactor
            # (to_host(n) truncates rows on host either way)
            waste = self.nbytes() * (self.capacity - out_cap) \
                // self.capacity
            if waste <= max_shrink_waste_bytes:
                from spark_rapids_tpu import perfcounters as PC

                PC.bump("collect_shrinks_elided")
            else:
                shrunk = self.shrink_to_fit()
        # DeviceColumn is a pytree, so one device_get fetches every buffer
        # of every column (incl. struct children) in one logical round trip
        from spark_rapids_tpu.perfcounters import sync_get

        host = sync_get(shrunk.columns)
        n = self.num_rows
        return [c.to_host(n) for c in host]

    def to_pydict(self) -> dict:
        host = self.to_host_columns()
        return {f.name: c.to_pylist()
                for f, c in zip(self.schema.fields, host)}

    def to_rows(self) -> List[tuple]:
        cols = [c.to_pylist() for c in self.to_host_columns()]
        return list(zip(*cols)) if cols else [()] * self.num_rows

    def with_columns(self, columns: List[DeviceColumn],
                     schema: Optional[T.StructType] = None,
                     num_rows: Optional[int] = None) -> "ColumnarBatch":
        return ColumnarBatch(columns,
                             self.num_rows if num_rows is None else num_rows,
                             schema or self.schema)

    def select(self, indices: Sequence[int]) -> "ColumnarBatch":
        return ColumnarBatch(
            [self.columns[i] for i in indices], self.num_rows,
            T.StructType([self.schema.fields[i] for i in indices]))

    # -- concat (GpuCoalesceBatches building block) -------------------------
    @staticmethod
    def concat(batches: Sequence["ColumnarBatch"],
               row_buckets=DEFAULT_ROW_BUCKETS) -> "ColumnarBatch":
        """Concatenate batches (same schema) into one padded batch.

        Reference analog: cuDF Table.concatenate used by GpuCoalesceBatches.
        Device-resident: pure jnp ops, no host round-trip.
        """
        assert batches, "concat of zero batches"
        if len(batches) == 1:
            return batches[0]
        total = sum(b.num_rows for b in batches)
        cap = round_up_bucket(max(total, 1), row_buckets)
        schema = batches[0].schema
        ncols = batches[0].num_cols
        rows = [b.num_rows for b in batches]

        def _concat_col(cols: List[DeviceColumn]) -> DeviceColumn:
            dtype = cols[0].dtype
            if cols[0].is_struct:
                validity = jnp.zeros(cap, jnp.bool_)
                lengths = (jnp.zeros(cap, jnp.int32)
                           if cols[0].lengths is not None else None)
                off = 0
                for n, c in zip(rows, cols):
                    if n == 0:
                        continue
                    validity = jax.lax.dynamic_update_slice(
                        validity, c.validity[:n], (off,))
                    if lengths is not None:
                        lengths = jax.lax.dynamic_update_slice(
                            lengths, c.lengths[:n].astype(jnp.int32),
                            (off,))
                    off += n
                kids = tuple(
                    _concat_col([c.children[k] for c in cols])
                    for k in range(len(cols[0].children)))
                return DeviceColumn(dtype, validity, lengths=lengths,
                                    children=kids)
            if cols[0].is_string_array:
                ew = max(c.ewidth for c in cols)
                w = max(c.width for c in cols)
                chars = jnp.zeros((cap, ew, w), jnp.uint8)
                elens = jnp.zeros((cap, ew), jnp.int32)
                ev = jnp.zeros((cap, ew), jnp.bool_)
                lengths = jnp.zeros(cap, jnp.int32)
                validity = jnp.zeros(cap, jnp.bool_)
                off = 0
                for b, c in zip(batches, cols):
                    nn = b.num_rows
                    if nn == 0:
                        continue
                    cpad = jnp.pad(c.chars, ((0, 0), (0, ew - c.ewidth),
                                             (0, w - c.width)))[:nn]
                    chars = jax.lax.dynamic_update_slice(
                        chars, cpad.astype(jnp.uint8), (off, 0, 0))
                    elens = jax.lax.dynamic_update_slice(
                        elens,
                        jnp.pad(c.data, ((0, 0), (0, ew - c.ewidth))
                                )[:nn].astype(jnp.int32), (off, 0))
                    ev = jax.lax.dynamic_update_slice(
                        ev, jnp.pad(c.elem_valid,
                                    ((0, 0), (0, ew - c.ewidth)))[:nn],
                        (off, 0))
                    lengths = jax.lax.dynamic_update_slice(
                        lengths, c.lengths[:nn], (off,))
                    validity = jax.lax.dynamic_update_slice(
                        validity, c.validity[:nn], (off,))
                    off += nn
                return DeviceColumn(dtype, validity, chars=chars,
                                    data=elens, lengths=lengths,
                                    elem_valid=ev)
            if cols[0].is_string:
                width = max(c.width for c in cols)
                chars = jnp.zeros((cap, width), jnp.uint8)
                lengths = jnp.zeros(cap, jnp.int32)
                validity = jnp.zeros(cap, jnp.bool_)
                off = 0
                for b, c in zip(batches, cols):
                    n = b.num_rows
                    if n == 0:
                        continue
                    chars = jax.lax.dynamic_update_slice(
                        chars,
                        jnp.pad(c.chars[:, :],
                                ((0, 0), (0, width - c.width))).astype(jnp.uint8)[:n],
                        (off, 0))
                    lengths = jax.lax.dynamic_update_slice(lengths, c.lengths[:n], (off,))
                    validity = jax.lax.dynamic_update_slice(validity, c.validity[:n], (off,))
                    off += n
                return DeviceColumn(dtype, validity, chars=chars,
                                    lengths=lengths)
            if cols[0].is_array:
                ew = max(c.ewidth for c in cols)
                data = jnp.zeros((cap, ew), cols[0].data.dtype)
                ev = jnp.zeros((cap, ew), jnp.bool_)
                lengths = jnp.zeros(cap, jnp.int32)
                validity = jnp.zeros(cap, jnp.bool_)
                off = 0
                for b, c in zip(batches, cols):
                    n = b.num_rows
                    if n == 0:
                        continue
                    pad = ew - c.ewidth
                    data = jax.lax.dynamic_update_slice(
                        data, jnp.pad(c.data, ((0, 0), (0, pad)))[:n],
                        (off, 0))
                    ev = jax.lax.dynamic_update_slice(
                        ev, jnp.pad(c.elem_valid, ((0, 0), (0, pad)))[:n],
                        (off, 0))
                    lengths = jax.lax.dynamic_update_slice(
                        lengths, c.lengths[:n], (off,))
                    validity = jax.lax.dynamic_update_slice(
                        validity, c.validity[:n], (off,))
                    off += n
                return DeviceColumn(dtype, validity, data=data,
                                    lengths=lengths, elem_valid=ev)
            trail = cols[0].data.shape[1:]
            data = jnp.zeros((cap,) + trail, cols[0].data.dtype)
            validity = jnp.zeros(cap, jnp.bool_)
            off = 0
            for b, c in zip(batches, cols):
                n = b.num_rows
                if n == 0:
                    continue
                data = jax.lax.dynamic_update_slice(
                    data, c.data[:n], (off,) + (0,) * len(trail))
                validity = jax.lax.dynamic_update_slice(validity, c.validity[:n], (off,))
                off += n
            return DeviceColumn(dtype, validity, data=data)

        out_cols = [_concat_col([b.columns[ci] for b in batches])
                    for ci in range(ncols)]
        return ColumnarBatch(out_cols, total, schema)

    def slice_rows(self, start: int, length: int,
                   row_buckets=DEFAULT_ROW_BUCKETS) -> "ColumnarBatch":
        """Host-driven row slice (used by split-and-retry)."""
        cap = round_up_bucket(max(length, 1), row_buckets)

        def _slice_col(c: DeviceColumn) -> DeviceColumn:
            sl = slice(start, start + length)
            if c.is_string_array:
                return DeviceColumn(c.dtype, c.validity[sl],
                                    chars=c.chars[sl], data=c.data[sl],
                                    lengths=c.lengths[sl],
                                    elem_valid=c.elem_valid[sl]).slice_to(cap)
            if c.is_string:
                return DeviceColumn(c.dtype, c.validity[sl], chars=c.chars[sl],
                                    lengths=c.lengths[sl]).slice_to(cap)
            if c.is_array:
                return DeviceColumn(c.dtype, c.validity[sl], data=c.data[sl],
                                    lengths=c.lengths[sl],
                                    elem_valid=c.elem_valid[sl]).slice_to(cap)
            if c.is_struct:
                return DeviceColumn(
                    c.dtype, c.validity[sl],
                    children=tuple(_slice_col(k) for k in c.children)
                ).slice_to(cap)
            return DeviceColumn(c.dtype, c.validity[sl],
                                data=c.data[sl]).slice_to(cap)

        cols = [_slice_col(c) for c in self.columns]
        return ColumnarBatch(cols, length, self.schema)

    def __repr__(self):
        return (f"ColumnarBatch(rows={self.num_rows}, cap={self.capacity}, "
                f"schema={self.schema.simpleString})")


def _shrink_cols(out_cap: int, cols):
    """Slice every buffer of every column to ``out_cap`` leading rows,
    jitted once per (out_cap, batch structure)."""
    from spark_rapids_tpu.perfcounters import tpu_jit

    key = out_cap
    fn = _SHRINK_JITS.get(key)
    if fn is None:
        import functools

        fn = _SHRINK_JITS[key] = tpu_jit(
            functools.partial(_shrink_trace, out_cap))
    return fn(cols)


def _shrink_trace(out_cap: int, cols):
    return jax.tree_util.tree_map(lambda a: a[:out_cap], cols)


_SHRINK_JITS: dict = {}


def empty_batch(schema: T.StructType, capacity: int = 1) -> ColumnarBatch:
    cols = []
    for f in schema.fields:
        if isinstance(f.dataType, T.StringType):
            cols.append(DeviceColumn(f.dataType, jnp.zeros(capacity, jnp.bool_),
                                     chars=jnp.zeros((capacity, 8), jnp.uint8),
                                     lengths=jnp.zeros(capacity, jnp.int32)))
        else:
            sdt = T.storage_dtype(f.dataType)
            shape = ((capacity, 2)
                     if isinstance(f.dataType, T.DecimalType)
                     and f.dataType.is_128 else (capacity,))
            cols.append(DeviceColumn(f.dataType, jnp.zeros(capacity, jnp.bool_),
                                     data=jnp.zeros(shape, sdt)))
    return ColumnarBatch(cols, 0, schema)
