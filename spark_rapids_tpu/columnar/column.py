"""Device/host column vectors — the TPU answer to GpuColumnVector.

Reference analog: sql-plugin/src/main/java/com/nvidia/spark/rapids/
GpuColumnVector.java and RapidsHostColumnVector.java, which wrap cuDF device
columns (data + validity bitmask + offsets) as Spark ColumnVectors.

TPU-first design decisions (NOT a translation of the cuDF layout):

* **Padded capacities.** XLA compiles per shape.  Every column is padded to a
  row-capacity bucket (pow2 ladder, ``spark.rapids.tpu.batch.rowBuckets``) so
  a query sees a handful of compiled programs, not one per batch size.  The
  logical row count rides alongside (host int) and as a device scalar inside
  fused programs; rows past ``num_rows`` are garbage and masked off.

* **Validity as bool vector, not bitmask.**  cuDF packs validity 1 bit/row
  because PCIe bytes are precious; on TPU the VPU operates on 8x128 lanes of
  bytes and XLA fuses the mask reads into consumers, so a bool vector is both
  faster and simpler.

* **Strings as length-bucketed padded char matrices.**  cuDF stores
  (chars, offsets); offset-indirection defeats XLA's static-shape tiling, so
  strings here are a ``(capacity, width)`` uint8 matrix plus an int32 length
  vector, with ``width`` drawn from a bucket ladder
  (``spark.rapids.tpu.string.widthBuckets``).  Lexicographic compare, hash,
  substring etc. become dense vector ops.  Memory overhead is bounded by the
  ladder and by width re-bucketing at coalesce time.

* **Decimals** are unscaled int64 (precision<=18); decimal128 is a two-limb
  (hi int64, lo uint-as-int64) pair — see expr/decimal128.py.

Columns are registered as JAX pytrees so whole-stage-fused programs take and
return them directly.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T

DEFAULT_ROW_BUCKETS = (1024, 8192, 65536, 262144, 1048576, 4194304)
DEFAULT_WIDTH_BUCKETS = (8, 32, 128, 512, 2048)


def _np_tree_bytes(tree) -> int:
    """Total numpy bytes across a pytree's array leaves (the actual
    transfer size of a padded host column set)."""
    return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(tree)
               if hasattr(leaf, "nbytes"))


def round_up_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    # beyond the ladder: next pow2
    p = 1
    while p < n:
        p <<= 1
    return p


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DeviceColumn:
    """One column resident in TPU HBM.

    kind "flat": data (capacity,) of storage dtype; chars/lengths None.
    kind "string": chars (capacity, width) uint8; lengths (capacity,) int32;
                   data is None.
    kind "array":  data (capacity, ewidth) of element storage dtype;
                   elem_valid (capacity, ewidth) bool; lengths (capacity,)
                   int32 — a padded list-column (primitive elements), the
                   TPU answer to cuDF LIST columns (offsets + child).
    kind "struct": children = tuple of full child DeviceColumns (one per
                   struct field) — cuDF STRUCT columns are likewise a
                   validity mask over recursively stored children.
    kind "string_array": chars (capacity, ewidth, width) uint8;
                   data (capacity, ewidth) int32 holds PER-ELEMENT byte
                   lengths; lengths (capacity,) element counts;
                   elem_valid (capacity, ewidth) — array<string> as a 3-D
                   padded char tensor (cuDF: LIST of STRING offsets).
    validity: (capacity,) bool; True = valid (non-null).
    """

    dtype: T.DataType
    validity: jax.Array
    data: Optional[jax.Array] = None
    chars: Optional[jax.Array] = None
    lengths: Optional[jax.Array] = None
    elem_valid: Optional[jax.Array] = None
    children: Optional[tuple] = None  # tuple of DeviceColumn (structs)

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        children = (self.validity, self.data, self.chars, self.lengths,
                    self.elem_valid, self.children)
        return children, self.dtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        validity, data, chars, lengths, elem_valid, kids = children
        return cls(dtype=aux, validity=validity, data=data, chars=chars,
                   lengths=lengths, elem_valid=elem_valid, children=kids)

    # -- properties ---------------------------------------------------------
    @property
    def is_string(self) -> bool:
        return self.chars is not None and self.chars.ndim == 2

    @property
    def is_array(self) -> bool:
        return self.elem_valid is not None and self.chars is None

    @property
    def is_struct(self) -> bool:
        return self.children is not None

    @property
    def is_string_array(self) -> bool:
        return self.chars is not None and self.chars.ndim == 3

    @property
    def is_dec128(self) -> bool:
        """decimal(p>18): data is (capacity, 2) int64 [hi, lo] limbs."""
        return isinstance(self.dtype, T.DecimalType) and self.dtype.is_128

    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @property
    def width(self) -> int:
        if self.chars is None:
            return 0
        return int(self.chars.shape[-1])

    @property
    def ewidth(self) -> int:
        """Element capacity per row for array columns."""
        if self.is_string_array:
            return int(self.chars.shape[1])
        return int(self.data.shape[1]) if self.is_array else 0

    def nbytes(self) -> int:
        n = self.validity.size  # bool = 1 byte
        if self.data is not None:
            n += self.data.size * self.data.dtype.itemsize
        if self.chars is not None:
            n += self.chars.size + self.lengths.size * 4
        if self.elem_valid is not None:
            n += self.elem_valid.size + self.lengths.size * 4
        if self.children is not None:
            n += sum(c.nbytes() for c in self.children)
        return int(n)

    def gather(self, idx) -> "DeviceColumn":
        """Row gather (works for every column kind)."""
        if self.is_string_array:
            return DeviceColumn(self.dtype, self.validity[idx],
                                chars=self.chars[idx], data=self.data[idx],
                                lengths=self.lengths[idx],
                                elem_valid=self.elem_valid[idx])
        if self.is_string:
            return DeviceColumn(self.dtype, self.validity[idx],
                                chars=self.chars[idx],
                                lengths=self.lengths[idx])
        if self.is_array:
            return DeviceColumn(self.dtype, self.validity[idx],
                                data=self.data[idx],
                                lengths=self.lengths[idx],
                                elem_valid=self.elem_valid[idx])
        if self.is_struct:
            return DeviceColumn(
                self.dtype, self.validity[idx],
                lengths=None if self.lengths is None
                else self.lengths[idx],
                children=tuple(c.gather(idx) for c in self.children))
        return DeviceColumn(self.dtype, self.validity[idx],
                            data=self.data[idx])

    # -- constructors -------------------------------------------------------
    @staticmethod
    def _padded_host(h: "HostColumn", capacity: Optional[int] = None,
                     width_buckets: Sequence[int] = DEFAULT_WIDTH_BUCKETS,
                     row_buckets: Sequence[int] = DEFAULT_ROW_BUCKETS
                     ) -> "DeviceColumn":
        """Padded column with NUMPY leaves (no transfer yet).

        DeviceColumn is a registered pytree, so the result can be
        device_put as part of a larger structure — that is how
        ``ColumnarBatch.from_host_columns`` folds a whole batch's
        columns into ONE multi-array transfer instead of paying a
        dispatch per buffer per column (ISSUE 6 satellite)."""
        n = h.num_rows
        cap = capacity or round_up_bucket(max(n, 1), row_buckets)
        validity = np.zeros(cap, dtype=np.bool_)
        validity[:n] = h.validity[:n]
        if h.is_string_array:
            ew = h.chars.shape[1]
            w = h.chars.shape[2]
            chars = np.zeros((cap, max(ew, 1), max(w, 1)), np.uint8)
            chars[:n, :ew, :w] = h.chars[:n]
            elens = np.zeros((cap, max(ew, 1)), np.int32)
            elens[:n, :ew] = h.data[:n]
            ev = np.zeros((cap, max(ew, 1)), np.bool_)
            ev[:n, :ew] = h.elem_valid[:n]
            lengths = np.zeros(cap, np.int32)
            lengths[:n] = h.lengths[:n]
            return DeviceColumn(dtype=h.dtype, validity=validity,
                                chars=chars, data=elens, lengths=lengths,
                                elem_valid=ev)
        if h.is_string:
            max_len = int(h.lengths[:n].max()) if n else 0
            width = round_up_bucket(max(max_len, 1), width_buckets)
            chars = np.zeros((cap, width), dtype=np.uint8)
            chars[:n, : h.chars.shape[1]] = h.chars[:n, :min(width, h.chars.shape[1])]
            lengths = np.zeros(cap, dtype=np.int32)
            lengths[:n] = h.lengths[:n]
            return DeviceColumn(dtype=h.dtype, validity=validity,
                                chars=chars, lengths=lengths)
        if h.is_array:
            max_len = int(h.lengths[:n].max()) if n else 0
            width = round_up_bucket(max(max_len, 1), width_buckets)
            data = np.zeros((cap, width), dtype=h.data.dtype)
            ev = np.zeros((cap, width), dtype=np.bool_)
            w0 = min(width, h.data.shape[1])
            data[:n, :w0] = h.data[:n, :w0]
            ev[:n, :w0] = h.elem_valid[:n, :w0]
            lengths = np.zeros(cap, dtype=np.int32)
            lengths[:n] = h.lengths[:n]
            return DeviceColumn(dtype=h.dtype, validity=validity,
                                data=data, lengths=lengths, elem_valid=ev)
        if h.is_struct:
            kids = tuple(DeviceColumn._padded_host(
                c, capacity=cap, width_buckets=width_buckets,
                row_buckets=row_buckets) for c in h.children)
            lengths = None
            if h.lengths is not None:      # entries layout (array<struct>)
                lengths = np.zeros(cap, np.int32)
                lengths[:n] = h.lengths[:n]
            return DeviceColumn(dtype=h.dtype, validity=validity,
                                lengths=lengths, children=kids)
        data = np.zeros((cap,) + h.data.shape[1:], dtype=h.data.dtype)
        data[:n] = h.data[:n]
        return DeviceColumn(dtype=h.dtype, validity=validity, data=data)

    @staticmethod
    def from_host(h: "HostColumn", capacity: Optional[int] = None,
                  width_buckets: Sequence[int] = DEFAULT_WIDTH_BUCKETS,
                  row_buckets: Sequence[int] = DEFAULT_ROW_BUCKETS) -> "DeviceColumn":
        import jax as _jax

        from spark_rapids_tpu.perfcounters import count_h2d

        padded = DeviceColumn._padded_host(h, capacity, width_buckets,
                                           row_buckets)
        # bytes_h2d counts what actually crosses the link (the PADDED
        # buffers); the useful decoded size rides in bytes_h2d_logical
        count_h2d(_np_tree_bytes(padded), logical=h.nbytes())
        return _jax.device_put(padded)

    def to_host(self, num_rows: int) -> "HostColumn":
        validity = np.asarray(self.validity)[:num_rows]
        if self.is_string_array:
            return HostColumn(dtype=self.dtype, validity=validity,
                              chars=np.asarray(self.chars)[:num_rows],
                              data=np.asarray(self.data)[:num_rows],
                              lengths=np.asarray(self.lengths)[:num_rows],
                              elem_valid=np.asarray(
                                  self.elem_valid)[:num_rows])
        if self.is_string:
            return HostColumn(dtype=self.dtype, validity=validity,
                              chars=np.asarray(self.chars)[:num_rows],
                              lengths=np.asarray(self.lengths)[:num_rows])
        if self.is_array:
            return HostColumn(dtype=self.dtype, validity=validity,
                              data=np.asarray(self.data)[:num_rows],
                              lengths=np.asarray(self.lengths)[:num_rows],
                              elem_valid=np.asarray(self.elem_valid)[:num_rows])
        if self.is_struct:
            # entries layout (array<struct>): ArrayType with per-field
            # array-column children sharing ``lengths``
            return HostColumn(
                dtype=self.dtype, validity=validity,
                lengths=None if self.lengths is None
                else np.asarray(self.lengths)[:num_rows],
                children=[c.to_host(num_rows) for c in self.children])
        return HostColumn(dtype=self.dtype, validity=validity,
                          data=np.asarray(self.data)[:num_rows])

    def slice_to(self, capacity: int) -> "DeviceColumn":
        """Re-pad (grow or shrink capacity); keeps device residency."""
        if capacity == self.capacity:
            return self
        if capacity < self.capacity:
            if self.is_string_array:
                return DeviceColumn(self.dtype, self.validity[:capacity],
                                    chars=self.chars[:capacity],
                                    data=self.data[:capacity],
                                    lengths=self.lengths[:capacity],
                                    elem_valid=self.elem_valid[:capacity])
            if self.is_string:
                return DeviceColumn(self.dtype, self.validity[:capacity],
                                    chars=self.chars[:capacity],
                                    lengths=self.lengths[:capacity])
            if self.is_array:
                return DeviceColumn(self.dtype, self.validity[:capacity],
                                    data=self.data[:capacity],
                                    lengths=self.lengths[:capacity],
                                    elem_valid=self.elem_valid[:capacity])
            if self.is_struct:
                return DeviceColumn(
                    self.dtype, self.validity[:capacity],
                    lengths=None if self.lengths is None
                    else self.lengths[:capacity],
                    children=tuple(c.slice_to(capacity)
                                   for c in self.children))
            return DeviceColumn(self.dtype, self.validity[:capacity],
                                data=self.data[:capacity])
        pad = capacity - self.capacity
        validity = jnp.concatenate([self.validity, jnp.zeros(pad, jnp.bool_)])
        if self.is_string_array:
            return DeviceColumn(
                self.dtype, validity,
                chars=jnp.concatenate(
                    [self.chars,
                     jnp.zeros((pad,) + self.chars.shape[1:], jnp.uint8)]),
                data=jnp.concatenate(
                    [self.data, jnp.zeros((pad, self.ewidth), jnp.int32)]),
                lengths=jnp.concatenate(
                    [self.lengths, jnp.zeros(pad, jnp.int32)]),
                elem_valid=jnp.concatenate(
                    [self.elem_valid,
                     jnp.zeros((pad, self.ewidth), jnp.bool_)]))
        if self.is_string:
            return DeviceColumn(
                self.dtype, validity,
                chars=jnp.concatenate(
                    [self.chars, jnp.zeros((pad, self.width), jnp.uint8)]),
                lengths=jnp.concatenate(
                    [self.lengths, jnp.zeros(pad, jnp.int32)]))
        if self.is_array:
            return DeviceColumn(
                self.dtype, validity,
                data=jnp.concatenate(
                    [self.data,
                     jnp.zeros((pad, self.ewidth), self.data.dtype)]),
                lengths=jnp.concatenate(
                    [self.lengths, jnp.zeros(pad, jnp.int32)]),
                elem_valid=jnp.concatenate(
                    [self.elem_valid,
                     jnp.zeros((pad, self.ewidth), jnp.bool_)]))
        if self.is_struct:
            return DeviceColumn(
                self.dtype, validity,
                lengths=None if self.lengths is None
                else jnp.concatenate(
                    [self.lengths, jnp.zeros(pad, jnp.int32)]),
                children=tuple(c.slice_to(capacity) for c in self.children))
        return DeviceColumn(
            self.dtype, validity,
            data=jnp.concatenate(
                [self.data,
                 jnp.zeros((pad,) + self.data.shape[1:], self.data.dtype)]))


@dataclasses.dataclass
class HostColumn:
    """Host-side column (numpy), the RapidsHostColumnVector analog.

    Also the interchange point with pyarrow and with the CPU oracle.
    """

    dtype: T.DataType
    validity: np.ndarray
    data: Optional[np.ndarray] = None
    chars: Optional[np.ndarray] = None     # (n, width) uint8
    lengths: Optional[np.ndarray] = None   # (n,) int32
    elem_valid: Optional[np.ndarray] = None  # (n, ewidth) bool (arrays)
    children: Optional[List["HostColumn"]] = None  # structs

    def nbytes(self) -> int:
        n = self.validity.nbytes
        for buf in (self.data, self.chars, self.lengths, self.elem_valid):
            if buf is not None:
                n += buf.nbytes
        if self.children is not None:
            n += sum(c.nbytes() for c in self.children)
        return int(n)

    @property
    def is_string(self) -> bool:
        return self.chars is not None and self.chars.ndim == 2

    @property
    def is_array(self) -> bool:
        return self.elem_valid is not None and self.chars is None

    @property
    def is_string_array(self) -> bool:
        return self.chars is not None and self.chars.ndim == 3

    @property
    def is_struct(self) -> bool:
        return self.children is not None

    @property
    def num_rows(self) -> int:
        return int(self.validity.shape[0])

    def slice_rows(self, start: int, end: int) -> "HostColumn":
        """Row range view (all column kinds)."""
        if self.is_string_array:
            return HostColumn(self.dtype, self.validity[start:end],
                              chars=self.chars[start:end],
                              data=self.data[start:end],
                              lengths=self.lengths[start:end],
                              elem_valid=self.elem_valid[start:end])
        if self.is_string:
            return HostColumn(self.dtype, self.validity[start:end],
                              chars=self.chars[start:end],
                              lengths=self.lengths[start:end])
        if self.is_array:
            return HostColumn(self.dtype, self.validity[start:end],
                              data=self.data[start:end],
                              lengths=self.lengths[start:end],
                              elem_valid=self.elem_valid[start:end])
        if self.is_struct:
            return HostColumn(self.dtype, self.validity[start:end],
                              children=[c.slice_rows(start, end)
                                        for c in self.children])
        return HostColumn(self.dtype, self.validity[start:end],
                          data=self.data[start:end])

    # -- python interchange -------------------------------------------------
    @staticmethod
    def from_pylist(values: List, dtype: T.DataType) -> "HostColumn":
        n = len(values)
        validity = np.array([v is not None for v in values], dtype=np.bool_)
        if isinstance(dtype, T.MapType):
            # map rows are python dicts; device layout = (keys array col,
            # values array col) children sharing lengths
            keys = [list(v.keys()) if v is not None else None
                    for v in values]
            vals = [list(v.values()) if v is not None else None
                    for v in values]
            kcol = HostColumn.from_pylist(
                keys, T.ArrayType(dtype.keyType, containsNull=False))
            vcol = HostColumn.from_pylist(
                vals, T.ArrayType(dtype.valueType))
            return HostColumn(dtype, validity, children=[kcol, vcol])
        if isinstance(dtype, T.StructType):
            # rows are dicts (by field name) or sequences (by position);
            # null rows become all-null children (Spark reads null.field
            # as null)
            kids = []
            for fi, f in enumerate(dtype.fields):
                fv = []
                for v in values:
                    if v is None:
                        fv.append(None)
                    elif isinstance(v, dict):
                        fv.append(v.get(f.name))
                    else:
                        fv.append(v[fi])
                kids.append(HostColumn.from_pylist(fv, f.dataType))
            return HostColumn(dtype, validity, children=kids)
        if isinstance(dtype, T.ArrayType) and isinstance(
                dtype.elementType, T.StringType):
            # array<string>: 3-D padded char tensor
            ew = max((len(v) for v in values if v is not None),
                     default=1) or 1
            encoded = [[e.encode("utf-8") if e is not None else None
                        for e in v] if v is not None else None
                       for v in values]
            w = max((len(b) for row in encoded if row is not None
                     for b in row if b is not None), default=1) or 1
            chars = np.zeros((n, ew, w), np.uint8)
            elens = np.zeros((n, ew), np.int32)
            ev = np.zeros((n, ew), np.bool_)
            lengths = np.zeros(n, np.int32)
            for i, row in enumerate(encoded):
                if row is None:
                    continue
                lengths[i] = len(row)
                for j, b in enumerate(row):
                    if b is None:
                        continue
                    ev[i, j] = True
                    elens[i, j] = len(b)
                    chars[i, j, :len(b)] = np.frombuffer(b, np.uint8)
            return HostColumn(dtype, validity, chars=chars, data=elens,
                              lengths=lengths, elem_valid=ev)
        if isinstance(dtype, T.ArrayType) and isinstance(
                dtype.elementType, T.StructType):
            # entries layout: decompose rows of [{f1,f2}|tuple, ...] into
            # one ARRAY child per struct field sharing ``lengths``
            et = dtype.elementType
            lengths = np.zeros(n, np.int32)
            for i, v in enumerate(values):
                if v is not None:
                    lengths[i] = len(v)
            kids = []
            for fi, f in enumerate(et.fields):
                rows = []
                for v in values:
                    if v is None:
                        rows.append(None)
                        continue
                    fr = []
                    for e in v:
                        if e is None:
                            fr.append(None)
                        elif isinstance(e, dict):
                            fr.append(e.get(f.name))
                        else:
                            fr.append(e[fi])
                    rows.append(fr)
                kids.append(HostColumn.from_pylist(
                    rows, T.ArrayType(f.dataType)))
            return HostColumn(dtype, validity, lengths=lengths,
                              children=kids)
        if isinstance(dtype, T.ArrayType):
            elem_host = HostColumn.from_pylist(
                [e for v in values if v is not None for e in v],
                dtype.elementType)
            width = max((len(v) for v in values if v is not None),
                        default=1) or 1
            sdt = elem_host.data.dtype if elem_host.data is not None else None
            if sdt is None:
                raise NotImplementedError(
                    "nested array elements are not supported yet")
            data = np.zeros((n, width), dtype=sdt)
            ev = np.zeros((n, width), np.bool_)
            lengths = np.zeros(n, np.int32)
            pos = 0
            for i, v in enumerate(values):
                if v is None:
                    continue
                ln = len(v)
                lengths[i] = ln
                data[i, :ln] = elem_host.data[pos:pos + ln]
                ev[i, :ln] = elem_host.validity[pos:pos + ln]
                pos += ln
            return HostColumn(dtype, validity, data=data, lengths=lengths,
                              elem_valid=ev)
        if isinstance(dtype, T.StringType):
            encoded = [v.encode("utf-8") if v is not None else b"" for v in values]
            width = max((len(b) for b in encoded), default=1) or 1
            chars = np.zeros((n, width), dtype=np.uint8)
            lengths = np.zeros(n, dtype=np.int32)
            for i, b in enumerate(encoded):
                chars[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
                lengths[i] = len(b)
            return HostColumn(dtype, validity, chars=chars, lengths=lengths)
        sdt = T.storage_dtype(dtype)
        if isinstance(dtype, T.DecimalType) and dtype.is_128:
            from decimal import Decimal

            from spark_rapids_tpu.expr.decimal128 import limbs_of

            data = np.zeros((n, 2), dtype=np.int64)
            for i, v in enumerate(values):
                if v is not None:
                    d = Decimal(str(v)).scaleb(dtype.scale)
                    hi, lo = limbs_of(int(d.to_integral_value()))
                    data[i, 0] = hi
                    data[i, 1] = lo
            return HostColumn(dtype, validity, data=data)
        data = np.zeros(n, dtype=sdt)
        for i, v in enumerate(values):
            if v is not None:
                if isinstance(dtype, T.DecimalType):
                    # accept python Decimal/int/float as scaled value
                    from decimal import Decimal

                    d = Decimal(str(v)).scaleb(dtype.scale)
                    data[i] = int(d.to_integral_value())
                elif isinstance(dtype, T.BooleanType):
                    data[i] = bool(v)
                elif isinstance(dtype, T.DateType):
                    import datetime as _dt

                    data[i] = (v - _dt.date(1970, 1, 1)).days if isinstance(
                        v, _dt.date) else v
                elif isinstance(dtype, T.TimestampType):
                    import datetime as _dt

                    if isinstance(v, _dt.datetime):
                        epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
                        vv = v if v.tzinfo else v.replace(tzinfo=_dt.timezone.utc)
                        data[i] = int((vv - epoch).total_seconds() * 1_000_000)
                    else:
                        data[i] = v
                else:
                    data[i] = v
        return HostColumn(dtype, validity, data=data)

    def to_pylist(self) -> List:
        if self.is_string_array:
            out = []
            for i in range(self.num_rows):
                if not self.validity[i]:
                    out.append(None)
                    continue
                ln = int(self.lengths[i])
                row = []
                for j in range(ln):
                    if not self.elem_valid[i, j]:
                        row.append(None)
                    else:
                        row.append(bytes(
                            self.chars[i, j, :self.data[i, j]]).decode(
                            "utf-8", "replace"))
                out.append(row)
            return out
        if isinstance(self.dtype, T.MapType):
            keys = self.children[0].to_pylist()
            vals = self.children[1].to_pylist()
            return [dict(zip(keys[i], vals[i])) if self.validity[i]
                    else None for i in range(self.num_rows)]
        if self.is_struct:
            if isinstance(self.dtype, T.ArrayType):
                # entries layout: children are per-field ARRAY columns
                kid_rows = [c.to_pylist() for c in self.children]
                out = []
                for i in range(self.num_rows):
                    if not self.validity[i]:
                        out.append(None)
                        continue
                    ln = int(self.lengths[i])
                    out.append([
                        tuple((kr[i][j] if kr[i] is not None
                               and j < len(kr[i]) else None)
                              for kr in kid_rows)
                        for j in range(ln)])
                return out
            kid_vals = [c.to_pylist() for c in self.children]
            return [tuple(kv[i] for kv in kid_vals) if self.validity[i]
                    else None for i in range(self.num_rows)]
        if self.is_array:
            elem_t = self.dtype.elementType
            out = []
            for i in range(self.num_rows):
                if not self.validity[i]:
                    out.append(None)
                    continue
                ln = int(self.lengths[i])
                row = HostColumn(elem_t, self.elem_valid[i, :ln],
                                 data=self.data[i, :ln])
                out.append(row.to_pylist())
            return out
        out: List = []
        for i in range(self.num_rows):
            if not self.validity[i]:
                out.append(None)
            elif self.is_string:
                ln = int(self.lengths[i])
                out.append(bytes(self.chars[i, :ln]).decode("utf-8", "replace"))
            elif isinstance(self.dtype, T.DecimalType):
                from decimal import Decimal

                if self.dtype.is_128:
                    from spark_rapids_tpu.expr.decimal128 import to_py

                    v = to_py(int(self.data[i, 0]), int(self.data[i, 1]))
                    out.append(Decimal(v).scaleb(-self.dtype.scale))
                else:
                    out.append(
                        Decimal(int(self.data[i])).scaleb(-self.dtype.scale))
            elif isinstance(self.dtype, T.BooleanType):
                out.append(bool(self.data[i]))
            elif isinstance(self.dtype, (T.FloatType, T.DoubleType)):
                out.append(float(self.data[i]))
            elif isinstance(self.dtype, T.DateType):
                import datetime as _dt

                out.append(_dt.date(1970, 1, 1) + _dt.timedelta(days=int(self.data[i])))
            elif isinstance(self.dtype, T.TimestampType):
                import datetime as _dt

                out.append(_dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
                           + _dt.timedelta(microseconds=int(self.data[i])))
            else:
                out.append(int(self.data[i]))
        return out

    @staticmethod
    def from_numpy(arr: np.ndarray, dtype: T.DataType,
                   validity: Optional[np.ndarray] = None) -> "HostColumn":
        v = validity if validity is not None else np.ones(len(arr), np.bool_)
        return HostColumn(dtype, v, data=np.ascontiguousarray(arr))

    @staticmethod
    def from_strings(strs: List[Optional[str]]) -> "HostColumn":
        return HostColumn.from_pylist(strs, T.STRING)

    # -- pyarrow interchange (used by the IO layer) -------------------------
    @staticmethod
    def from_arrow(arr, dtype: T.DataType) -> "HostColumn":
        import pyarrow as pa

        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        n = len(arr)
        validity = np.asarray(arr.is_valid())
        if isinstance(dtype, T.StructType):
            kids = [HostColumn.from_arrow(arr.field(f.name), f.dataType)
                    for f in dtype.fields]
            return HostColumn(dtype, validity, children=kids)
        if isinstance(dtype, T.MapType):
            # pyarrow MapArray.to_pylist yields [(k, v), ...] pairs
            rows = arr.to_pylist()
            return HostColumn.from_pylist(
                [dict(v) if v is not None else None for v in rows], dtype)
        if isinstance(dtype, T.ArrayType):
            # list columns come through the python interchange (scan
            # formats with nested data: parquet lists, avro arrays)
            return HostColumn.from_pylist(arr.to_pylist(), dtype)
        if isinstance(dtype, T.StringType):
            arr = arr.cast(pa.large_binary()) if not pa.types.is_large_binary(arr.type) else arr
            buf = np.frombuffer(arr.buffers()[2] or b"", dtype=np.uint8)
            offs = np.frombuffer(arr.buffers()[1], dtype=np.int64)[arr.offset: arr.offset + n + 1]
            lengths = (offs[1:] - offs[:-1]).astype(np.int32)
            width = int(lengths.max()) if n and lengths.size else 1
            width = max(width, 1)
            from spark_rapids_tpu.native import ragged_to_padded

            chars = ragged_to_padded(buf, offs, width)
            return HostColumn(dtype, validity, chars=chars, lengths=lengths)
        sdt = T.storage_dtype(dtype)
        if isinstance(dtype, T.DecimalType):
            # arrow decimal128 storage is 16-byte little-endian (lo, hi)
            arr2 = arr.cast(pa.decimal128(38, dtype.scale)) \
                if arr.type.scale != dtype.scale else arr
            buf = arr2.buffers()[1]
            raw = np.frombuffer(buf, dtype=np.int64)
            lo = raw[0::2][arr2.offset: arr2.offset + n]
            if dtype.is_128:
                hi = raw[1::2][arr2.offset: arr2.offset + n]
                limbs = np.zeros((n, 2), np.int64)
                limbs[:, 0] = np.where(validity, hi, 0)
                limbs[:, 1] = np.where(validity, lo, 0)
                return HostColumn(dtype, validity, data=limbs)
            # precision<=18: the signed low word IS the unscaled value
            np_arr = np.where(validity, lo, 0)
        else:
            if isinstance(dtype, T.TimestampType) and pa.types.is_timestamp(
                    arr.type) and arr.type.unit != "us":
                arr = arr.cast(pa.timestamp("us", tz=arr.type.tz))
            fill = False if pa.types.is_boolean(arr.type) else 0
            np_arr = np.asarray(arr.fill_null(fill)).astype(sdt, copy=False)
        return HostColumn(dtype, validity, data=np_arr)

    def to_arrow(self):
        import pyarrow as pa

        mask = ~self.validity
        if isinstance(self.dtype, T.MapType):
            # dict inference would require string keys; build the MapArray
            # as [(k, v), ...] item lists instead
            rows = self.to_pylist()
            items = [list(d.items()) if d is not None else None
                     for d in rows]
            return pa.array(items, type=pa.map_(
                self.children[0].to_arrow().type.value_type,
                self.children[1].to_arrow().type.value_type))
        if self.is_array:
            return pa.array(self.to_pylist())
        if self.is_struct:
            kid_arrays = [c.to_arrow() for c in self.children]
            fields = [pa.field(f.name, a.type) for f, a in
                      zip(self.dtype.fields, kid_arrays)]
            return pa.StructArray.from_arrays(
                kid_arrays, fields=fields,
                mask=pa.array(mask) if mask.any() else None)
        if self.is_string:
            return pa.array(self.to_pylist(), type=pa.string())
        if isinstance(self.dtype, T.DecimalType):
            from decimal import Decimal

            if self.dtype.is_128:
                from spark_rapids_tpu.expr.decimal128 import to_py

                vals = [Decimal(to_py(int(self.data[i, 0]),
                                      int(self.data[i, 1])))
                        .scaleb(-self.dtype.scale)
                        if self.validity[i] else None
                        for i in range(self.num_rows)]
            else:
                vals = [Decimal(int(self.data[i])).scaleb(-self.dtype.scale)
                        if self.validity[i] else None
                        for i in range(self.num_rows)]
            return pa.array(vals, type=pa.decimal128(
                self.dtype.precision, self.dtype.scale))
        if isinstance(self.dtype, T.DateType):
            return pa.array(np.ma.masked_array(self.data, mask)).cast(pa.date32())
        if isinstance(self.dtype, T.TimestampType):
            return pa.array(np.ma.masked_array(self.data, mask)).cast(
                pa.timestamp("us", tz="UTC"))
        return pa.array(np.ma.masked_array(self.data, mask))
