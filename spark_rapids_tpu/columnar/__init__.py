from spark_rapids_tpu.columnar.column import (  # noqa: F401
    DeviceColumn,
    HostColumn,
    round_up_bucket,
)
from spark_rapids_tpu.columnar.batch import ColumnarBatch  # noqa: F401
