"""UDF compiler — translate simple python functions into expressions.

Reference analog: the udf-compiler module (SURVEY.md §2.8):
CatalystExpressionBuilder decompiles Scala UDF BYTECODE (javassist) into
Catalyst expressions so the rewritten query runs fully on device.

Python needs no decompiler: expressions already overload the arithmetic /
comparison / logical operators, so the function is compiled by CALLING it
with symbolic arguments (the expression nodes themselves) and capturing
the tree it builds — operator-overload tracing.  Functions that branch on
data (`if x > 0:`) or call unsupported libraries raise during tracing and
keep the arrow-eval python path instead; ``F``-namespace helpers cover the
common non-operator calls (sqrt/abs/when...).
"""
from __future__ import annotations

from typing import Callable, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.base import Expression, Literal, lit


class UDFTraceError(TypeError):
    """The function's result depends on python control flow over data."""


class _Sym:
    """Symbolic argument: overloads operators, FORBIDS data-dependent
    python control flow (bool/len/iter raise, unlike raw Expressions,
    which are always truthy and would silently mistrace `if x > 0:`)."""

    __slots__ = ("e",)

    def __init__(self, e: Expression):
        self.e = e

    def __bool__(self):
        raise UDFTraceError("data-dependent branch (if/while/and/or)")

    def __len__(self):
        raise UDFTraceError("len() over a column")

    def __iter__(self):
        raise UDFTraceError("iteration over a column")

    def __index__(self):
        raise UDFTraceError("indexing with a column")

    def __float__(self):
        raise UDFTraceError("float() over a column")

    def __int__(self):
        raise UDFTraceError("int() over a column")

    def _bin(self, other, cls, swap=False):
        l, r = self.e, _as_expr(other)
        if swap:
            l, r = r, l
        return _Sym(cls(l, r))

    def __add__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Add

        return self._bin(o, Add)

    def __radd__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Add

        return self._bin(o, Add, swap=True)

    def __sub__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Subtract

        return self._bin(o, Subtract)

    def __rsub__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Subtract

        return self._bin(o, Subtract, swap=True)

    def __mul__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Multiply

        return self._bin(o, Multiply)

    def __rmul__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Multiply

        return self._bin(o, Multiply, swap=True)

    def __truediv__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Divide

        return self._bin(o, Divide)

    def __rtruediv__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Divide

        return self._bin(o, Divide, swap=True)

    def __mod__(self, o):
        # python % follows the divisor's sign == SQL pmod, NOT Remainder
        from spark_rapids_tpu.expr.arithmetic import Pmod

        return self._bin(o, Pmod)

    def __pow__(self, o):
        from spark_rapids_tpu.expr.mathfuncs import Pow

        return self._bin(o, Pow)

    def __neg__(self):
        from spark_rapids_tpu.expr.arithmetic import UnaryMinus

        return _Sym(UnaryMinus(self.e))

    def __abs__(self):
        from spark_rapids_tpu.expr.arithmetic import Abs

        return _Sym(Abs(self.e))

    def __lt__(self, o):
        from spark_rapids_tpu.expr.predicates import LessThan

        return self._bin(o, LessThan)

    def __le__(self, o):
        from spark_rapids_tpu.expr.predicates import LessThanOrEqual

        return self._bin(o, LessThanOrEqual)

    def __gt__(self, o):
        from spark_rapids_tpu.expr.predicates import GreaterThan

        return self._bin(o, GreaterThan)

    def __ge__(self, o):
        from spark_rapids_tpu.expr.predicates import GreaterThanOrEqual

        return self._bin(o, GreaterThanOrEqual)

    def __eq__(self, o):  # noqa: A003 - symbolic equality
        from spark_rapids_tpu.expr.predicates import EqualTo

        return self._bin(o, EqualTo)

    def __ne__(self, o):
        from spark_rapids_tpu.expr.predicates import EqualTo, Not

        return _Sym(Not(EqualTo(self.e, _as_expr(o))))

    def __hash__(self):
        return id(self)

    def __and__(self, o):
        from spark_rapids_tpu.expr.predicates import And

        return self._bin(o, And)

    def __or__(self, o):
        from spark_rapids_tpu.expr.predicates import Or

        return self._bin(o, Or)

    def __invert__(self):
        from spark_rapids_tpu.expr.predicates import Not

        return _Sym(Not(self.e))


class _F:
    """Function namespace usable inside compiled UDFs (F.sqrt(x)...).

    Dual-mode: symbolic arguments build expressions (the compile trace);
    plain scalars compute with python math (so the SAME function body
    also runs row-based on the oracle / arrow-eval path)."""

    @staticmethod
    def _sym(v):
        return isinstance(v, (_Sym, Expression))

    @staticmethod
    def sqrt(x):
        if _F._sym(x):
            from spark_rapids_tpu.expr.mathfuncs import Sqrt

            return _Sym(Sqrt(_as_expr(x)))
        import math

        return None if x is None else (
            math.sqrt(x) if x >= 0 else float("nan"))

    @staticmethod
    def abs(x):
        if _F._sym(x):
            from spark_rapids_tpu.expr.arithmetic import Abs

            return _Sym(Abs(_as_expr(x)))
        return None if x is None else abs(x)

    @staticmethod
    def log(x):
        if _F._sym(x):
            from spark_rapids_tpu.expr.mathfuncs import Log

            return _Sym(Log(_as_expr(x)))
        import math

        return None if x is None or x <= 0 else math.log(x)

    @staticmethod
    def exp(x):
        if _F._sym(x):
            from spark_rapids_tpu.expr.mathfuncs import Exp

            return _Sym(Exp(_as_expr(x)))
        import math

        return None if x is None else math.exp(x)

    @staticmethod
    def when(cond, value, otherwise):
        if _F._sym(cond):
            from spark_rapids_tpu.expr.conditional import If

            return _Sym(If(_as_expr(cond), _as_expr(value),
                           _as_expr(otherwise)))
        return value if cond else otherwise

    @staticmethod
    def upper(x):
        if _F._sym(x):
            from spark_rapids_tpu.expr.strings import Upper

            return _Sym(Upper(_as_expr(x)))
        return None if x is None else x.upper()

    @staticmethod
    def lower(x):
        if _F._sym(x):
            from spark_rapids_tpu.expr.strings import Lower

            return _Sym(Lower(_as_expr(x)))
        return None if x is None else x.lower()

    @staticmethod
    def length(x):
        if _F._sym(x):
            from spark_rapids_tpu.expr.strings import Length

            return _Sym(Length(_as_expr(x)))
        return None if x is None else len(x)

    @staticmethod
    def concat(*xs):
        if any(_F._sym(x) for x in xs):
            from spark_rapids_tpu.expr.strings import Concat

            return _Sym(Concat([_as_expr(x) for x in xs]))
        if any(x is None for x in xs):
            return None
        return "".join(xs)


F = _F()


def _as_expr(v) -> Expression:
    if isinstance(v, _Sym):
        return v.e
    return v if isinstance(v, Expression) else Literal.of(v)


_UNSAFE_OPS = {"IS_OP", "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE",
               "CONTAINS_OP"}


def _trace_safe(fn) -> bool:
    """Identity/None tests trace unsoundly (`a is None` is always False
    over a symbolic argument, silently folding the null branch away), so
    any function using them keeps the python path."""
    import dis

    try:
        return not any(ins.opname in _UNSAFE_OPS
                       for ins in dis.get_instructions(fn))
    except TypeError:
        return False


def compile_udf(fn: Callable, args) -> Optional[Expression]:
    """Trace fn over symbolic arguments; None if untranslatable."""
    if not _trace_safe(fn):
        return None
    sym_args = [_Sym(a) for a in args]
    try:
        result = fn(*sym_args, F) if _wants_namespace(fn) \
            else fn(*sym_args)
    except Exception:
        return None
    if isinstance(result, _Sym):
        return result.e
    if isinstance(result, Expression):
        return result
    try:
        return Literal.of(result)
    except TypeError:
        return None


def _wants_namespace(fn) -> bool:
    try:
        import inspect

        params = inspect.signature(fn).parameters
        return len(params) > 0 and list(params)[-1] in ("F", "functions")
    except (TypeError, ValueError):
        return False


def try_compile(fn: Callable, children, conf_settings=None):
    """Plan-time entry: expression tree or None.

    The result still re-resolves against the child schema downstream, so
    types line up exactly as if the user had written the expression."""
    if conf_settings is not None:
        from spark_rapids_tpu.config import UDF_COMPILER_ENABLED

        if not UDF_COMPILER_ENABLED.get(conf_settings):
            return None
    return compile_udf(fn, list(children))
