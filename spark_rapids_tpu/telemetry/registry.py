"""Time-series metrics registry — gauges, counters, histograms with
bounded ring-buffer retention.

Reference analog: the scheduler/data-movement telemetry Theseus
(arXiv:2508.05029) treats as the substrate an accelerated SQL service is
operated on, and the metrics surface Presto's accelerator integration
exports to its fleet dashboards (arXiv:2606.24647).  The registry is
deliberately dependency-free (no prometheus_client): series live in
plain dicts, each keeping a bounded ring of ``(unix_ts, value)`` samples
(``spark.rapids.tpu.telemetry.retention`` points) so a long-running
process holds a sliding window, never an unbounded history.

Three series kinds:

* **gauge**   — instantaneous level (queue depth, HBM bytes in use);
  each sample overwrites "current" and appends to the ring.
* **counter** — monotonic cumulative count mirrored from
  ``perfcounters`` (bytes moved, cache hits); consumers diff samples
  for rates.
* **histogram** — fixed-bucket latency distribution with per-label
  (plan-signature) sub-series; p50/p95 are estimated by linear
  interpolation inside the winning bucket, which is exact enough for
  SLO tracking and requires no per-observation storage.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

# latency histogram upper bounds, milliseconds (the +Inf bucket is
# implicit); spans sub-ms cached-plan replays through minute-long
# tunnel compiles
DEFAULT_LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0, 300000.0)


class Series:
    """One gauge/counter time series with a bounded sample ring.

    ``labels`` (ISSUE 15): an optional sorted tuple of ``(key, value)``
    pairs — per-worker federated series (``worker="w0"``) export as
    Prometheus-labeled samples of one family instead of name-mangled
    singletons, so a fleet dashboard can aggregate across workers."""

    __slots__ = ("name", "kind", "help", "value", "ring", "labels")

    def __init__(self, name: str, kind: str, help_: str, retention: int,
                 labels: Optional[Tuple[Tuple[str, str], ...]] = None):
        self.name = name
        self.kind = kind            # "gauge" | "counter"
        self.help = help_
        self.value: float = 0.0
        self.ring: deque = deque(maxlen=max(int(retention), 1))
        self.labels = labels

    def record(self, value: float, ts: Optional[float] = None) -> None:
        self.value = float(value)
        self.ring.append((ts if ts is not None else time.time(),
                          float(value)))


class _HistShard:
    """Per-label bucket counts for one histogram."""

    __slots__ = ("counts", "sum", "count", "max")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)   # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self.max = 0.0


class Histogram:
    """Fixed-bucket histogram with optional per-label sub-series (the
    label is the plan signature for query-latency SLOs).  Thread-safe on
    its own leaf lock: observers (collect exits) and readers (SLO
    summaries, Prometheus scrapes) arrive under DIFFERENT outer locks,
    and a scrape must never see a shard whose bucket cumsum disagrees
    with its count."""

    def __init__(self, name: str, help_: str,
                 buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_MS,
                 label_name: str = ""):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self.label_name = label_name
        self._lock = threading.Lock()
        self._shards: Dict[str, _HistShard] = {}

    def observe(self, value: float, label: str = "") -> None:
        with self._lock:
            sh = self._shards.get(label)
            if sh is None:
                sh = self._shards[label] = _HistShard(len(self.buckets))
            i = 0
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    break
            else:
                i = len(self.buckets)
            sh.counts[i] += 1
            sh.sum += value
            sh.count += 1
            if value > sh.max:
                sh.max = value

    def labels(self) -> List[str]:
        with self._lock:
            return list(self._shards)

    def snapshot_shards(self) -> Dict[str, Dict[str, object]]:
        """Consistent per-label copies for the exporter: counts list,
        sum, count, max captured under one lock acquisition."""
        with self._lock:
            return {lbl: {"counts": list(sh.counts), "sum": sh.sum,
                          "count": sh.count, "max": sh.max}
                    for lbl, sh in self._shards.items()}

    def _quantile_locked(self, q: float, sh: _HistShard) -> float:
        if sh.count == 0:
            return 0.0
        target = q * sh.count
        cum = 0
        lo = 0.0
        for i, ub in enumerate(self.buckets):
            c = sh.counts[i]
            if cum + c >= target and c:
                frac = (target - cum) / c
                # clamp to the observed max: interpolation inside the
                # winning bucket must not report a latency no query had
                return min(lo + frac * (ub - lo), sh.max)
            cum += c
            lo = ub
        return sh.max                          # landed in the +Inf bucket

    def quantile(self, q: float, label: str = "") -> float:
        """Bucket-interpolated quantile estimate (0 when empty)."""
        with self._lock:
            sh = self._shards.get(label)
            return 0.0 if sh is None else self._quantile_locked(q, sh)

    def stats(self, label: str = "") -> Dict[str, float]:
        with self._lock:
            sh = self._shards.get(label)
            if sh is None:
                return {"count": 0, "sum": 0.0, "max": 0.0,
                        "p50": 0.0, "p95": 0.0}
            return {"count": sh.count, "sum": sh.sum, "max": sh.max,
                    "p50": self._quantile_locked(0.50, sh),
                    "p95": self._quantile_locked(0.95, sh)}


class MetricsRegistry:
    """Process-global registry: get-or-create series by name, record
    samples, and expose snapshots to the exporter / JSONL sink /
    timeline consumers.  All mutation is under one lock — the sampler
    ticks at 100s-of-ms cadence and observations are per-query, so
    contention is negligible."""

    def __init__(self, retention: int = 720):
        self.retention = max(int(retention), 1)
        self._lock = threading.Lock()
        self._series: Dict[str, Series] = {}
        # labeled sub-series keyed (family name, sorted label tuple) —
        # the per-worker federated metrics (ISSUE 15)
        self._labeled: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                            Series] = {}
        self._hists: Dict[str, Histogram] = {}

    # -- series ----------------------------------------------------------
    def gauge(self, name: str, help_: str = "") -> Series:
        return self._get(name, "gauge", help_)

    def counter(self, name: str, help_: str = "") -> Series:
        return self._get(name, "counter", help_)

    def _get(self, name: str, kind: str, help_: str) -> Series:
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = self._series[name] = Series(name, kind, help_,
                                                self.retention)
            return s

    def record(self, name: str, value: float, kind: str = "gauge",
               help_: str = "", ts: Optional[float] = None) -> None:
        s = self._get(name, kind, help_)
        with self._lock:
            s.record(value, ts)

    def record_many(self, kind: str, values: Dict[str, float],
                    ts: Optional[float] = None) -> None:
        """One lock acquisition for a whole sampler tick."""
        ts = ts if ts is not None else time.time()
        with self._lock:
            for name, v in values.items():
                s = self._series.get(name)
                if s is None:
                    s = self._series[name] = Series(name, kind, "",
                                                    self.retention)
                s.record(v, ts)

    def record_labeled(self, name: str, value: float,
                       labels: Dict[str, str], kind: str = "gauge",
                       ts: Optional[float] = None) -> None:
        """Record one sample of a LABELED sub-series (get-or-create).
        One family may hold many label sets; the exporter emits them as
        ``srt_<name>{k="v",...}`` samples under one TYPE header."""
        key = (name, tuple(sorted((str(k), str(v))
                                  for k, v in labels.items())))
        self.record_labeled_many(kind, {key: float(value)}, ts)

    def record_labeled_many(self, kind: str,
                            values: Dict[Tuple[str,
                                               Tuple[Tuple[str, str],
                                                     ...]], float],
                            ts: Optional[float] = None) -> None:
        """One lock acquisition for a whole sampler tick's worth of
        labeled samples (keys are (family, sorted label tuple))."""
        ts = ts if ts is not None else time.time()
        with self._lock:
            for key, v in values.items():
                s = self._labeled.get(key)
                if s is None:
                    s = self._labeled[key] = Series(
                        key[0], kind, "", self.retention, labels=key[1])
                s.record(float(v), ts)

    def labeled_items(self) -> List[Series]:
        with self._lock:
            return list(self._labeled.values())

    def histogram(self, name: str, help_: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_MS,
                  label_name: str = "") -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name, help_, buckets,
                                                  label_name)
            return h

    def observe(self, name: str, value: float, label: str = "") -> None:
        # the histogram carries its own leaf lock
        self.histogram(name).observe(value, label)

    # -- snapshots -------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict]:
        """Current values of every series (no rings) + histogram stats —
        the JSONL sink's per-tick record shape."""
        with self._lock:
            out = {"gauges": {}, "counters": {}, "histograms": {},
                   "labeled": {}}
            for s in self._series.values():
                out["gauges" if s.kind == "gauge"
                    else "counters"][s.name] = s.value
            for s in self._labeled.values():
                lbl = ",".join(f'{k}="{v}"' for k, v in (s.labels or ()))
                out["labeled"].setdefault(s.name, {})[lbl] = s.value
            for h in self._hists.values():
                out["histograms"][h.name] = {
                    (lbl or ""): h.stats(lbl) for lbl in h.labels()}
            return out

    def series_items(self) -> List[Series]:
        with self._lock:
            return list(self._series.values())

    def hist_items(self) -> List[Histogram]:
        with self._lock:
            return list(self._hists.values())
