"""Prometheus exposition-format exporter + the localhost scrape
endpoint.

``render_prometheus(hub)`` emits the text format (version 0.0.4) every
Prometheus-compatible scraper parses: gauges and counters from the
time-series registry (counters get the conventional ``_total`` suffix)
and the SLO latency histograms as ``_bucket{le=...}`` / ``_sum`` /
``_count`` families labeled by plan signature.  Metric names are
prefixed ``srt_`` and sanitized to the exposition charset; a parse test
round-trips the output through a from-scratch parser
(tests/test_telemetry.py) so the format itself is pinned, not just the
substring shapes.

``spark.rapids.tpu.telemetry.port`` > 0 binds a daemon HTTP server to
``127.0.0.1:<port>`` serving ``GET /metrics`` — localhost-only by
design: fleet exposure belongs to a real sidecar, not this library.
"""
from __future__ import annotations

import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _name(raw: str) -> str:
    return "srt_" + _NAME_RE.sub("_", raw)


def _esc_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus(hub) -> str:
    out = []
    for s in sorted(hub.registry.series_items(), key=lambda s: s.name):
        name = _name(s.name) + ("_total" if s.kind == "counter" else "")
        if s.help:
            out.append(f"# HELP {name} {s.help}")
        out.append(f"# TYPE {name} {s.kind}")
        out.append(f"{name} {_fmt(s.value)}")
    # labeled families (ISSUE 15): the per-worker federated series —
    # one TYPE header per family, then one labeled sample per label set
    by_family = {}
    for s in hub.registry.labeled_items():
        by_family.setdefault((s.name, s.kind), []).append(s)
    for (fam, kind) in sorted(by_family):
        name = _name(fam) + ("_total" if kind == "counter" else "")
        out.append(f"# TYPE {name} {kind}")
        for s in sorted(by_family[(fam, kind)],
                        key=lambda s: s.labels or ()):
            lbls = ",".join(f'{k}="{_esc_label(v)}"'
                            for k, v in (s.labels or ()))
            out.append(f"{name}{{{lbls}}} {_fmt(s.value)}")
    for h in sorted(hub.registry.hist_items(), key=lambda h: h.name):
        name = _name(h.name)
        if h.help:
            out.append(f"# HELP {name} {h.help}")
        out.append(f"# TYPE {name} histogram")
        lname = h.label_name or "label"
        # one consistent copy per histogram: a scrape racing a collect()
        # exit must never emit buckets whose cumsum disagrees with _count
        shards = h.snapshot_shards()
        for lbl in sorted(shards):
            sh = shards[lbl]
            prefix = (f'{lname}="{_esc_label(lbl)}",' if lbl else "")
            cum = 0
            for i, ub in enumerate(h.buckets):
                cum += sh["counts"][i]
                out.append(f'{name}_bucket{{{prefix}le="{_fmt(ub)}"}} '
                           f'{cum}')
            cum += sh["counts"][len(h.buckets)]
            out.append(f'{name}_bucket{{{prefix}le="+Inf"}} {cum}')
            suffix = f"{{{prefix[:-1]}}}" if prefix else ""
            out.append(f"{name}_sum{suffix} {_fmt(sh['sum'])}")
            out.append(f"{name}_count{suffix} {sh['count']}")
    return "\n".join(out) + "\n"


class _Handler(BaseHTTPRequestHandler):
    hub = None                      # set per server class below

    def do_GET(self):               # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/progress":
            # live per-query progress JSON (ISSUE 12) next to the
            # scrape: the same payload session.progress() returns —
            # what an operator (or the multi-tenant scheduler tier)
            # polls to see an 8-way stress run while it is happening
            import json

            from spark_rapids_tpu.progress import snapshot

            try:
                body = json.dumps(snapshot()).encode()
            except Exception as e:
                self._fail(e)
                return
            self._ok(body, "application/json; charset=utf-8")
            return
        if path not in ("", "/metrics"):
            self.send_response(404)
            self.end_headers()
            return
        try:
            body = render_prometheus(self.hub).encode()
        except Exception as e:      # a scrape must never crash the server
            self._fail(e)
            return
        self._ok(body, "text/plain; version=0.0.4; charset=utf-8")

    def _ok(self, body: bytes, ctype: str) -> None:
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _fail(self, e: Exception) -> None:
        self.send_response(500)
        self.end_headers()
        self.wfile.write(str(e).encode())

    def log_message(self, *a):      # no stderr chatter per scrape
        pass


def start_http(hub, port: int) -> Tuple[Optional[ThreadingHTTPServer],
                                        Optional[int]]:
    """Bind the scrape endpoint on 127.0.0.1 (port 0 = ephemeral, used
    by tests); returns (server, bound_port) or (None, None) when the
    bind fails (a busy port must not fail session construction)."""
    handler = type("_BoundHandler", (_Handler,), {"hub": hub})
    try:
        srv = ThreadingHTTPServer(("127.0.0.1", int(port)), handler)
    except OSError:
        return None, None
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever,
                         name="srt-telemetry-http", daemon=True)
    t.start()
    return srv, srv.server_address[1]
