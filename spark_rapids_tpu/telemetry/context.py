"""Telemetry context — the ONLY telemetry module instrumented sites read.

``HUB`` is the process-wide active :class:`~spark_rapids_tpu.telemetry.
TelemetryHub` (or None).  Like ``diagnostics.context.RECORDER`` it is a
plain module attribute, not a contextvar: telemetry is deliberately
process-scoped (queue depth, HBM occupancy, and per-plan latency are
properties of the *service*, not of one query), and signals arrive from
engine-owned helper threads (the watchdog, the AOT pool, shuffle pools)
that a contextvar would silently drop.

Disabled-path contract (mirrors ISSUE 3's diagnostics contract, pinned
by tests/test_telemetry.py): every instrumented site performs exactly
ONE ambient check — ``if CTX.HUB is None: skip`` — before doing any
other telemetry work, so the sampler-off/hub-off path costs an attribute
read and nothing else.
"""
from __future__ import annotations

# the active TelemetryHub; None = telemetry off.  Read lock-free from
# instrumented sites; written only by telemetry.maybe_configure /
# telemetry.shutdown under the hub lock.
HUB = None


def active():
    """The active hub or None (one ambient check)."""
    return HUB
