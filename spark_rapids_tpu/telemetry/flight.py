"""Flight recorder — an always-on bounded ring of recent query events,
plus the post-mortem bundle builder.

Reference analog: the JVM's JFR "flight recorder" stance applied to the
query engine: full diagnostics (ISSUE 3) are opt-in and per-query; the
flight recorder is ON BY DEFAULT and process-wide, recording only
coarse query-level events (admitted / started / finished / cancelled /
deadline trip / breaker open) into a fixed-size ring — a handful of
dict appends per QUERY, never per batch, so the always-on cost is
unmeasurable next to a single program launch.

When something goes wrong — a deadline trips, a query is cancelled
mid-batch, a circuit breaker opens, or ``collect()`` raises — the hub
dumps a **post-mortem bundle**: the ring contents, a stack trace of
every live thread (the offending query's collect thread called out by
name), the process counter snapshot, and the active-query table.  The
bundle is what an operator opens FIRST when a serving-tier query
wedges: it answers "what was the process doing in the seconds before"
without anyone having enabled anything in advance.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional


class FlightRecorder:
    """Fixed-size ring of recent events.  ``record`` is the only method
    on a query path: one small dict + one deque append under a lock."""

    def __init__(self, capacity: int = 2048):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(capacity), 16))
        self.events_recorded = 0

    def record(self, kind: str, **fields) -> None:
        e = {"ev": kind, "ts": time.time()}
        e.update(fields)
        with self._lock:
            self._ring.append(e)
            self.events_recorded += 1

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def _thread_stacks(offender_ident: Optional[int] = None) -> Dict[str, List[str]]:
    """Formatted stacks of every live thread, keyed
    ``"<name>@<ident>"``; the offending query's thread key gets an
    ``"*offender*"`` suffix so the bundle names it unambiguously."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, '?')}@{ident}"
        if offender_ident is not None and ident == offender_ident:
            key += " *offender*"
        out[key] = traceback.format_stack(frame)
    return out


def _active_query_table() -> List[Dict[str, Any]]:
    from spark_rapids_tpu.lifecycle import watchdog as _wd

    now = time.monotonic_ns()
    rows = []
    for ctx in _wd.active_queries():
        rows.append({
            "query_id": ctx.query_id,
            "trace_id": getattr(ctx, "trace_id", ""),
            "age_ms": round((now - ctx.started_ns) / 1e6, 1),
            "deadline_set": ctx.deadline_ns is not None,
            "deadline_expired": ctx.deadline_expired(now),
            "cancelled": ctx.token.cancelled,
            "owner_thread": ctx.owner_thread,
        })
    return rows


def _progress_snapshot(query_id: str) -> Optional[Dict[str, Any]]:
    """The offender's live progress snapshot (ISSUE 12): the operator
    table with last-advance timestamps, so a deadline-trip dump says
    *where* the query was stuck, not just which threads existed.  None
    when progress tracking is off (the default) or the query is
    unknown; never raises (a dump must not fail on its garnish)."""
    if not query_id:
        return None
    try:
        from spark_rapids_tpu.progress import snapshot_for

        return snapshot_for(query_id)
    except Exception:
        return None


def build_bundle(recorder: FlightRecorder, reason: str,
                 query_id: str = "", detail: str = "",
                 offender_ident: Optional[int] = None,
                 extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Assemble one post-mortem bundle (pure data, JSON-serializable).
    ``extra`` merges caller-provided context at the top level — the
    worker-loss bundle (ISSUE 14) carries the placement table and the
    re-drive plan this way."""
    from spark_rapids_tpu import perfcounters as PC

    bundle = {
        "bundle": "spark_rapids_tpu_postmortem",
        "reason": reason,
        "query_id": query_id,
        "detail": str(detail)[:2000],
        "ts": time.time(),
        "pid": os.getpid(),
        "counters": PC.snapshot(),
        "active_queries": _active_query_table(),
        "thread_stacks": _thread_stacks(offender_ident),
        "progress": _progress_snapshot(query_id),
        "ring": recorder.snapshot(),
    }
    if extra:
        for k, v in extra.items():
            bundle.setdefault(k, v)
    return bundle


def write_bundle(bundle: Dict[str, Any], dump_dir: str) -> Optional[str]:
    """Atomic (tmp + rename) JSON write; returns the path or None on
    I/O failure (a dump must never fail the process it describes)."""
    try:
        os.makedirs(dump_dir, exist_ok=True)
        name = (f"postmortem-{int(bundle['ts'] * 1000):013d}-"
                f"{bundle['reason']}"
                + (f"-{bundle['query_id']}" if bundle["query_id"] else "")
                + ".json")
        path = os.path.join(dump_dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
        os.replace(tmp, path)
        return path
    except OSError:
        return None
