"""SLO tracking — per-plan-signature latency histograms observed at
``collect()`` exit.

Reference analog: the serving-tier p95 discipline in "Accelerating
Presto with GPUs" (arXiv:2606.24647) — a dashboard deployment is tuned
against tail latency of REPEATED queries, so latency must be keyed by
plan shape, not pooled.  Every lifecycle-managed ``collect()`` lands one
observation here: the query's wall time into (a) the global latency
histogram and (b) its plan-signature sub-series (the same
``path:OperatorName|...`` signature ``tools/profile_report.py --diff``
matches queries by, so SLO series line up with diagnostics diffs).

``spark.rapids.tpu.telemetry.slo.targetP95Ms`` arms a per-query latency
target: any single query slower than the target bumps
``slo_violations`` and drops a ``slo_violation`` event into the flight
ring.  The cross-run regression gate lives in ``tools/bench_gate.py``,
which diffs the histogram-derived p50/p95 a bench run records.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from spark_rapids_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS_MS,
    MetricsRegistry,
)

LATENCY_HIST = "query_latency_ms"


def tenant_label(tenant: str) -> str:
    """The per-tenant SLO sub-series label (ISSUE 19).  Plan signatures
    are ``path:Name|...`` strings and never contain ``=``, so the two
    label families share one histogram without collisions."""
    return f"tenant={tenant}"


class SloTracker:
    def __init__(self, registry: MetricsRegistry):
        self._registry = registry
        self._lock = threading.Lock()
        self._hist = registry.histogram(
            LATENCY_HIST,
            "per-query collect() wall time, labeled by plan signature",
            DEFAULT_LATENCY_BUCKETS_MS, label_name="plan_sig")
        self._status: Dict[str, Dict[str, int]] = {}

    def observe(self, plan_sig: str, wall_ns: int, status: str,
                target_p95_ms: float = 0.0, tenant: str = "") -> bool:
        """Record one query; True when it violated the armed target.
        ``tenant`` (ISSUE 19) lands the wall into a per-tenant
        sub-series too (label ``tenant=<name>`` — disjoint from plan
        signatures, which never contain '='), so a serving deployment
        reads each tenant's p95 from the same histogram the starved
        -tenant pin asserts against."""
        ms = wall_ns / 1e6
        key = "ok" if status == "ok" else "error"
        with self._lock:
            self._hist.observe(ms, "")            # the all-queries series
            self._status.setdefault("", {"ok": 0, "error": 0})[key] += 1
            if plan_sig:
                self._hist.observe(ms, plan_sig)
                self._status.setdefault(
                    plan_sig, {"ok": 0, "error": 0})[key] += 1
            if tenant:
                lbl = tenant_label(tenant)
                self._hist.observe(ms, lbl)
                self._status.setdefault(
                    lbl, {"ok": 0, "error": 0})[key] += 1
        return bool(target_p95_ms and ms > target_p95_ms)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-plan-signature latency summary ("" = all queries):
        count / error_count / p50_ms / p95_ms / max_ms."""
        with self._lock:
            out = {}
            for lbl in self._hist.labels():
                s = self._hist.stats(lbl)
                st = self._status.get(lbl or "", {})
                out[lbl or ""] = {
                    "count": s["count"],
                    "errors": st.get("error", 0),
                    "p50_ms": round(s["p50"], 3),
                    "p95_ms": round(s["p95"], 3),
                    "max_ms": round(s["max"], 3),
                    "mean_ms": round(s["sum"] / s["count"], 3)
                    if s["count"] else 0.0,
                }
            return out

    def p95_ms(self, plan_sig: str = "") -> float:
        with self._lock:
            return self._hist.quantile(0.95, plan_sig)


def plan_signature(root) -> str:
    """The diagnostics-compatible plan signature of a planned exec tree
    (``path:NodeName`` in path order) — cheap: one walk per collect."""
    from spark_rapids_tpu.exec.base import TpuExec

    parts = []

    def walk(node, path: str) -> None:
        parts.append(f"{path}:{type(node).__name__}")
        for i, c in enumerate(getattr(node, "children", ())):
            if isinstance(c, TpuExec):
                walk(c, f"{path}.{i}")

    try:
        walk(root, "0")
    except Exception:
        return ""
    return "|".join(parts)
