"""The telemetry sampler — one daemon thread snapshots process state
into the time-series registry.

Every ``spark.rapids.tpu.telemetry.samplePeriodMs`` the sampler reads —
via the singletons' ``peek_*`` accessors only, so an idle tick can never
*create* a spill framework, admission controller, or cache — and
records:

* admission queue depth / running / limit and cumulative queue wait
  (``lifecycle/admission.py``),
* active and cumulative cancelled/admitted/rejected query counts
  (``lifecycle/watchdog.py`` + perfcounters),
* memory-pool occupancy and spill-tier movement (``memory/spill.py``),
* hot-table-cache and compile-registry occupancy plus hit rates,
* H2D logical-vs-physical transfer volume and prefetch stalls
  (``perfcounters``),
* the rolling all-queries p95 from the SLO histogram.

Each tick also appends one combined row to the bounded in-memory
timeline (what ``tools/run_stress.py`` dumps) and, when
``spark.rapids.tpu.telemetry.jsonlDir`` is set, one JSON line to
``telemetry-<pid>.jsonl`` — the periodic process-level companion of the
per-query diagnostics event log.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, Optional

# the perfcounters mirrored into counter series each tick (cumulative;
# consumers diff for rates)
SAMPLED_COUNTERS = (
    "queries_admitted", "queries_rejected", "queries_cancelled",
    "deadline_trips", "admission_wait_ns",
    "bytes_h2d", "bytes_h2d_logical", "bytes_h2d_overlapped",
    "bytes_d2h", "prefetch_stall_ns", "scan_transfer_ns",
    "hot_cache_hits", "hot_cache_misses", "hot_cache_evictions",
    "compile_cache_hits", "compile_cache_misses", "compile_wall_ns",
    "host_syncs", "programs_launched", "compiles",
    "transient_retries", "runtime_fallbacks", "breaker_trips",
    "slo_violations", "postmortem_dumps",
    "stalls_detected", "progress_snapshots",
    "governor_transitions", "queries_shed", "preempt_pauses",
    "degraded_batches",
    "workers_joined", "worker_lost", "worker_heartbeat_misses",
    "partitions_replayed", "dist_worker_dumps",
    "dist_worker_spans_merged",
    "fetch_hedges", "hedges_won", "workers_degraded",
    "speculative_redrives",
    "fair_share_admissions", "serving_sessions_opened",
    "serving_sessions_closed", "result_cache_hits",
    "result_cache_misses", "result_cache_evictions",
    "tenant_sheds", "tenant_preempts",
)


def _ratio(hits: float, misses: float) -> float:
    total = hits + misses
    return hits / total if total else 0.0


def collect_gauges() -> Dict[str, float]:
    """One tick's gauge readings (peek-only; shared with tests)."""
    g: Dict[str, float] = {}
    from spark_rapids_tpu.lifecycle.admission import peek_admission

    ctl = peek_admission()
    if ctl is not None:
        st = ctl.stats()
        g["admission_running"] = st["running"]
        g["admission_queued"] = st["queued"]
        g["admission_limit"] = st["limit"]
        # serving tier (ISSUE 19): tenants with work in flight right now
        tenants = st.get("tenants") or {}
        if tenants:
            g["serving_tenants_active"] = sum(
                1 for t in tenants.values()
                if t["running"] + t["queued"] > 0)
    from spark_rapids_tpu.lifecycle import watchdog as _wd

    g["active_queries"] = len(_wd.active_queries())
    from spark_rapids_tpu.memory.spill import peek_spill_framework

    fw = peek_spill_framework()
    if fw is not None:
        g["hbm_pool_bytes"] = fw.pool_bytes
        g["hbm_used_bytes"] = fw.device_used
        g["hbm_occupancy"] = (fw.device_used / fw.pool_bytes
                              if fw.pool_bytes else 0.0)
        g["spill_to_host_count"] = fw.spill_to_host_count
        g["spill_to_disk_count"] = fw.spill_to_disk_count
        g["spill_to_host_bytes"] = fw.spill_to_host_bytes
        g["spill_to_disk_bytes"] = fw.spill_to_disk_bytes
    from spark_rapids_tpu.io.hot_cache import peek_hot_cache

    hc = peek_hot_cache()
    if hc is not None:
        st = hc.stats()
        g["hot_cache_entries"] = st["entries"]
        g["hot_cache_bytes"] = st["bytes"]
    from spark_rapids_tpu.compilecache.registry import get_registry

    g["compile_registry_programs"] = get_registry().stats()["programs"]
    from spark_rapids_tpu import perfcounters as PC

    c = PC.COUNTERS
    g["hot_cache_hit_rate"] = _ratio(c.get("hot_cache_hits", 0),
                                     c.get("hot_cache_misses", 0))
    g["compile_cache_hit_rate"] = _ratio(c.get("compile_cache_hits", 0),
                                         c.get("compile_cache_misses", 0))
    # live progress aggregates (ISSUE 12): per-tick queries running,
    # min/median percent-complete, stalled count — peek-only like every
    # other gauge (aggregate_stats never bumps counters), absent when
    # no enabled query ever installed the tracker
    from spark_rapids_tpu.progress import context as _PROG

    trk = _PROG.TRACKER
    if trk is not None:
        g.update(trk.aggregate_stats())
    # overload governor (ISSUE 13): per-tick pressure state/level — the
    # gauges call runs one rate-limited pressure update, so a process
    # whose queries are all blocked still de-escalates on sampler ticks
    from spark_rapids_tpu.governor import context as _GOV

    gov = _GOV.GOVERNOR
    if gov is not None:
        g.update(gov.gauges())
    # distributed cross-host tier (ISSUE 14): live worker count,
    # quarantined count, and the re-placement backlog still awaiting
    # producer re-drive — peek-only like every other gauge
    from spark_rapids_tpu.distributed import peek_coordinator

    coord = peek_coordinator()
    if coord is not None:
        g.update(coord.gauges())
    # serving tier (ISSUE 19): result-fragment-cache occupancy —
    # sys.modules peek so a process that never enabled serving makes
    # zero serving-module calls (the cProfile-pinned disabled path)
    import sys as _sys

    srv = _sys.modules.get("spark_rapids_tpu.serving.context")
    rc = getattr(srv, "RESULT_CACHE", None)
    if rc is not None:
        st = rc.stats()
        g["result_cache_entries"] = st["entries"]
        g["result_cache_bytes"] = st["bytes"]
    return g


def collect_tenant_series() -> Dict[str, Dict[str, float]]:
    """Per-tenant admission occupancy for one tick (ISSUE 19), keyed
    ``{tenant: {series_name: value}}`` — peek-only.  The registry
    records them labeled ``tenant="<name>"`` (the ISSUE 15 per-worker
    pattern), so dashboards see one ``serving_queue_depth`` family
    across tenants instead of N ad-hoc gauge names."""
    from spark_rapids_tpu.lifecycle.admission import peek_admission

    ctl = peek_admission()
    if ctl is None:
        return {}
    out: Dict[str, Dict[str, float]] = {}
    for t, row in (ctl.stats().get("tenants") or {}).items():
        out[t] = {"serving_queue_depth": float(row["queued"]),
                  "serving_running": float(row["running"])}
    return out


def collect_worker_series() -> Dict[str, Dict[str, float]]:
    """Federated per-worker telemetry for one tick (ISSUE 15): the
    heartbeat-reported worker-local counters and store occupancy, keyed
    ``{worker_id: {series_name: value}}`` — peek-only (latest folded
    snapshots; an idle tick does no network I/O).  Series names carry a
    ``worker_`` prefix; the registry records them labeled
    ``worker="<id>"`` so the Prometheus export and the history-server
    cluster page see one family per metric across workers."""
    from spark_rapids_tpu.distributed import peek_coordinator

    coord = peek_coordinator()
    if coord is None:
        return {}
    out: Dict[str, Dict[str, Dict[str, float]]] = {}
    for wid, view in coord.worker_telemetry().items():
        out[wid] = {
            # cumulative worker-local counters -> counter kind
            "counters": {f"worker_{k}": float(v)
                         for k, v in view["counters"].items()},
            # instantaneous store occupancy -> gauge kind
            "gauges": {f"worker_store_{k}": float(v)
                       for k, v in view.get("store_stats", {}).items()},
        }
        # gray failure (ISSUE 20): the coordinator's p95-biased per-op
        # latency EWMA for this worker — the evidence a DEGRADED
        # demotion cites, as a per-worker gauge family
        out[wid]["gauges"]["worker_lat_ewma_ms"] = float(
            view.get("lat_ewma_ms", 0.0))
    return out


class Sampler:
    """Owns the daemon thread, the timeline ring, and the JSONL sink."""

    def __init__(self, hub, period_s: float, retention: int,
                 jsonl_dir: Optional[str] = None):
        self._hub = hub
        self.period_s = max(float(period_s), 0.01)
        self.timeline: deque = deque(maxlen=max(int(retention), 1))
        self._jsonl_dir = jsonl_dir or None
        self._jsonl = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="srt-telemetry-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(self.period_s * 4 + 1.0)
        self._thread = None
        self.flush()
        # a stopped sampler never writes again: close the sink so hub
        # shutdown/rebuild cycles do not accumulate open fds
        f, self._jsonl = self._jsonl, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.tick()
            except Exception:        # a broken peek must not kill the loop
                pass

    # -- one sample ------------------------------------------------------
    def tick(self) -> Dict[str, float]:
        from spark_rapids_tpu import perfcounters as PC

        ts = time.time()
        gauges = collect_gauges()
        counters = {k: float(PC.COUNTERS.get(k, 0))
                    for k in SAMPLED_COUNTERS}
        reg = self._hub.registry
        reg.record_many("gauge", gauges, ts)
        reg.record_many("counter", counters, ts)
        # per-worker federated series (ISSUE 15): worker-local counters
        # piggybacked on heartbeats, recorded labeled worker="<id>"
        workers = collect_worker_series()
        if workers:
            for kind, group in (("counter", "counters"),
                                ("gauge", "gauges")):
                reg.record_labeled_many(
                    kind,
                    {(name, (("worker", wid),)): v
                     for wid, row in workers.items()
                     for name, v in row[group].items()}, ts)
        # per-tenant serving series (ISSUE 19): admission queue depth
        # and running counts, recorded labeled tenant="<name>"
        tenants = collect_tenant_series()
        if tenants:
            reg.record_labeled_many(
                "gauge",
                {(name, (("tenant", t),)): v
                 for t, row in tenants.items()
                 for name, v in row.items()}, ts)
        p95 = self._hub.slo.p95_ms()
        reg.record("query_latency_p95_ms", p95, "gauge",
                   "rolling all-queries p95 collect latency", ts)
        row = {"ts": round(ts, 3), "p95_ms": round(p95, 3)}
        row.update({k: v for k, v in gauges.items()})
        row.update({k: int(v) for k, v in counters.items()})
        if workers:
            row["workers"] = {
                wid: {k: int(v)
                      for group in r.values() for k, v in group.items()}
                for wid, r in workers.items()}
        self.timeline.append(row)
        self.ticks += 1
        self._write_jsonl(row)
        return row

    def timeline_snapshot(self) -> list:
        return list(self.timeline)

    # -- JSONL sink ------------------------------------------------------
    def _write_jsonl(self, row: Dict) -> None:
        if not self._jsonl_dir:
            return
        try:
            if self._jsonl is None:
                os.makedirs(self._jsonl_dir, exist_ok=True)
                self._jsonl = open(
                    os.path.join(self._jsonl_dir,
                                 f"telemetry-{os.getpid()}.jsonl"), "a")
            self._jsonl.write(json.dumps(row) + "\n")
            self._jsonl.flush()
        except OSError:
            self._jsonl_dir = None       # disable after an I/O failure

    def flush(self) -> None:
        f = self._jsonl
        if f is not None:
            try:
                f.flush()
            except OSError:
                pass
