"""Always-on telemetry tier (ISSUE 7): a process-global time-series
metrics registry, a low-overhead sampler thread, per-plan-signature SLO
latency histograms, a Prometheus exporter, and an always-on failure
flight recorder with post-mortem bundles.

Reference analog: PR 3's diagnostics layer observes ONE query at a time
and is off by default; an always-on multi-tenant serving tier (ROADMAP
north star) is tuned and operated on *continuous, process-level*
signals — queue depth, HBM occupancy, cache hit rates, tail latency per
plan shape (Theseus, arXiv:2508.05029; Presto+GPU, arXiv:2606.24647).
This package is that substrate:

  context.py   — the active-hub slot (ONE ambient check on hot paths)
  registry.py  — gauges / counters / histograms, bounded sample rings
  sampler.py   — the daemon sampler thread + timeline + JSONL sink
  slo.py       — per-plan-signature latency histograms, p50/p95
  flight.py    — the always-on event ring + post-mortem bundles
  prometheus.py — Prometheus text exporter + localhost scrape endpoint

The hub is created by the first ``TpuSession`` whose conf leaves
``spark.rapids.tpu.telemetry.enabled`` true (the default) and lives for
the process; per-batch hot paths are NEVER instrumented — the flight
recorder records a handful of events per QUERY and the sampler reads
peek-only singletons on its own thread.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from spark_rapids_tpu.telemetry import context as CTX
from spark_rapids_tpu.telemetry.flight import (
    FlightRecorder,
    build_bundle,
    write_bundle,
)
from spark_rapids_tpu.telemetry.registry import MetricsRegistry
from spark_rapids_tpu.telemetry.sampler import Sampler
from spark_rapids_tpu.telemetry.slo import SloTracker, plan_signature

_LOCK = threading.Lock()

# per-reason minimum interval between post-mortem dumps: failure storms
# (a chaos sweep, a flapping stage) must not turn every error into a
# thread-stack capture
_DUMP_MIN_INTERVAL_S = 1.0


class TelemetryHub:
    """Everything the telemetry tier owns, wired together."""

    def __init__(self, conf):
        from spark_rapids_tpu.config import (
            TELEMETRY_FLIGHT_CAPACITY,
            TELEMETRY_FLIGHT_DUMP_DIR,
            TELEMETRY_FLIGHT_ENABLED,
            TELEMETRY_JSONL_DIR,
            TELEMETRY_RETENTION,
            TELEMETRY_SAMPLE_PERIOD_MS,
        )

        retention = int(conf.get(TELEMETRY_RETENTION))
        self.registry = MetricsRegistry(retention)
        self.slo = SloTracker(self.registry)
        self.flight_enabled = bool(conf.get(TELEMETRY_FLIGHT_ENABLED))
        self.flight = FlightRecorder(
            int(conf.get(TELEMETRY_FLIGHT_CAPACITY)))
        self.dump_dir: Optional[str] = conf.get(TELEMETRY_FLIGHT_DUMP_DIR)
        self.postmortems: deque = deque(maxlen=8)
        self._dumped_qids: "OrderedDict[str, float]" = OrderedDict()
        self._last_dump_ts: Dict[str, float] = {}
        self._dump_lock = threading.Lock()
        self.sampler = Sampler(
            self,
            period_s=float(conf.get(TELEMETRY_SAMPLE_PERIOD_MS)) / 1000.0,
            retention=retention,
            jsonl_dir=conf.get(TELEMETRY_JSONL_DIR))
        if float(conf.get(TELEMETRY_SAMPLE_PERIOD_MS)) > 0:
            self.sampler.start()
        self._http_server = None
        self.http_port: Optional[int] = None
        self.ensure_http(conf)

    # -- endpoint --------------------------------------------------------
    def ensure_http(self, conf) -> None:
        from spark_rapids_tpu.config import TELEMETRY_PORT

        port = int(conf.get(TELEMETRY_PORT))
        if port <= 0 or self._http_server is not None:
            return
        from spark_rapids_tpu.telemetry.prometheus import start_http

        self._http_server, self.http_port = start_http(self, port)

    # -- the per-query observation (session.DataFrame.collect) ----------
    def observed_collect(self, df, qctx):
        """Run ``df._collect_impl`` under flight/SLO observation.  Only
        lifecycle-managed top-level queries land here (``qctx`` is not
        None); the cost is a handful of dict appends + one plan walk per
        QUERY — nothing per batch."""
        from spark_rapids_tpu.config import TELEMETRY_SLO_TARGET_P95_MS
        from spark_rapids_tpu.lifecycle.context import (
            QueryCancelled,
            QueryDeadlineExceeded,
        )

        qid = qctx.query_id
        self.record_event("query_start", query_id=qid,
                          thread=threading.get_ident())
        t0 = time.perf_counter_ns()
        try:
            rows = df._collect_impl(qctx)
        except BaseException as e:
            wall = time.perf_counter_ns() - t0
            status = type(e).__name__
            self._finish(df, qid, wall, status,
                         float(df.session.conf.get(
                             TELEMETRY_SLO_TARGET_P95_MS)),
                         tenant=getattr(qctx, "tenant", ""))
            # QueryRejected never lands here: admission raises inside
            # query_lifecycle.__enter__, before this wrapper runs — the
            # lifecycle layer records the query_rejected flight event
            if isinstance(e, QueryDeadlineExceeded):
                self.postmortem("deadline_trip", query_id=qid,
                                detail=str(e))
            elif isinstance(e, QueryCancelled):
                self.postmortem("query_cancelled", query_id=qid,
                                detail=str(e))
            else:
                self.postmortem("collect_error", query_id=qid,
                                detail=f"{type(e).__name__}: {e}")
            raise
        wall = time.perf_counter_ns() - t0
        self._finish(df, qid, wall, "ok",
                     float(df.session.conf.get(TELEMETRY_SLO_TARGET_P95_MS)),
                     tenant=getattr(qctx, "tenant", ""))
        return rows

    def _finish(self, df, qid: str, wall_ns: int, status: str,
                target_p95_ms: float, tenant: str = "") -> None:
        sig = ""
        cached = getattr(df, "_plan_cache", None)
        if cached is not None:
            from spark_rapids_tpu.exec.base import TpuExec

            root = cached[1]
            if isinstance(root, TpuExec):
                sig = plan_signature(root)
        # per-tenant SLO sub-series (ISSUE 19): the serving tier's
        # starved-tenant pin reads hub.slo.p95_ms(tenant_label(t))
        violated = self.slo.observe(sig, wall_ns, status, target_p95_ms,
                                    tenant=tenant)
        if violated:
            from spark_rapids_tpu import perfcounters as PC

            PC.bump("slo_violations")
            self.record_event("slo_violation", query_id=qid,
                              wall_ms=round(wall_ns / 1e6, 3),
                              target_p95_ms=target_p95_ms, plan_sig=sig)
        self.record_event("query_end", query_id=qid, status=status,
                          wall_ms=round(wall_ns / 1e6, 3), plan_sig=sig)

    # -- flight ring -----------------------------------------------------
    def record_event(self, kind: str, **fields) -> None:
        if self.flight_enabled:
            self.flight.record(kind, **fields)

    # -- failure hooks ---------------------------------------------------
    def deadline_tripped(self, ctx) -> None:
        """Watchdog hook: dump WHILE the offending query's thread is
        still blocked, so the bundle's stack shows where it is stuck
        (at collect-raise time the stack has already unwound)."""
        self.record_event("deadline_trip", query_id=ctx.query_id)
        self.postmortem("deadline_trip", query_id=ctx.query_id,
                        offender_ident=ctx.owner_thread,
                        detail="watchdog tripped "
                               "spark.rapids.tpu.query.timeoutMs")

    def breaker_opened(self, key, reason: str) -> None:
        self.record_event("breaker_open", op=key[0], fingerprint=key[1],
                          reason=str(reason)[:300])
        self.postmortem("breaker_open",
                        detail=f"{key[0]}[{key[1]}]: {reason}")

    def postmortem(self, reason: str, query_id: str = "",
                   detail: str = "",
                   offender_ident: Optional[int] = None,
                   force: bool = False,
                   claim_query: bool = True,
                   extra: Optional[Dict[str, Any]] = None
                   ) -> Optional[Dict[str, Any]]:
        """Build (and optionally persist) one post-mortem bundle.
        Deduped per query (a deadline trip dumps from the watchdog; the
        same query's collect unwinding must not dump again) and
        rate-limited per reason against failure storms.

        ``claim_query=False`` (the stall detector, ISSUE 12): the dump
        neither consumes nor honors the per-query dedup slot — a stall
        bundle must not suppress the later deadline-trip bundle for the
        same query (nor be suppressed by it), and a re-armed second
        stall episode may dump again; the per-reason rate limit is the
        storm guard on this path."""
        if not self.flight_enabled:
            return None
        now = time.monotonic()
        with self._dump_lock:
            if not force:
                if claim_query and query_id \
                        and query_id in self._dumped_qids:
                    return None
                last = self._last_dump_ts.get(reason, 0.0)
                if now - last < _DUMP_MIN_INTERVAL_S:
                    return None
            self._last_dump_ts[reason] = now
            if query_id and claim_query:
                self._dumped_qids[query_id] = now
                while len(self._dumped_qids) > 256:
                    self._dumped_qids.popitem(last=False)
        bundle = build_bundle(self.flight, reason, query_id=query_id,
                              detail=detail,
                              offender_ident=offender_ident,
                              extra=extra)
        if self.dump_dir:
            bundle["path"] = write_bundle(bundle, self.dump_dir)
        self.postmortems.append(bundle)
        from spark_rapids_tpu import perfcounters as PC

        PC.bump("postmortem_dumps")
        return bundle

    def reset_dump_limits(self) -> None:
        """Test hook: forget dedupe/rate-limit state."""
        with self._dump_lock:
            self._dumped_qids.clear()
            self._last_dump_ts.clear()

    # -- surfaces --------------------------------------------------------
    def export(self) -> str:
        from spark_rapids_tpu.telemetry.prometheus import render_prometheus

        return render_prometheus(self)

    def timeline_snapshot(self) -> List[Dict]:
        return self.sampler.timeline_snapshot()

    def slo_summary(self) -> Dict[str, Dict[str, float]]:
        return self.slo.summary()

    def shutdown(self) -> None:
        self.sampler.stop()
        if self._http_server is not None:
            try:
                self._http_server.shutdown()
                self._http_server.server_close()
            except Exception:
                pass
            self._http_server = None
            self.http_port = None


# ---------------------------------------------------------------------------
# module-level lifecycle
# ---------------------------------------------------------------------------

def maybe_configure(conf) -> Optional[TelemetryHub]:
    """Idempotent process-global start (called by TpuSession.__init__):
    the FIRST enabling conf builds the hub; later sessions reuse it (a
    later conf can still add the HTTP endpoint).  Returns None when the
    conf disables telemetry."""
    from spark_rapids_tpu.config import TELEMETRY_ENABLED

    if not conf.get(TELEMETRY_ENABLED):
        return None
    with _LOCK:
        if CTX.HUB is None:
            CTX.HUB = TelemetryHub(conf)
        else:
            CTX.HUB.ensure_http(conf)
        return CTX.HUB


def get_hub() -> Optional[TelemetryHub]:
    return CTX.HUB


def export() -> str:
    """Prometheus text of the active hub ('' when telemetry is off)."""
    hub = CTX.HUB
    return hub.export() if hub is not None else ""


def timeline() -> List[Dict]:
    hub = CTX.HUB
    return hub.timeline_snapshot() if hub is not None else []


def slo_summary() -> Dict[str, Dict[str, float]]:
    hub = CTX.HUB
    return hub.slo_summary() if hub is not None else {}


def last_postmortem() -> Optional[Dict[str, Any]]:
    hub = CTX.HUB
    if hub is None or not hub.postmortems:
        return None
    return hub.postmortems[-1]


def flush() -> None:
    """Flush the JSONL sink (TpuSession.close)."""
    hub = CTX.HUB
    if hub is not None:
        hub.sampler.flush()


def shutdown() -> None:
    """Stop the sampler + endpoint and clear the hub slot (tests /
    process teardown); the next enabling TpuSession rebuilds."""
    with _LOCK:
        hub = CTX.HUB
        CTX.HUB = None
    if hub is not None:
        hub.shutdown()


__all__ = [
    "TelemetryHub", "export", "flush", "get_hub", "last_postmortem",
    "maybe_configure", "plan_signature", "shutdown", "slo_summary",
    "timeline",
]
