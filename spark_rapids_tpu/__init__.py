"""spark_rapids_tpu — a TPU-native accelerator framework for Spark-SQL-style
columnar query execution.

This is a brand-new, TPU-first framework with the capabilities of the RAPIDS
Accelerator for Apache Spark (reference: LuciferYang/spark-rapids, a fork of
NVIDIA/spark-rapids).  It is NOT a port: where the reference rewrites Spark
physical plans into GPU operators backed by libcudf/CUDA, this framework
rewrites columnar query plans into TPU operators backed by JAX/XLA/Pallas:

  * columns live in TPU HBM as validity-masked dense arrays (strings as
    length-bucketed padded byte matrices — the TPU-idiomatic answer to
    cuDF's offset-based layout, because XLA wants static shapes and the
    VPU operates on 8x128 tiles);
  * query-plan fragments between pipeline breakers are traced ONCE and
    compiled by XLA into a single fused program (whole-stage jit — the
    TPU answer to cuDF AST fusion, reference:
    sql-plugin/src/main/scala/com/nvidia/spark/rapids/GpuTieredProject);
  * group-by / join / sort are sort-based (lax.sort + segment reductions),
    because a systolic/vector machine without device-wide atomics favors
    sorting networks over hash tables (SURVEY.md §7 hard-part #3);
  * the shuffle's device-direct mode rides XLA all-to-all collectives over
    ICI via jax.sharding + shard_map, replacing the reference's UCX/NVLink
    point-to-point transport (reference: com/nvidia/spark/rapids/shuffle/**).

Layer map (mirrors SURVEY.md §1):
  config.py        — RapidsConf analog (typed spark.rapids.* registry)    [L8]
  types.py         — Spark SQL type system
  columnar/        — device ColumnVector / ColumnarBatch                  [L3]
  expr/            — GpuExpression library analog                         [L4/2.5]
  plan/            — plan nodes + DataFrame builder (CPU-plan stand-in)
  overrides/       — TpuOverrides / RapidsMeta tagging / transitions      [L2]
  exec/            — TpuExec operators                                    [L4]
  mem/             — semaphore, spill, OOM-retry, device manager          [L3]
  io/              — Parquet/CSV/JSON readers + writers                   [L6]
  shuffle/         — serializer + shuffle manager + ICI all-to-all        [L5]
  parallel/        — Mesh / collectives / multi-chip planning             [L5]
  ops/             — jnp/Pallas kernels (segment, sort, string, hash)     [L0]
  cpu/             — independent CPU oracle (differential-test golden)    [L9]
"""

__version__ = "0.1.0"

# Spark semantics are 64-bit (bigint, double).  Must be set before any jax
# array is created.  On TPU f64 is emulated (slow) — hot numeric paths use
# int64 decimals / f32 where Spark compatibility allows.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

from spark_rapids_tpu.config import TpuConf, get_conf, set_conf  # noqa: F401
