"""Plan-time cost model over the calibration store.

Before execution (and for ``df.explain("cost")``) the model walks the
planned exec tree, computes each node's calibration identity — the
``resilience.breaker.plan_key`` (operator class + expression
fingerprint) via the exec's plan twin, exactly what the breaker and the
plan-time tagging already compute — predicts its shape bucket from the
AOT row estimates, and matches the store: exact-bucket hits predict at
full confidence, nearest-bucket matches at half, and unseen pairs are
misses.  Predictions are per-operator EWMAs read straight back
(``self_wall_ns``, transfer bytes, host syncs), so a store seeded from
one recorded run predicts that run's profile exactly — the property the
feedback-loop pin in tests/test_profiling.py asserts.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from spark_rapids_tpu.profiling.store import CalibrationStore, bucket_of

# observations before an exact-bucket match reaches full confidence
_FULL_CONFIDENCE_OBS = 5


class NodePrediction:
    __slots__ = ("path", "node_name", "describe", "op_class", "fp",
                 "bucket", "matched", "obs", "predicted_self_wall_ns",
                 "predicted_transfer_bytes", "predicted_syncs",
                 "confidence")

    def __init__(self, path: str, node_name: str, describe: str):
        self.path = path
        self.node_name = node_name
        self.describe = describe
        self.op_class: Optional[str] = None
        self.fp: Optional[str] = None
        self.bucket: Optional[int] = None
        self.matched = "miss"          # "exact" | "nearest" | "miss"
        self.obs = 0
        self.predicted_self_wall_ns = 0.0
        self.predicted_transfer_bytes = 0.0
        self.predicted_syncs = 0.0
        self.confidence = 0.0


class QueryPrediction:
    __slots__ = ("nodes", "hits", "misses", "predicted_wall_ns")

    def __init__(self, nodes: List[NodePrediction]):
        self.nodes = nodes
        self.hits = sum(1 for n in nodes if n.matched != "miss")
        self.misses = len(nodes) - self.hits
        self.predicted_wall_ns = int(sum(
            n.predicted_self_wall_ns for n in nodes
            if n.matched != "miss"))

    def ranking(self) -> List[NodePrediction]:
        """Matched nodes, most-expensive predicted self wall first — the
        order ``explain("cost")`` reports and the feedback-loop test
        compares against the recorded profile's ranking."""
        return sorted((n for n in self.nodes if n.matched != "miss"),
                      key=lambda n: -n.predicted_self_wall_ns)

    def by_path(self) -> Dict[str, NodePrediction]:
        return {n.path: n for n in self.nodes}


def _planned_bucket(node) -> Optional[int]:
    """The shape bucket this operator's output will pad to, when the
    plan can predict it (same rule as the AOT concat estimate: total
    static rows); None when data-dependent."""
    try:
        rows_fn = getattr(node, "aot_output_rows", None)
        rows = rows_fn() if rows_fn is not None else None
        if rows:
            return bucket_of(sum(rows))
    except Exception:
        pass
    return None


def predicted_intermediate_bytes(node, conf) -> Optional[int]:
    """Predicted bytes of the intermediate batch ``node``'s output
    materializes — the cost-model input to the whole-plan fusion
    boundary rule (exec/fusion.py): a chain fuses through an edge only
    while this stays within the HBM budget.  Delegates to the same
    estimate ladder the out-of-core exchange sizing uses (static AOT
    rows, then the calibration store's measured rows EWMA, then the
    capacity bound — exec/partition_sizing.estimate_input_bytes), so a
    store-profiled operator moves the fusion boundary exactly where the
    partition sizing would move an exchange."""
    from spark_rapids_tpu.exec.partition_sizing import estimate_input_bytes

    return estimate_input_bytes(node, conf)


def predict_tree(root, store: CalibrationStore) -> QueryPrediction:
    """Walk the planned exec tree (paths follow the diagnostics
    ``register_root`` convention, so predictions line up with recorded
    operator spans) and match every node against the store."""
    from spark_rapids_tpu.exec.base import TpuExec
    from spark_rapids_tpu.resilience.domain import _breaker_key_of

    nodes: List[NodePrediction] = []

    def walk(node, path):
        pred = NodePrediction(path, node.node_name, node.describe())
        key = None
        try:
            key = _breaker_key_of(node)
        except Exception:
            key = None
        if key is not None:
            pred.op_class, pred.fp = key
            pred.bucket = _planned_bucket(node)
            ent, kind = store.match(pred.op_class, pred.fp, pred.bucket)
            if ent is not None:
                ew = ent.get("ewma") or {}
                pred.matched = kind
                pred.obs = int(ent.get("obs", 0))
                pred.predicted_self_wall_ns = float(
                    ew.get("self_wall_ns", 0.0))
                pred.predicted_transfer_bytes = float(
                    ew.get("bytes_h2d", 0.0)) + float(
                    ew.get("bytes_d2h", 0.0))
                pred.predicted_syncs = float(ew.get("host_syncs", 0.0))
                conf = min(1.0, pred.obs / float(_FULL_CONFIDENCE_OBS))
                pred.confidence = conf if kind == "exact" else conf * 0.5
        nodes.append(pred)
        for i, c in enumerate(node.children):
            if isinstance(c, TpuExec):
                walk(c, f"{path}.{i}")

    walk(root, "0")
    return QueryPrediction(nodes)


def render_cost_tree(root, pred: QueryPrediction,
                     diag=None, store_path: str = "") -> str:
    """The ``explain("cost")`` text: the plan tree annotated with each
    node's prediction, a predicted-cost ranking, and — when the last
    collect's recorder matches this plan — the predicted-vs-actual
    comparison per operator."""
    from spark_rapids_tpu.diagnostics.report import _fmt_bytes
    from spark_rapids_tpu.exec.base import TpuExec

    by_path = pred.by_path()
    # actuals only where the RECORDED operator at a path is the same
    # operator the current tree has there: a re-plan since the recorded
    # run (breaker trip, advisory change) renumbers paths, and pairing
    # a node with a different operator's measured wall would corrupt
    # the predicted-vs-actual comparison this mode exists for
    names_by_path = {n.path: n.node_name for n in pred.nodes}
    actual: Dict[str, int] = {}
    if diag is not None:
        with diag._lock:
            for e in diag.events:
                if e.get("ev") == "operator" and names_by_path.get(
                        e.get("path", "")) == e.get("name"):
                    actual[e.get("path", "")] = int(
                        e.get("self_wall_ns", 0))
        if not actual:
            # sinks already dropped the in-memory events; recompute the
            # exclusive (self) wall from the surviving per-op stats the
            # same way recorder.finish does — inclusive wall minus the
            # DIRECT children's (a parent's inclusive wall would be
            # systematically inflated next to the predicted SELF wall)
            stats = [st for st in diag.operator_stats() if st.path]
            child_wall: Dict[str, int] = {}
            for st in stats:
                dot = st.path.rfind(".")
                if dot > 0:
                    parent = st.path[:dot]
                    child_wall[parent] = child_wall.get(parent, 0) \
                        + st.wall_ns
            for st in stats:
                if names_by_path.get(st.path) == st.name:
                    actual[st.path] = max(
                        st.wall_ns - child_wall.get(st.path, 0), 0)
    lines = []

    def annotate(node, path, indent):
        p = by_path.get(path)
        s = "  " * indent + node.describe()
        if p is None:
            lines.append(s)
        elif p.matched == "miss":
            lines.append(s + "  [cost: no calibration"
                         + (f" ({p.op_class})" if p.op_class
                            else " (unfingerprintable)") + "]")
        else:
            parts = [f"wall≈{p.predicted_self_wall_ns / 1e6:.2f}ms",
                     f"xfer≈{_fmt_bytes(p.predicted_transfer_bytes)}",
                     f"syncs≈{p.predicted_syncs:.1f}",
                     f"conf={p.confidence:.2f}",
                     f"obs={p.obs}"]
            if p.matched == "nearest":
                parts.append("bucket=nearest")
            elif p.bucket is not None:
                parts.append(f"bucket={p.bucket}")
            if path in actual:
                parts.append(f"actual={actual[path] / 1e6:.2f}ms")
            lines.append(s + "  [cost: " + ", ".join(parts) + "]")
        for i, c in enumerate(node.children):
            if isinstance(c, TpuExec):
                annotate(c, f"{path}.{i}", indent + 1)
            elif hasattr(c, "pretty"):
                lines.append(c.pretty(indent + 1))

    annotate(root, "0", 0)
    lines.append(
        f"cost model: {pred.hits} matched / {pred.misses} unmatched | "
        f"predicted wall {pred.predicted_wall_ns / 1e6:.2f}ms"
        + (f" | store {store_path}" if store_path else ""))
    ranking = pred.ranking()
    if ranking:
        lines.append("predicted top operators by self wall:")
        for p in ranking:
            line = (f"  {p.node_name:<30} "
                    f"{p.predicted_self_wall_ns / 1e6:9.2f}ms  "
                    f"(conf {p.confidence:.2f}, path {p.path})")
            if p.path in actual:
                line += f"  actual {actual[p.path] / 1e6:.2f}ms"
            lines.append(line)
    return "\n".join(lines)
