"""Qualification / routing advisor (the spark-rapids-tools qualification
analog, SURVEY §5.1).

:func:`classify` rolls the calibration store up per operator CLASS and
flags each as **fallback-heavy** (runtime CPU fallbacks dominate its
observations — the device placement is wasted work), **sync-bound**
(host round-trips per batch above threshold), or **transport-bound**
(scan-transfer wall dominates its span).  Only fallback-heavy flips the
routing recommendation (``device`` → ``native``): that is the one case
the profile *proves* the default placement loses; sync/transport flags
are tuning advice, not routing.

The advisory is a machine-readable JSON file (``tools/qualify.py
--advisory-out``); :func:`consult_plan_advisor` is the plan-time hook
``overrides/meta.py`` calls behind the off-by-default
``spark.rapids.tpu.profile.advisor.enabled`` — the seed of cost-based
routing without changing default plans.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from spark_rapids_tpu.profiling.store import CalibrationStore

ADVISORY_VERSION = 1
ADVISORY_FILENAME = "advisory.json"

ROUTE_DEVICE = "device"
ROUTE_NATIVE = "native"
ROUTE_CPU = "cpu"

# classification thresholds (CLI-overridable in tools/qualify.py)
DEFAULT_MIN_OBS = 2             # classes seen fewer times stay device
DEFAULT_FALLBACK_RATIO = 0.5    # fallback obs / obs ≥ this → native
DEFAULT_SYNCS_PER_BATCH = 4.0   # host syncs per batch ≥ this → flagged
DEFAULT_TRANSPORT_SHARE = 0.5   # scan transfer / wall ≥ this → flagged


def classify(store: CalibrationStore,
             min_obs: int = DEFAULT_MIN_OBS,
             fallback_ratio: float = DEFAULT_FALLBACK_RATIO,
             syncs_per_batch: float = DEFAULT_SYNCS_PER_BATCH,
             transport_share: float = DEFAULT_TRANSPORT_SHARE
             ) -> Dict[str, Any]:
    """The advisory payload for one store."""
    operators: Dict[str, Dict[str, Any]] = {}
    for op, a in sorted(store.by_op_class().items()):
        obs = int(a["obs"])
        flags: List[str] = []
        reasons: List[str] = []
        route = ROUTE_DEVICE
        fb = a["fallback_obs"] / obs if obs else 0.0
        if obs >= min_obs and fb >= fallback_ratio:
            flags.append("fallback-heavy")
            reasons.append(
                f"{int(a['fallback_obs'])}/{obs} observed spans fell "
                f"back to CPU at runtime ({fb * 100:.0f}%)")
            route = ROUTE_NATIVE
        batches = a["batches"] or 1.0
        spb = a["host_syncs"] / batches
        if obs >= min_obs and spb >= syncs_per_batch:
            flags.append("sync-bound")
            reasons.append(
                f"{spb:.1f} host syncs per batch (threshold "
                f"{syncs_per_batch:g})")
        wall = a["wall_ns"] or 1.0
        tshare = a["scan_transfer_ns"] / wall
        if obs >= min_obs and tshare >= transport_share:
            flags.append("transport-bound")
            reasons.append(
                f"{tshare * 100:.0f}% of wall inside scan transfer "
                f"(threshold {transport_share * 100:.0f}%)")
        operators[op] = {
            "route": route,
            "flags": flags,
            "reasons": reasons,
            "confidence": min(1.0, obs / 10.0),
            "stats": {
                "obs": obs,
                "fallback_ratio": round(fb, 4),
                "syncs_per_batch": round(spb, 3),
                "transport_share": round(tshare, 4),
                "mean_self_wall_ms":
                    round(a["self_wall_ns"] / 1e6, 3),
                "mean_bytes_h2d": round(a["bytes_h2d"], 1),
                "mean_bytes_d2h": round(a["bytes_d2h"], 1),
            },
        }
    return {
        "version": ADVISORY_VERSION,
        "generated_at": time.time(),
        "store": store.path,
        "thresholds": {"min_obs": min_obs,
                       "fallback_ratio": fallback_ratio,
                       "syncs_per_batch": syncs_per_batch,
                       "transport_share": transport_share},
        "operators": operators,
    }


def write_advisory(advisory: Dict[str, Any], path: str) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(advisory, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


# -- plan-time consult (overrides/meta.py hook) -----------------------------

_CACHE_LOCK = threading.Lock()
# bounded like the store read cache: many distinct advisory paths over
# a process lifetime must not pin one parsed advisory each forever
_CACHE_MAX = 8
_CACHED: Dict[str, Tuple[Tuple[int, int], Optional[Dict]]] = {}


def advisory_path(conf) -> Optional[str]:
    """Where the consult reads from: the explicit file conf, else the
    profile dir's default advisory name, else nowhere."""
    from spark_rapids_tpu.config import PROFILE_ADVISOR_FILE, PROFILE_DIR

    explicit = conf.get(PROFILE_ADVISOR_FILE)
    if explicit:
        return explicit
    prof_dir = conf.get(PROFILE_DIR)
    if prof_dir:
        return os.path.join(prof_dir, ADVISORY_FILENAME)
    return None


def advisory_state(conf) -> Optional[Tuple[str, int, int]]:
    """(path, mtime_ns, size) of the advisory the consult would read —
    part of the plan-cache key, so editing the file re-tags cached
    plans; None when no advisory applies."""
    path = advisory_path(conf)
    if not path:
        return None
    try:
        st = os.stat(path)
    except OSError:
        return (path, 0, -1)
    return (path, st.st_mtime_ns, st.st_size)


def load_advisory(path: str) -> Optional[Dict[str, Any]]:
    """Parse + cache by (mtime_ns, size); None when absent, unreadable,
    or a different version (an old advisory must not silently keep
    routing under new semantics)."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    stamp = (st.st_mtime_ns, st.st_size)
    with _CACHE_LOCK:
        hit = _CACHED.get(path)
        if hit is not None and hit[0] == stamp:
            return hit[1]
    try:
        with open(path) as f:
            payload = json.load(f)
        if not isinstance(payload, dict) \
                or payload.get("version") != ADVISORY_VERSION:
            payload = None
    except (OSError, ValueError):
        payload = None
    from spark_rapids_tpu.profiling.store import bounded_cache_put

    with _CACHE_LOCK:
        bounded_cache_put(_CACHED, path, (stamp, payload), _CACHE_MAX)
    return payload


def consult_plan_advisor(plan, conf) -> Optional[str]:
    """The fallback reason when the advisory routes this plan node's
    operator class off the device, else None.  Caller (SparkPlanMeta)
    already checked spark.rapids.tpu.profile.advisor.enabled."""
    path = advisory_path(conf)
    if not path:
        return None
    adv = load_advisory(path)
    if adv is None:
        return None
    ent = (adv.get("operators") or {}).get(type(plan).__name__)
    if not ent:
        return None
    route = ent.get("route")
    if route not in (ROUTE_NATIVE, ROUTE_CPU):
        return None
    why = "; ".join(ent.get("reasons") or []) or "profile recommendation"
    return (f"profiling advisor routes {type(plan).__name__} to {route} "
            f"({why}) [spark.rapids.tpu.profile.advisor.enabled=true, "
            f"advisory {path}]")
