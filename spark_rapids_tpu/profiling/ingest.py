"""Event-log → calibration-store ingestion (the offline half).

Two producers feed the same :class:`~spark_rapids_tpu.profiling.store.
CalibrationStore`:

* **online** — ``profiling.record_query`` (wired into the diagnostics
  ``query_scope`` finish hook) harvests the just-finished recorder's
  operator events at ``query_end`` through
  :func:`observations_from_events`;
* **offline** — :func:`ingest_logs` replays diagnostics JSONL event
  logs (``tools/profile_ingest.py``), tolerating truncated trailing
  lines, so a recorded bench corpus or a production event-log directory
  can seed a fresh store byte-identically to what the online path would
  have accumulated (the feedback-loop pin in tests/test_profiling.py
  relies on this equivalence).
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List

from spark_rapids_tpu.profiling.store import CalibrationStore, Observation


def observations_from_events(events: Iterable[Dict[str, Any]]
                             ) -> List[Observation]:
    """Observations from an event stream (parsed JSONL lines or a live
    recorder's in-memory list) — one per ``operator`` event that carries
    a calibration identity and recorded work."""
    out = []
    for e in events:
        if e.get("ev") != "operator":
            continue
        obs = Observation.from_operator_event(e)
        if obs is not None:
            out.append(obs)
    return out


def ingest_logs(log_paths: List[str], store_dir: str,
                alpha: float = 0.25, return_store: bool = False):
    """Replay diagnostics event logs into the store at ``store_dir``;
    returns ingestion stats (or ``(stats, store)`` with
    ``return_store=True`` — the merged in-memory state, saving callers
    a re-parse).  Truncated/corrupt trailing lines are skipped with a
    count (``parse_errors``), never raised — a query killed mid-write
    must not poison the whole corpus.  Queries that did not finish
    clean (``status != ok``: cancelled, deadline-tripped, errored) are
    skipped — their spans are truncated mid-flight and would bias the
    wall EWMAs short (mirrors the online ``record_query`` rule)."""
    from spark_rapids_tpu.diagnostics.report import load_logs

    profiles = load_logs(log_paths)
    store = CalibrationStore.load(store_dir, alpha=alpha)
    n_obs = 0
    parse_errors = 0
    incomplete = 0
    skipped_unclean = 0
    for qp in profiles:
        parse_errors += qp.parse_errors
        if qp.events_dropped:
            incomplete += 1
        if qp.status != "ok":
            skipped_unclean += 1
            continue
        n_obs += store.observe_many(
            Observation.from_operator_event(e) for e in qp.operators)
    if n_obs:
        store.save()
    stats = {"queries": len(profiles), "observations": n_obs,
             "entries": len(store.entries),
             "parse_errors": parse_errors,
             "incomplete_queries": incomplete,
             "skipped_unclean": skipped_unclean}
    return (stats, store) if return_store else stats
