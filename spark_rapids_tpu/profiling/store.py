"""The persistent operator calibration store (ISSUE 8 tentpole).

Reference analog: the spark-rapids-tools qualification/profiling suite
mines Spark event logs into per-operator cost estimates (SURVEY §5.1);
here the diagnostics layer (PR 3) already attributes ``self_wall_ns``,
host syncs, and H2D/D2H bytes to every operator span, so this module
closes the loop: observations fold into a persistent JSON store keyed by
``(operator-class, expr-fingerprint, shape-bucket)`` — the same
``resilience.breaker.plan_key`` identity the circuit breaker and the
plan-time tagging compute, plus the AOT row-bucket ladder — and the
plan-time cost model (``profiling/model.py``) reads them back before the
next execution.

Store file: ``<spark.rapids.tpu.profile.dir>/calibration.json``.  Writes
are **merge-on-write**: ``save()`` re-reads the file under a module
lock, applies only the observations recorded since load, and atomically
replaces it (tmp + ``os.replace``) — two sequential processes
accumulate instead of clobbering, and a killed writer never leaves a
torn file.  Per-metric values are observation-counted decaying EWMAs
(``spark.rapids.tpu.profile.ewmaAlpha``), so the store tracks drift
without unbounded history.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

STORE_VERSION = 1
STORE_FILENAME = "calibration.json"

# pinned copy of columnar.column.DEFAULT_ROW_BUCKETS (the ladder the
# runtime batches actually pad to — compilecache.aot.bucket_of uses the
# same module default, NOT the conf ladder, for the same reason); kept
# here as a pure-python constant so offline tools never import jax.
# tests/test_profiling.py asserts the two stay equal.
DEFAULT_ROW_BUCKETS = (1024, 8192, 65536, 262144, 1048576, 4194304)

# per-metric decaying EWMAs kept per entry; sourced from the operator
# event's own fields (wall/self_wall/rows/batches) and its attributed
# counter deltas (syncs / transfer bytes / scan transfer wall)
EWMA_KEYS = ("self_wall_ns", "wall_ns", "rows", "batches", "host_syncs",
             "bytes_h2d", "bytes_d2h", "scan_transfer_ns")

# monotone outcome tallies (never decayed): how often this entry's spans
# ended in a fallback, and the resilience counters they attributed
OUTCOME_KEYS = ("fallback_obs", "runtime_fallbacks", "transient_retries",
                "oom_restarts", "breaker_trips")

# per-plan-signature EWMA dimensions (ISSUE 18): the regression
# sentinel's baselines, stored under the payload's "signatures" section
# beside the per-operator "entries" (old stores read back with an empty
# section; old readers ignore the new key — no version bump needed)
SIGNATURE_EWMA_KEYS = ("wall_ns", "host_syncs", "spill_bytes",
                       "cache_hit_rate")

_IO_LOCK = threading.Lock()

# read-only store instances keyed by path, stamped by (mtime_ns, size,
# alpha) — see CalibrationStore.load_cached.  Bounded: a long-lived
# process touching many distinct profile dirs (per-tenant confs, a test
# sweep of tmp dirs) must not retain one parsed store per dead path
_READ_CACHE_MAX = 8
_READ_CACHE: Dict[str, Tuple[Tuple, "CalibrationStore"]] = {}


def bounded_cache_put(cache: Dict, key, value, max_items: int = 8) -> None:
    """Insert-most-recent with FIFO eviction (caller holds its own
    lock) — shared by the store read cache and the advisory cache."""
    cache.pop(key, None)
    while len(cache) >= max_items:
        cache.pop(next(iter(cache)))
    cache[key] = value


def _cache_put(path: str, stamp, store: "CalibrationStore") -> None:
    """Caller holds _IO_LOCK."""
    bounded_cache_put(_READ_CACHE, path, (stamp, store),
                      _READ_CACHE_MAX)


def bucket_of(rows: int) -> int:
    """Round a row count up the default bucket ladder (next pow2 beyond
    it) — mirrors compilecache.aot.bucket_of without importing jax."""
    n = max(int(rows), 1)
    for b in DEFAULT_ROW_BUCKETS:
        if n <= b:
            return b
    p = 1
    while p < n:
        p <<= 1
    return p


def entry_key(op_class: str, fp: str, bucket: int) -> str:
    return f"{op_class}|{fp}|{int(bucket)}"


class Observation:
    """One operator span's contribution: identity + metric values +
    outcome flags, decoupled from where it came from (a live recorder or
    a replayed event log — both route through
    :meth:`from_operator_event`)."""

    __slots__ = ("op_class", "fp", "bucket", "values", "fallback",
                 "outcomes", "path")

    def __init__(self, op_class: str, fp: str, bucket: int,
                 values: Dict[str, float], fallback: bool = False,
                 outcomes: Optional[Dict[str, int]] = None,
                 path: str = ""):
        self.op_class = op_class
        self.fp = fp
        self.bucket = int(bucket)
        self.values = values
        self.fallback = bool(fallback)
        self.outcomes = dict(outcomes or {})
        self.path = path

    @property
    def key(self) -> str:
        return entry_key(self.op_class, self.fp, self.bucket)

    @classmethod
    def from_operator_event(cls, e: Dict[str, Any]) -> Optional["Observation"]:
        """Build from one diagnostics ``operator`` event (live dict or a
        parsed JSONL line); None when the span carries no calibration
        identity (no plan twin / pre-ISSUE-8 log) or recorded no work."""
        op_class = e.get("op_class")
        fp = e.get("fp")
        if not op_class or not fp:
            return None
        wall = int(e.get("wall_ns") or 0)
        batches = int(e.get("batches") or 0)
        fallback = bool(e.get("fallback"))
        if wall <= 0 and batches <= 0 and not fallback:
            return None   # the operator never ran (planned but unpulled)
        rows = int(e.get("rows") or 0)
        counters = e.get("counters") or {}
        values = {
            "self_wall_ns": float(e.get("self_wall_ns", wall)),
            "wall_ns": float(wall),
            "rows": float(rows),
            "batches": float(batches),
            "host_syncs": float(counters.get("host_syncs", 0)),
            "bytes_h2d": float(counters.get("bytes_h2d", 0)),
            "bytes_d2h": float(counters.get("bytes_d2h", 0)),
            "scan_transfer_ns": float(counters.get("scan_transfer_ns", 0)),
        }
        outcomes = {
            "fallback_obs": 1 if fallback else 0,
            "runtime_fallbacks": int(counters.get("runtime_fallbacks", 0)),
            "transient_retries": int(counters.get("transient_retries", 0)),
            "oom_restarts": int(counters.get("oom_restarts", 0)),
            "breaker_trips": int(counters.get("breaker_trips", 0)),
        }
        return cls(op_class, fp, bucket_of(rows), values,
                   fallback=fallback, outcomes=outcomes,
                   path=str(e.get("path", "")))


def _new_entry(op_class: str, fp: str, bucket: int) -> Dict[str, Any]:
    return {"op": op_class, "fp": fp, "bucket": int(bucket), "obs": 0,
            "ewma": {}, "outcomes": {k: 0 for k in OUTCOME_KEYS},
            "last_at": 0.0}


def _apply(entries: Dict[str, Dict], obs: Observation,
           alpha: float) -> None:
    ent = entries.get(obs.key)
    if ent is None:
        ent = entries[obs.key] = _new_entry(obs.op_class, obs.fp,
                                            obs.bucket)
    ent["obs"] = int(ent.get("obs", 0)) + 1
    ent["last_at"] = time.time()
    ew = ent.setdefault("ewma", {})
    for k in EWMA_KEYS:
        v = float(obs.values.get(k, 0.0))
        old = ew.get(k)
        ew[k] = v if old is None else alpha * v + (1.0 - alpha) * old
    out = ent.setdefault("outcomes", {})
    for k in OUTCOME_KEYS:
        out[k] = int(out.get(k, 0)) + int(obs.outcomes.get(k, 0))


def _new_sig_entry() -> Dict[str, Any]:
    return {"n": 0, "ewma": {}, "wall_dev_ns": 0.0, "ops": {},
            "last_at": 0.0}


def _apply_signature(sigs: Dict[str, Dict], sig: str,
                     values: Dict[str, float], ops: Dict[str, float],
                     alpha: float) -> None:
    """Fold one per-query sentinel observation (ISSUE 18) into a
    signature's EWMAs.  The wall deviation EWMA tracks |obs - mean|
    against the PRE-update mean — the sentinel's z denominator."""
    ent = sigs.get(sig)
    if ent is None:
        ent = sigs[sig] = _new_sig_entry()
    ent["n"] = int(ent.get("n", 0)) + 1
    ent["last_at"] = time.time()
    ew = ent.setdefault("ewma", {})
    prev_mean = ew.get("wall_ns")
    if prev_mean is not None:
        dev = abs(float(values.get("wall_ns", 0.0)) - float(prev_mean))
        old_dev = float(ent.get("wall_dev_ns", 0.0))
        ent["wall_dev_ns"] = alpha * dev + (1.0 - alpha) * old_dev
    for k in SIGNATURE_EWMA_KEYS:
        v = float(values.get(k, 0.0))
        old = ew.get(k)
        ew[k] = v if old is None else alpha * v + (1.0 - alpha) * old
    ops_ew = ent.setdefault("ops", {})
    for key, wall in ops.items():
        old = ops_ew.get(key)
        ops_ew[key] = float(wall) if old is None \
            else alpha * float(wall) + (1.0 - alpha) * float(old)


class CalibrationStore:
    """In-memory view + pending observations over one store file."""

    def __init__(self, directory: str, alpha: float = 0.25):
        self.directory = directory
        self.path = os.path.join(directory, STORE_FILENAME)
        # clamp: a zero/negative alpha would freeze the first observation
        # forever; >1 would oscillate
        self.alpha = min(max(float(alpha), 1e-3), 1.0)
        self.entries: Dict[str, Dict] = {}
        # per-plan-signature sentinel baselines (ISSUE 18)
        self.signatures: Dict[str, Dict] = {}
        self._pending: List[Observation] = []
        self._pending_sigs: List[Tuple[str, Dict, Dict]] = []
        self._by_opfp: Dict[Tuple[str, str], List[str]] = {}

    # -- load/save ------------------------------------------------------
    @classmethod
    def load(cls, directory: str, alpha: float = 0.25) -> "CalibrationStore":
        st = cls(directory, alpha)
        st.entries, st.signatures = st._read_disk()
        st._reindex()
        return st

    @classmethod
    def load_cached(cls, directory: str,
                    alpha: float = 0.25) -> "CalibrationStore":
        """READ-ONLY load, cached by the file's (mtime_ns, size) stamp —
        the per-collect prediction path must not re-parse the whole
        store when nothing changed.  Callers must not observe()/save()
        on the returned instance (it is shared); writers use load()."""
        path = os.path.join(directory, STORE_FILENAME)
        # same clamp as __init__: the stamp must match what save()
        # refreshes the cache with (self.alpha), or an out-of-range
        # conf value would defeat the cache forever
        alpha = min(max(float(alpha), 1e-3), 1.0)
        try:
            st = os.stat(path)
            stamp = (st.st_mtime_ns, st.st_size, alpha)
        except OSError:
            stamp = (0, -1, alpha)
        with _IO_LOCK:
            hit = _READ_CACHE.get(path)
            if hit is not None and hit[0] == stamp:
                return hit[1]
        store = cls.load(directory, alpha)
        with _IO_LOCK:
            _cache_put(path, stamp, store)
        return store

    def _read_disk(self) -> Tuple[Dict[str, Dict], Dict[str, Dict]]:
        """(entries, signatures) — pre-ISSUE-18 stores read back with an
        empty signatures section."""
        try:
            with open(self.path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return {}, {}
        if not isinstance(payload, dict) \
                or payload.get("version") != STORE_VERSION:
            return {}, {}   # incompatible/corrupt store: start fresh
        ents = payload.get("entries")
        sigs = payload.get("signatures")
        return (dict(ents) if isinstance(ents, dict) else {},
                dict(sigs) if isinstance(sigs, dict) else {})

    def _reindex(self) -> None:
        self._by_opfp = {}
        for key, ent in self.entries.items():
            self._by_opfp.setdefault(
                (ent.get("op", ""), ent.get("fp", "")), []).append(key)

    # -- observation ----------------------------------------------------
    def observe(self, obs: Optional[Observation]) -> None:
        if obs is None:
            return
        self._pending.append(obs)
        _apply(self.entries, obs, self.alpha)
        self._by_opfp.setdefault((obs.op_class, obs.fp), [])
        if obs.key not in self._by_opfp[(obs.op_class, obs.fp)]:
            self._by_opfp[(obs.op_class, obs.fp)].append(obs.key)

    def observe_many(self, obs_iter: Iterable[Optional[Observation]]) -> int:
        n = 0
        for obs in obs_iter:
            if obs is not None:
                self.observe(obs)
                n += 1
        return n

    def observe_signature(self, sig: str, values: Dict[str, float],
                          ops: Optional[Dict[str, float]] = None) -> None:
        """Fold one per-query sentinel observation (ISSUE 18) into the
        signature's baseline EWMAs; merged on save() like operator
        observations."""
        if not sig:
            return
        ops = dict(ops or {})
        self._pending_sigs.append((sig, dict(values), ops))
        _apply_signature(self.signatures, sig, values, ops, self.alpha)

    def signature(self, sig: str) -> Optional[Dict]:
        """The signature's baseline entry, or None when the store has
        never folded the plan shape."""
        return self.signatures.get(sig)

    def save(self) -> str:
        """Merge-on-write: re-read the file, apply only THIS store's
        pending observations on top of whatever is there now, replace
        atomically.  Sequential writers accumulate; the in-memory view
        becomes the merged state.  When the read cache's stamp still
        matches the file, its entries serve as the merge base (deep
        copy — the cached instance is shared read-only) instead of
        re-parsing the file, so the steady per-query online loop pays
        one serialize, not parse+serialize."""
        import copy

        with _IO_LOCK:
            disk = None
            sdisk = None
            try:
                st = os.stat(self.path)
                hit = _READ_CACHE.get(self.path)
                # never use SELF as the merge base: observe() already
                # applied the pending observations to self.entries, so
                # re-applying them onto that state would double-count
                # (a long-lived writer's second save would corrupt the
                # store); fall through to the fresh disk read instead
                if hit is not None and hit[1] is not self \
                        and hit[0] == (st.st_mtime_ns, st.st_size,
                                       float(self.alpha)):
                    # copy-on-write merge base: only the entries this
                    # save's pending observations touch are deep-copied
                    # (_apply mutates per-entry dicts in place, and the
                    # cached instance is shared read-only); untouched
                    # entries stay shared, so the per-query cost scales
                    # with the query's operators, not the store size
                    disk = dict(hit[1].entries)
                    for p in self._pending:
                        if p.key in disk:
                            disk[p.key] = copy.deepcopy(disk[p.key])
                    sdisk = dict(hit[1].signatures)
                    for sig, _v, _o in self._pending_sigs:
                        if sig in sdisk:
                            sdisk[sig] = copy.deepcopy(sdisk[sig])
            except OSError:
                pass
            if disk is None:
                disk, sdisk = self._read_disk()
            for obs in self._pending:
                _apply(disk, obs, self.alpha)
            for sig, values, ops in self._pending_sigs:
                _apply_signature(sdisk, sig, values, ops, self.alpha)
            self._pending = []
            self._pending_sigs = []
            self.entries = disk
            self.signatures = sdisk
            self._reindex()
            payload = {
                "version": STORE_VERSION,
                "alpha": self.alpha,
                "updated_at": time.time(),
                "total_obs": sum(int(e.get("obs", 0))
                                 for e in disk.values()),
                "entries": disk,
                "signatures": sdisk,
            }
            os.makedirs(self.directory, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
            # refresh the read cache with the merged state we already
            # hold: the next load_cached (the next collect's prediction
            # pass) hits instead of re-parsing the file this save just
            # invalidated.  Stamp from the TMP file BEFORE the rename
            # (rename preserves mtime/size): if another process replaces
            # the store after ours lands, its file carries a different
            # stamp and load_cached correctly misses — stat-ing after
            # the replace could capture the OTHER writer's stamp over
            # our (then stale) entries
            try:
                st = os.stat(tmp)
                stamp = (st.st_mtime_ns, st.st_size, float(self.alpha))
            except OSError:
                stamp = None
            os.replace(tmp, self.path)
            if stamp is not None:
                _cache_put(self.path, stamp, self)
        return self.path

    # -- lookup (the cost model's matcher) ------------------------------
    def match(self, op_class: str, fp: str,
              bucket: Optional[int]) -> Tuple[Optional[Dict], str]:
        """``(entry, kind)``: ``("exact")`` when the predicted shape
        bucket has its own entry, ``("nearest")`` when only other buckets
        of the same (operator, fingerprint) exist — pow2-nearest wins —
        and ``(None, "miss")`` when the store has never seen the pair."""
        keys = self._by_opfp.get((op_class, fp))
        if not keys:
            return None, "miss"
        if bucket is not None:
            ent = self.entries.get(entry_key(op_class, fp, bucket))
            if ent is not None:
                return ent, "exact"
        cands = [self.entries[k] for k in keys if k in self.entries]
        if not cands:
            return None, "miss"
        if bucket is None:
            # no plan-static shape: the most-observed bucket is the best
            # prior for what the runtime will actually see
            return max(cands, key=lambda e: int(e.get("obs", 0))), \
                "nearest"
        target = math.log2(max(int(bucket), 1))
        return min(cands,
                   key=lambda e: abs(
                       math.log2(max(int(e.get("bucket", 1)), 1))
                       - target)), "nearest"

    # -- aggregation (the advisor's view) -------------------------------
    def by_op_class(self) -> Dict[str, Dict[str, float]]:
        """Per-operator-class rollup across fingerprints and buckets,
        observation-weighted for the EWMA means."""
        agg: Dict[str, Dict[str, float]] = {}
        for ent in self.entries.values():
            op = ent.get("op", "?")
            n = int(ent.get("obs", 0))
            a = agg.setdefault(op, {"obs": 0.0, "entries": 0.0,
                                    **{k: 0.0 for k in EWMA_KEYS},
                                    **{k: 0.0 for k in OUTCOME_KEYS}})
            a["obs"] += n
            a["entries"] += 1
            for k in EWMA_KEYS:
                a[k] += float((ent.get("ewma") or {}).get(k, 0.0)) * n
            for k in OUTCOME_KEYS:
                a[k] += int((ent.get("outcomes") or {}).get(k, 0))
        for a in agg.values():
            n = a["obs"] or 1.0
            for k in EWMA_KEYS:
                a[k] /= n        # obs-weighted mean of the entry EWMAs
        return agg
