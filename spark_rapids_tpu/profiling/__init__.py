"""Profile-driven cost model (ISSUE 8): the persistent operator
calibration store, the plan-time cost model, and the qualification /
routing advisor.

Reference analog: NVIDIA ships a whole sibling repo of qualification and
profiling tools (spark-rapids-tools, SURVEY §5.1) that mine event logs
to tell users what will and won't benefit from acceleration.  Here the
loop closes in-process: diagnostics operator spans (PR 3) fold into a
persistent per-(operator, expr-fingerprint, shape-bucket) store at
``query_end``; before the NEXT execution the cost model matches the
planned exec tree against the store and annotates ``explain("cost")``
with predicted wall / transfer / confidence; and ``tools/qualify.py``
turns the accumulated profile into routing recommendations that
``overrides/meta.py`` consults behind the off-by-default advisor conf.

Layout:
  store.py    — CalibrationStore (atomic merge-on-write JSON, EWMAs)
  ingest.py   — event-log replay (tools/profile_ingest.py) + the live
                recorder harvest
  model.py    — plan-time prediction + explain("cost") rendering
  advisor.py  — per-operator-class qualification + the plan-time consult

Overhead contract: with ``spark.rapids.tpu.profile.dir`` unset (the
default) a collect makes ZERO calls into this package — every call site
gates on the conf before importing anything here
(tests/test_profiling.py pins it with cProfile, the same methodology as
the diagnostics and telemetry disabled-path pins).

This module is the session-facing surface: :func:`annotate_plan` runs
pre-execution inside the diagnostics window, :func:`record_query` runs
as the ``query_scope`` finish hook (post-``finish()``, pre-sink-flush).
Both swallow their own failures — profiling must never fail a query.
"""
from __future__ import annotations

import sys


def annotate_plan(root, conf, attributed: bool = True):
    """Pre-execution: predict the planned tree's cost from the store
    and bump the cost_model_* counters.  ``attributed=True`` only when
    THIS collect owns the active recorder (the bumps then land in its
    own window); a collect running unrecorded — diagnostics off, or it
    lost the one-recorder slot — must bump UNattributed, or its counts
    would land in the concurrently recorded query's log.  Returns the
    QueryPrediction or None.  The caller threads the return value to
    ``record_query`` itself — stashing it on the (cached, shared) plan
    root would let a losing concurrent collect of the same DataFrame
    clobber the recorded query's prediction."""
    try:
        from spark_rapids_tpu.config import (
            PROFILE_COST_MODEL_ENABLED,
            PROFILE_DIR,
            PROFILE_EWMA_ALPHA,
        )

        prof_dir = conf.get(PROFILE_DIR)
        if not prof_dir or not conf.get(PROFILE_COST_MODEL_ENABLED):
            return None
        from spark_rapids_tpu import perfcounters as PC
        from spark_rapids_tpu.profiling.model import predict_tree
        from spark_rapids_tpu.profiling.store import CalibrationStore

        store = CalibrationStore.load_cached(
            prof_dir, alpha=float(conf.get(PROFILE_EWMA_ALPHA)))
        pred = predict_tree(root, store)
        bump = PC.bump if attributed else PC.bump_unattributed
        if pred.hits:
            bump("cost_model_hits", pred.hits)
            bump("cost_model_predicted_wall_ns",
                 pred.predicted_wall_ns)
        if pred.misses:
            bump("cost_model_misses", pred.misses)
        # overload governor (ISSUE 13): an admitted query's predicted
        # wall joins the governor's backlog signal until its lifecycle
        # exits (one ambient check; cleared by note_query_end)
        if pred.hits and pred.predicted_wall_ns:
            from spark_rapids_tpu.governor import context as _GOV
            from spark_rapids_tpu.lifecycle.context import current

            gov = _GOV.GOVERNOR
            ctx = current()
            if gov is not None and ctx is not None:
                gov.note_predicted_wall(ctx.query_id,
                                        pred.predicted_wall_ns)
        return pred
    except Exception as e:
        print(f"spark_rapids_tpu.profiling: plan annotation failed: {e}",
              file=sys.stderr)
        return None


def record_query(diag, conf, prediction=None) -> None:
    """query_scope finish hook (caller gated on profile.dir): fold the
    finished recorder's operator spans into the calibration store,
    append the per-query predicted-vs-actual ``cost_model`` diagnostics
    event, and mirror it into the telemetry registry.  ``prediction``
    is THIS collect's ``annotate_plan`` result (None when the cost
    model is disabled or prediction failed)."""
    try:
        from spark_rapids_tpu.config import PROFILE_DIR, PROFILE_EWMA_ALPHA

        prof_dir = conf.get(PROFILE_DIR)
        if not prof_dir:
            return
        from spark_rapids_tpu.profiling.ingest import (
            observations_from_events,
        )
        from spark_rapids_tpu.profiling.store import CalibrationStore

        # ONE locked copy of the event list serves both harvests below
        # (the observations and the per-path actual self-walls)
        with diag._lock:
            events = list(diag.events)
        # only CLEAN queries calibrate: a cancelled/deadline-tripped/
        # failed query's spans are truncated mid-flight, and folding
        # their partial walls into the EWMAs would teach the cost model
        # systematically short estimates for exactly the operators that
        # time out
        obs = observations_from_events(events) \
            if diag.status == "ok" else []
        if obs:
            # write-only store: no load() — save() merges the pending
            # observations onto a fresh disk read anyway, so a prior
            # full parse of the store would be pure waste on the
            # query's exit path
            store = CalibrationStore(
                prof_dir, alpha=float(conf.get(PROFILE_EWMA_ALPHA)))
            store.observe_many(obs)
            store.save()
        pred = prediction
        if pred is None:
            return
        # apples-to-apples actual: the matched operators' recorded self
        # wall (the query wall includes unmatched operators + host work)
        actual_by_path = {
            e.get("path", ""): int(e.get("self_wall_ns", 0))
            for e in events if e.get("ev") == "operator"}
        matched_actual = sum(
            actual_by_path.get(n.path, 0)
            for n in pred.nodes if n.matched != "miss")
        from spark_rapids_tpu import perfcounters as PC

        # the measured twin of cost_model_predicted_wall_ns — bench
        # divides the two for an apples-to-apples prediction error.
        # UNATTRIBUTED: this hook runs after its own recorder closed; a
        # plain bump would attribute the value to whatever OTHER
        # query's recorder is installed by now
        PC.bump_unattributed("cost_model_matched_actual_wall_ns",
                             matched_actual)
        diag.record_cost_model(
            hits=pred.hits, misses=pred.misses,
            predicted_wall_ns=pred.predicted_wall_ns,
            actual_wall_ns=diag.wall_ns,
            matched_actual_wall_ns=matched_actual)
        _record_telemetry(pred, matched_actual, diag.wall_ns)
    except Exception as e:
        print(f"spark_rapids_tpu.profiling: query recording failed: {e}",
              file=sys.stderr)


def _record_telemetry(pred, matched_actual_ns: int,
                      wall_ns: int) -> None:
    """Predicted-vs-actual gauges for the always-on registry (ISSUE 7):
    calibration drift is visible on the same surface as latency/SLOs."""
    from spark_rapids_tpu import telemetry

    hub = telemetry.get_hub()
    if hub is None:
        return
    reg = hub.registry
    reg.record("cost_model_predicted_wall_ms",
               pred.predicted_wall_ns / 1e6)
    reg.record("cost_model_matched_actual_wall_ms",
               matched_actual_ns / 1e6)
    total = pred.hits + pred.misses
    reg.record("cost_model_hit_rate",
               pred.hits / total if total else 0.0)
    if pred.predicted_wall_ns and matched_actual_ns:
        err = abs(pred.predicted_wall_ns - matched_actual_ns) \
            / float(matched_actual_ns)
        reg.record("cost_model_prediction_error", err)


def explain_cost(df) -> str:
    """``df.explain("cost")`` implementation (session.py delegates)."""
    from spark_rapids_tpu.config import (
        PROFILE_COST_MODEL_ENABLED,
        PROFILE_DIR,
        PROFILE_EWMA_ALPHA,
    )
    from spark_rapids_tpu.exec.base import TpuExec

    root, _meta = df._planned()
    if not isinstance(root, TpuExec):
        return "(plan runs on the CPU oracle; no TPU cost model)"
    conf = df.session.conf
    prof_dir = conf.get(PROFILE_DIR)
    if not prof_dir:
        return ("(no calibration store: set spark.rapids.tpu.profile.dir "
                "to enable the cost model — see docs/profiling.md)")
    if not conf.get(PROFILE_COST_MODEL_ENABLED):
        return ("(cost model disabled by spark.rapids.tpu.profile."
                "costModel.enabled=false; the store still accumulates "
                "observations)")
    from spark_rapids_tpu.profiling.model import (
        predict_tree,
        render_cost_tree,
    )
    from spark_rapids_tpu.profiling.store import CalibrationStore

    store = CalibrationStore.load_cached(
        prof_dir, alpha=float(conf.get(PROFILE_EWMA_ALPHA)))
    pred = predict_tree(root, store)
    diag = getattr(df, "_last_diag", None)
    return render_cost_tree(root, pred, diag=diag,
                            store_path=store.path)
