"""GpuCast analog — Spark-exact cast matrix on TPU.

Reference analog: com/nvidia/spark/rapids/GpuCast.scala + spark-rapids-jni
cast_string.cu / cast_string_to_float.cu / cast_decimal_to_string.cu.  The
reference spent years making casts Spark-exact; this module reproduces the
semantics the differential harness exercises, entirely as fused vector ops:

  * numeric<->numeric: Java narrowing (wraps), double->integral saturates at
    long then narrows (Java (long)d then (int)), NaN -> 0; ANSI raises on
    out-of-range instead.
  * decimal rescale: HALF_UP rounding, overflow -> null (legacy) / error.
  * integral/decimal -> string: digit decomposition on device.
  * string -> integral: vectorized trim+parse, invalid -> null (legacy).
  * string <-> date (yyyy-MM-dd with civil-calendar day math on device, the
    Hinnant algorithm — branch-free integer ops, TPU-friendly).
  * date/timestamp conversions (micros <-> days, floor semantics).
  * string -> timestamp/date: vectorized variable-width civil parsing of
    Spark's stringToTimestamp grammar (see _FieldCursor for the documented
    subset; named timezones fall out as nulls).
  * float->string remains a plan-time fallback, gated exactly like the
    reference gates castFloatToString
    (spark.rapids.sql.castFloatToString.enabled) — see overrides/.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import EvalContext, UnaryExpression

_I_MIN = {T.ByteType: -(2 ** 7), T.ShortType: -(2 ** 15),
          T.IntegerType: -(2 ** 31), T.LongType: -(2 ** 63)}
_I_MAX = {T.ByteType: 2 ** 7 - 1, T.ShortType: 2 ** 15 - 1,
          T.IntegerType: 2 ** 31 - 1, T.LongType: 2 ** 63 - 1}


# ---------------------------------------------------------------------------
# civil-calendar day math (device, vectorized)
# ---------------------------------------------------------------------------

def civil_from_days(days):
    """days since 1970-01-01 -> (year, month, day); Hinnant algorithm."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def days_from_civil(y, m, d):
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


class Cast(UnaryExpression):
    def __init__(self, child, to: T.DataType, ansi: bool = False):
        super().__init__(child)
        self.to = to
        self._dataType = to
        self.ansi_override = ansi

    def sql_string(self):
        return f"CAST({self.child.sql_string()} AS {self.to.simpleString})"

    def _resolve_type(self):
        self._dataType = self.to
        self._nullable = True

    def resolve(self, schema):
        if schema is not None and not self.child.resolved:
            self.children = [self.child.resolve(schema)]
        self._resolve_type()
        self.resolved = True
        return self

    @property
    def is_host_kernel(self):
        """fp<->string casts run as host kernels (Java shortest-repr
        formatting / Spark float parsing), routed through the eager
        Project/Filter stage path like the JSON family."""
        srcdt = self.child._dataType
        if srcdt is None:
            return False
        fp = (T.FloatType, T.DoubleType)
        return ((isinstance(srcdt, fp) and isinstance(self.to, T.StringType))
                or (isinstance(srcdt, T.StringType)
                    and isinstance(self.to, fp)))

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        src, dst = self.child.dataType, self.to
        ansi = ctx.ansi or self.ansi_override
        if src == dst:
            return c
        fn = _dispatch(src, dst)
        if fn is None:
            raise TypeError(f"cast {src} -> {dst} not implemented on TPU")
        return fn(ctx, c, src, dst, ansi)


def _dispatch(src: T.DataType, dst: T.DataType):
    def k(t):
        if isinstance(t, T.DecimalType):
            return "dec"
        if isinstance(t, (T.FloatType, T.DoubleType)):
            return "fp"
        if isinstance(t, (T.ByteType, T.ShortType, T.IntegerType, T.LongType)):
            return "int"
        if isinstance(t, T.BooleanType):
            return "bool"
        if isinstance(t, T.StringType):
            return "str"
        if isinstance(t, T.DateType):
            return "date"
        if isinstance(t, T.TimestampType):
            return "ts"
        if isinstance(t, T.NullType):
            return "null"
        return "?"

    return _CASTS.get((k(src), k(dst)))


# -- numeric ---------------------------------------------------------------

def _int_to_int(ctx, c, src, dst, ansi):
    narrowing = (_I_MIN[type(dst)] > _I_MIN[type(src)]
                 or _I_MAX[type(dst)] < _I_MAX[type(src)])
    if ansi and narrowing:
        # narrowing only: a widening cast cannot overflow — and its
        # bound constants may not be representable in the SOURCE dtype
        # (2^63-1 wraps to -1 as an int32 operand, flagging every
        # non-negative row)
        mn, mx = _I_MIN[type(dst)], _I_MAX[type(dst)]
        bad = (c.data < mn) | (c.data > mx)
        ctx.add_error(bad & c.validity, f"cast overflow to {dst} (ANSI)")
    data = c.data.astype(T.storage_dtype(dst))  # wraps, Java semantics
    return DeviceColumn(dst, c.validity, data=data)


def _int_to_fp(ctx, c, src, dst, ansi):
    return DeviceColumn(dst, c.validity,
                        data=c.data.astype(T.storage_dtype(dst)))


def _fp_to_int(ctx, c, src, dst, ansi):
    mn, mx = _I_MIN[type(dst)], _I_MAX[type(dst)]
    x = c.data
    nan = jnp.isnan(x)
    tr = jnp.trunc(x)
    if ansi:
        bad = nan | (tr < mn) | (tr > mx)
        ctx.add_error(bad & c.validity, f"cast overflow to {dst} (ANSI)")
    # Java: (long) saturates, then narrowing wraps.  2^63-1 is not
    # representable as a double (rounds to 2^63, which wraps on convert),
    # so saturate explicitly by comparison.
    lmax_f = 9.223372036854775808e18  # == 2^63 exactly as a double
    safe = jnp.clip(tr, -9.2233720368547748e18, 9.2233720368547748e18)
    as_long = jnp.where(
        nan, 0,
        jnp.where(tr >= lmax_f, jnp.int64(_I_MAX[T.LongType]),
                  jnp.where(tr < -lmax_f, jnp.int64(_I_MIN[T.LongType]),
                            safe.astype(jnp.int64))))
    data = as_long.astype(T.storage_dtype(dst))  # narrowing wraps like Java
    return DeviceColumn(dst, c.validity, data=data)


def _fp_to_fp(ctx, c, src, dst, ansi):
    return DeviceColumn(dst, c.validity,
                        data=c.data.astype(T.storage_dtype(dst)))


def _num_to_bool(ctx, c, src, dst, ansi):
    return DeviceColumn(dst, c.validity, data=c.data != 0)


def _bool_to_num(ctx, c, src, dst, ansi):
    return DeviceColumn(dst, c.validity,
                        data=c.data.astype(T.storage_dtype(dst)))


# -- decimal ---------------------------------------------------------------

def _p10(k):
    return 10 ** int(min(max(k, 0), 18))


def _dec_rescale(ctx, data, validity, from_scale, to: T.DecimalType, ansi, op):
    from spark_rapids_tpu.expr.arithmetic import _decimal_bound_check

    diff = to.scale - from_scale
    if diff >= 0:
        out = data * _p10(diff)
    else:
        den = _p10(-diff)
        q = data // den
        rem = data - q * den
        q = q + jnp.where((rem != 0) & (data < 0), 1, 0)  # trunc toward 0
        rem2 = data - q * den
        round_away = jnp.abs(rem2) * 2 >= den
        out = q + jnp.where(round_away, jnp.sign(data), 0)
    validity = _decimal_bound_check(ctx, out, to, validity, ansi, op)
    return out, validity


def _dec128_rescale(ctx, hi, lo, validity, from_scale, dst: T.DecimalType,
                    ansi, op):
    """(hi, lo) at from_scale -> dst scale/precision; 128-bit limb path."""
    from spark_rapids_tpu.expr import decimal128 as D

    diff = dst.scale - from_scale
    over = jnp.zeros_like(validity)
    if diff >= 0:
        over, hi, lo = D.mul128_pow10(hi, lo, diff)
    else:
        hi, lo = D.div128_pow10_half_up(hi, lo, -diff)
    ok = D.in_bounds(hi, lo, dst.precision) & ~over
    if ansi:
        ctx.add_error(~ok & validity, f"decimal {op} overflow (ANSI)")
    else:
        validity = validity & ok
    return hi, lo, validity


def _dec_to_dec(ctx, c, src: T.DecimalType, dst: T.DecimalType, ansi):
    if not src.is_128 and not dst.is_128:
        data, validity = _dec_rescale(ctx, c.data, c.validity, src.scale, dst,
                                      ansi, "cast")
        return DeviceColumn(dst, validity, data=data)
    from spark_rapids_tpu.expr import decimal128 as D

    hi, lo = D.column_limbs(c)
    hi, lo, validity = _dec128_rescale(ctx, hi, lo, c.validity, src.scale,
                                       dst, ansi, "cast")
    if dst.is_128:
        return DeviceColumn(dst, validity, data=D.pack(hi, lo))
    # narrowing: bound check guarantees |v| < 10^18, so lo IS the value
    return DeviceColumn(dst, validity, data=lo)


def _int_to_dec(ctx, c, src, dst: T.DecimalType, ansi):
    if dst.is_128:
        from spark_rapids_tpu.expr import decimal128 as D

        hi, lo = D.from64(c.data.astype(jnp.int64))
        hi, lo, validity = _dec128_rescale(ctx, hi, lo, c.validity, 0, dst,
                                           ansi, "cast")
        return DeviceColumn(dst, validity, data=D.pack(hi, lo))
    data, validity = _dec_rescale(ctx, c.data.astype(jnp.int64), c.validity, 0,
                                  dst, ansi, "cast")
    return DeviceColumn(dst, validity, data=data)


def _dec_to_int(ctx, c, src: T.DecimalType, dst, ansi):
    if src.is_128:
        from spark_rapids_tpu.expr import decimal128 as D

        hi, lo = D.unpack(c.data)
        qh, ql = D.div128_pow10_trunc(hi, lo, src.scale)
        fits64 = (qh == (ql >> 63))      # pure sign extension
        mn, mx = _I_MIN[type(dst)], _I_MAX[type(dst)]
        bad = ~fits64 | (ql < mn) | (ql > mx)
        if ansi:
            ctx.add_error(bad & c.validity, f"cast overflow to {dst} (ANSI)")
            validity = c.validity
        else:
            validity = c.validity & ~bad
        return DeviceColumn(dst, validity,
                            data=ql.astype(T.storage_dtype(dst)))
    den = _p10(src.scale)
    q = c.data // den
    rem = c.data - q * den
    q = q + jnp.where((rem != 0) & (c.data < 0), 1, 0)
    mn, mx = _I_MIN[type(dst)], _I_MAX[type(dst)]
    bad = (q < mn) | (q > mx)
    if ansi:
        ctx.add_error(bad & c.validity, f"cast overflow to {dst} (ANSI)")
        validity = c.validity
    else:
        validity = c.validity & ~bad
    return DeviceColumn(dst, validity,
                        data=q.astype(T.storage_dtype(dst)))


def _dec_to_fp(ctx, c, src: T.DecimalType, dst, ansi):
    if src.is_128:
        from spark_rapids_tpu.expr import decimal128 as D

        hi, lo = D.unpack(c.data)
        lo_f = lo.astype(jnp.float64)
        lo_u = jnp.where(lo < 0, lo_f + 18446744073709551616.0, lo_f)
        val = hi.astype(jnp.float64) * 18446744073709551616.0 + lo_u
        data = val / (10.0 ** src.scale)
        return DeviceColumn(dst, c.validity,
                            data=data.astype(T.storage_dtype(dst)))
    data = c.data.astype(jnp.float64) / float(_p10(src.scale))
    return DeviceColumn(dst, c.validity,
                        data=data.astype(T.storage_dtype(dst)))


def _fp_to_dec(ctx, c, src, dst: T.DecimalType, ansi):
    from spark_rapids_tpu.expr.arithmetic import _decimal_bound_check

    scaled = c.data.astype(jnp.float64) * float(_p10(dst.scale))
    nan = jnp.isnan(scaled) | jnp.isinf(scaled)
    data = jnp.where(nan, 0.0, jnp.round(scaled)).astype(jnp.int64)
    validity = c.validity & ~nan
    if ansi:
        ctx.add_error(nan & c.validity, "cast NaN/Inf to decimal (ANSI)")
    validity = _decimal_bound_check(ctx, data, dst, validity, ansi, "cast")
    return DeviceColumn(dst, validity, data=data)


# -- to string (device digit decomposition) --------------------------------

_MAX_I64_DIGITS = 19


def _digits_of(absval, ndig_max):
    """(n,) uint64/int64 magnitudes -> (n, ndig_max) digits (at 10^i) plus
    (n,) significant digit count (>=1).  uint64 input handles 2^63
    (|Long.MIN_VALUE|)."""
    work = absval.astype(jnp.uint64)
    pows = jnp.asarray([10 ** i for i in range(ndig_max)], jnp.uint64)
    ds = (work[:, None] // pows[None, :]) % jnp.uint64(10)
    ndig = jnp.sum(work[:, None] >= pows[None, :], axis=1)
    ndig = jnp.maximum(ndig, 1)
    return ds.astype(jnp.int64), ndig.astype(jnp.int64)  # ds[:, i] = digit at 10^i


def _emit_int_string(absval, neg, ndig_max, width):
    """Build (n, width) char matrix + lengths for signed integers."""
    n = absval.shape[0]
    ds, ndig = _digits_of(absval, ndig_max)
    lengths = ndig + neg.astype(jnp.int32)
    # position p in output (0-based): if p==0 and neg: '-'
    # digit index from msd: p - neg ; value digit exponent = ndig-1-(p-neg)
    pos = jnp.arange(width)[None, :]
    digit_idx = ndig[:, None] - 1 - (pos - neg[:, None].astype(jnp.int32))
    in_digits = (digit_idx >= 0) & (digit_idx < ndig_max) & (pos < lengths[:, None])
    safe_idx = jnp.clip(digit_idx, 0, ndig_max - 1)
    dig = jnp.take_along_axis(ds, safe_idx, axis=1)
    chars = jnp.where(in_digits, dig + ord("0"), 0)
    chars = jnp.where((pos == 0) & neg[:, None], ord("-"), chars)
    return chars.astype(jnp.uint8), lengths.astype(jnp.int32)


def _magnitude_u64(x_i64):
    """|x| as uint64 — exact for Long.MIN_VALUE (2^63)."""
    u = x_i64.astype(jnp.int64).view(jnp.uint64)
    return jnp.where(x_i64 < 0, jnp.uint64(0) - u, u)


def _int_to_string(ctx, c, src, dst, ansi):
    width = 20
    neg = c.data < 0
    absval = _magnitude_u64(c.data)
    chars, lengths = _emit_int_string(absval, neg, _MAX_I64_DIGITS, width)
    return DeviceColumn(T.STRING, c.validity, chars=chars, lengths=lengths)


def _bool_to_string(ctx, c, src, dst, ansi):
    width = 5
    t = np.zeros(width, np.uint8)
    t[:4] = np.frombuffer(b"true", np.uint8)
    f = np.frombuffer(b"false", np.uint8)
    chars = jnp.where(c.data[:, None], jnp.asarray(t)[None, :],
                      jnp.asarray(f)[None, :])
    lengths = jnp.where(c.data, 4, 5).astype(jnp.int32)
    return DeviceColumn(T.STRING, c.validity, chars=chars, lengths=lengths)


def _dec_to_string(ctx, c, src: T.DecimalType, dst, ansi):
    """Spark: unscaled/10^s with exactly s fractional digits."""
    s = src.scale
    neg = c.data < 0
    absval = _magnitude_u64(c.data)
    if s == 0:
        return _int_to_string(ctx, c, src, dst, ansi)
    intpart = absval // jnp.uint64(_p10(s))
    frac = absval % jnp.uint64(_p10(s))
    width = _MAX_I64_DIGITS + s + 3
    ds_int, ndig_int = _digits_of(intpart, _MAX_I64_DIGITS)
    ds_frac, _ = _digits_of(frac, s)
    lengths = (ndig_int + 1 + s + neg.astype(jnp.int32)).astype(jnp.int32)
    pos = jnp.arange(width)[None, :]
    negi = neg[:, None].astype(jnp.int32)
    int_idx = ndig_int[:, None] - 1 - (pos - negi)
    in_int = (int_idx >= 0) & (int_idx < _MAX_I64_DIGITS)
    dot_pos = negi + ndig_int[:, None]
    frac_idx = s - 1 - (pos - dot_pos - 1)
    in_frac = (pos > dot_pos) & (frac_idx >= 0) & (frac_idx < s)
    dig_i = jnp.take_along_axis(ds_int, jnp.clip(int_idx, 0, _MAX_I64_DIGITS - 1), axis=1)
    dig_f = jnp.take_along_axis(ds_frac, jnp.clip(frac_idx, 0, max(s - 1, 0)), axis=1)
    chars = jnp.zeros((c.capacity, width), jnp.int64)
    chars = jnp.where(in_int, dig_i + ord("0"), chars)
    chars = jnp.where(pos == dot_pos, ord("."), chars)
    chars = jnp.where(in_frac, dig_f + ord("0"), chars)
    chars = jnp.where((pos == 0) & neg[:, None], ord("-"), chars)
    chars = jnp.where(pos < lengths[:, None], chars, 0)
    return DeviceColumn(T.STRING, c.validity, chars=chars.astype(jnp.uint8),
                        lengths=lengths)


def _date_to_string(ctx, c, src, dst, ansi):
    y, m, d = civil_from_days(c.data)
    width = 10
    neg_year = y < 0
    ya = jnp.abs(y)
    chars = jnp.zeros((c.capacity, width), jnp.int64)
    # yyyy-MM-dd (years padded to 4)
    chars = chars.at[:, 0].set(ord("0") + (ya // 1000) % 10)
    chars = chars.at[:, 1].set(ord("0") + (ya // 100) % 10)
    chars = chars.at[:, 2].set(ord("0") + (ya // 10) % 10)
    chars = chars.at[:, 3].set(ord("0") + ya % 10)
    chars = chars.at[:, 4].set(ord("-"))
    chars = chars.at[:, 5].set(ord("0") + (m // 10) % 10)
    chars = chars.at[:, 6].set(ord("0") + m % 10)
    chars = chars.at[:, 7].set(ord("-"))
    chars = chars.at[:, 8].set(ord("0") + (d // 10) % 10)
    chars = chars.at[:, 9].set(ord("0") + d % 10)
    del neg_year  # years <0 / >9999 rare; differential tests bound the range
    lengths = jnp.full(c.capacity, width, jnp.int32)
    return DeviceColumn(T.STRING, c.validity, chars=chars.astype(jnp.uint8),
                        lengths=lengths)


def _ts_to_string(ctx, c, src, dst, ansi):
    """yyyy-MM-dd HH:mm:ss[.ffffff] in UTC (session-tz support: later round)."""
    us = c.data
    days = jnp.floor_divide(us, 86_400_000_000)
    rem = us - days * 86_400_000_000
    y, m, d = civil_from_days(days)
    hh = rem // 3_600_000_000
    mm = (rem // 60_000_000) % 60
    ss = (rem // 1_000_000) % 60
    frac = rem % 1_000_000
    width = 26
    ch = jnp.zeros((c.capacity, width), jnp.int64)
    ya = jnp.abs(y)

    def put2(ch, i, v):
        ch = ch.at[:, i].set(ord("0") + (v // 10) % 10)
        return ch.at[:, i + 1].set(ord("0") + v % 10)

    ch = ch.at[:, 0].set(ord("0") + (ya // 1000) % 10)
    ch = ch.at[:, 1].set(ord("0") + (ya // 100) % 10)
    ch = ch.at[:, 2].set(ord("0") + (ya // 10) % 10)
    ch = ch.at[:, 3].set(ord("0") + ya % 10)
    ch = ch.at[:, 4].set(ord("-"))
    ch = put2(ch, 5, m)
    ch = ch.at[:, 7].set(ord("-"))
    ch = put2(ch, 8, d)
    ch = ch.at[:, 10].set(ord(" "))
    ch = put2(ch, 11, hh)
    ch = ch.at[:, 13].set(ord(":"))
    ch = put2(ch, 14, mm)
    ch = ch.at[:, 16].set(ord(":"))
    ch = put2(ch, 17, ss)
    # fractional seconds: Spark trims trailing zeros; compute sig digits
    has_frac = frac > 0
    ds, _ = _digits_of(frac, 6)
    # trailing zeros count
    tz = jnp.argmax(jnp.where(ds > 0, 1, 0), axis=1)  # first nonzero from lsd
    ndigits = 6 - jnp.where(has_frac, tz, 6)
    ch = ch.at[:, 19].set(jnp.where(has_frac, ord("."), 0))
    for i in range(6):
        digit = ds[:, 5 - i] + ord("0")
        ch = ch.at[:, 20 + i].set(jnp.where(i < ndigits, digit, 0))
    lengths = jnp.where(has_frac, 20 + ndigits, 19).astype(jnp.int32)
    pos = jnp.arange(width)[None, :]
    ch = jnp.where(pos < lengths[:, None], ch, 0)
    return DeviceColumn(T.STRING, c.validity, chars=ch.astype(jnp.uint8),
                        lengths=lengths)


# -- from string -----------------------------------------------------------

def _parse_trim(c: DeviceColumn):
    """Strip ASCII whitespace both ends: returns (chars, start, end)."""
    pos = jnp.arange(c.width)[None, :]
    is_ws = (c.chars == ord(" ")) | ((c.chars >= 9) & (c.chars <= 13))
    in_str = pos < c.lengths[:, None]
    nonws = in_str & ~is_ws
    any_nonws = jnp.any(nonws, axis=1)
    first = jnp.argmax(nonws, axis=1)
    last = c.width - 1 - jnp.argmax(nonws[:, ::-1], axis=1)
    return any_nonws, first, last


def _string_to_int(ctx, c, src, dst, ansi):
    any_nonws, first, last = _parse_trim(c)
    pos = jnp.arange(c.width)[None, :]
    active = (pos >= first[:, None]) & (pos <= last[:, None])
    ch = jnp.where(active, c.chars, 0)
    sign_pos = first
    rows = jnp.arange(c.capacity)
    sign_char = ch[rows, sign_pos]
    neg = sign_char == ord("-")
    has_sign = neg | (sign_char == ord("+"))
    dig_start = first + has_sign.astype(jnp.int32)
    is_digit = (ch >= ord("0")) & (ch <= ord("9"))
    digit_active = (pos >= dig_start[:, None]) & (pos <= last[:, None])
    all_digits = jnp.all(~digit_active | is_digit, axis=1)
    ndig = last - dig_start + 1
    valid_parse = any_nonws & all_digits & (ndig >= 1) & (ndig <= 19)
    # magnitude = sum digit * 10^(last - pos), in uint64 (10^19-1 fits)
    exp = last[:, None] - pos
    p10 = jnp.where((exp >= 0) & (exp < 19) & digit_active,
                    jnp.asarray([10 ** i for i in range(19)] + [0],
                                jnp.uint64)[jnp.clip(exp, 0, 19)],
                    jnp.uint64(0))
    mag = jnp.sum(jnp.where(digit_active & is_digit,
                            (ch - ord("0")).astype(jnp.uint64) * p10,
                            jnp.uint64(0)), axis=1)
    # fits signed 64? positive <= 2^63-1, negative magnitude <= 2^63
    fits_i64 = jnp.where(neg, mag <= jnp.uint64(2 ** 63),
                         mag <= jnp.uint64(2 ** 63 - 1))
    val = jnp.where(neg, jnp.uint64(0) - mag, mag).view(jnp.int64)
    mn, mx = _I_MIN[type(dst)], _I_MAX[type(dst)]
    in_range = fits_i64 & (val >= mn) & (val <= mx)
    ok = valid_parse & in_range
    if ansi:
        ctx.add_error(~ok & c.validity, f"invalid cast string->{dst} (ANSI)")
        validity = c.validity
    else:
        validity = c.validity & ok
    return DeviceColumn(dst, validity, data=val.astype(T.storage_dtype(dst)))


def _string_to_date(ctx, c, src, dst, ansi):
    """Parse yyyy-MM-dd (also yyyy-M-d per Spark leniency: later round)."""
    ok_len = c.lengths == 10
    ch = c.chars[:, :10] if c.width >= 10 else jnp.pad(
        c.chars, ((0, 0), (0, 10 - c.width)))
    dig = (ch - ord("0")).astype(jnp.int64)
    is_d = (ch >= ord("0")) & (ch <= ord("9"))
    pattern_ok = (is_d[:, 0] & is_d[:, 1] & is_d[:, 2] & is_d[:, 3]
                  & (ch[:, 4] == ord("-")) & is_d[:, 5] & is_d[:, 6]
                  & (ch[:, 7] == ord("-")) & is_d[:, 8] & is_d[:, 9])
    y = dig[:, 0] * 1000 + dig[:, 1] * 100 + dig[:, 2] * 10 + dig[:, 3]
    m = dig[:, 5] * 10 + dig[:, 6]
    d = dig[:, 8] * 10 + dig[:, 9]
    range_ok = (m >= 1) & (m <= 12) & (d >= 1) & (d <= 31)
    days = days_from_civil(y, m, d)
    # round-trip check rejects e.g. Feb 30
    y2, m2, d2 = civil_from_days(days)
    rt_ok = (y2 == y) & (m2 == m) & (d2 == d)
    ok = ok_len & pattern_ok & range_ok & rt_ok
    if ansi:
        ctx.add_error(~ok & c.validity, "invalid cast string->date (ANSI)")
        validity = c.validity
    else:
        validity = c.validity & ok
    return DeviceColumn(T.DATE, validity, data=days.astype(jnp.int32))


def _string_to_bool(ctx, c, src, dst, ansi):
    def match(s):
        b = s.encode()
        w = max(c.width, len(b))
        padded = jnp.pad(c.chars, ((0, 0), (0, w - c.width)))
        tgt = np.zeros(w, np.uint8)
        tgt[: len(b)] = np.frombuffer(b, np.uint8)
        # case-insensitive ASCII
        lower = jnp.where((padded >= 65) & (padded <= 90), padded + 32, padded)
        return (c.lengths == len(b)) & jnp.all(lower == jnp.asarray(tgt), axis=1)

    true_m = match("true") | match("t") | match("yes") | match("y") | match("1")
    false_m = match("false") | match("f") | match("no") | match("n") | match("0")
    ok = true_m | false_m
    if ansi:
        ctx.add_error(~ok & c.validity, "invalid cast string->boolean (ANSI)")
        validity = c.validity
    else:
        validity = c.validity & ok
    return DeviceColumn(T.BOOLEAN, validity, data=true_m)


# -- date/timestamp --------------------------------------------------------

def _date_to_ts(ctx, c, src, dst, ansi):
    return DeviceColumn(T.TIMESTAMP, c.validity,
                        data=c.data.astype(jnp.int64) * 86_400_000_000)


def _ts_to_date(ctx, c, src, dst, ansi):
    days = jnp.floor_divide(c.data, 86_400_000_000)
    return DeviceColumn(T.DATE, c.validity, data=days.astype(jnp.int32))


def _ts_to_long(ctx, c, src, dst, ansi):
    secs = jnp.floor_divide(c.data, 1_000_000)
    return DeviceColumn(dst, c.validity, data=secs.astype(T.storage_dtype(dst)))


def _long_to_ts(ctx, c, src, dst, ansi):
    return DeviceColumn(T.TIMESTAMP, c.validity,
                        data=c.data.astype(jnp.int64) * 1_000_000)


def _null_to_any(ctx, c, src, dst, ansi):
    from spark_rapids_tpu.expr.base import Literal

    return Literal(None, dst).eval_tpu(ctx)


def java_fp_to_string(v: float, is_float: bool) -> str:
    """Java Float/Double.toString: shortest round-trip digits, positional
    for 1e-3 <= |v| < 1e7, else "d.dddEnn".  Shared by the device
    host-kernel cast and the CPU oracle (reference: cast_string.cu /
    format_float.cu, SURVEY.md §2.5 Cast)."""
    import math

    import numpy as np

    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "Infinity" if v > 0 else "-Infinity"
    if v == 0.0:
        return "-0.0" if math.copysign(1.0, v) < 0 else "0.0"
    x = np.float32(v) if is_float else np.float64(v)
    s = np.format_float_scientific(x, unique=True, trim="-")
    mant, _, exps = s.partition("e")
    exp = int(exps)
    neg = mant.startswith("-")
    if neg:
        mant = mant[1:]
    digits = (mant.replace(".", "").rstrip("0")) or "0"
    if -3 <= exp <= 6:
        if exp >= 0:
            ip = digits[: exp + 1].ljust(exp + 1, "0")
            fp = digits[exp + 1:] or "0"
        else:
            ip = "0"
            fp = "0" * (-exp - 1) + digits
        out = f"{ip}.{fp}"
    else:
        out = f"{digits[0]}.{digits[1:] or '0'}E{exp}"
    return ("-" if neg else "") + out


def _fp_to_string(ctx, c, src, dst, ansi):
    """HOST kernel (eager path): Java shortest-repr formatting."""
    from spark_rapids_tpu.columnar.column import HostColumn

    cap = c.capacity
    n = int(ctx.batch.num_rows)
    vals = c.to_host(n).to_pylist()
    is_f = isinstance(src, T.FloatType)
    out = [None if v is None else java_fp_to_string(float(v), is_f)
           for v in vals]
    host = HostColumn.from_pylist(out, T.STRING)
    return DeviceColumn.from_host(host, capacity=cap)


def spark_string_to_double(s: str):
    """Spark's cast(string as double): trimmed Java Double.parseDouble
    grammar (shared by the device host-kernel and the CPU oracle).
    Returns None for Spark-invalid input.  Python-only syntax Java
    rejects — digit underscores and the bare 'inf'/'-inf' spellings —
    is rejected; Java's trailing d/f suffix is accepted."""
    t = s.strip()
    if not t or "_" in t:
        return None
    low = t.lower()
    if low.lstrip("+-") in ("inf",):
        return None              # Java wants 'Infinity'
    if low and low[-1] in "df" and any(ch.isdigit() for ch in low[:-1]) \
            and "x" not in low:
        t = t[:-1]               # Java FP suffix
    try:
        return float(t)
    except ValueError:
        return None


def _string_to_fp(ctx, c, src, dst, ansi):
    """HOST kernel: Spark string->float parse via the shared
    spark_string_to_double grammar; invalid -> null (ANSI: error)."""
    import numpy as np

    from spark_rapids_tpu.columnar.column import HostColumn

    cap = c.capacity
    n = int(ctx.batch.num_rows)
    vals = c.to_host(n).to_pylist()
    out = []
    bad = np.zeros(cap, np.bool_)
    for i, v in enumerate(vals):
        if v is None:
            out.append(None)
            continue
        f = spark_string_to_double(str(v))
        if f is None:
            out.append(None)
            bad[i] = True
        else:
            out.append(f)
    if ansi:
        ctx.add_error(jnp.asarray(bad),
                      "invalid input syntax for type numeric (ANSI)")
    host = HostColumn.from_pylist(out, dst)
    return DeviceColumn.from_host(host, capacity=cap)


_CASTS = {
    ("int", "int"): _int_to_int,
    ("int", "fp"): _int_to_fp,
    ("fp", "int"): _fp_to_int,
    ("fp", "fp"): _fp_to_fp,
    ("int", "bool"): _num_to_bool,
    ("fp", "bool"): _num_to_bool,
    ("bool", "int"): _bool_to_num,
    ("bool", "fp"): _bool_to_num,
    ("dec", "dec"): _dec_to_dec,
    ("int", "dec"): _int_to_dec,
    ("dec", "int"): _dec_to_int,
    ("dec", "fp"): _dec_to_fp,
    ("fp", "dec"): _fp_to_dec,
    ("int", "str"): _int_to_string,
    ("fp", "str"): _fp_to_string,
    ("str", "fp"): _string_to_fp,
    ("bool", "str"): _bool_to_string,
    ("dec", "str"): _dec_to_string,
    ("date", "str"): _date_to_string,
    ("ts", "str"): _ts_to_string,
    ("str", "int"): _string_to_int,
    ("str", "date"): _string_to_date,
    ("str", "bool"): _string_to_bool,
    ("date", "ts"): _date_to_ts,
    ("ts", "date"): _ts_to_date,
    ("ts", "int"): _ts_to_long,
    ("int", "ts"): _long_to_ts,
    ("null", "int"): _null_to_any,
    ("null", "fp"): _null_to_any,
    ("null", "str"): _null_to_any,
    ("null", "bool"): _null_to_any,
    ("null", "dec"): _null_to_any,
    ("null", "date"): _null_to_any,
    ("null", "ts"): _null_to_any,
}


def cast_supported(src: T.DataType, dst: T.DataType) -> bool:
    """Tag-time check used by overrides; mirrors GpuCast.canCast."""
    if src == dst:
        return True
    return _dispatch(src, dst) is not None


# ---------------------------------------------------------------------------
# string -> timestamp / date: variable-width civil parsing (GpuCast analog
# of spark-rapids-jni cast_string.cu's stringToTimestamp kernel)
# ---------------------------------------------------------------------------

_P10_I64 = [10 ** i for i in range(19)]


class _FieldCursor:
    """Vectorized cursor over trimmed char windows: digit-run extraction and
    single-char matches, all as masked vector ops (no per-row loops).

    Grammar supported (documented subset of Spark's stringToTimestamp):
      [y]yyyy[-[m]m[-[d]d[( |T)[h]h[:[m]m[:[s]s[.f{1,9}]]]][tz]]]]
      tz := Z | z | +-h[h] | +-hh:mm | +-h:mm | +-hhmm
    Named zones (e.g. "UTC", "America/New_York") are not recognized and
    parse as invalid (the reference handles them via GpuTimeZoneDB)."""

    def __init__(self, c: DeviceColumn):
        self.c = c
        self.any_nonws, self.first, self.last = _parse_trim(c)
        w = c.width
        self.w = w
        self.pos = jnp.arange(w)[None, :]
        self.rows = jnp.arange(c.capacity)
        active = ((self.pos >= self.first[:, None])
                  & (self.pos <= self.last[:, None])
                  & (self.pos < c.lengths[:, None]))
        self.ch = jnp.where(active, c.chars, 0)
        self.is_digit = (self.ch >= ord("0")) & (self.ch <= ord("9"))

    def char_at(self, p):
        safe = jnp.clip(p, 0, self.w - 1)
        v = self.ch[self.rows, safe]
        return jnp.where((p >= 0) & (p < self.w), v, 0)

    def digit_run_end(self, p):
        """Exclusive end of the digit run starting at p (<= last+1)."""
        nd = ((self.pos >= p[:, None]) & ~self.is_digit
              & (self.pos <= self.last[:, None]))
        has = jnp.any(nd, axis=1)
        idx = jnp.argmax(nd, axis=1).astype(jnp.int32)
        return jnp.where(has, idx, self.last + 1).astype(jnp.int32)

    def parse_int(self, start, end_excl, max_digits):
        """Integer from digits [start, end_excl); caller validates length."""
        exp = end_excl[:, None] - 1 - self.pos
        dig_active = ((self.pos >= start[:, None])
                      & (self.pos < end_excl[:, None]))
        p10 = jnp.asarray(_P10_I64[:max_digits] + [0], jnp.int64)
        mult = p10[jnp.clip(exp, 0, max_digits)]
        contrib = jnp.where(dig_active,
                            (self.ch - ord("0")).astype(jnp.int64) * mult,
                            jnp.int64(0))
        return jnp.sum(contrib, axis=1)


def _parse_civil_string(c: DeviceColumn):
    """Parse the shared date prefix + optional time/tz suffix.

    Returns a dict of fields and per-shape validity flags; consumers pick
    the shapes they accept (date cast ignores everything after the day)."""
    cur = _FieldCursor(c)
    last = cur.last
    ys = cur.first
    ye = cur.digit_run_end(ys)
    ylen = ye - ys
    y = cur.parse_int(ys, ye, 6)
    # year capped at 9999: the collect layer renders python datetimes
    year_ok = cur.any_nonws & (ylen >= 4) & (ylen <= 6) & (y <= 9999)
    only_year = ye > last
    dash1 = cur.char_at(ye) == ord("-")
    ms = ye + 1
    me = cur.digit_run_end(ms)
    mlen = me - ms
    m = cur.parse_int(ms, me, 2)
    month_ok = (mlen >= 1) & (mlen <= 2)
    only_ym = me > last
    dash2 = cur.char_at(me) == ord("-")
    ds = me + 1
    de = cur.digit_run_end(ds)
    dlen = de - ds
    d = cur.parse_int(ds, de, 2)
    day_ok = (dlen >= 1) & (dlen <= 2)
    only_date = de > last
    sepc = cur.char_at(de)
    sep = (sepc == ord(" ")) | (sepc == ord("T"))
    # time fields
    hs = de + 1
    he = cur.digit_run_end(hs)
    hlen = he - hs
    h = cur.parse_int(hs, he, 2)
    hour_ok = (hlen >= 1) & (hlen <= 2)
    colon1 = cur.char_at(he) == ord(":")
    mins = he + 1
    mine = cur.digit_run_end(mins)
    minlen = mine - mins
    mi = cur.parse_int(mins, mine, 2)
    min_ok = (minlen >= 1) & (minlen <= 2)
    colon2 = cur.char_at(mine) == ord(":")
    ss = mine + 1
    se = cur.digit_run_end(ss)
    slen = se - ss
    s = cur.parse_int(ss, se, 2)
    sec_ok = (slen >= 1) & (slen <= 2)
    dot = cur.char_at(se) == ord(".")
    fs = se + 1
    fe = cur.digit_run_end(fs)
    flen = fe - fs
    frac_ok = (flen >= 1) & (flen <= 9)
    frac_raw = cur.parse_int(fs, fe, 9)
    # fraction -> micros (truncating past 6 digits)
    scale_up = jnp.asarray([_P10_I64[i] for i in range(7)], jnp.int64)
    up = scale_up[jnp.clip(6 - flen, 0, 6)]
    down = scale_up[jnp.clip(flen - 6, 0, 6)]
    frac_us = jnp.where(flen <= 6, frac_raw * up, frac_raw // down)
    # time shape: hour [: min [: sec [.frac]]], ending at time_end
    time_end = jnp.where(
        dot & frac_ok, fe,
        jnp.where(colon2 & sec_ok, se,
                  jnp.where(colon1 & min_ok, mine, he)))
    has_min = colon1 & min_ok
    has_sec = has_min & colon2 & sec_ok
    has_frac = has_sec & dot & frac_ok
    mi = jnp.where(has_min, mi, 0)
    s = jnp.where(has_sec, s, 0)
    frac_us = jnp.where(has_frac, frac_us, 0)
    time_shape_ok = hour_ok & (
        (time_end == he)
        | (has_min & (time_end == mine))
        | (has_sec & (time_end == se))
        | (has_frac & (time_end == fe)))
    # tz suffix after the time
    tzp = time_end
    tz_none = tzp > last
    tzc = cur.char_at(tzp)
    tz_z = ((tzc == ord("Z")) | (tzc == ord("z"))) & (tzp == last)
    tz_sign = jnp.where(tzc == ord("+"), 1,
                        jnp.where(tzc == ord("-"), -1, 0)).astype(jnp.int64)
    ths = tzp + 1
    the = cur.digit_run_end(ths)
    thlen = the - ths
    th_raw = cur.parse_int(ths, the, 4)
    # forms: hhmm (4 digits), h/hh (then optional :mm)
    tz_hhmm = thlen == 4
    tzh = jnp.where(tz_hhmm, th_raw // 100, th_raw)
    tcolon = cur.char_at(the) == ord(":")
    tms = the + 1
    tme = cur.digit_run_end(tms)
    tmlen = tme - tms
    tzm_c = cur.parse_int(tms, tme, 2)
    has_tzm = tcolon & (tmlen == 2)
    tzm = jnp.where(tz_hhmm, th_raw % 100,
                    jnp.where(has_tzm, tzm_c, 0))
    tz_num_end = jnp.where(has_tzm & ~tz_hhmm, tme, the)
    tz_num_ok = ((tz_sign != 0)
                 & ((tz_hhmm & ~tcolon)
                    | ((thlen >= 1) & (thlen <= 2)))
                 & (tz_num_end > last))
    tz_off_ok = (tzh <= 18) & (tzm <= 59) \
        & ((tzh * 60 + tzm) <= 18 * 60)
    tz_ok = tz_none | tz_z | (tz_num_ok & tz_off_ok)
    tz_offset_s = jnp.where(tz_none | tz_z, 0,
                            tz_sign * (tzh * 3600 + tzm * 60))
    return dict(
        cur=cur, y=y, m=m, d=d, h=h, mi=mi, s=s, frac_us=frac_us,
        year_ok=year_ok, only_year=only_year,
        dash1=dash1, month_ok=month_ok, only_ym=only_ym, dash2=dash2,
        day_ok=day_ok, only_date=only_date, sep=sep,
        time_shape_ok=time_shape_ok, tz_ok=tz_ok,
        tz_offset_s=tz_offset_s, h_ok=(h <= 23), mi_ok=(mi <= 59),
        s_ok=(s <= 59))


def _string_to_timestamp(ctx, c, src, dst, ansi):
    """Spark stringToTimestamp subset — see _FieldCursor for the grammar."""
    f = _parse_civil_string(c)
    m_eff = jnp.where(f["only_year"], 1, f["m"])
    d_eff = jnp.where(f["only_year"] | f["only_ym"], 1, f["d"])
    days = days_from_civil(f["y"], jnp.maximum(m_eff, 1),
                           jnp.maximum(d_eff, 1))
    y2, m2, d2 = civil_from_days(days)
    civil_ok = ((y2 == f["y"]) & (m2 == jnp.maximum(m_eff, 1))
                & (d2 == jnp.maximum(d_eff, 1)))
    date_part_ok = (
        f["only_year"]
        | (f["dash1"] & f["month_ok"]
           & (f["only_ym"]
              | (f["dash2"] & f["day_ok"]))))
    time_part_ok = (
        f["only_date"] | f["only_year"] | f["only_ym"]
        | (f["sep"] & f["time_shape_ok"] & f["tz_ok"]
           & f["h_ok"] & f["mi_ok"] & f["s_ok"]))
    has_time = ~(f["only_date"] | f["only_year"] | f["only_ym"])
    ok = (f["year_ok"] & date_part_ok & time_part_ok & civil_ok
          & (m_eff >= 1) & (d_eff >= 1))
    h = jnp.where(has_time, f["h"], 0)
    mi = jnp.where(has_time, f["mi"], 0)
    s = jnp.where(has_time, f["s"], 0)
    frac = jnp.where(has_time, f["frac_us"], 0)
    off = jnp.where(has_time, f["tz_offset_s"], 0)
    micros = (days.astype(jnp.int64) * 86_400_000_000
              + h * 3_600_000_000 + mi * 60_000_000 + s * 1_000_000
              + frac - off * 1_000_000)
    if ansi:
        ctx.add_error(~ok & c.validity,
                      "invalid cast string->timestamp (ANSI)")
        validity = c.validity
    else:
        validity = c.validity & ok
    return DeviceColumn(T.TIMESTAMP, validity, data=micros)


def _string_to_date_v2(ctx, c, src, dst, ansi):
    """Spark stringToDate: [y]yyyy[-[m]m[-[d]d]], with anything after the
    day accepted when separated by ' ' or 'T' (Spark ignores the tail)."""
    f = _parse_civil_string(c)
    m_eff = jnp.where(f["only_year"], 1, f["m"])
    d_eff = jnp.where(f["only_year"] | f["only_ym"], 1, f["d"])
    days = days_from_civil(f["y"], jnp.maximum(m_eff, 1),
                           jnp.maximum(d_eff, 1))
    y2, m2, d2 = civil_from_days(days)
    civil_ok = ((y2 == f["y"]) & (m2 == jnp.maximum(m_eff, 1))
                & (d2 == jnp.maximum(d_eff, 1)))
    tail_ok = f["only_date"] | f["sep"]
    ok = (f["year_ok"] & civil_ok & (m_eff >= 1) & (d_eff >= 1)
          & (f["only_year"]
             | (f["dash1"] & f["month_ok"]
                & (f["only_ym"] | (f["dash2"] & f["day_ok"] & tail_ok)))))
    if ansi:
        ctx.add_error(~ok & c.validity, "invalid cast string->date (ANSI)")
        validity = c.validity
    else:
        validity = c.validity & ok
    return DeviceColumn(T.DATE, validity, data=days.astype(jnp.int32))


_CASTS[("str", "ts")] = _string_to_timestamp
_CASTS[("str", "date")] = _string_to_date_v2
