"""Misc expression breadth: digests, encodings, number formatting, URL
parsing, soundex, ids, rand.

Reference analogs (SURVEY.md §2.5): GpuMd5 (cudf md5), GpuSha1/GpuSha2,
GpuCrc32, GpuBase64/GpuUnBase64, GpuHex/GpuUnhex, GpuConv (jni conv.cu),
GpuFormatNumber (jni format_float.cu), GpuParseUrl (jni parse_uri.cu),
GpuMonotonicallyIncreasingID, GpuSparkPartitionID, GpuRand.

TPU design notes:
  * digest/encoding/url functions are irregular byte-twiddling with no MXU
    upside; like JSON they run as host kernels behind jax.pure_callback
    (SURVEY.md §2.10 item 10's host-parse stance) — levenshtein, which IS
    dense-vectorizable, runs on device as a lax.scan DP.
  * Rand uses jax's counter-based threefry keyed on (seed, row_id): a
    deterministic, seedable stream, but NOT Spark's XORShiftRandom
    sequence (TypeSig note; the reference matches Spark bit-exactly, which
    a counter-based TPU PRNG deliberately does not attempt).
"""
from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (
    BinaryExpression,
    call_host_kernel,
    EvalContext,
    Expression,
    UnaryExpression,
)


def _host_string_map(c: DeviceColumn, out_width: int,
                     fn: Callable[[bytes], Optional[bytes]]) -> DeviceColumn:
    """Row-wise bytes->bytes host kernel behind pure_callback."""
    cap = c.capacity

    def run(chars, lengths, validity):
        chars = np.asarray(chars)
        lengths = np.asarray(lengths)
        validity = np.asarray(validity)
        out_chars = np.zeros((cap, out_width), np.uint8)
        out_lens = np.zeros(cap, np.int32)
        out_valid = np.zeros(cap, np.bool_)
        for i in range(cap):
            if not validity[i]:
                continue
            res = fn(bytes(chars[i, :lengths[i]]))
            if res is None:
                continue
            res = res[:out_width]
            out_chars[i, :len(res)] = np.frombuffer(res, np.uint8)
            out_lens[i] = len(res)
            out_valid[i] = True
        return out_chars, out_lens, out_valid

    shapes = (jax.ShapeDtypeStruct((cap, out_width), np.uint8),
              jax.ShapeDtypeStruct((cap,), np.int32),
              jax.ShapeDtypeStruct((cap,), np.bool_))
    och, oln, ova = call_host_kernel(run, shapes, c.chars, c.lengths,
                                      c.validity)
    return DeviceColumn(T.STRING, ova, chars=och, lengths=oln)


class _HostStringUnary(UnaryExpression):
    """Base for string->string host-kernel expressions."""

    is_host_kernel = True

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def _out_width(self, c: DeviceColumn) -> int:
        return max(c.width, 1)

    def _fn(self, b: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def do_columnar_eval(self, ctx, cols):
        return _host_string_map(cols[0], self._out_width(cols[0]), self._fn)


class Md5(_HostStringUnary):
    def _out_width(self, c):
        return 32

    def _fn(self, b):
        import hashlib

        return hashlib.md5(b).hexdigest().encode()


class Sha1(_HostStringUnary):
    def _out_width(self, c):
        return 40

    def _fn(self, b):
        import hashlib

        return hashlib.sha1(b).hexdigest().encode()


class Sha2(Expression):
    """sha2(s, bitLength) with bitLength in {0(=256), 224, 256, 384, 512}."""

    is_host_kernel = True

    def __init__(self, child: Expression, bits: Expression):
        super().__init__([child, bits])

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True
        from spark_rapids_tpu.expr.base import Literal

        self._bits = None
        if isinstance(self.children[1], Literal) \
                and self.children[1].value is not None:
            self._bits = int(self.children[1].value)

    def do_columnar_eval(self, ctx, cols):
        import hashlib

        bits = self._bits
        algo = {0: "sha256", 224: "sha224", 256: "sha256",
                384: "sha384", 512: "sha512"}.get(bits)

        def fn(b):
            if algo is None:
                return None  # Spark: invalid bit length -> null
            return getattr(hashlib, algo)(b).hexdigest().encode()

        return _host_string_map(cols[0], 128, fn)


class Crc32(UnaryExpression):
    is_host_kernel = True

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        import zlib

        c = cols[0]
        cap = c.capacity

        def run(chars, lengths, validity):
            chars = np.asarray(chars)
            lengths = np.asarray(lengths)
            validity = np.asarray(validity)
            out = np.zeros(cap, np.int64)
            for i in range(cap):
                if validity[i]:
                    out[i] = zlib.crc32(bytes(chars[i, :lengths[i]]))
            return (out,)

        (data,) = call_host_kernel(
            run, (jax.ShapeDtypeStruct((cap,), np.int64),),
            c.chars, c.lengths, c.validity)
        return DeviceColumn(T.LONG, c.validity, data=data)


class Base64(_HostStringUnary):
    def _out_width(self, c):
        return ((max(c.width, 1) + 2) // 3) * 4

    def _fn(self, b):
        import base64 as b64

        return b64.b64encode(b)


class UnBase64(_HostStringUnary):
    """unbase64 -> binary; surfaced as a string column (the engine's
    binary representation)."""

    is_host_kernel = True

    def _fn(self, b):
        import base64 as b64

        try:
            return b64.b64decode(b, validate=False)
        except Exception:
            return None


_CHARSETS = {"utf-8", "utf8", "us-ascii", "iso-8859-1", "utf-16", "utf-16be",
             "utf-16le"}


class Encode(Expression):
    """encode(str, charset) -> binary (string column)."""

    is_host_kernel = True

    def __init__(self, child: Expression, charset: Expression):
        super().__init__([child, charset])

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True
        from spark_rapids_tpu.expr.base import Literal

        self._charset = None
        if isinstance(self.children[1], Literal) \
                and self.children[1].value is not None:
            self._charset = str(self.children[1].value).lower()

    def do_columnar_eval(self, ctx, cols):
        cs = self._charset

        def fn(b):
            try:
                return b.decode("utf-8").encode(cs)
            except (UnicodeError, LookupError, TypeError):
                return None

        return _host_string_map(cols[0], max(cols[0].width * 4, 4), fn)


class Decode(Encode):
    """decode(binary, charset) -> string."""

    def do_columnar_eval(self, ctx, cols):
        cs = self._charset

        def fn(b):
            try:
                return b.decode(cs).encode("utf-8")
            except (UnicodeError, LookupError, TypeError):
                return None

        return _host_string_map(cols[0], max(cols[0].width * 4, 4), fn)


class Hex(UnaryExpression):
    """hex(int) / hex(string): Spark uppercase, no leading zeros for ints."""

    is_host_kernel = True

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        if c.is_string:
            return _host_string_map(
                c, max(c.width * 2, 2), lambda b: b.hex().upper().encode())
        cap = c.capacity

        def run(data, validity):
            data = np.asarray(data)
            validity = np.asarray(validity)
            out_chars = np.zeros((cap, 16), np.uint8)
            out_lens = np.zeros(cap, np.int32)
            for i in range(cap):
                if not validity[i]:
                    continue
                v = int(data[i]) & 0xFFFFFFFFFFFFFFFF
                s = format(v, "X").encode()
                out_chars[i, :len(s)] = np.frombuffer(s, np.uint8)
                out_lens[i] = len(s)
            return out_chars, out_lens

        och, oln = call_host_kernel(
            run, (jax.ShapeDtypeStruct((cap, 16), np.uint8),
                  jax.ShapeDtypeStruct((cap,), np.int32)),
            c.data, c.validity)
        return DeviceColumn(T.STRING, c.validity, chars=och, lengths=oln)


class Unhex(_HostStringUnary):
    def _fn(self, b):
        s = b.decode("utf-8", "replace")
        if len(s) % 2:
            s = "0" + s
        try:
            return bytes.fromhex(s)
        except ValueError:
            return None


class Bin(UnaryExpression):
    """bin(long) — binary text of the two's-complement value."""

    is_host_kernel = True

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        cap = c.capacity

        def run(data, validity):
            data = np.asarray(data)
            validity = np.asarray(validity)
            out_chars = np.zeros((cap, 64), np.uint8)
            out_lens = np.zeros(cap, np.int32)
            for i in range(cap):
                if not validity[i]:
                    continue
                v = int(data[i]) & 0xFFFFFFFFFFFFFFFF
                s = format(v, "b").encode()
                out_chars[i, :len(s)] = np.frombuffer(s, np.uint8)
                out_lens[i] = len(s)
            return out_chars, out_lens

        och, oln = call_host_kernel(
            run, (jax.ShapeDtypeStruct((cap, 64), np.uint8),
                  jax.ShapeDtypeStruct((cap,), np.int32)),
            c.data, c.validity)
        return DeviceColumn(T.STRING, c.validity, chars=och, lengths=oln)


def _conv_str(s: str, from_base: int, to_base: int) -> Optional[str]:
    """Spark conv(): parse leading digits, unsigned 64-bit wrap."""
    s = s.strip()
    if not s or not (2 <= abs(from_base) <= 36) \
            or not (2 <= abs(to_base) <= 36):
        return None
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    digits = "0123456789abcdefghijklmnopqrstuvwxyz"
    val = 0
    seen = False
    for ch in s.lower():
        d = digits.find(ch)
        if d < 0 or d >= abs(from_base):
            break
        val = val * abs(from_base) + d
        seen = True
    if not seen:
        return "0"
    if neg:
        val = -val
    val &= 0xFFFFFFFFFFFFFFFF
    if to_base < 0:
        # signed output
        if val >= 1 << 63:
            val -= 1 << 64
        sign = "-" if val < 0 else ""
        val = abs(val)
        base = -to_base
    else:
        sign = ""
        base = to_base
    if val == 0:
        return "0"
    out = []
    while val:
        out.append(digits[val % base].upper())
        val //= base
    return sign + "".join(reversed(out))


class Conv(Expression):
    """conv(num_str, from_base, to_base) with literal bases."""

    is_host_kernel = True

    def __init__(self, child: Expression, fb: Expression, tb: Expression):
        super().__init__([child, fb, tb])

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True
        from spark_rapids_tpu.expr.base import Literal

        self._fb = self._tb = None
        if isinstance(self.children[1], Literal) \
                and self.children[1].value is not None:
            self._fb = int(self.children[1].value)
        if isinstance(self.children[2], Literal) \
                and self.children[2].value is not None:
            self._tb = int(self.children[2].value)

    def do_columnar_eval(self, ctx, cols):
        fb, tb = self._fb, self._tb

        def fn(b):
            if fb is None or tb is None:
                return None
            r = _conv_str(b.decode("utf-8", "replace"), fb, tb)
            return None if r is None else r.encode()

        return _host_string_map(cols[0], 65, fn)


class FormatNumber(Expression):
    """format_number(x, d): thousands separators, HALF_EVEN to d places."""

    is_host_kernel = True

    def __init__(self, child: Expression, d: Expression):
        super().__init__([child, d])

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c, dcol = cols
        cap = c.capacity
        dt = c.dtype
        is_dec = isinstance(dt, T.DecimalType)
        scale = dt.scale if is_dec else 0
        is_f = isinstance(dt, (T.FloatType, T.DoubleType))
        # 1.8e308 with grouping commas needs ~410 bytes + decimal places
        width = 512 if is_f else 64

        def run(data, validity, dvals, dvalid):
            import decimal as pydec

            data = np.asarray(data)
            validity = np.asarray(validity)
            dvals = np.asarray(dvals)
            dvalid = np.asarray(dvalid)
            out_chars = np.zeros((cap, width), np.uint8)
            out_lens = np.zeros(cap, np.int32)
            out_valid = np.zeros(cap, np.bool_)
            for i in range(cap):
                if not validity[i] or not dvalid[i]:
                    continue
                d = int(dvals[i])
                if d < 0:
                    continue  # Spark: negative d -> null
                if is_dec:
                    v = pydec.Decimal(int(data[i])).scaleb(-scale)
                elif is_f:
                    import math as _m

                    fv = float(data[i])
                    if _m.isnan(fv) or _m.isinf(fv):
                        # Java DecimalFormat: NaN / \u221e literals
                        s = ("NaN" if _m.isnan(fv) else
                             ("\u221e" if fv > 0 else "-\u221e")).encode()
                        out_chars[i, :len(s)] = np.frombuffer(s, np.uint8)
                        out_lens[i] = len(s)
                        out_valid[i] = True
                        continue
                    v = pydec.Decimal(repr(fv))
                else:
                    v = pydec.Decimal(int(data[i]))
                with pydec.localcontext() as lctx:
                    lctx.prec = 400  # 1e308 doubles need quantize headroom
                    q = v.quantize(pydec.Decimal(1).scaleb(-d),
                                   rounding=pydec.ROUND_HALF_EVEN)
                s = f"{q:,.{d}f}".encode()[:width]
                out_chars[i, :len(s)] = np.frombuffer(s, np.uint8)
                out_lens[i] = len(s)
                out_valid[i] = True
            return out_chars, out_lens, out_valid

        shapes = (jax.ShapeDtypeStruct((cap, width), np.uint8),
                  jax.ShapeDtypeStruct((cap,), np.int32),
                  jax.ShapeDtypeStruct((cap,), np.bool_))
        och, oln, ova = call_host_kernel(
            run, shapes, c.data, c.validity, dcol.data, dcol.validity)
        return DeviceColumn(T.STRING, ova, chars=och, lengths=oln)


_URL_PARTS = {"HOST", "PATH", "QUERY", "REF", "PROTOCOL", "FILE",
              "AUTHORITY", "USERINFO"}


def _parse_url_part(url: str, part: str,
                    key: Optional[str]) -> Optional[str]:
    from urllib.parse import parse_qs, urlparse

    try:
        u = urlparse(url)
    except ValueError:
        return None
    if not u.scheme:
        return None
    if part == "PROTOCOL":
        return u.scheme or None
    if part == "HOST":
        return u.hostname
    if part == "PATH":
        return u.path
    if part == "QUERY":
        if key is not None:
            if not u.query:
                return None
            vals = parse_qs(u.query, keep_blank_values=True).get(key)
            return vals[0] if vals else None
        return u.query or None
    if part == "REF":
        return u.fragment or None
    if part == "FILE":
        return u.path + ("?" + u.query if u.query else "")
    if part == "AUTHORITY":
        return u.netloc or None
    if part == "USERINFO":
        if "@" in u.netloc:
            return u.netloc.rsplit("@", 1)[0]
        return None
    return None


class ParseUrl(Expression):
    """parse_url(url, part[, key]) — host urllib kernel."""

    is_host_kernel = True

    def __init__(self, url: Expression, part: Expression,
                 key: Expression = None):
        kids = [url, part] + ([key] if key is not None else [])
        super().__init__(kids)

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True
        from spark_rapids_tpu.expr.base import Literal

        self._part = None
        self._key = None
        if isinstance(self.children[1], Literal) \
                and self.children[1].value is not None:
            self._part = str(self.children[1].value)
        if len(self.children) > 2 and isinstance(self.children[2], Literal):
            self._key = self.children[2].value

    def do_columnar_eval(self, ctx, cols):
        part, key = self._part, self._key

        def fn(b):
            if part not in _URL_PARTS:
                return None
            r = _parse_url_part(b.decode("utf-8", "replace"), part, key)
            return None if r is None else r.encode()

        return _host_string_map(cols[0], max(cols[0].width, 1), fn)


_SOUNDEX_CODE = {
    **{c: "1" for c in "BFPV"}, **{c: "2" for c in "CGJKQSXZ"},
    **{c: "3" for c in "DT"}, "L": "4", **{c: "5" for c in "MN"}, "R": "6",
}


def _soundex_str(s: str) -> str:
    if not s or not s[0].isalpha():
        return s  # Spark returns input unchanged when not soundex-able
    up = s.upper()
    first = up[0]
    codes = [first]
    prev = _SOUNDEX_CODE.get(first, "")
    for ch in up[1:]:
        code = _SOUNDEX_CODE.get(ch, "")
        if ch in "HW":
            continue  # h/w do not break runs
        if code and code != prev:
            codes.append(code)
        prev = code
        if len(codes) == 4:
            break
    return "".join(codes).ljust(4, "0")


class Soundex(_HostStringUnary):
    def _out_width(self, c):
        return max(c.width, 4)

    def _fn(self, b):
        return _soundex_str(b.decode("utf-8", "replace")).encode()


class Levenshtein(BinaryExpression):
    """levenshtein(a, b) — edit-distance DP as a lax.scan over a's bytes
    with the full DP row as carry: O(w1) fused vector steps over all rows
    at once (the one misc function with a real device win)."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        a, b = cols
        w1, w2 = a.width, b.width
        la = a.lengths.astype(jnp.int32)
        lb = b.lengths.astype(jnp.int32)
        cap = a.capacity
        # dp[j] = distance(a[:i], b[:j]); init row: dp[j] = j
        init = jnp.broadcast_to(jnp.arange(w2 + 1, dtype=jnp.int32),
                                (cap, w2 + 1))

        bj = b.chars  # (cap, w2)

        def step(dp, ai):
            # ai: (cap,) byte of a at position i (garbage past la, masked)
            achar, idx = ai
            sub_cost = (bj != achar[:, None]).astype(jnp.int32)
            # new[0] = i+1
            def inner(carry, j):
                prev_diag, new_prev = carry
                dele = dp[:, j + 1] + 1
                ins = new_prev + 1
                sub = prev_diag + sub_cost[:, j]
                val = jnp.minimum(jnp.minimum(dele, ins), sub)
                return (dp[:, j + 1], val), val

            first = jnp.full((cap,), 0, jnp.int32) + (idx + 1)
            (_, _), rest = jax.lax.scan(
                inner, (dp[:, 0], first), jnp.arange(w2))
            new_dp = jnp.concatenate([first[:, None], rest.T], axis=1)
            keep = idx < la
            new_dp = jnp.where(keep[:, None], new_dp, dp)
            return new_dp, None

        xs = (a.chars.T, jnp.arange(w1, dtype=jnp.int32))
        dp, _ = jax.lax.scan(step, init, xs)
        res = jnp.take_along_axis(dp, jnp.clip(lb, 0, w2)[:, None],
                                  axis=1)[:, 0]
        validity = a.validity & b.validity
        return DeviceColumn(T.INT, validity, data=res)


class MonotonicallyIncreasingID(Expression):
    """monotonically_increasing_id(): (partition_id << 33) | row index.

    The session executes one logical partition; batches contribute a
    running row offset carried on the EvalContext (host-kernel flag forces
    the eager stage path, where the offset is a concrete int)."""

    is_host_kernel = True

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = False

    def eval_tpu(self, ctx: EvalContext) -> DeviceColumn:
        cap = ctx.batch.capacity
        base = jnp.int64(ctx.row_offset)
        ids = base + jnp.arange(cap, dtype=jnp.int64)
        return DeviceColumn(T.LONG, jnp.ones(cap, jnp.bool_), data=ids)


class SparkPartitionID(Expression):
    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = False

    def eval_tpu(self, ctx: EvalContext) -> DeviceColumn:
        cap = ctx.batch.capacity
        pid = jnp.int32(getattr(ctx, "partition_id", 0))
        return DeviceColumn(T.INT, jnp.ones(cap, jnp.bool_),
                            data=jnp.full(cap, pid, jnp.int32))


class Rand(Expression):
    """rand([seed]) — uniform [0,1) from threefry keyed on (seed, row).

    Deterministic and seedable but NOT Spark's XORShiftRandom sequence
    (TypeSig note); the oracle evaluates the identical spec."""

    is_host_kernel = True

    def __init__(self, seed: int = 0):
        super().__init__([])
        self.seed = int(seed)

    def _resolve_type(self):
        self._dataType = T.DOUBLE
        self._nullable = False

    @staticmethod
    def _u64_for_rows(seed: int, base: int, n: int) -> np.ndarray:
        """Spec shared with the oracle: splitmix64 of (seed*2^32 + row)."""
        rows = np.arange(base, base + n, dtype=np.uint64)
        x = (np.uint64(seed) << np.uint64(32)) + rows
        z = (x + np.uint64(0x9E3779B97F4A7C15))
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        z = z ^ (z >> np.uint64(31))
        return z

    def eval_tpu(self, ctx: EvalContext) -> DeviceColumn:
        cap = ctx.batch.capacity
        base = int(ctx.row_offset)
        seed = self.seed

        def run():
            z = Rand._u64_for_rows(seed, base, cap)
            return ((z >> np.uint64(11)).astype(np.float64)
                    / float(1 << 53),)

        (vals,) = call_host_kernel(
            run, (jax.ShapeDtypeStruct((cap,), np.float64),))
        return DeviceColumn(T.DOUBLE, jnp.ones(cap, jnp.bool_), data=vals)


class RaiseError(UnaryExpression):
    """raise_error(msg) — surfaces through the batch error flags."""

    def _resolve_type(self):
        self._dataType = T.NULL
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        ctx.add_error(c.validity, "raise_error invoked")
        cap = c.capacity
        return DeviceColumn(T.NULL, jnp.zeros(cap, jnp.bool_),
                            data=jnp.zeros(cap, jnp.int32))


class UrlEncode(_HostStringUnary):
    """url_encode(s) — application/x-www-form-urlencoded (Spark 3.4)."""

    def _out_width(self, c):
        return max(c.width * 3, 3)

    def _fn(self, b):
        from urllib.parse import quote_plus

        return quote_plus(b.decode("utf-8", "replace")).encode()


class UrlDecode(_HostStringUnary):
    """url_decode(s) — invalid escapes raise in Spark; here -> null."""

    def _fn(self, b):
        from urllib.parse import unquote_plus

        s = b.decode("utf-8", "replace")
        import re as _re

        if _re.search(r"%(?![0-9A-Fa-f]{2})", s):
            return None
        return unquote_plus(s).encode()


class JsonArrayLength(_HostStringUnary):
    """json_array_length(s) -> int (null unless a valid JSON array)."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        import json as _json

        c = cols[0]
        cap = c.capacity

        def run(chars, lengths, validity):
            chars = np.asarray(chars)
            lengths = np.asarray(lengths)
            validity = np.asarray(validity)
            out = np.zeros(cap, np.int32)
            ok = np.zeros(cap, np.bool_)
            for i in range(cap):
                if not validity[i]:
                    continue
                try:
                    v = _json.loads(bytes(chars[i, :lengths[i]]))
                except ValueError:
                    continue
                if isinstance(v, list):
                    out[i] = len(v)
                    ok[i] = True
            return out, ok

        shapes = (jax.ShapeDtypeStruct((cap,), np.int32),
                  jax.ShapeDtypeStruct((cap,), np.bool_))
        o, ok = call_host_kernel(run, shapes, c.chars, c.lengths,
                                 c.validity)
        return DeviceColumn(T.INT, ok, data=o)


class JsonObjectKeys(_HostStringUnary):
    """json_object_keys(s) -> array<string> (null unless a JSON object)."""

    MAX_KEYS = 64
    KEY_WIDTH = 32

    def _resolve_type(self):
        self._dataType = T.ArrayType(T.STRING, containsNull=False)
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        import json as _json

        c = cols[0]
        cap = c.capacity
        ew, w = self.MAX_KEYS, self.KEY_WIDTH

        def run(chars, lengths, validity):
            chars = np.asarray(chars)
            lengths = np.asarray(lengths)
            validity = np.asarray(validity)
            och = np.zeros((cap, ew, w), np.uint8)
            olen = np.zeros((cap, ew), np.int32)
            cnt = np.zeros(cap, np.int32)
            ok = np.zeros(cap, np.bool_)
            ev = np.zeros((cap, ew), np.bool_)
            for i in range(cap):
                if not validity[i]:
                    continue
                try:
                    v = _json.loads(bytes(chars[i, :lengths[i]]))
                except ValueError:
                    continue
                if not isinstance(v, dict):
                    continue
                ok[i] = True
                for j, k in enumerate(list(v)[:ew]):
                    kb = str(k).encode()[:w]
                    och[i, j, :len(kb)] = np.frombuffer(kb, np.uint8)
                    olen[i, j] = len(kb)
                    ev[i, j] = True
                cnt[i] = min(len(v), ew)
            return och, olen, cnt, ok, ev

        shapes = (jax.ShapeDtypeStruct((cap, ew, w), np.uint8),
                  jax.ShapeDtypeStruct((cap, ew), np.int32),
                  jax.ShapeDtypeStruct((cap,), np.int32),
                  jax.ShapeDtypeStruct((cap,), np.bool_),
                  jax.ShapeDtypeStruct((cap, ew), np.bool_))
        och, olen, cnt, ok, ev = call_host_kernel(
            run, shapes, c.chars, c.lengths, c.validity)
        return DeviceColumn(self.dataType, ok, chars=och, data=olen,
                            lengths=cnt, elem_valid=ev)


class FormatString(Expression):
    """format_string(fmt, args...) — literal java-style fmt (the %s/%d/%f
    family), host kernel."""

    is_host_kernel = True

    def __init__(self, children):
        super().__init__(list(children))

    def sql_string(self):
        return ("format_string("
                + ", ".join(c.sql_string() for c in self.children) + ")")

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        fmt = str(self.children[0].value)
        args = cols[1:]
        cap = args[0].capacity if args else ctx.batch.capacity
        arg_rows = []
        for a, e in zip(args, self.children[1:]):
            arg_rows.append((a, e.dataType))
        str_w = sum(a.width for a in args if a.is_string)
        out_w = max(len(fmt) * 4 + 64 + str_w, 64)

        def run(*flat):
            vals = []
            k = 0
            for a, dt in arg_rows:
                if a.is_string:
                    vals.append(("s", np.asarray(flat[k]),
                                 np.asarray(flat[k + 1]),
                                 np.asarray(flat[k + 2])))
                    k += 3
                else:
                    vals.append(("n", np.asarray(flat[k]),
                                 np.asarray(flat[k + 1]), dt))
                    k += 2
            och = np.zeros((cap, out_w), np.uint8)
            oln = np.zeros(cap, np.int32)
            ova = np.zeros(cap, np.bool_)
            pyfmt = fmt.replace("%%", "\x00")
            for i in range(cap):
                row = []
                null = False
                for v in vals:
                    if v[0] == "s":
                        _, ch, ln, va = v
                        if not va[i]:
                            null = True
                            break
                        row.append(bytes(ch[i, :ln[i]]).decode(
                            "utf-8", "replace"))
                    else:
                        _, d, va, dt = v
                        if not va[i]:
                            null = True
                            break
                        row.append(float(d[i]) if isinstance(
                            dt, (T.FloatType, T.DoubleType))
                            else int(d[i]))
                if null:
                    continue
                try:
                    res = (pyfmt % tuple(row)).replace("\x00", "%")
                except (TypeError, ValueError):
                    continue
                rb = res.encode()[:out_w]
                och[i, :len(rb)] = np.frombuffer(rb, np.uint8)
                oln[i] = len(rb)
                ova[i] = True
            return och, oln, ova

        flat = []
        for a, dt in arg_rows:
            if a.is_string:
                flat += [a.chars, a.lengths, a.validity]
            else:
                flat += [a.data, a.validity]
        shapes = (jax.ShapeDtypeStruct((cap, out_w), np.uint8),
                  jax.ShapeDtypeStruct((cap,), np.int32),
                  jax.ShapeDtypeStruct((cap,), np.bool_))
        och, oln, ova = call_host_kernel(run, shapes, *flat)
        return DeviceColumn(T.STRING, ova, chars=och, lengths=oln)


class Uuid(Expression):
    """uuid(): deterministic splitmix64 stream per (seed, row) — the same
    documented-determinism stance as Rand (reference marks both
    nondeterministic-incompat)."""

    is_host_kernel = True

    def __init__(self, seed: int = 0):
        super().__init__([])
        self.seed = seed

    def sql_string(self):
        return "uuid()"

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        cap = ctx.batch.capacity
        base = jnp.uint64((self.seed * 0x9E3779B97F4A7C15 + 0xA5A5A5A5)
                          & 0xFFFFFFFFFFFFFFFF)
        idx = (jnp.arange(cap, dtype=jnp.uint64)
               + jnp.uint64(ctx.row_offset))

        def mix(z):
            z = (z + jnp.uint64(0x9E3779B97F4A7C15))
            z = (z ^ (z >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
            z = (z ^ (z >> 27)) * jnp.uint64(0x94D049BB133111EB)
            return z ^ (z >> 31)

        hi = mix(base + idx * jnp.uint64(2))
        lo = mix(base + idx * jnp.uint64(2) + jnp.uint64(1))
        # rfc-4122 v4 bits
        hi = (hi & jnp.uint64(0xFFFFFFFFFFFF0FFF)) | jnp.uint64(0x4000)
        lo = (lo & jnp.uint64(0x3FFFFFFFFFFFFFFF)) | jnp.uint64(1 << 63)
        hexd = jnp.asarray(
            np.frombuffer(b"0123456789abcdef", np.uint8))
        out = jnp.zeros((cap, 36), jnp.uint8)
        dash = jnp.uint8(ord("-"))
        spans = [(0, 8, "hi", 32), (9, 4, "hi", 16), (14, 4, "hi", 0),
                 (19, 4, "lo", 48), (24, 12, "lo", 0)]
        for start, nd, which, shift in spans:
            word = hi if which == "hi" else lo
            seg = (word >> jnp.uint64(shift)) & \
                jnp.uint64((1 << (nd * 4)) - 1)
            for j in range(nd):
                nib = ((seg >> jnp.uint64((nd - 1 - j) * 4))
                       & jnp.uint64(0xF)).astype(jnp.int32)
                out = out.at[:, start + j].set(hexd[nib])
        for pos in (8, 13, 18, 23):
            out = out.at[:, pos].set(dash)
        return DeviceColumn(T.STRING, jnp.ones(cap, jnp.bool_),
                            chars=out,
                            lengths=jnp.full(cap, 36, jnp.int32))


class Pi(Expression):
    """pi()"""

    def __init__(self):
        super().__init__([])

    def sql_string(self):
        return "pi()"

    def _resolve_type(self):
        self._dataType = T.DOUBLE
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        import math as _m

        cap = ctx.batch.capacity
        return DeviceColumn(T.DOUBLE, jnp.ones(cap, jnp.bool_),
                            data=jnp.full(cap, _m.pi, jnp.float64))


class EulerNumber(Expression):
    """e()"""

    def __init__(self):
        super().__init__([])

    def sql_string(self):
        return "e()"

    def _resolve_type(self):
        self._dataType = T.DOUBLE
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        import math as _m

        cap = ctx.batch.capacity
        return DeviceColumn(T.DOUBLE, jnp.ones(cap, jnp.bool_),
                            data=jnp.full(cap, _m.e, jnp.float64))


class BitGet(BinaryExpression):
    """bit_get(v, pos) -> 0/1 byte; pos outside [0, bits) errors.

    Reference analog: GpuBitwiseGet (SURVEY.md §2.5 Hash/misc)."""

    def _resolve_type(self):
        self._dataType = T.BYTE
        self._nullable = True

    def sql_string(self):
        return (f"bit_get({self.left.sql_string()}, "
                f"{self.right.sql_string()})")

    def do_columnar_eval(self, ctx: EvalContext, cols):
        v, p = cols
        bits = {T.ByteType: 8, T.ShortType: 16, T.IntegerType: 32,
                T.LongType: 64}[type(self.left.dataType)]
        pos = p.data.astype(jnp.int32)
        valid = v.validity & p.validity
        bad = valid & ((pos < 0) | (pos >= bits))
        ctx.add_error(bad, f"Invalid bit position: must be in [0, {bits})")
        safe = jnp.clip(pos, 0, bits - 1)
        out = jax.lax.shift_right_logical(
            v.data.astype(jnp.int64),
            safe.astype(jnp.int64)) & jnp.int64(1)
        return DeviceColumn(T.BYTE, valid, data=out.astype(jnp.int8))


class AssertTrue(UnaryExpression):
    """assert_true(cond): NULL, erroring when any row is false."""

    def _resolve_type(self):
        self._dataType = T.NullType()
        self._nullable = True

    def sql_string(self):
        return f"assert_true({self.child.sql_string()})"

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        bad = ~(c.validity & c.data.astype(jnp.bool_))
        ctx.add_error(bad, f"'{self.child.sql_string()}' is not true!")
        cap = c.capacity
        return DeviceColumn(T.NullType(), jnp.zeros(cap, jnp.bool_),
                            data=jnp.zeros(cap, jnp.int8))


class TypeOf(UnaryExpression):
    """typeof(expr) -> the SQL type name (constant per column)."""

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = False

    def sql_string(self):
        return f"typeof({self.child.sql_string()})"

    def do_columnar_eval(self, ctx: EvalContext, cols):
        from spark_rapids_tpu.columnar.column import HostColumn

        cap = cols[0].capacity
        s = self.child.dataType.simpleString
        host = HostColumn.from_pylist([s] * cap, T.STRING)
        return DeviceColumn.from_host(host, capacity=cap)


class ToBinary(Expression):
    """to_binary(str[, fmt]) -> binary (string column, the engine's binary
    representation).  fmt literal in {'utf-8','utf8','hex','base64'}.

    Reference analog: GpuToBinary paths (hex via GpuUnhex, utf-8 identity;
    SURVEY.md §2.5 Strings)."""

    is_host_kernel = True
    _try = False

    def __init__(self, child: Expression, fmt: Optional[Expression] = None):
        super().__init__([child] if fmt is None else [child, fmt])

    def _resolve_type(self):
        from spark_rapids_tpu.expr.base import Literal

        self._dataType = T.STRING
        self._nullable = True
        self._fmt = "hex"
        if len(self.children) > 1:
            f = self.children[1]
            if isinstance(f, Literal) and f.value is not None:
                self._fmt = str(f.value).lower()

    def sql_string(self):
        name = "try_to_binary" if self._try else "to_binary"
        return f"{name}({self.children[0].sql_string()}, '{self._fmt}')"

    def do_columnar_eval(self, ctx, cols):
        fmt = self._fmt
        c = cols[0]

        if fmt in ("utf-8", "utf8"):
            return DeviceColumn(T.STRING, c.validity, chars=c.chars,
                                lengths=c.lengths)

        import base64 as b64

        def from_hex(b):
            t = b.decode("ascii", "replace")
            if not all(ch in "0123456789abcdefABCDEF" for ch in t):
                return None
            if len(t) % 2:
                t = "0" + t
            return bytes.fromhex(t)

        def from_b64(b):
            try:
                return b64.b64decode(b, validate=True)
            except Exception:
                return None

        fn = from_hex if fmt == "hex" else from_b64
        width = max(1, (c.width + 1) // 2 if fmt == "hex"
                    else (c.width * 3 + 3) // 4)
        out = _host_string_map(c, width, fn)
        if not self._try:
            bad = c.validity & ~out.validity
            ctx.add_error(bad, f"to_binary: malformed {fmt} input")
        return out


class TryToBinary(ToBinary):
    """try_to_binary: NULL instead of error on malformed input."""

    _try = True


class BitmapBitPosition(UnaryExpression):
    """bitmap_bit_position(long): 0-based position within a bitmap bucket
    (Spark: (input - 1) % 32768 for positive, input % 32768 otherwise)."""

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        v = cols[0].data.astype(jnp.int64)
        adj = jnp.where(v > 0, v - 1, v)
        # Spark uses Math.floorMod against the bitmap bit count
        pos = jnp.remainder(adj, jnp.int64(32768))
        pos = jnp.where(pos < 0, pos + 32768, pos)
        return DeviceColumn(T.LONG, cols[0].validity, data=pos)


class BitmapBucketNumber(UnaryExpression):
    """bitmap_bucket_number(long): 1-based bucket (floorDiv by 32768 + 1
    for positive inputs; Spark's GetBucketNumber)."""

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        v = cols[0].data.astype(jnp.int64)
        adj = jnp.where(v > 0, v - 1, v)
        bucket = jnp.floor_divide(adj, jnp.int64(32768))
        bucket = jnp.where(v > 0, bucket + 1, bucket)
        return DeviceColumn(T.LONG, cols[0].validity, data=bucket)


class BitmapCount(UnaryExpression):
    """bitmap_count(binary): number of set bits in the blob.

    Caveat (shared with every binary-as-string surface, e.g. UnBase64):
    the engine's binary representation round-trips through utf-8-replace
    at row boundaries, so blobs with bytes >= 0x80 lose bit fidelity when
    they cross a host row boundary before reaching this expression; the
    device-resident path counts the raw bytes."""

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        if not c.width:
            return DeviceColumn(T.LONG, c.validity,
                                data=jnp.zeros(c.capacity, jnp.int64))
        in_len = jnp.arange(c.width)[None, :] < c.lengths[:, None]
        pop = _popcount_u8(c.chars)
        total = jnp.sum(jnp.where(in_len, pop, 0), axis=1).astype(jnp.int64)
        return DeviceColumn(T.LONG, c.validity, data=total)


def _popcount_u8(b):
    x = b.astype(jnp.int32)
    x = x - ((x >> 1) & 0x55)
    x = (x & 0x33) + ((x >> 2) & 0x33)
    return (x + (x >> 4)) & 0x0F


class Randn(UnaryExpression):
    """randn([seed]): standard normal via Box-Muller over the same
    splitmix stream Rand uses (not Spark's XORShiftRandom sequence —
    documented incompatibility, like GpuRand)."""

    def __init__(self, seed: Expression):
        super().__init__(seed)

    def _resolve_type(self):
        self._dataType = T.DOUBLE
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        seed = 0
        if isinstance(self.child, Literal) and self.child.value is not None:
            seed = int(self.child.value)
        cap = ctx.batch.capacity
        idx = jnp.arange(cap, dtype=jnp.uint64)
        u1 = _splitmix_unit(idx, jnp.uint64(seed * 2654435769 + 1))
        u2 = _splitmix_unit(idx, jnp.uint64(seed * 2654435769 + 2))
        r = jnp.sqrt(-2.0 * jnp.log(jnp.maximum(u1, 1e-300)))
        out = r * jnp.cos(2.0 * jnp.pi * u2)
        return DeviceColumn(T.DOUBLE, jnp.ones(cap, jnp.bool_), data=out)


def _splitmix_unit(idx, salt):
    z = idx * jnp.uint64(0x9E3779B97F4A7C15) + salt
    z = (z ^ (z >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> 27)) * jnp.uint64(0x94D049BB133111EB)
    z = z ^ (z >> 31)
    return (z >> 11).astype(jnp.float64) / float(1 << 53)


class Sentences(Expression):
    """sentences(str[, lang, country]) -> array<array<string>> of words
    per sentence.

    The output type needs a nested list-of-list-of-string device layout
    that the padded columnar model does not carry; the expression is
    registered with a permanent tag-time fallback (overrides.py
    _check_sentences) and executes on the CPU oracle — the reference
    likewise leaves Sentences on CPU (no GpuSentences rule)."""

    def __init__(self, child, lang=None, country=None):
        kids = [child]
        if lang is not None:
            kids.append(lang)
        if country is not None:
            kids.append(country)
        super().__init__(kids)

    def _resolve_type(self):
        self._dataType = T.ArrayType(T.ArrayType(T.STRING))
        self._nullable = True

    def sql_string(self):
        return f"sentences({self.children[0].sql_string()})"

    def do_columnar_eval(self, ctx, cols):
        raise NotImplementedError(
            "Sentences always falls back to CPU (nested array<array> "
            "layout); the tag rule prevents this path")


def _parse_number_format(fmt: str):
    """Validate a to_number/to_char format and derive (precision, scale,
    grouping, currency, sign_mode).  Subset: 0/9 digits, ',' grouping,
    '.' point, leading '$', 'S' (start/end), trailing 'MI'."""
    f = fmt.upper()
    sign = None
    if f.startswith("S"):
        sign, f = "S_START", f[1:]
    elif f.endswith("S"):
        sign, f = "S_END", f[:-1]
    elif f.endswith("MI"):
        sign, f = "MI", f[:-2]
    currency = False
    if f.startswith("$"):
        currency, f = True, f[1:]
    if "." in f:
        ip, _, fp = f.partition(".")
    else:
        ip, fp = f, ""
    if not all(c in "09," for c in ip) or not all(c in "09" for c in fp):
        return None
    int_digits = sum(1 for c in ip if c in "09")
    scale = len(fp)
    if int_digits + scale == 0 or int_digits + scale > 38:
        return None
    return {"precision": int_digits + scale, "scale": scale,
            "grouping": "," in ip, "currency": currency, "sign": sign,
            "int_digits": int_digits}


class ToNumber(Expression):
    """to_number(str, fmt) -> decimal; strict parse per the format.

    Reference analog: GpuToNumber subset (sql-plugin stringFunctions).
    Host kernel (format grammar is branchy row work; the batch stays
    columnar around it)."""

    is_host_kernel = True
    _try = False

    def __init__(self, child: Expression, fmt: Expression):
        super().__init__([child, fmt])

    def _resolve_type(self):
        from spark_rapids_tpu.expr.base import Literal

        self._spec = None
        f = self.children[1]
        if isinstance(f, Literal) and f.value is not None:
            self._spec = _parse_number_format(str(f.value))
        if self._spec:
            self._dataType = T.DecimalType(self._spec["precision"],
                                           self._spec["scale"])
        else:
            self._dataType = T.DecimalType(38, 0)
        self._nullable = True

    def sql_string(self):
        name = "try_to_number" if self._try else "to_number"
        return (f"{name}({self.children[0].sql_string()}, "
                f"{self.children[1].sql_string()})")

    def do_columnar_eval(self, ctx, cols):
        import re as _re

        c = cols[0]
        cap = c.capacity
        spec = self._spec
        scale = spec["scale"]
        pat = "^"
        if spec["sign"] == "S_START":
            pat += "([+-])?"
        if spec["currency"]:
            pat += r"\$"
        pat += r"([0-9][0-9,]*)?" if spec["grouping"] else "([0-9]+)?"
        if scale:
            pat += r"(?:\.([0-9]{0,%d}))?" % scale
        else:
            pat += "()?"
        if spec["sign"] == "S_END":
            pat += "([+-])?"
        elif spec["sign"] == "MI":
            pat += "(-)?"
        else:
            pat += "()?"
        pat += "$"
        rx = _re.compile(pat)
        int_digits = spec["int_digits"]
        two_limb = self.dataType.is_128

        def run(chars, lengths, validity):
            chars = np.asarray(chars)
            lengths = np.asarray(lengths)
            validity = np.asarray(validity)
            if two_limb:
                out = np.zeros((cap, 2), np.int64)
            else:
                out = np.zeros(cap, np.int64)
            ok = np.zeros(cap, np.bool_)
            for i in range(cap):
                if not validity[i]:
                    continue
                s = bytes(chars[i, :lengths[i]]).decode("utf-8", "replace")
                m = rx.match(s.strip())
                if not m:
                    continue
                g = m.groups()
                sign_s = g[0] if len(g) > 2 and spec["sign"] == "S_START" \
                    else (g[-1] or "")
                ipart = (g[1] if spec["sign"] == "S_START" else g[0]) or ""
                fpart = (g[2] if spec["sign"] == "S_START" else g[1]) or ""
                digits = ipart.replace(",", "")
                if not digits and not fpart:
                    continue
                if len(digits.lstrip("0") or "0") > int_digits \
                        and len(digits.lstrip("0")) > int_digits:
                    continue
                unscaled = int((digits or "0")
                               + (fpart or "").ljust(scale, "0"))
                if sign_s == "-":
                    unscaled = -unscaled
                if two_limb:
                    out[i, 0] = unscaled >> 64 if unscaled >= 0 \
                        else ~((~unscaled) >> 64)
                    out[i, 1] = np.uint64(
                        unscaled & ((1 << 64) - 1)).astype(np.int64)
                else:
                    out[i] = unscaled
                ok[i] = True
            return out, ok

        shape = ((cap, 2) if two_limb else (cap,))
        o, ok = call_host_kernel(
            run, (jax.ShapeDtypeStruct(shape, np.int64),
                  jax.ShapeDtypeStruct((cap,), np.bool_)),
            c.chars, c.lengths, c.validity)
        if not self._try:
            ctx.add_error(c.validity & ~ok,
                          "to_number: input does not match the format")
        return DeviceColumn(self.dataType, ok, data=o)


class TryToNumber(ToNumber):
    _try = True


class ToCharacter(Expression):
    """to_char(numeric, fmt) -> string (same format subset as ToNumber)."""

    is_host_kernel = True

    def __init__(self, child: Expression, fmt: Expression):
        super().__init__([child, fmt])

    def _resolve_type(self):
        from spark_rapids_tpu.expr.base import Literal

        self._spec = None
        f = self.children[1]
        if isinstance(f, Literal) and f.value is not None:
            self._spec = _parse_number_format(str(f.value))
        self._dataType = T.STRING
        self._nullable = True

    def sql_string(self):
        return (f"to_char({self.children[0].sql_string()}, "
                f"{self.children[1].sql_string()})")

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        cap = c.capacity
        spec = self._spec
        in_dt = self.children[0].dataType
        in_scale = in_dt.scale if isinstance(in_dt, T.DecimalType) else 0
        scale = spec["scale"]
        width = spec["precision"] + 8
        two_limb = isinstance(in_dt, T.DecimalType) and in_dt.is_128

        def run(data, validity):
            import decimal
            from decimal import Decimal as D

            data = np.asarray(data)
            validity = np.asarray(validity)
            out_chars = np.zeros((cap, width), np.uint8)
            out_lens = np.zeros(cap, np.int32)
            ok = np.zeros(cap, np.bool_)
            for i in range(cap):
                if not validity[i]:
                    continue
                if two_limb:
                    unscaled = (int(data[i, 0]) << 64) | int(
                        np.uint64(data[i, 1]))
                else:
                    unscaled = int(data[i])
                with decimal.localcontext() as dctx:
                    dctx.prec = 60      # 38-digit decimals need headroom
                    v = D(unscaled).scaleb(-in_scale)
                    q = v.quantize(D(1).scaleb(-scale)) if scale else \
                        v.quantize(D(1))
                neg = q < 0
                digits = format(abs(q), "f")
                if "." in digits:
                    ipart, _, fpart = digits.partition(".")
                else:
                    ipart, fpart = digits, ""
                if len(ipart.lstrip("0") or "") > spec["int_digits"]:
                    s = "#" * (spec["precision"] + (1 if scale else 0))
                else:
                    if spec["grouping"]:
                        rev = ipart[::-1]
                        ipart = ",".join(rev[j:j + 3]
                                         for j in range(0, len(rev),
                                                        3))[::-1]
                    s = ipart + (("." + fpart.ljust(scale, "0"))
                                 if scale else "")
                    if spec["currency"]:
                        s = "$" + s
                    if spec["sign"] == "S_START":
                        s = ("-" if neg else "+") + s
                    elif spec["sign"] == "S_END":
                        s = s + ("-" if neg else "+")
                    elif spec["sign"] == "MI":
                        s = s + ("-" if neg else " ")
                    elif neg:
                        s = "-" + s
                b = s.encode("ascii")[:width]
                out_chars[i, :len(b)] = np.frombuffer(b, np.uint8)
                out_lens[i] = len(b)
                ok[i] = True
            return out_chars, out_lens, ok

        och, oln, ok = call_host_kernel(
            run, (jax.ShapeDtypeStruct((cap, width), np.uint8),
                  jax.ShapeDtypeStruct((cap,), np.int32),
                  jax.ShapeDtypeStruct((cap,), np.bool_)),
            c.data, c.validity)
        return DeviceColumn(T.STRING, c.validity & ok, chars=och,
                            lengths=oln)


CURRENT_INPUT_FILE = [""]    # set by the scan exec at batch-yield time


class InputFileName(Expression):
    """input_file_name(): path of the file the current batch was scanned
    from; empty string outside a file scan (Spark semantics, backed by
    the InputFileBlockHolder analog ``CURRENT_INPUT_FILE``).

    Marked as a host kernel so the enclosing stage runs EAGERLY: under a
    jit trace the path would bake into the cached program as a constant
    and go stale on the next file; eager evaluation reads the holder at
    batch-processing time (pull execution makes that the right file)."""

    is_host_kernel = True

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = False

    def sql_string(self):
        return "input_file_name()"

    def do_columnar_eval(self, ctx, cols):
        cap = ctx.batch.capacity
        path = getattr(ctx.batch, "input_file", None)
        if path is None:
            path = CURRENT_INPUT_FILE[0]
        b = path.encode("utf-8")
        w = max(len(b), 1)
        chars = jnp.broadcast_to(
            jnp.asarray(np.frombuffer(b.ljust(w, b"\0"), np.uint8)),
            (cap, w))
        lengths = jnp.full(cap, len(b), jnp.int32)
        return DeviceColumn(T.STRING, jnp.ones(cap, jnp.bool_),
                            chars=chars, lengths=lengths)
