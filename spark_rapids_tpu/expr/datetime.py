"""Date/time expressions.

Reference analog: org/apache/spark/sql/rapids/datetimeExpressions.scala
(GpuYear/GpuMonth/GpuDayOfMonth/GpuHour..., GpuDateAdd/GpuDateSub,
GpuDateDiff, GpuToUnixTimestamp) with jni timezones.cu for tz conversion.
Timestamps are UTC micros; session-timezone tables come in a later round
(reference gates non-UTC behind GpuTimeZoneDB the same way).

All field extraction rides the branch-free civil-calendar math in cast.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (BinaryExpression, Expression,
                                        UnaryExpression)
from spark_rapids_tpu.expr.cast import civil_from_days, days_from_civil

_US_PER_DAY = 86_400_000_000


def _days_of(c: DeviceColumn, dtype: T.DataType):
    if isinstance(dtype, T.TimestampType):
        return jnp.floor_divide(c.data, _US_PER_DAY)
    return c.data.astype(jnp.int64)


class _DateField(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        days = _days_of(c, self.child.dataType)
        y, m, d = civil_from_days(days)
        return DeviceColumn(T.INT, c.validity,
                            data=self._field(y, m, d, days).astype(jnp.int32))

    def _field(self, y, m, d, days):
        raise NotImplementedError


class Year(_DateField):
    def _field(self, y, m, d, days):
        return y


class Month(_DateField):
    def _field(self, y, m, d, days):
        return m


class DayOfMonth(_DateField):
    def _field(self, y, m, d, days):
        return d


class DayOfWeek(_DateField):
    """Spark: Sunday=1 ... Saturday=7; epoch day 0 was a Thursday."""

    def _field(self, y, m, d, days):
        return ((days + 4) % 7) + 1


class DayOfYear(_DateField):
    def _field(self, y, m, d, days):
        jan1 = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        return (days - jan1 + 1).astype(jnp.int64)


class Quarter(_DateField):
    def _field(self, y, m, d, days):
        return (m - 1) // 3 + 1


class LastDay(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        days = _days_of(c, self.child.dataType)
        y, m, _ = civil_from_days(days)
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        first_next = days_from_civil(ny, nm, jnp.ones_like(nm))
        return DeviceColumn(T.DATE, c.validity,
                            data=(first_next - 1).astype(jnp.int32))


class _TimeField(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        rem = c.data - jnp.floor_divide(c.data, _US_PER_DAY) * _US_PER_DAY
        return DeviceColumn(T.INT, c.validity,
                            data=self._field(rem).astype(jnp.int32))


class Hour(_TimeField):
    def _field(self, rem):
        return rem // 3_600_000_000


class Minute(_TimeField):
    def _field(self, rem):
        return (rem // 60_000_000) % 60


class Second(_TimeField):
    def _field(self, rem):
        return (rem // 1_000_000) % 60


class DateAdd(BinaryExpression):
    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        d, n = cols
        return DeviceColumn(T.DATE, d.validity & n.validity,
                            data=(d.data + n.data.astype(jnp.int32)))


class DateSub(BinaryExpression):
    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        d, n = cols
        return DeviceColumn(T.DATE, d.validity & n.validity,
                            data=(d.data - n.data.astype(jnp.int32)))


class DateDiff(BinaryExpression):
    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        a, b = cols
        return DeviceColumn(T.INT, a.validity & b.validity,
                            data=(a.data - b.data).astype(jnp.int32))


class UnixTimestamp(UnaryExpression):
    """to_unix_timestamp(ts) -> seconds."""

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        if isinstance(self.child.dataType, T.DateType):
            secs = c.data.astype(jnp.int64) * 86_400
        else:
            secs = jnp.floor_divide(c.data, 1_000_000)
        return DeviceColumn(T.LONG, c.validity, data=secs)


class WeekOfYear(_DateField):
    """ISO-8601 week number (Spark WeekOfYear: week containing Thursday)."""

    def _field(self, y, m, d, days):
        # ISO week: shift to the Thursday of this row's week, then count
        # weeks from that year's Jan 1st week
        dow0 = (days + 3) % 7          # Monday=0 ... Sunday=6
        thursday = days - dow0 + 3
        ty, _, _ = civil_from_days(thursday)
        jan1 = days_from_civil(ty, jnp.full_like(ty, 1), jnp.full_like(ty, 1))
        return ((thursday - jan1) // 7 + 1).astype(jnp.int64)


def _month_len(y, m):
    """Days in month (y, m) via civil-day differences."""
    next_m_y = jnp.where(m == 12, y + 1, y)
    next_m = jnp.where(m == 12, 1, m + 1)
    return (days_from_civil(next_m_y, next_m, jnp.ones_like(m))
            - days_from_civil(y, m, jnp.ones_like(m)))


def _clamped_ymd_to_days(y, m, d):
    """days_from_civil with day-of-month clamped to the month length."""
    return days_from_civil(y, m, jnp.minimum(d, _month_len(y, m)))


class AddMonths(BinaryExpression):
    """add_months(date, n): day clamped to the target month's last day."""

    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c, n = cols
        days = _days_of(c, self.children[0].dataType)
        y, m, d = civil_from_days(days)
        total = (y * 12 + (m - 1)) + n.data.astype(jnp.int64)
        ny = total // 12
        nm = total % 12 + 1
        out = _clamped_ymd_to_days(ny, nm, d)
        return DeviceColumn(T.DATE, c.validity & n.validity,
                            data=out.astype(jnp.int32))


class MonthsBetween(BinaryExpression):
    """months_between(ts1, ts2[, roundOff=true]) -> double.

    Spark: whole months when both are the same day-of-month or both are
    month ends; otherwise day difference / 31 with time-of-day fraction,
    rounded to 8 digits when roundOff."""

    def __init__(self, left, right, round_off: bool = True):
        super().__init__(left, right)
        self.round_off = round_off

    def _resolve_type(self):
        self._dataType = T.DOUBLE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        a, b = cols

        def parts(c, dt):
            days = _days_of(c, dt)
            y, m, d = civil_from_days(days)
            if isinstance(dt, T.TimestampType):
                tod = (c.data - days * _US_PER_DAY).astype(jnp.float64) / 1e6
            else:
                tod = jnp.zeros_like(days, jnp.float64)
            return y, m, d, tod, _month_len(y, m)

        ya, ma, da, ta, la = parts(a, self.children[0].dataType)
        yb, mb, db, tb, lb = parts(b, self.children[1].dataType)
        months = (ya - yb) * 12 + (ma - mb)
        both_end = (da == la) & (db == lb)
        # Spark DateTimeUtils.monthsBetween: equal day-of-month (or both
        # month ends) -> whole months, time of day IGNORED
        same_day = da == db
        whole = months.astype(jnp.float64)
        frac_days = (da - db).astype(jnp.float64)
        secs = ta - tb
        frac = (frac_days * 86400.0 + secs) / (31.0 * 86400.0)
        out = jnp.where(both_end | same_day, whole, whole + frac)
        if self.round_off:
            out = jnp.round(out * 1e8) / 1e8
        return DeviceColumn(T.DOUBLE, a.validity & b.validity, data=out)


class TruncDate(BinaryExpression):
    """trunc(date, fmt): fmt is a plan-time literal (year/quarter/month/week)."""

    _FMTS = {"year": "year", "yyyy": "year", "yy": "year",
             "quarter": "quarter", "month": "month", "mon": "month",
             "mm": "month", "week": "week"}

    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        c = cols[0]
        fmt = self.children[1]
        unit = self._FMTS.get(str(fmt.value).lower()) \
            if isinstance(fmt, Literal) and fmt.value is not None else None
        days = _days_of(c, self.children[0].dataType)
        y, m, d = civil_from_days(days)
        if unit == "year":
            out = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        elif unit == "quarter":
            qm = (m - 1) // 3 * 3 + 1
            out = days_from_civil(y, qm, jnp.ones_like(d))
        elif unit == "month":
            out = days_from_civil(y, m, jnp.ones_like(d))
        elif unit == "week":
            out = days - (days + 3) % 7  # back to Monday
        else:
            # unsupported fmt -> null (Spark behavior)
            return DeviceColumn(T.DATE, jnp.zeros_like(c.validity),
                                data=jnp.zeros_like(days, jnp.int32))
        return DeviceColumn(T.DATE, c.validity, data=out.astype(jnp.int32))


class NextDay(BinaryExpression):
    """next_day(date, 'Mon'): first strictly-later date with that weekday."""

    _DOW = {"su": 0, "sun": 0, "sunday": 0, "mo": 1, "mon": 1, "monday": 1,
            "tu": 2, "tue": 2, "tues": 2, "tuesday": 2, "we": 3, "wed": 3,
            "wednesday": 3, "th": 4, "thu": 4, "thur": 4, "thurs": 4,
            "thursday": 4, "fr": 5, "fri": 5, "friday": 5, "sa": 6,
            "sat": 6, "saturday": 6}

    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        c = cols[0]
        lit_ = self.children[1]
        target = self._DOW.get(str(lit_.value).strip().lower()) \
            if isinstance(lit_, Literal) and lit_.value is not None else None
        days = _days_of(c, self.children[0].dataType)
        if target is None:
            return DeviceColumn(T.DATE, jnp.zeros_like(c.validity),
                                data=jnp.zeros_like(days, jnp.int32))
        dow = (days + 4) % 7          # Sunday=0
        delta = (target - dow) % 7
        delta = jnp.where(delta == 0, 7, delta)
        return DeviceColumn(T.DATE, c.validity,
                            data=(days + delta).astype(jnp.int32))


# -- formatting (UTC session timezone; the reference gates non-UTC behind
# GpuTimeZoneDB the same way) ------------------------------------------------

_FMT_TOKENS = ("yyyy", "MM", "dd", "HH", "mm", "ss")


def parse_format(fmt: str):
    """Pattern -> list of ('tok', name) | ('lit', char); None if unsupported."""
    out = []
    i = 0
    while i < len(fmt):
        for t in _FMT_TOKENS:
            if fmt.startswith(t, i):
                out.append(("tok", t))
                i += len(t)
                break
        else:
            ch = fmt[i]
            if ch.isalpha():
                return None          # unknown format letter
            out.append(("lit", ch))
            i += 1
    return out


def _format_to_chars(segments, y, mo, d, h, mi, s):
    """Render the static pattern into a (n, width) char matrix."""
    vals = {"yyyy": (y, 4), "MM": (mo, 2), "dd": (d, 2), "HH": (h, 2),
            "mm": (mi, 2), "ss": (s, 2)}
    cols = []
    for kind, v in segments:
        if kind == "lit":
            cols.append(jnp.full_like(y, ord(v)).astype(jnp.uint8)[:, None])
        else:
            num, w = vals[v]
            for k in range(w - 1, -1, -1):
                digit = (num // (10 ** k)) % 10
                cols.append((digit + ord("0")).astype(jnp.uint8)[:, None])
    return jnp.concatenate(cols, axis=1)


class _FormatBase(BinaryExpression):
    """Common machinery for from_unixtime / date_format with a literal
    pattern from the supported token subset."""

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def _segments(self):
        from spark_rapids_tpu.expr.base import Literal

        fmt = self.children[1]
        if not isinstance(fmt, Literal) or fmt.value is None:
            return None
        return parse_format(str(fmt.value))

    def _render(self, c, micros):
        segs = self._segments()
        days = jnp.floor_divide(micros, _US_PER_DAY)
        rem = micros - days * _US_PER_DAY
        y, mo, d = civil_from_days(days)
        h = rem // 3_600_000_000
        mi = (rem // 60_000_000) % 60
        s = (rem // 1_000_000) % 60
        chars = _format_to_chars(segs, y, mo, d, h, mi, s)
        lengths = jnp.full(c.capacity, chars.shape[1], jnp.int32)
        return DeviceColumn(T.STRING, c.validity, chars=chars,
                            lengths=lengths)


class FromUnixTime(_FormatBase):
    """from_unixtime(seconds, fmt) -> string (UTC)."""

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return self._render(c, c.data.astype(jnp.int64) * 1_000_000)


class DateFormat(_FormatBase):
    """date_format(ts_or_date, fmt) -> string (UTC)."""

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        if isinstance(self.children[0].dataType, T.DateType):
            micros = c.data.astype(jnp.int64) * _US_PER_DAY
        else:
            micros = c.data
        return self._render(c, micros)


class _UtcTzShift(BinaryExpression):
    """Base for from_utc_timestamp / to_utc_timestamp.

    Reference analog: GpuFromUTCTimestamp/GpuToUTCTimestamp via
    GpuTimeZoneDB (jni timezones.cu).  The zone's transition tables
    (spark_rapids_tpu/tzdb.py, parsed from TZif + POSIX footer rules)
    upload once; every row resolves its offset with one vectorized
    searchsorted — same shape as the reference's device binary search."""

    _to_utc = False

    def _resolve_type(self):
        self._dataType = T.TIMESTAMP
        self._nullable = True
        from spark_rapids_tpu.expr.base import Literal

        self._tz = None
        if isinstance(self.right, Literal) and self.right.value is not None:
            self._tz = str(self.right.value)

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.tzdb import zone_tables

        c = cols[0]
        tables = zone_tables(self._tz)
        offsets = jnp.asarray(tables["offsets"])
        key = "wall_starts" if self._to_utc else "utc_instants"
        bounds = jnp.asarray(tables[key])
        secs = jnp.floor_divide(c.data.astype(jnp.int64), 1_000_000)
        idx = jnp.searchsorted(bounds, secs, side="right") - 1
        off = offsets[jnp.clip(idx, 0, offsets.shape[0] - 1)]
        shift = off * jnp.int64(1_000_000)
        data = c.data - shift if self._to_utc else c.data + shift
        validity = c.validity & cols[1].validity
        return DeviceColumn(T.TIMESTAMP, validity, data=data)


class FromUTCTimestamp(_UtcTzShift):
    """from_utc_timestamp(ts, tz): render a UTC instant in tz's wall
    clock."""

    _to_utc = False


class ToUTCTimestamp(_UtcTzShift):
    """to_utc_timestamp(ts, tz): interpret ts as tz wall time; gap/overlap
    resolution matches java.time (forward shift / earlier offset)."""

    _to_utc = True


class ToUnixTimestamp(UnixTimestamp):
    """to_unix_timestamp — same device kernel as unix_timestamp."""


class WeekDay(_DateField):
    """weekday(date): Monday=0 ... Sunday=6."""

    def _field(self, y, m, d, days):
        return (days + 3) % 7


class MakeDate(Expression):
    """make_date(y, m, d) — invalid civil dates yield NULL (ANSI: error).

    Reference analog: GpuMakeDate (datetimeExpressions.scala)."""

    def __init__(self, y, m, d):
        super().__init__([y, m, d])

    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = True

    def sql_string(self):
        return ("make_date("
                + ", ".join(c.sql_string() for c in self.children) + ")")

    def do_columnar_eval(self, ctx, cols):
        y, m, d = (c.data.astype(jnp.int64) for c in cols)
        days = days_from_civil(y, m, d)
        y2, m2, d2 = civil_from_days(days)
        ok = ((y2 == y) & (m2 == m) & (d2 == d)
              & (y >= 1) & (y <= 9999))
        validity = cols[0].validity & cols[1].validity & cols[2].validity
        if ctx.ansi:
            ctx.add_error(~ok & validity, "invalid date in make_date (ANSI)")
        else:
            validity = validity & ok
        return DeviceColumn(T.DATE, validity,
                            data=days.astype(jnp.int32))


class MakeTimestamp(Expression):
    """make_timestamp(y, m, d, h, min, sec) in the UTC session timezone;
    sec is integral or fractional (micros kept exactly for decimals)."""

    def __init__(self, y, m, d, h, mi, s):
        super().__init__([y, m, d, h, mi, s])

    def _resolve_type(self):
        self._dataType = T.TIMESTAMP
        self._nullable = True

    def sql_string(self):
        return ("make_timestamp("
                + ", ".join(c.sql_string() for c in self.children) + ")")

    def do_columnar_eval(self, ctx, cols):
        y, m, d, h, mi = (c.data.astype(jnp.int64) for c in cols[:5])
        sec_col = cols[5]
        st = self.children[5].dataType
        if isinstance(st, T.DecimalType):
            micros_in_sec = (sec_col.data.astype(jnp.int64)
                             * (10 ** (6 - st.scale)))
        elif isinstance(st, (T.FloatType, T.DoubleType)):
            micros_in_sec = jnp.round(
                sec_col.data.astype(jnp.float64) * 1e6).astype(jnp.int64)
        else:
            micros_in_sec = sec_col.data.astype(jnp.int64) * 1_000_000
        days = days_from_civil(y, m, d)
        y2, m2, d2 = civil_from_days(days)
        # Spark: seconds==60 rolls to the next minute only when exactly 60
        ok = ((y2 == y) & (m2 == m) & (d2 == d) & (y >= 1) & (y <= 9999)
              & (h >= 0) & (h <= 23) & (mi >= 0) & (mi <= 59)
              & (micros_in_sec >= 0) & (micros_in_sec <= 60_000_000))
        micros = (days * _US_PER_DAY + h * 3_600_000_000
                  + mi * 60_000_000 + micros_in_sec)
        validity = cols[0].validity
        for c in cols[1:]:
            validity = validity & c.validity
        if ctx.ansi:
            ctx.add_error(~ok & validity,
                          "invalid timestamp in make_timestamp (ANSI)")
        else:
            validity = validity & ok
        return DeviceColumn(T.TIMESTAMP, validity, data=micros)


class _CapturedNow(Expression):
    """Base for current_date()/current_timestamp(): the instant is captured
    when the expression is constructed (Spark: once per query at analysis),
    so every row — and every batch — sees the same value."""

    def __init__(self):
        super().__init__([])
        import time

        self.captured_micros = int(time.time() * 1_000_000)

    def sql_string(self):
        return f"{self.pretty_name.lower()}()"


class CurrentDate(_CapturedNow):
    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        cap = ctx.batch.capacity
        days = self.captured_micros // _US_PER_DAY
        return DeviceColumn(T.DATE, jnp.ones(cap, jnp.bool_),
                            data=jnp.full(cap, days, jnp.int32))


class CurrentTimestamp(_CapturedNow):
    def _resolve_type(self):
        self._dataType = T.TIMESTAMP
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        cap = ctx.batch.capacity
        return DeviceColumn(T.TIMESTAMP, jnp.ones(cap, jnp.bool_),
                            data=jnp.full(cap, self.captured_micros,
                                          jnp.int64))


class TimestampSeconds(UnaryExpression):
    """timestamp_seconds(n) — integral or fractional seconds -> ts."""

    def _resolve_type(self):
        self._dataType = T.TIMESTAMP
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        st = self.child.dataType
        if isinstance(st, (T.FloatType, T.DoubleType)):
            f = c.data.astype(jnp.float64) * 1e6
            ok = jnp.isfinite(f) & (jnp.abs(f) < 2.0 ** 63)
            data = jnp.round(f).astype(jnp.int64)
            validity = c.validity & ok
            return DeviceColumn(T.TIMESTAMP, validity, data=data)
        v = c.data.astype(jnp.int64)
        ok = (v >= -9223372036854) & (v <= 9223372036854)
        data = v * 1_000_000
        if ctx.ansi:
            ctx.add_error(~ok & c.validity,
                          "timestamp_seconds overflow (ANSI)")
            return DeviceColumn(T.TIMESTAMP, c.validity, data=data)
        return DeviceColumn(T.TIMESTAMP, c.validity & ok, data=data)


class TimestampMillis(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.TIMESTAMP
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        v = c.data.astype(jnp.int64)
        ok = (v >= -9223372036854775) & (v <= 9223372036854775)
        return DeviceColumn(T.TIMESTAMP, c.validity & ok, data=v * 1_000)


class TimestampMicros(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.TIMESTAMP
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(T.TIMESTAMP, c.validity,
                            data=c.data.astype(jnp.int64))


class UnixDate(UnaryExpression):
    """unix_date(date) -> days since epoch (int)."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(T.INT, c.validity,
                            data=c.data.astype(jnp.int32))


class DateFromUnixDate(UnaryExpression):
    """date_from_unix_date(days)."""

    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(T.DATE, c.validity,
                            data=c.data.astype(jnp.int32))


class _UnixExtract(UnaryExpression):
    """unix_seconds/millis/micros(ts) — floorDiv like Spark's
    DateTimeUtils."""

    _div = 1

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        v = c.data.astype(jnp.int64)
        data = jnp.floor_divide(v, self._div) if self._div != 1 else v
        return DeviceColumn(T.LONG, c.validity, data=data)


class UnixSeconds(_UnixExtract):
    _div = 1_000_000


class UnixMillis(_UnixExtract):
    _div = 1_000


class UnixMicros(_UnixExtract):
    _div = 1


class ToDate(UnaryExpression):
    """to_date(e) — no-format variant: Cast-to-date semantics."""

    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        ct = self.child.dataType
        if isinstance(ct, T.DateType):
            return c
        if isinstance(ct, T.TimestampType):
            return DeviceColumn(T.DATE, c.validity,
                                data=jnp.floor_divide(
                                    c.data, _US_PER_DAY).astype(jnp.int32))
        from spark_rapids_tpu.expr.cast import _string_to_date_v2

        return _string_to_date_v2(ctx, c, ct, T.DATE, False)


class ToTimestamp(UnaryExpression):
    """to_timestamp(e) — no-format variant: Cast-to-timestamp semantics."""

    def _resolve_type(self):
        self._dataType = T.TIMESTAMP
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        ct = self.child.dataType
        if isinstance(ct, T.TimestampType):
            return c
        if isinstance(ct, T.DateType):
            return DeviceColumn(T.TIMESTAMP, c.validity,
                                data=c.data.astype(jnp.int64) * _US_PER_DAY)
        from spark_rapids_tpu.expr.cast import _string_to_timestamp

        return _string_to_timestamp(ctx, c, ct, T.TIMESTAMP, False)


class TruncTimestamp(BinaryExpression):
    """date_trunc(fmt, ts): fmt is a plan-time literal.

    Reference analog: GpuTruncTimestamp (datetimeExpressions.scala)."""

    _DAY_FMTS = dict(TruncDate._FMTS)
    _TIME = {"day": 86_400_000_000, "dd": 86_400_000_000,
             "hour": 3_600_000_000, "minute": 60_000_000,
             "second": 1_000_000, "millisecond": 1_000, "microsecond": 1}

    def _resolve_type(self):
        self._dataType = T.TIMESTAMP
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        fmt, c = cols  # date_trunc(fmt, ts)
        f = self.children[0]
        unit = (str(f.value).lower()
                if isinstance(f, Literal) and f.value is not None else "")
        micros = c.data.astype(jnp.int64)
        if unit in self._TIME:
            q = self._TIME[unit]
            out = jnp.floor_divide(micros, q) * q
            return DeviceColumn(T.TIMESTAMP, c.validity, data=out)
        if unit in self._DAY_FMTS:
            days = jnp.floor_divide(micros, _US_PER_DAY)
            y, m, d = civil_from_days(days)
            u = self._DAY_FMTS[unit]
            if u == "year":
                out = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
            elif u == "quarter":
                out = days_from_civil(y, (m - 1) // 3 * 3 + 1,
                                      jnp.ones_like(d))
            elif u == "month":
                out = days_from_civil(y, m, jnp.ones_like(d))
            else:  # week
                out = days - (days + 3) % 7
            return DeviceColumn(T.TIMESTAMP, c.validity,
                                data=out * _US_PER_DAY)
        return DeviceColumn(T.TIMESTAMP, jnp.zeros_like(c.validity),
                            data=jnp.zeros_like(micros))


class TimestampAdd(Expression):
    """timestampadd(unit, n, ts) — unit is a plan-time literal."""

    _FIXED = {"microsecond": 1, "millisecond": 1_000, "second": 1_000_000,
              "minute": 60_000_000, "hour": 3_600_000_000,
              "day": _US_PER_DAY, "week": 7 * _US_PER_DAY}

    def __init__(self, unit, n, ts):
        super().__init__([n, ts])
        self.unit = str(unit).lower()

    def sql_string(self):
        return (f"timestampadd({self.unit}, "
                f"{self.children[0].sql_string()}, "
                f"{self.children[1].sql_string()})")

    def _resolve_type(self):
        self._dataType = T.TIMESTAMP
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        n, c = cols
        micros = c.data.astype(jnp.int64)
        k = n.data.astype(jnp.int64)
        validity = n.validity & c.validity
        if self.unit in self._FIXED:
            out = micros + k * self._FIXED[self.unit]
            return DeviceColumn(T.TIMESTAMP, validity, data=out)
        # month-based units ride the clamped civil add (add_months rules)
        mult = {"month": 1, "quarter": 3, "year": 12}.get(self.unit)
        if mult is None:
            return DeviceColumn(T.TIMESTAMP,
                                jnp.zeros_like(validity),
                                data=jnp.zeros_like(micros))
        days = jnp.floor_divide(micros, _US_PER_DAY)
        tod = micros - days * _US_PER_DAY
        y, m, d = civil_from_days(days)
        tot = y * 12 + (m - 1) + k * mult
        ny = tot // 12
        nm = tot % 12 + 1
        out_days = _clamped_ymd_to_days(ny, nm, d)
        return DeviceColumn(T.TIMESTAMP, validity,
                            data=out_days * _US_PER_DAY + tod)


class TimestampDiff(Expression):
    """timestampdiff(unit, start, end) — whole units, truncated toward
    zero (java.time.temporal semantics for the fixed units; month-family
    counts civil month steps)."""

    def __init__(self, unit, start, end):
        super().__init__([start, end])
        self.unit = str(unit).lower()

    def sql_string(self):
        return (f"timestampdiff({self.unit}, "
                f"{self.children[0].sql_string()}, "
                f"{self.children[1].sql_string()})")

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        a, b = cols
        validity = a.validity & b.validity
        s = a.data.astype(jnp.int64)
        e = b.data.astype(jnp.int64)
        fixed = TimestampAdd._FIXED.get(self.unit)
        if fixed is not None:
            diff = e - s
            out = jnp.where(diff >= 0, diff // fixed, -((-diff) // fixed))
            return DeviceColumn(T.LONG, validity, data=out)
        mult = {"month": 1, "quarter": 3, "year": 12}.get(self.unit)
        if mult is None:
            return DeviceColumn(T.LONG, jnp.zeros_like(validity),
                                data=jnp.zeros_like(s))
        sd = jnp.floor_divide(s, _US_PER_DAY)
        ed = jnp.floor_divide(e, _US_PER_DAY)
        sy, sm, sdd = civil_from_days(sd)
        ey, em, edd = civil_from_days(ed)
        months = (ey * 12 + em) - (sy * 12 + sm)
        # partial month does not count: back off when the end day-of-month
        # + time hasn't reached the start's
        stod = s - sd * _US_PER_DAY
        etod = e - ed * _US_PER_DAY
        fwd = e >= s
        short = jnp.where(
            fwd,
            (edd < sdd) | ((edd == sdd) & (etod < stod)),
            (edd > sdd) | ((edd == sdd) & (etod > stod)))
        months = months - jnp.where(short & fwd, 1, 0) \
            + jnp.where(short & ~fwd, 1, 0)
        out = jnp.where(months >= 0, months // mult,
                        -((-months) // mult))
        return DeviceColumn(T.LONG, validity, data=out.astype(jnp.int64))


class ConvertTimezone(Expression):
    """convert_timezone(source_tz, target_tz, ts): both tz are plan-time
    literals; rides the TZif transition tables like from/to_utc."""

    def __init__(self, source_tz, target_tz, ts):
        super().__init__([ts])
        self.source_tz = str(source_tz)
        self.target_tz = str(target_tz)

    def sql_string(self):
        return (f"convert_timezone({self.source_tz}, {self.target_tz}, "
                f"{self.children[0].sql_string()})")

    def _resolve_type(self):
        self._dataType = T.TIMESTAMP
        self._nullable = self.children[0].nullable

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.tzdb import zone_tables

        c = cols[0]
        micros = c.data.astype(jnp.int64)
        # wall(source) -> utc
        tsrc = zone_tables(self.source_tz)
        secs = jnp.floor_divide(micros, 1_000_000)
        i1 = jnp.searchsorted(jnp.asarray(tsrc["wall_starts"]), secs,
                              side="right") - 1
        off1 = jnp.asarray(tsrc["offsets"])[
            jnp.clip(i1, 0, len(tsrc["offsets"]) - 1)]
        utc = micros - off1 * jnp.int64(1_000_000)
        # utc -> wall(target)
        ttgt = zone_tables(self.target_tz)
        usecs = jnp.floor_divide(utc, 1_000_000)
        i2 = jnp.searchsorted(jnp.asarray(ttgt["utc_instants"]), usecs,
                              side="right") - 1
        off2 = jnp.asarray(ttgt["offsets"])[
            jnp.clip(i2, 0, len(ttgt["offsets"]) - 1)]
        return DeviceColumn(T.TIMESTAMP, c.validity,
                            data=utc + off2 * jnp.int64(1_000_000))


class _NameLookup(_DateField):
    """3-letter name columns from a fixed lookup table (device gather)."""

    _NAMES: tuple = ()

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        import numpy as np

        c = cols[0]
        days = _days_of(c, self.child.dataType)
        y, m, d = civil_from_days(days)
        idx = self._index(y, m, d, days)
        tbl = np.zeros((len(self._NAMES), 3), np.uint8)
        for i, nm in enumerate(self._NAMES):
            tbl[i] = np.frombuffer(nm.encode(), np.uint8)
        chars = jnp.asarray(tbl)[jnp.clip(idx, 0, len(self._NAMES) - 1)]
        return DeviceColumn(T.STRING, c.validity, chars=chars,
                            lengths=jnp.full(c.capacity, 3, jnp.int32))


class MonthName(_NameLookup):
    _NAMES = ("Jan", "Feb", "Mar", "Apr", "May", "Jun",
              "Jul", "Aug", "Sep", "Oct", "Nov", "Dec")

    def _index(self, y, m, d, days):
        return m - 1


class DayName(_NameLookup):
    _NAMES = ("Mon", "Tue", "Wed", "Thu", "Fri", "Sat", "Sun")

    def _index(self, y, m, d, days):
        return (days + 3) % 7


class LocalTimestamp(CurrentTimestamp):
    """localtimestamp() — UTC session timezone makes it current_timestamp."""


class DatePart(Expression):
    """date_part(field, source) / extract(field FROM source): the literal
    field routes to the matching extraction at plan time."""

    _FIELDS = {"year": Year, "yr": Year, "years": Year,
               "month": Month, "mon": Month, "months": Month,
               "day": DayOfMonth, "d": DayOfMonth, "days": DayOfMonth,
               "dayofweek": DayOfWeek, "dow": DayOfWeek,
               "doy": DayOfYear, "quarter": Quarter, "qtr": Quarter,
               "week": WeekOfYear, "weeks": WeekOfYear,
               "hour": Hour, "hours": Hour, "h": Hour,
               "minute": Minute, "min": Minute, "minutes": Minute,
               "second": Second, "sec": Second, "seconds": Second}

    def __init__(self, field, source):
        super().__init__([source])
        self.field = str(field).lower()
        self._inner = None

    def sql_string(self):
        return f"date_part({self.field}, {self.children[0].sql_string()})"

    def resolve(self, schema):
        self.children = [c.resolve(schema) for c in self.children]
        cls = self._FIELDS.get(self.field)
        if cls is None:
            # Spark raises an analysis error for unsupported fields; so do
            # both backends (resolve() IS the analysis step here)
            raise ValueError(
                f"date_part: unsupported extract field {self.field!r}")
        self._inner = cls(self.children[0])
        self._inner.resolved = True
        self._inner._resolve_type()
        self._resolve_type()
        self.resolved = True
        return self

    def _resolve_type(self):
        self._dataType = (self._inner.dataType if self._inner is not None
                          else T.INT)
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        return self._inner.do_columnar_eval(ctx, cols)


class ParseToDate(Expression):
    """to_date(e, fmt) — format-carrying variant.  The default-grammar
    formats ('yyyy-MM-dd') delegate to the cast parser; other literal
    formats are tag-time fallbacks (overrides._check_parse_to_date).

    Reference analog: GpuParseToDate via GpuGetTimestamp rewrite."""

    def __init__(self, child: Expression, fmt: Expression = None):
        super().__init__([child] if fmt is None else [child, fmt])

    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = True

    def sql_string(self):
        return f"to_date({', '.join(c.sql_string() for c in self.children)})"

    @property
    def fmt_literal(self):
        from spark_rapids_tpu.expr.base import Literal

        if len(self.children) == 1:
            return None
        f = self.children[1]
        return str(f.value) if isinstance(f, Literal) and f.value is not None \
            else False      # non-literal / null format: unsupported

    def do_columnar_eval(self, ctx, cols):
        d = ToDate(self.children[0])
        d._resolve_type()
        return d.do_columnar_eval(ctx, cols[:1])


class ParseToTimestamp(Expression):
    """to_timestamp(e, fmt) — format-carrying variant (default grammar
    only, like ParseToDate)."""

    def __init__(self, child: Expression, fmt: Expression = None):
        super().__init__([child] if fmt is None else [child, fmt])

    def _resolve_type(self):
        self._dataType = T.TIMESTAMP
        self._nullable = True

    def sql_string(self):
        return (f"to_timestamp("
                f"{', '.join(c.sql_string() for c in self.children)})")

    fmt_literal = ParseToDate.fmt_literal

    def do_columnar_eval(self, ctx, cols):
        t = ToTimestamp(self.children[0])
        t._resolve_type()
        return t.do_columnar_eval(ctx, cols[:1])


_EXTRACT_FIELDS = {
    "year": Year, "yearofweek": Year, "month": Month, "mon": Month,
    "day": DayOfMonth, "days": DayOfMonth, "d": DayOfMonth,
    "dayofweek": DayOfWeek, "dow": DayOfWeek,
    "doy": DayOfYear, "quarter": Quarter, "qtr": Quarter,
    "week": WeekOfYear, "weeks": WeekOfYear, "w": WeekOfYear,
    "hour": Hour, "hours": Hour, "h": Hour,
    "minute": Minute, "minutes": Minute, "min": Minute,
    "second": Second, "seconds": Second, "s": Second,
}


class Extract(Expression):
    """extract(FIELD FROM source): delegates to the matching field
    expression (Spark resolves Extract the same way at analysis time)."""

    def __init__(self, field: Expression, source: Expression):
        super().__init__([field, source])

    def _resolve_type(self):
        from spark_rapids_tpu.expr.base import Literal

        f = self.children[0]
        name = str(f.value).lower() if isinstance(f, Literal) else None
        cls = _EXTRACT_FIELDS.get(name)
        self._delegate = None
        if cls is not None:
            d = cls(self.children[1])
            d._resolve_type()
            self._delegate = d
        self._dataType = (self._delegate._dataType if self._delegate
                          else T.INT)
        self._nullable = True

    def sql_string(self):
        return (f"extract({self.children[0].sql_string()} FROM "
                f"{self.children[1].sql_string()})")

    def do_columnar_eval(self, ctx, cols):
        return self._delegate.do_columnar_eval(ctx, cols[1:])


class TryToTimestamp(ParseToTimestamp):
    """try_to_timestamp: NULL instead of error on malformed input (the
    non-ANSI cast grammar already nulls; this pins ANSI mode too)."""

    def sql_string(self):
        return (f"try_to_timestamp("
                f"{', '.join(c.sql_string() for c in self.children)})")

    def do_columnar_eval(self, ctx, cols):
        saved = ctx.ansi
        ctx.ansi = False
        try:
            return super().do_columnar_eval(ctx, cols)
        finally:
            ctx.ansi = saved
