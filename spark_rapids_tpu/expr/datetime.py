"""Date/time expressions.

Reference analog: org/apache/spark/sql/rapids/datetimeExpressions.scala
(GpuYear/GpuMonth/GpuDayOfMonth/GpuHour..., GpuDateAdd/GpuDateSub,
GpuDateDiff, GpuToUnixTimestamp) with jni timezones.cu for tz conversion.
Timestamps are UTC micros; session-timezone tables come in a later round
(reference gates non-UTC behind GpuTimeZoneDB the same way).

All field extraction rides the branch-free civil-calendar math in cast.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import BinaryExpression, UnaryExpression
from spark_rapids_tpu.expr.cast import civil_from_days, days_from_civil

_US_PER_DAY = 86_400_000_000


def _days_of(c: DeviceColumn, dtype: T.DataType):
    if isinstance(dtype, T.TimestampType):
        return jnp.floor_divide(c.data, _US_PER_DAY)
    return c.data.astype(jnp.int64)


class _DateField(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        days = _days_of(c, self.child.dataType)
        y, m, d = civil_from_days(days)
        return DeviceColumn(T.INT, c.validity,
                            data=self._field(y, m, d, days).astype(jnp.int32))

    def _field(self, y, m, d, days):
        raise NotImplementedError


class Year(_DateField):
    def _field(self, y, m, d, days):
        return y


class Month(_DateField):
    def _field(self, y, m, d, days):
        return m


class DayOfMonth(_DateField):
    def _field(self, y, m, d, days):
        return d


class DayOfWeek(_DateField):
    """Spark: Sunday=1 ... Saturday=7; epoch day 0 was a Thursday."""

    def _field(self, y, m, d, days):
        return ((days + 4) % 7) + 1


class DayOfYear(_DateField):
    def _field(self, y, m, d, days):
        jan1 = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        return (days - jan1 + 1).astype(jnp.int64)


class Quarter(_DateField):
    def _field(self, y, m, d, days):
        return (m - 1) // 3 + 1


class LastDay(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        days = _days_of(c, self.child.dataType)
        y, m, _ = civil_from_days(days)
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        first_next = days_from_civil(ny, nm, jnp.ones_like(nm))
        return DeviceColumn(T.DATE, c.validity,
                            data=(first_next - 1).astype(jnp.int32))


class _TimeField(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        rem = c.data - jnp.floor_divide(c.data, _US_PER_DAY) * _US_PER_DAY
        return DeviceColumn(T.INT, c.validity,
                            data=self._field(rem).astype(jnp.int32))


class Hour(_TimeField):
    def _field(self, rem):
        return rem // 3_600_000_000


class Minute(_TimeField):
    def _field(self, rem):
        return (rem // 60_000_000) % 60


class Second(_TimeField):
    def _field(self, rem):
        return (rem // 1_000_000) % 60


class DateAdd(BinaryExpression):
    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        d, n = cols
        return DeviceColumn(T.DATE, d.validity & n.validity,
                            data=(d.data + n.data.astype(jnp.int32)))


class DateSub(BinaryExpression):
    def _resolve_type(self):
        self._dataType = T.DATE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        d, n = cols
        return DeviceColumn(T.DATE, d.validity & n.validity,
                            data=(d.data - n.data.astype(jnp.int32)))


class DateDiff(BinaryExpression):
    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        a, b = cols
        return DeviceColumn(T.INT, a.validity & b.validity,
                            data=(a.data - b.data).astype(jnp.int32))


class UnixTimestamp(UnaryExpression):
    """to_unix_timestamp(ts) -> seconds."""

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        if isinstance(self.child.dataType, T.DateType):
            secs = c.data.astype(jnp.int64) * 86_400
        else:
            secs = jnp.floor_divide(c.data, 1_000_000)
        return DeviceColumn(T.LONG, c.validity, data=secs)
