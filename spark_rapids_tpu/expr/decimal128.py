"""128-bit decimal limb arithmetic on TPU.

Reference analog: spark-rapids-jni ``decimal_utils.cu`` (SURVEY.md §2.5
Arithmetic/decimal row) — CUDA kernels for decimal128 multiply/divide and
overflow checks.  TPU-first redesign: a decimal with precision > 18 is a
two-limb value ``(hi, lo)`` where ``hi`` is the signed high 64 bits and
``lo`` holds the unsigned low 64 bits *as an int64 bit pattern*.  All limb
math is ordinary wrapping int64 vector arithmetic, which XLA lowers to fast
32-bit pair ops on TPU (no f64 custom-call penalty, no host round trips).

Column storage: a decimal128 DeviceColumn packs the limbs as ``data`` of
shape ``(capacity, 2)`` with ``data[:, 0] = hi`` and ``data[:, 1] = lo``.
Kernels in this file work on unpacked ``(hi, lo)`` pairs.

Segmented sums use 32-bit limb splitting so up to 2^31 rows accumulate in
int64 without overflow, with an explicit sign-extension limb making the
reconstruction exact past 2^128 (so wraparound cannot silently produce an
in-bounds wrong answer).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# python ints (NOT jnp arrays): module-level jax arrays become closure
# constants hoisted as executable parameters, which breaks jit re-dispatch
# and pins a backend at import time
_M32 = 0xFFFFFFFF
_SIGN64 = -0x8000000000000000   # 1 << 63 bit


def _i64(x) -> jax.Array:
    return jnp.asarray(x, jnp.int64)


# -- basic limb helpers ------------------------------------------------------

def ult(a: jax.Array, b: jax.Array) -> jax.Array:
    """Unsigned < on int64 bit patterns."""
    return (a ^ _SIGN64) < (b ^ _SIGN64)


def from64(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Sign-extend an int64 unscaled value to (hi, lo)."""
    x = _i64(x)
    return x >> 63, x


def pack(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """(hi, lo) -> (n, 2) column storage."""
    return jnp.stack([hi, lo], axis=-1)


def unpack(data: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(n, 2) column storage -> (hi, lo)."""
    return data[..., 0], data[..., 1]


def to_py(hi: int, lo: int) -> int:
    """Host-side: limbs -> arbitrary-precision python int."""
    return (int(hi) << 64) | (int(lo) & 0xFFFFFFFFFFFFFFFF)


def limbs_of(v: int) -> Tuple[int, int]:
    """Host-side: python int -> (hi, lo) int64 bit patterns."""
    masked = v & ((1 << 128) - 1)
    lo = masked & 0xFFFFFFFFFFFFFFFF
    hi = (masked >> 64) & 0xFFFFFFFFFFFFFFFF
    if lo >= 1 << 63:
        lo -= 1 << 64
    if hi >= 1 << 63:
        hi -= 1 << 64
    return hi, lo


# -- arithmetic --------------------------------------------------------------

def add128(ah, al, bh, bl) -> Tuple[jax.Array, jax.Array]:
    lo = al + bl                       # wraps mod 2^64
    carry = ult(lo, al).astype(jnp.int64)
    hi = ah + bh + carry
    return hi, lo


def neg128(h, l) -> Tuple[jax.Array, jax.Array]:
    lo = -l
    hi = -h - (l != 0).astype(jnp.int64)
    return hi, lo


def sub128(ah, al, bh, bl) -> Tuple[jax.Array, jax.Array]:
    nh, nl = neg128(bh, bl)
    return add128(ah, al, nh, nl)


def is_neg(h, l) -> jax.Array:
    return h < 0


def abs128(h, l) -> Tuple[jax.Array, jax.Array]:
    nh, nl = neg128(h, l)
    n = is_neg(h, l)
    return jnp.where(n, nh, h), jnp.where(n, nl, l)


def eq128(ah, al, bh, bl) -> jax.Array:
    return (ah == bh) & (al == bl)


def lt128(ah, al, bh, bl) -> jax.Array:
    """Signed 128-bit <."""
    return (ah < bh) | ((ah == bh) & ult(al, bl))


def umulhi64(a, b) -> jax.Array:
    """High 64 bits of the unsigned 64x64 product (int64 bit patterns)."""
    a0 = a & _M32
    a1 = (a >> 32) & _M32
    b0 = b & _M32
    b1 = (b >> 32) & _M32
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = ((p00 >> 32) & _M32) + (p01 & _M32) + (p10 & _M32)
    return (p11 + ((p01 >> 32) & _M32) + ((p10 >> 32) & _M32)
            + ((mid >> 32) & _M32))


def mul64_to_128(a, b) -> Tuple[jax.Array, jax.Array]:
    """Signed 64x64 -> exact signed 128-bit product."""
    a = _i64(a)
    b = _i64(b)
    lo = a * b                         # low 64 bits, signed == unsigned
    uhi = umulhi64(a, b)
    # signed correction: mulhs = umulh - (a<0 ? b : 0) - (b<0 ? a : 0)
    hi = uhi - jnp.where(a < 0, b, 0) - jnp.where(b < 0, a, 0)
    return hi, lo


def umul128_by_u32(h, l, m) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Unsigned 128-bit value times a uint32 scalar -> (carry, hi, lo).

    ``carry`` is the overflow limb (bits 128..159); zero iff the product
    still fits in 128 bits."""
    m = _i64(m)
    l0 = l & _M32
    l1 = (l >> 32) & _M32
    h0 = h & _M32
    h1 = (h >> 32) & _M32
    p0 = l0 * m
    p1 = l1 * m + ((p0 >> 32) & _M32)
    p2 = h0 * m + ((p1 >> 32) & _M32)
    p3 = h1 * m + ((p2 >> 32) & _M32)
    lo = (p0 & _M32) | (p1 << 32)
    hi = (p2 & _M32) | (p3 << 32)
    carry = (p3 >> 32) & _M32
    return carry, hi, lo


_POW10_32 = [10 ** k for k in range(10)]   # fits uint32 up to 10^9


def mul128_pow10(h, l, k: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Signed 128 x 10^k -> (overflowed, hi, lo); k is a static python int."""
    if k == 0:
        return jnp.zeros_like(h, jnp.bool_), h, l
    neg = is_neg(h, l)
    uh, ul = abs128(h, l)
    over = jnp.zeros_like(h, jnp.bool_)
    kk = k
    while kk > 0:
        step = min(kk, 9)
        carry, uh, ul = umul128_by_u32(uh, ul, _POW10_32[step])
        over = over | (carry != 0)
        kk -= step
    over = over | (uh < 0)             # magnitude crossed into the sign bit
    rh, rl = neg128(uh, ul)
    return over, jnp.where(neg, rh, uh), jnp.where(neg, rl, ul)


def udivmod128_by_u32(h, l, d):
    """Unsigned 128-bit // d -> (qhi, qlo, rem) for 1 <= d <= 2^31-1.

    Long division over four 32-bit limbs; the divisor bound keeps every
    partial remainder in a signed int64.  ``d`` may be a python int or an
    int64 vector (per-element divisors, e.g. group counts for decimal avg)."""
    d64 = jnp.asarray(d, jnp.int64)
    limbs = [(h >> 32) & _M32, h & _M32, (l >> 32) & _M32, l & _M32]
    q = []
    rem = jnp.zeros_like(h)
    for limb in limbs:
        cur = (rem << 32) | limb
        q.append(cur // d64)
        rem = cur - q[-1] * d64
    qhi = (q[0] << 32) | q[1]
    qlo = (q[2] << 32) | q[3]
    return qhi, qlo, rem


def div128_pow10_trunc(h, l, k: int) -> Tuple[jax.Array, jax.Array]:
    """Signed 128 / 10^k truncating toward zero."""
    if k == 0:
        return h, l
    neg = is_neg(h, l)
    uh, ul = abs128(h, l)
    kk = k
    while kk > 0:
        step = min(kk, 9)
        uh, ul, _ = udivmod128_by_u32(uh, ul, _POW10_32[step])
        kk -= step
    rh, rl = neg128(uh, ul)
    return jnp.where(neg, rh, uh), jnp.where(neg, rl, ul)


def div128_pow10_half_up(h, l, k: int) -> Tuple[jax.Array, jax.Array]:
    """Signed 128 / 10^k with HALF_UP rounding (Spark decimal scale change)."""
    if k == 0:
        return h, l
    neg = is_neg(h, l)
    uh0, ul0 = abs128(h, l)
    # truncating quotient: divide by 10^k in <=9-digit chunks (divisor < 2^31)
    uh, ul = uh0, ul0
    kk = k
    while kk > 0:
        step = min(kk, 9)
        uh, ul, _ = udivmod128_by_u32(uh, ul, _POW10_32[step])
        kk -= step
    # exact remainder in 128 bits: rem = |v| - q * 10^k
    _, qph, qpl = mul128_pow10(uh, ul, k)
    rem_h, rem_l = sub128(uh0, ul0, qph, qpl)
    # HALF_UP: round away from zero when rem >= 10^k / 2 (comparing against
    # the halved divisor instead of doubling rem, which would overflow
    # signed 128 bits at k=38)
    bh_, bl_ = limbs_of(10 ** k // 2)
    round_up = ~lt128(rem_h, rem_l, jnp.full_like(h, bh_),
                      jnp.full_like(l, bl_))
    one = round_up.astype(jnp.int64)
    uh, ul = add128(uh, ul, jnp.zeros_like(h), one)
    rh, rl = neg128(uh, ul)
    return jnp.where(neg, rh, uh), jnp.where(neg, rl, ul)


def bound128(precision: int) -> Tuple[int, int]:
    """(hi, lo) limbs of 10^precision (the exclusive overflow bound)."""
    return limbs_of(10 ** precision)


def in_bounds(h, l, precision: int) -> jax.Array:
    """|value| < 10^precision."""
    bh, bl = bound128(precision)
    ah, al = abs128(h, l)
    return lt128(ah, al, jnp.full_like(h, bh), jnp.full_like(l, bl))


# -- sums --------------------------------------------------------------------

def _limbs32(h, l):
    """Two's-complement 128-bit -> five int64 limb vectors (4x32-bit value
    limbs + one 32-bit sign-extension limb)."""
    return (
        l & _M32,
        (l >> 32) & _M32,
        h & _M32,
        (h >> 32) & _M32,
        jnp.where(h < 0, _M32, jnp.int64(0)),
    )


def _recombine(sums) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Limb sums -> (ok, hi, lo).  ``ok`` is False where the true sum does
    not fit in signed 128 bits."""
    s0, s1, s2, s3, s4 = sums
    c0 = s0
    r0 = c0 & _M32
    c1 = s1 + ((c0 >> 32) & _M32)
    r1 = c1 & _M32
    c2 = s2 + ((c1 >> 32) & _M32)
    r2 = c2 & _M32
    c3 = s3 + ((c2 >> 32) & _M32)
    r3 = c3 & _M32
    # extension limbs: rows contribute the same sign mask at every position
    # >= 4, so limb 4 and limb 5 share s4; propagate two of them and require
    # pure sign extension (all-ones or all-zero matching the result sign).
    c4 = s4 + ((c3 >> 32) & _M32)
    r4 = c4 & _M32
    c5 = s4 + ((c4 >> 32) & _M32)
    r5 = c5 & _M32
    lo = r0 | (r1 << 32)
    hi = r2 | (r3 << 32)
    sign_limb = jnp.where(hi < 0, _M32, jnp.int64(0))
    ok = (r4 == sign_limb) & (r5 == sign_limb)
    return ok, hi, lo


def sum128_global(h, l, validity) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Masked global sum -> (ok, any_valid, hi, lo); each a scalar-shaped
    (1,) array.  Exact for up to 2^31 rows."""
    limbs = _limbs32(h, l)
    sums = [jnp.sum(jnp.where(validity, x, 0), keepdims=True) for x in limbs]
    ok, hi, lo = _recombine(sums)
    any_valid = jnp.sum(validity.astype(jnp.int32), keepdims=True) > 0
    return ok, any_valid, hi, lo


def sum128_segments(h, l, validity, seg_ids, num_segments: int):
    """Masked segmented sum -> (ok, any_valid, hi, lo) per segment."""
    if num_segments == 1:
        return sum128_global(h, l, validity)
    limbs = _limbs32(h, l)
    sums = [jax.ops.segment_sum(jnp.where(validity, x, 0), seg_ids,
                                num_segments=num_segments) for x in limbs]
    ok, hi, lo = _recombine(sums)
    any_valid = jax.ops.segment_sum(validity.astype(jnp.int32), seg_ids,
                                    num_segments=num_segments) > 0
    return ok, any_valid, hi, lo


def column_limbs(c) -> Tuple[jax.Array, jax.Array]:
    """Any decimal DeviceColumn -> (hi, lo): unpack two-limb storage or
    sign-extend 64-bit storage."""
    if c.is_dec128:
        return unpack(c.data)
    return from64(c.data)


# -- ordering ---------------------------------------------------------------

def key_words(h, l) -> Tuple[jax.Array, jax.Array]:
    """Sort-key words: (hi signed, lo rebased to signed) — lexicographic
    signed ordering of the pair equals signed 128-bit numeric ordering."""
    return h, l ^ _SIGN64
