"""Higher-order array functions: transform / filter / exists / forall.

Reference analog: org/apache/spark/sql/rapids/higherOrderFunctions.scala
(GpuArrayTransform, GpuArrayFilter, GpuArrayExists, SURVEY.md §2.5
Collections/higher-order).

TPU design: the lambda body is an ordinary expression tree resolved against
an EXTENDED schema (outer columns + the lambda variable).  Evaluation
flattens the (capacity, ewidth) element matrix into a (capacity*ewidth)
pseudo-batch — outer columns repeated per element — and runs the body ONCE
as part of the enclosing jitted stage, so the lambda fuses with everything
else (the reference instead re-enters cuDF per lambda node).  The result
reshapes back to the padded element matrix.
"""
from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import EvalContext, Expression
from spark_rapids_tpu.expr.collections import _compact_elems, _in_len


def _repeat_col(c: DeviceColumn, w: int) -> DeviceColumn:
    """Repeat each row w times (row-major, matching a (cap, w) flatten)."""
    validity = jnp.repeat(c.validity, w)
    if c.is_string:
        return DeviceColumn(c.dtype, validity,
                            chars=jnp.repeat(c.chars, w, axis=0),
                            lengths=jnp.repeat(c.lengths, w))
    if c.is_array:
        return DeviceColumn(c.dtype, validity,
                            data=jnp.repeat(c.data, w, axis=0),
                            lengths=jnp.repeat(c.lengths, w),
                            elem_valid=jnp.repeat(c.elem_valid, w, axis=0))
    if c.is_struct:
        return DeviceColumn(c.dtype, validity,
                            children=tuple(_repeat_col(k, w)
                                           for k in c.children))
    return DeviceColumn(c.dtype, validity,
                        data=jnp.repeat(c.data, w, axis=0))


class HigherOrderFunction(Expression):
    """Base: one array child + a lambda body over (outer cols, element)."""

    def __init__(self, arr: Expression, var_name: str, body: Expression):
        super().__init__([arr])
        self.var_name = var_name
        self.body = body

    @property
    def arr(self):
        return self.children[0]

    def sql_string(self):
        return (f"{self.pretty_name.lower()}({self.arr.sql_string()}, "
                f"{self.var_name} -> {self.body.sql_string()})")

    def resolve(self, schema: T.StructType) -> Expression:
        self.children = [c.resolve(schema) for c in self.children]
        et = self.arr.dataType.elementType
        ext = T.StructType(
            list(schema.fields) + [T.StructField(self.var_name, et, True)])
        self.body = self.body.resolve(ext)
        self._resolve_type()
        self.resolved = True
        return self

    def collect(self, pred):
        out = super().collect(pred)
        out.extend(self.body.collect(pred))
        return out

    def _eval_body(self, ctx: EvalContext, arr: DeviceColumn):
        """Flatten elements, run the body, return its (cap*w,) column."""
        cap, w = arr.capacity, max(arr.ewidth, 1)
        inl = _in_len(arr)
        elem = DeviceColumn(self.arr.dataType.elementType,
                            (arr.elem_valid & inl).reshape(-1),
                            data=arr.data.reshape(cap * w))
        outer = [_repeat_col(c, w) for c in ctx.batch.columns]
        ext = T.StructType(
            list(ctx.batch.schema.fields)
            + [T.StructField(self.var_name,
                             self.arr.dataType.elementType, True)])
        flat = ColumnarBatch(outer + [elem], cap * w, ext)
        sub = EvalContext(flat, ansi=ctx.ansi, error_flags=ctx.error_flags)
        res = self.body.eval_tpu(sub)
        return res, inl


class ArrayTransform(HigherOrderFunction):
    """transform(arr, x -> f(x))."""

    def _resolve_type(self):
        self._dataType = T.ArrayType(self.body.dataType)
        self._nullable = self.arr.nullable

    def do_columnar_eval(self, ctx, cols):
        arr = cols[0]
        res, inl = self._eval_body(ctx, arr)
        cap, w = arr.capacity, max(arr.ewidth, 1)
        data = res.data.reshape(cap, w)
        ev = res.validity.reshape(cap, w) & inl
        return DeviceColumn(self.dataType, arr.validity, data=data,
                            lengths=arr.lengths, elem_valid=ev)


class ArrayFilter(HigherOrderFunction):
    """filter(arr, x -> pred(x)): keeps elements where pred is TRUE
    (null predicate drops the element, like Spark)."""

    def _resolve_type(self):
        self._dataType = self.arr.dataType
        self._nullable = self.arr.nullable

    def do_columnar_eval(self, ctx, cols):
        arr = cols[0]
        res, inl = self._eval_body(ctx, arr)
        cap, w = arr.capacity, max(arr.ewidth, 1)
        keep = (res.data.reshape(cap, w) & res.validity.reshape(cap, w)
                & inl)
        data, ev, lengths = _compact_elems(arr.data, arr.elem_valid, keep)
        return DeviceColumn(self.dataType, arr.validity, data=data,
                            lengths=lengths, elem_valid=ev)


class ArrayExists(HigherOrderFunction):
    """exists(arr, pred): three-valued — true if any TRUE, null if no TRUE
    but some null predicate results, else false."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        arr = cols[0]
        res, inl = self._eval_body(ctx, arr)
        cap, w = arr.capacity, max(arr.ewidth, 1)
        pred = res.data.reshape(cap, w)
        pv = res.validity.reshape(cap, w)
        any_true = jnp.any(pred & pv & inl, axis=1)
        any_null = jnp.any(~pv & inl, axis=1)
        validity = arr.validity & (any_true | ~any_null)
        return DeviceColumn(T.BOOLEAN, validity, data=any_true)


class ArrayForAll(HigherOrderFunction):
    """forall(arr, pred): false if any FALSE, null if no FALSE but some
    null predicate results, else true."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        arr = cols[0]
        res, inl = self._eval_body(ctx, arr)
        cap, w = arr.capacity, max(arr.ewidth, 1)
        pred = res.data.reshape(cap, w)
        pv = res.validity.reshape(cap, w)
        any_false = jnp.any(~pred & pv & inl, axis=1)
        any_null = jnp.any(~pv & inl, axis=1)
        validity = arr.validity & (any_false | ~any_null)
        return DeviceColumn(T.BOOLEAN, validity, data=~any_false)


class ArrayAggregate(Expression):
    """aggregate(arr, zero, (acc, x) -> merge, acc -> finish).

    Sequential fold unrolled over the STATIC element width — each step is
    one fused vector op over all rows, so the fold costs O(ewidth) vector
    ops, not O(rows*ewidth) scalar ops."""

    def __init__(self, arr: Expression, zero: Expression,
                 acc_name: str, var_name: str, merge: Expression,
                 finish: Expression = None):
        super().__init__([arr, zero])
        self.acc_name = acc_name
        self.var_name = var_name
        self.merge = merge
        self.finish = finish

    @property
    def arr(self):
        return self.children[0]

    def resolve(self, schema: T.StructType) -> Expression:
        self.children = [c.resolve(schema) for c in self.children]
        et = self.arr.dataType.elementType
        acc_t = self.children[1].dataType
        ext = T.StructType(
            list(schema.fields)
            + [T.StructField(self.acc_name, acc_t, True),
               T.StructField(self.var_name, et, True)])
        self.merge = self.merge.resolve(ext)
        if self.finish is not None:
            fin_schema = T.StructType(
                list(schema.fields)
                + [T.StructField(self.acc_name, self.merge.dataType, True)])
            self.finish = self.finish.resolve(fin_schema)
        self._resolve_type()
        self.resolved = True
        return self

    def _resolve_type(self):
        self._dataType = (self.finish.dataType if self.finish is not None
                          else self.merge.dataType)
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        arr, zero = cols
        cap, w = arr.capacity, max(arr.ewidth, 1)
        inl_np = _in_len(arr)
        acc = zero
        for j in range(arr.ewidth):
            elem = DeviceColumn(self.arr.dataType.elementType,
                                arr.elem_valid[:, j], data=arr.data[:, j])
            ext = T.StructType(
                list(ctx.batch.schema.fields)
                + [T.StructField(self.acc_name, acc.dtype, True),
                   T.StructField(self.var_name, elem.dtype, True)])
            sub = EvalContext(
                ColumnarBatch(list(ctx.batch.columns) + [acc, elem],
                              ctx.batch.num_rows, ext),
                ansi=ctx.ansi, error_flags=ctx.error_flags)
            merged = self.merge.eval_tpu(sub)
            take = inl_np[:, j]
            acc = DeviceColumn(
                merged.dtype,
                jnp.where(take, merged.validity, acc.validity),
                data=jnp.where(take, merged.data, acc.data))
        if self.finish is not None:
            ext = T.StructType(
                list(ctx.batch.schema.fields)
                + [T.StructField(self.acc_name, acc.dtype, True)])
            sub = EvalContext(
                ColumnarBatch(list(ctx.batch.columns) + [acc],
                              ctx.batch.num_rows, ext),
                ansi=ctx.ansi, error_flags=ctx.error_flags)
            acc = self.finish.eval_tpu(sub)
        validity = acc.validity & arr.validity
        return DeviceColumn(self.dataType, validity, data=acc.data,
                            chars=acc.chars, lengths=acc.lengths,
                            elem_valid=acc.elem_valid,
                            children=acc.children)

    def sql_string(self):
        return (f"aggregate({self.arr.sql_string()}, "
                f"{self.children[1].sql_string()}, "
                f"({self.acc_name}, {self.var_name}) -> "
                f"{self.merge.sql_string()})")


class MapHigherOrderFunction(Expression):
    """Base: one map child + a lambda body over (outer cols, key, value).

    Reference analog: GpuTransformKeys/GpuTransformValues/GpuMapFilter
    (higherOrderFunctions.scala).  Same flatten trick as the array HOFs:
    the aligned key/value element matrices flatten into a (cap*ewidth)
    pseudo-batch with two lambda columns."""

    def __init__(self, m: Expression, key_name: str, val_name: str,
                 body: Expression):
        super().__init__([m])
        self.key_name = key_name
        self.val_name = val_name
        self.body = body

    @property
    def m(self):
        return self.children[0]

    def sql_string(self):
        return (f"{self.pretty_name.lower()}({self.m.sql_string()}, "
                f"({self.key_name}, {self.val_name}) -> "
                f"{self.body.sql_string()})")

    def resolve(self, schema: T.StructType) -> Expression:
        self.children = [c.resolve(schema) for c in self.children]
        mt = self.m.dataType
        ext = T.StructType(
            list(schema.fields)
            + [T.StructField(self.key_name, mt.keyType, False),
               T.StructField(self.val_name, mt.valueType, True)])
        self.body = self.body.resolve(ext)
        self._resolve_type()
        self.resolved = True
        return self

    def collect(self, pred):
        out = super().collect(pred)
        out.extend(self.body.collect(pred))
        return out

    def _eval_body(self, ctx: EvalContext, m: DeviceColumn):
        kcol, vcol = m.children
        cap, w = kcol.capacity, max(kcol.ewidth, 1)
        inl = _in_len(kcol)
        mt = self.m.dataType
        ek = DeviceColumn(mt.keyType, (kcol.elem_valid & inl).reshape(-1),
                          data=kcol.data.reshape(cap * w))
        ev = DeviceColumn(mt.valueType, (vcol.elem_valid & inl).reshape(-1),
                          data=vcol.data.reshape(cap * w))
        outer = [_repeat_col(c, w) for c in ctx.batch.columns]
        ext = T.StructType(
            list(ctx.batch.schema.fields)
            + [T.StructField(self.key_name, mt.keyType, False),
               T.StructField(self.val_name, mt.valueType, True)])
        flat = ColumnarBatch(outer + [ek, ev], cap * w, ext)
        sub = EvalContext(flat, ansi=ctx.ansi, error_flags=ctx.error_flags)
        res = self.body.eval_tpu(sub)
        return res, inl


class TransformKeys(MapHigherOrderFunction):
    """transform_keys(m, (k, v) -> f): new keys must be non-null and
    duplicate-free (Spark's EXCEPTION dedup policy) — checked via the
    batch error flags like CreateMap."""

    def _resolve_type(self):
        mt = self.m.dataType
        self._dataType = T.MapType(self.body.dataType, mt.valueType)
        self._nullable = self.m.nullable

    def do_columnar_eval(self, ctx, cols):
        m = cols[0]
        kcol, vcol = m.children
        cap, w = kcol.capacity, max(kcol.ewidth, 1)
        res, inl = self._eval_body(ctx, m)
        nk = res.data.reshape(cap, w)
        nk_valid = res.validity.reshape(cap, w)
        live = kcol.elem_valid & inl
        ctx.add_error(m.validity & jnp.any(live & ~nk_valid, axis=1),
                      "Cannot use null as map key")
        from spark_rapids_tpu.expr.collections import _dup_map_keys

        ctx.add_error(
            m.validity & _dup_map_keys(nk, live & nk_valid,
                                       self.body.dataType),
            "Duplicate map key was found")
        keys = DeviceColumn(T.ArrayType(self.body.dataType,
                                        containsNull=False),
                            kcol.validity, data=nk, lengths=kcol.lengths,
                            elem_valid=live)
        return DeviceColumn(self.dataType, m.validity,
                            children=(keys, vcol))


class TransformValues(MapHigherOrderFunction):
    """transform_values(m, (k, v) -> f)."""

    def _resolve_type(self):
        mt = self.m.dataType
        self._dataType = T.MapType(mt.keyType, self.body.dataType)
        self._nullable = self.m.nullable

    def do_columnar_eval(self, ctx, cols):
        m = cols[0]
        kcol, vcol = m.children
        cap, w = kcol.capacity, max(kcol.ewidth, 1)
        res, inl = self._eval_body(ctx, m)
        nv = res.data.reshape(cap, w)
        nv_valid = res.validity.reshape(cap, w) & kcol.elem_valid & inl
        vals = DeviceColumn(T.ArrayType(self.body.dataType), vcol.validity,
                            data=nv, lengths=vcol.lengths,
                            elem_valid=nv_valid)
        return DeviceColumn(self.dataType, m.validity,
                            children=(kcol, vals))


class MapFilter(MapHigherOrderFunction):
    """map_filter(m, (k, v) -> pred): keeps entries where pred is TRUE."""

    def _resolve_type(self):
        self._dataType = self.m.dataType
        self._nullable = self.m.nullable

    def do_columnar_eval(self, ctx, cols):
        m = cols[0]
        kcol, vcol = m.children
        cap, w = kcol.capacity, max(kcol.ewidth, 1)
        res, inl = self._eval_body(ctx, m)
        keep = (res.data.reshape(cap, w) & res.validity.reshape(cap, w)
                & kcol.elem_valid & inl)
        kd, kev, lengths = _compact_elems(kcol.data, kcol.elem_valid, keep)
        vd, vev, _ = _compact_elems(vcol.data, vcol.elem_valid, keep)
        keys = DeviceColumn(kcol.dtype, kcol.validity, data=kd,
                            lengths=lengths, elem_valid=kev)
        vals = DeviceColumn(vcol.dtype, vcol.validity, data=vd,
                            lengths=lengths, elem_valid=vev)
        return DeviceColumn(self.dataType, m.validity,
                            children=(keys, vals))


class ZipWith(Expression):
    """zip_with(a, b, (x, y) -> f): zips to the LONGER array; the shorter
    side contributes nulls (Spark semantics)."""

    def __init__(self, left: Expression, right: Expression,
                 x_name: str, y_name: str, body: Expression):
        super().__init__([left, right])
        self.x_name = x_name
        self.y_name = y_name
        self.body = body

    def sql_string(self):
        return (f"zip_with({self.children[0].sql_string()}, "
                f"{self.children[1].sql_string()}, "
                f"({self.x_name}, {self.y_name}) -> "
                f"{self.body.sql_string()})")

    def resolve(self, schema: T.StructType) -> Expression:
        self.children = [c.resolve(schema) for c in self.children]
        ext = T.StructType(
            list(schema.fields)
            + [T.StructField(self.x_name,
                             self.children[0].dataType.elementType, True),
               T.StructField(self.y_name,
                             self.children[1].dataType.elementType, True)])
        self.body = self.body.resolve(ext)
        self._resolve_type()
        self.resolved = True
        return self

    def collect(self, pred):
        out = super().collect(pred)
        out.extend(self.body.collect(pred))
        return out

    def _resolve_type(self):
        self._dataType = T.ArrayType(self.body.dataType)
        self._nullable = (self.children[0].nullable
                          or self.children[1].nullable)

    def do_columnar_eval(self, ctx, cols):
        a, b = cols
        cap = a.capacity
        w = max(a.ewidth, b.ewidth, 1)

        def pad(c):
            if c.ewidth == w:
                return c.data, c.elem_valid
            pw = w - c.ewidth
            if c.ewidth == 0:
                sdt = T.storage_dtype(c.dtype.elementType)
                return (jnp.zeros((cap, w), sdt),
                        jnp.zeros((cap, w), jnp.bool_))
            return (jnp.pad(c.data, ((0, 0), (0, pw))),
                    jnp.pad(c.elem_valid, ((0, 0), (0, pw))))

        ad, aev = pad(a)
        bd, bev = pad(b)
        out_len = jnp.maximum(a.lengths, b.lengths)
        pos = jnp.arange(w, dtype=jnp.int32)[None, :]
        inl = pos < out_len[:, None]
        in_a = pos < a.lengths[:, None]
        in_b = pos < b.lengths[:, None]
        ex = DeviceColumn(self.children[0].dataType.elementType,
                          (aev & in_a & inl).reshape(-1),
                          data=ad.reshape(cap * w))
        ey = DeviceColumn(self.children[1].dataType.elementType,
                          (bev & in_b & inl).reshape(-1),
                          data=bd.reshape(cap * w))
        outer = [_repeat_col(c, w) for c in ctx.batch.columns]
        ext = T.StructType(
            list(ctx.batch.schema.fields)
            + [T.StructField(self.x_name,
                             self.children[0].dataType.elementType, True),
               T.StructField(self.y_name,
                             self.children[1].dataType.elementType, True)])
        flat = ColumnarBatch(outer + [ex, ey], cap * w, ext)
        sub = EvalContext(flat, ansi=ctx.ansi, error_flags=ctx.error_flags)
        res = self.body.eval_tpu(sub)
        data = res.data.reshape(cap, w)
        ev = res.validity.reshape(cap, w) & inl
        return DeviceColumn(self.dataType, a.validity & b.validity,
                            data=data, lengths=out_len, elem_valid=ev)


class MapZipWith(Expression):
    """map_zip_with(m1, m2, (k, v1, v2) -> f): the key UNION (m1's keys
    in order, then m2-only keys), each value null where its map lacks
    the key.

    Reference analog: GpuMapZipWith (higherOrderFunctions.scala)."""

    def __init__(self, m1: Expression, m2: Expression, k_name: str,
                 v1_name: str, v2_name: str, body: Expression):
        super().__init__([m1, m2])
        self.k_name = k_name
        self.v1_name = v1_name
        self.v2_name = v2_name
        self.body = body

    def sql_string(self):
        return (f"map_zip_with({self.children[0].sql_string()}, "
                f"{self.children[1].sql_string()}, "
                f"({self.k_name}, {self.v1_name}, {self.v2_name}) -> "
                f"{self.body.sql_string()})")

    def resolve(self, schema: T.StructType) -> Expression:
        self.children = [c.resolve(schema) for c in self.children]
        m1t = self.children[0].dataType
        m2t = self.children[1].dataType
        ext = T.StructType(
            list(schema.fields)
            + [T.StructField(self.k_name, m1t.keyType, False),
               T.StructField(self.v1_name, m1t.valueType, True),
               T.StructField(self.v2_name, m2t.valueType, True)])
        self.body = self.body.resolve(ext)
        self._resolve_type()
        self.resolved = True
        return self

    def collect(self, pred):
        out = super().collect(pred)
        out.extend(self.body.collect(pred))
        return out

    def _resolve_type(self):
        self._dataType = T.MapType(self.children[0].dataType.keyType,
                                   self.body.dataType)
        self._nullable = (self.children[0].nullable
                          or self.children[1].nullable)

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.collections import _elem_eq

        m1, m2 = cols
        k1, v1 = m1.children
        k2, v2 = m2.children
        kt = self.children[0].dataType.keyType
        cap = m1.capacity
        w1, w2 = max(k1.ewidth, 1), max(k2.ewidth, 1)
        w = w1 + w2

        def padk(c, width):
            if c.ewidth == width:
                return c.data
            if c.ewidth == 0:
                return jnp.zeros((cap, width),
                                 T.storage_dtype(kt))
            return jnp.pad(c.data, ((0, 0), (0, width - c.ewidth)))

        live1 = k1.elem_valid & _in_len(k1)
        live2 = k2.elem_valid & _in_len(k2)
        catk = jnp.concatenate([padk(k1, w1), padk(k2, w2)], axis=1)
        live = jnp.concatenate([live1, live2], axis=1)
        # first-occurrence dedup over the concat (m1 keys first)
        eq = _elem_eq(catk[:, :, None], catk[:, None, :], kt)
        both = live[:, :, None] & live[:, None, :]
        earlier = jnp.tril(jnp.ones((w, w), jnp.bool_), k=-1)[None]
        dup = jnp.any(eq & both & earlier, axis=2)
        keep = live & ~dup
        kd, kev, lengths = _compact_elems(catk, keep, keep)
        # per union key, look up each side's value (first match)
        def lookup(kc, vc, width, livem):
            eqm = (_elem_eq(kd[:, :, None], padk(kc, width)[:, None, :],
                            kt) & livem[:, None, :] & kev[:, :, None])
            found = jnp.any(eqm, axis=2)
            pos = jnp.argmax(eqm, axis=2)
            safe = jnp.clip(pos, 0, max(width - 1, 0))
            vd = jnp.take_along_axis(
                jnp.pad(vc.data, ((0, 0), (0, width - vc.ewidth)))
                if vc.ewidth < width else vc.data, safe, axis=1)
            vev = jnp.take_along_axis(
                jnp.pad(vc.elem_valid, ((0, 0), (0, width - vc.ewidth)))
                if vc.ewidth < width else vc.elem_valid, safe, axis=1)
            return vd, vev & found, found

        v1d, v1ok, _ = lookup(k1, v1, w1, live1)
        v2d, v2ok, _ = lookup(k2, v2, w2, live2)
        # flatten for the lambda body
        m1t = self.children[0].dataType
        m2t = self.children[1].dataType
        ek = DeviceColumn(kt, kev.reshape(-1), data=kd.reshape(cap * w))
        e1 = DeviceColumn(m1t.valueType, v1ok.reshape(-1),
                          data=v1d.reshape(cap * w))
        e2 = DeviceColumn(m2t.valueType, v2ok.reshape(-1),
                          data=v2d.reshape(cap * w))
        outer = [_repeat_col(c, w) for c in ctx.batch.columns]
        ext = T.StructType(
            list(ctx.batch.schema.fields)
            + [T.StructField(self.k_name, kt, False),
               T.StructField(self.v1_name, m1t.valueType, True),
               T.StructField(self.v2_name, m2t.valueType, True)])
        flat = ColumnarBatch(outer + [ek, e1, e2], cap * w, ext)
        sub = EvalContext(flat, ansi=ctx.ansi)
        res = self.body.eval_tpu(sub)
        for f, msg in sub.error_flags:
            ctx.add_error(jnp.any(f.reshape(cap, w) & kev, axis=1), msg)
        validity = m1.validity & m2.validity
        keys = DeviceColumn(T.ArrayType(kt, containsNull=False), validity,
                            data=kd, lengths=lengths, elem_valid=kev)
        vals = DeviceColumn(T.ArrayType(self.body.dataType), validity,
                            data=res.data.reshape(cap, w),
                            lengths=lengths,
                            elem_valid=res.validity.reshape(cap, w) & kev)
        return DeviceColumn(self.dataType, validity,
                            children=(keys, vals))
