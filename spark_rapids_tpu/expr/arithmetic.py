"""Arithmetic expressions with Spark-exact semantics.

Reference analog: org/apache/spark/sql/rapids/arithmetic.scala (GpuAdd,
GpuSubtract, GpuMultiply, GpuDivide, GpuIntegralDivide, GpuRemainder,
GpuUnaryMinus, GpuAbs, GpuPmod) and spark-rapids-jni decimal_utils.cu for
decimal precision/overflow behavior.

Spark semantics reproduced here:
  * integral overflow wraps (Java two's complement) in legacy mode; ANSI mode
    raises — on TPU the wrap comes free from int arithmetic and the ANSI
    check is a fused overflow-flag reduction (EvalContext.add_error).
  * Divide on non-decimals always yields double; x/0 -> null (legacy) or
    error (ANSI).
  * Decimal +,-,* follow DecimalPrecision: add/sub s=max(s1,s2),
    p=max(p1-s1,p2-s2)+s+1; mul p=p1+p2+1, s=s1+s2 (capped at 38).
    Results beyond the result precision -> null (legacy) / error (ANSI).
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (
    BinaryExpression,
    EvalContext,
    Expression,
    UnaryExpression,
)

_INT_MIN = {T.ByteType: -(2 ** 7), T.ShortType: -(2 ** 15),
            T.IntegerType: -(2 ** 31), T.LongType: -(2 ** 63)}
_INT_MAX = {T.ByteType: 2 ** 7 - 1, T.ShortType: 2 ** 15 - 1,
            T.IntegerType: 2 ** 31 - 1, T.LongType: 2 ** 63 - 1}


def _pow10_i64(k: int):
    return 10 ** min(k, 18)


class BinaryArithmetic(BinaryExpression):
    symbol = "?"

    def sql_string(self):
        return f"({self.left.sql_string()} {self.symbol} {self.right.sql_string()})"

    def _resolve_type(self):
        from spark_rapids_tpu.expr.cast import Cast

        lt, rt = self.left.dataType, self.right.dataType
        if isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType):
            ld = lt if isinstance(lt, T.DecimalType) else _int_as_decimal(lt)
            rd = rt if isinstance(rt, T.DecimalType) else _int_as_decimal(rt)
            if not isinstance(lt, T.DecimalType):
                self.children[0] = Cast(self.left, ld).resolve(None)
            if not isinstance(rt, T.DecimalType):
                self.children[1] = Cast(self.right, rd).resolve(None)
            self._dataType = self._decimal_result(ld, rd)
            self._nullable = True
            return
        if lt != rt:
            common = T.numeric_promote(lt, rt)
            if lt != common:
                self.children[0] = Cast(self.left, common).resolve(None)
            if rt != common:
                self.children[1] = Cast(self.right, common).resolve(None)
        self._dataType = self.left.dataType
        self._nullable = self.left.nullable or self.right.nullable

    def _decimal_result(self, ld: T.DecimalType, rd: T.DecimalType) -> T.DecimalType:
        raise NotImplementedError

    def do_columnar_eval(self, ctx: EvalContext, cols: List[DeviceColumn]):
        l, r = cols
        validity = l.validity & r.validity
        dt = self.dataType
        if isinstance(dt, T.DecimalType):
            return self._eval_decimal(ctx, l, r, validity)
        data = self._op(l.data, r.data)
        if ctx.ansi and dt.is_integral:
            over = self._overflow_flag(l.data, r.data, data)
            if over is not None:
                ctx.add_error(over & validity,
                              f"{self.pretty_name} caused overflow (ANSI)")
        return DeviceColumn(dt, validity, data=data)

    def _op(self, a, b):
        raise NotImplementedError

    def _overflow_flag(self, a, b, res):
        return None

    def _eval_decimal(self, ctx, l, r, validity):
        raise NotImplementedError(f"decimal {self.pretty_name}")


def _int_as_decimal(t: T.DataType) -> T.DecimalType:
    digits = {T.ByteType: 3, T.ShortType: 5, T.IntegerType: 10,
              T.LongType: 20}.get(type(t))
    if digits is None:
        raise TypeError(f"cannot mix {t} with decimal")
    return T.DecimalType(min(digits, 38), 0)


def _decimal_bound_check(ctx, data, dt: T.DecimalType, validity, ansi: bool,
                         op: str, extra_invalid=None):
    """null-out (legacy) / flag (ANSI) results beyond 10^precision.

    precision>=19 exceeds int64 storage; the effective bound is then the
    int64 range itself (callers must detect intermediate wraps separately)."""
    if dt.precision >= 19:
        # int64 storage bound, inclusive; only INT64_MIN is excluded (callers
        # use it as a wrap sentinel when detecting intermediate overflow)
        bound_ok = data != jnp.int64(-(2 ** 63))
    else:
        bound = _pow10_i64(dt.precision)
        bound_ok = (data < bound) & (data > -bound)
    if extra_invalid is not None:
        bound_ok = bound_ok & ~extra_invalid
    if ansi:
        ctx.add_error(~bound_ok & validity, f"decimal {op} overflow (ANSI)")
        return validity
    return validity & bound_ok


def _dec_limbs(c: DeviceColumn):
    """Any decimal column -> (hi, lo) limb pair."""
    from spark_rapids_tpu.expr.decimal128 import column_limbs

    return column_limbs(c)


class Add(BinaryArithmetic):
    symbol = "+"

    def _op(self, a, b):
        return a + b

    def _overflow_flag(self, a, b, res):
        return ((a > 0) & (b > 0) & (res < 0)) | ((a < 0) & (b < 0) & (res >= 0))

    def _decimal_result(self, ld, rd):
        s = max(ld.scale, rd.scale)
        p = max(ld.precision - ld.scale, rd.precision - rd.scale) + s + 1
        return T.DecimalType(min(p, 38), s)

    _dec_sign = 1

    def _eval_decimal(self, ctx, l, r, validity):
        dt: T.DecimalType = self.dataType
        lt: T.DecimalType = self.left.dataType
        rt: T.DecimalType = self.right.dataType
        op = "add" if self._dec_sign > 0 else "subtract"
        if dt.is_128 or lt.is_128 or rt.is_128:
            from spark_rapids_tpu.expr import decimal128 as D

            ah, al = _dec_limbs(l)
            bh, bl = _dec_limbs(r)
            oa, ah, al = D.mul128_pow10(ah, al, dt.scale - lt.scale)
            ob, bh, bl = D.mul128_pow10(bh, bl, dt.scale - rt.scale)
            if self._dec_sign < 0:
                bh, bl = D.neg128(bh, bl)
            rh, rl = D.add128(ah, al, bh, bl)
            # signed 128 wrap: same operand signs, different result sign
            wrap = (ah < 0) == (bh < 0)
            wrap = wrap & ((rh < 0) != (ah < 0))
            ok = D.in_bounds(rh, rl, dt.precision) & ~wrap & ~oa & ~ob
            if ctx.ansi:
                ctx.add_error(~ok & validity, f"decimal {op} overflow (ANSI)")
            else:
                validity = validity & ok
            data = D.pack(rh, rl) if dt.is_128 else rl
            return DeviceColumn(dt, validity, data=data)
        a = l.data * _pow10_i64(dt.scale - lt.scale)
        b = r.data * _pow10_i64(dt.scale - rt.scale)
        data = a + b if self._dec_sign > 0 else a - b
        validity = _decimal_bound_check(ctx, data, dt, validity, ctx.ansi, op)
        return DeviceColumn(dt, validity, data=data)


class Subtract(Add):
    symbol = "-"

    _dec_sign = -1

    def _op(self, a, b):
        return a - b

    def _overflow_flag(self, a, b, res):
        return ((a >= 0) & (b < 0) & (res < 0)) | ((a < 0) & (b > 0) & (res >= 0))


class Multiply(BinaryArithmetic):
    symbol = "*"

    def _op(self, a, b):
        return a * b

    def _overflow_flag(self, a, b, res):
        # res/b != a detects int overflow without widening; INT_MIN * -1
        # needs its own check (the division wraps back to INT_MIN)
        imin = jnp.asarray(jnp.iinfo(res.dtype).min, res.dtype)
        return ((b != 0) & (res // jnp.where(b == 0, 1, b) != a)) \
            | ((a == imin) & (b == -1))

    def _decimal_result(self, ld, rd):
        return T.DecimalType(min(ld.precision + rd.precision + 1, 38),
                             min(ld.scale + rd.scale, 38))

    def _eval_decimal(self, ctx, l, r, validity):
        dt: T.DecimalType = self.dataType
        lt: T.DecimalType = self.left.dataType
        rt: T.DecimalType = self.right.dataType
        if lt.is_128 or rt.is_128:
            # 128x128 -> 256-bit intermediates; rejected at tag time
            # (overrides _check_decimal_mult), mirroring the reference's
            # DECIMAL128 ceiling in GpuDecimalMultiply.
            raise NotImplementedError("decimal multiply operands > 18 digits")
        if dt.is_128:
            from spark_rapids_tpu.expr import decimal128 as D

            rh, rl = D.mul64_to_128(l.data, r.data)   # exact, cannot wrap
            ok = D.in_bounds(rh, rl, dt.precision)
            if ctx.ansi:
                ctx.add_error(~ok & validity, "decimal multiply overflow (ANSI)")
            else:
                validity = validity & ok
            return DeviceColumn(dt, validity, data=D.pack(rh, rl))
        data = l.data * r.data
        # int64 intermediate overflow detection via float magnitude estimate
        approx = l.data.astype(jnp.float64) * r.data.astype(jnp.float64)
        i64_over = jnp.abs(approx) > 9.1e18
        validity = _decimal_bound_check(ctx, data, dt, validity, ctx.ansi,
                                        "multiply", extra_invalid=i64_over)
        return DeviceColumn(dt, validity, data=data)


class Divide(BinaryArithmetic):
    """Spark Divide: non-decimal operands -> double division."""

    symbol = "/"

    def _resolve_type(self):
        from spark_rapids_tpu.expr.cast import Cast

        lt, rt = self.left.dataType, self.right.dataType
        if isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType):
            super()._resolve_type()
            return
        if lt != T.DOUBLE:
            self.children[0] = Cast(self.left, T.DOUBLE).resolve(None)
        if rt != T.DOUBLE:
            self.children[1] = Cast(self.right, T.DOUBLE).resolve(None)
        self._dataType = T.DOUBLE
        self._nullable = True

    def _decimal_result(self, ld, rd):
        s = max(6, ld.scale + rd.precision + 1)
        p = ld.precision - ld.scale + rd.scale + s
        if p > 38:
            # Spark reduces scale to fit
            s = max(6, 38 - (p - s))
            p = 38
        return T.DecimalType(p, s)

    def do_columnar_eval(self, ctx, cols):
        l, r = cols
        if isinstance(self.dataType, T.DecimalType):
            return self._eval_decimal(ctx, l, r, l.validity & r.validity)
        div_by_zero = r.data == 0.0
        validity = l.validity & r.validity & ~div_by_zero
        if ctx.ansi:
            ctx.add_error(div_by_zero & l.validity & r.validity,
                          "division by zero (ANSI)")
        data = l.data / jnp.where(div_by_zero, 1.0, r.data)
        return DeviceColumn(T.DOUBLE, validity, data=data)

    def _eval_decimal(self, ctx, l, r, validity):
        dt: T.DecimalType = self.dataType
        lt: T.DecimalType = self.left.dataType
        rt: T.DecimalType = self.right.dataType
        div_by_zero = r.data == 0
        if ctx.ansi:
            ctx.add_error(div_by_zero & validity, "division by zero (ANSI)")
        validity = validity & ~div_by_zero
        # target scale: s; numerator scaled to s + rt.scale then HALF_UP
        shift = dt.scale - lt.scale + rt.scale
        num_scale = _pow10_i64(max(shift, 0))
        # int64 intermediate overflow: |l| * 10^shift must fit
        num_limit = (2 ** 63 - 1) // num_scale
        num_over = jnp.abs(l.data) > num_limit
        if ctx.ansi:
            ctx.add_error(num_over & validity, "decimal divide overflow (ANSI)")
        validity = validity & ~num_over
        num = jnp.where(num_over, 0, l.data) * num_scale
        den = jnp.where(div_by_zero, 1, r.data) * _pow10_i64(max(-shift, 0))
        half = jnp.abs(den)
        sign = jnp.where((num < 0) ^ (den < 0), -1, 1)
        # truncate toward zero (jnp // floors), then HALF_UP away from zero
        q = num // den
        rem = num - q * den
        q = q + jnp.where((rem != 0) & ((num < 0) ^ (den < 0)), 1, 0)
        rem2 = num - q * den
        round_away = (jnp.abs(rem2) * 2 >= half) & (rem2 != 0)
        data = q + jnp.where(round_away, sign, 0)
        validity = _decimal_bound_check(ctx, data, dt, validity, ctx.ansi, "divide")
        return DeviceColumn(dt, validity, data=data)


class IntegralDivide(BinaryArithmetic):
    """`div` — integral division returning LONG (Spark semantics)."""

    symbol = "div"

    def _resolve_type(self):
        from spark_rapids_tpu.expr.cast import Cast

        for i in (0, 1):
            if self.children[i].dataType != T.LONG:
                self.children[i] = Cast(self.children[i], T.LONG).resolve(None)
        self._dataType = T.LONG
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        l, r = cols
        div_by_zero = r.data == 0
        validity = l.validity & r.validity & ~div_by_zero
        if ctx.ansi:
            ctx.add_error(div_by_zero & l.validity & r.validity,
                          "division by zero (ANSI)")
        den = jnp.where(div_by_zero, 1, r.data)
        q = l.data // den
        rem = l.data - q * den
        # Java integer division truncates toward zero; jnp floors.
        q = q + jnp.where((rem != 0) & ((l.data < 0) ^ (den < 0)), 1, 0)
        return DeviceColumn(T.LONG, validity, data=q)


class Remainder(BinaryArithmetic):
    symbol = "%"

    def _op(self, a, b):
        raise AssertionError("handled in do_columnar_eval")

    def _decimal_result(self, ld, rd):
        s = max(ld.scale, rd.scale)
        p = min(ld.precision - ld.scale, rd.precision - rd.scale) + s
        return T.DecimalType(min(p, 38), s)

    def do_columnar_eval(self, ctx, cols):
        l, r = cols
        dt = self.dataType
        zero = r.data == 0 if not dt.is_floating else r.data == 0.0
        validity = l.validity & r.validity
        if not dt.is_floating:
            if ctx.ansi:
                ctx.add_error(zero & validity, "division by zero (ANSI)")
            validity = validity & ~zero
            den = jnp.where(zero, 1, r.data)
            data = l.data - _trunc_div(l.data, den) * den
        else:
            # float % follows Java Math.IEEEremainder-like fmod (sign of dividend)
            data = _fmod(l.data, r.data)
            validity = validity & ~zero
        return DeviceColumn(dt, validity, data=data)

    def _eval_decimal(self, ctx, l, r, validity):
        dt: T.DecimalType = self.dataType
        lt: T.DecimalType = self.left.dataType
        rt: T.DecimalType = self.right.dataType
        a = l.data * _pow10_i64(dt.scale - lt.scale)
        b = r.data * _pow10_i64(dt.scale - rt.scale)
        zero = b == 0
        if ctx.ansi:
            ctx.add_error(zero & validity, "division by zero (ANSI)")
        validity = validity & ~zero
        den = jnp.where(zero, 1, b)
        data = a - _trunc_div(a, den) * den
        return DeviceColumn(dt, validity, data=data)


def _trunc_div(a, b):
    q = a // b
    rem = a - q * b
    return q + jnp.where((rem != 0) & ((a < 0) ^ (b < 0)), 1, 0)


def _fmod(a, b):
    safe_b = jnp.where(b == 0.0, 1.0, b)
    return a - jnp.trunc(a / safe_b) * safe_b


class UnaryMinus(UnaryExpression):
    def sql_string(self):
        return f"(- {self.child.sql_string()})"

    def _resolve_type(self):
        self._dataType = self.child.dataType
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        dt = self.dataType
        validity = c.validity
        if ctx.ansi and dt.is_integral:
            mn = _INT_MIN[type(dt)]
            ctx.add_error((c.data == mn) & validity, "negate overflow (ANSI)")
        return DeviceColumn(dt, validity, data=-c.data)


class Abs(UnaryExpression):
    def _resolve_type(self):
        self._dataType = self.child.dataType
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        dt = self.dataType
        if ctx.ansi and dt.is_integral:
            mn = _INT_MIN[type(dt)]
            ctx.add_error((c.data == mn) & c.validity, "abs overflow (ANSI)")
        return DeviceColumn(dt, c.validity, data=jnp.abs(c.data))


class Pmod(BinaryArithmetic):
    """pmod(a, b): non-negative remainder."""

    symbol = "pmod"

    def _op(self, a, b):
        raise AssertionError

    def _decimal_result(self, ld, rd):
        return Remainder(self.left, self.right)._decimal_result(ld, rd)

    def do_columnar_eval(self, ctx, cols):
        l, r = cols
        zero = r.data == 0
        validity = l.validity & r.validity & ~zero
        if ctx.ansi:
            ctx.add_error(zero & l.validity & r.validity,
                          "division by zero (ANSI)")
        den = jnp.where(zero, 1, r.data)
        # Spark Pmod: r = a % n (Java truncated); if r < 0 then (r + n) % n
        # — note the sign of a NEGATIVE divisor is preserved.
        m = l.data - _trunc_div(l.data, den) * den
        adjusted = m + den
        adjusted = adjusted - _trunc_div(adjusted, den) * den
        data = jnp.where(m < 0, adjusted, m)
        return DeviceColumn(self.dataType, validity, data=data)


# -- bitwise (GpuBitwiseAnd/Or/Xor/Not, GpuShiftLeft/Right/RightUnsigned;
# reference: org/apache/spark/sql/rapids/bitwise.scala analog) --------------

class _BitwiseBinary(BinaryExpression):
    def _resolve_type(self):
        lt, rt = self.left.dataType, self.right.dataType
        if not (lt.is_integral and rt.is_integral):
            raise TypeError(f"{self.pretty_name} needs integral operands")
        common = T.numeric_promote(lt, rt)
        from spark_rapids_tpu.expr.cast import Cast

        self.children = [
            c if c.dataType == common else Cast(c, common).resolve(None)
            for c in self.children]
        self._dataType = common
        self._nullable = self.left.nullable or self.right.nullable

    def do_columnar_eval(self, ctx, cols):
        l, r = cols
        return DeviceColumn(self.dataType, l.validity & r.validity,
                            data=self._fn(l.data, r.data))


class BitwiseAnd(_BitwiseBinary):
    symbol = "&"

    def _fn(self, a, b):
        return a & b


class BitwiseOr(_BitwiseBinary):
    symbol = "|"

    def _fn(self, a, b):
        return a | b


class BitwiseXor(_BitwiseBinary):
    symbol = "^"

    def _fn(self, a, b):
        return a ^ b


class BitwiseNot(UnaryExpression):
    def _resolve_type(self):
        if not self.child.dataType.is_integral:
            raise TypeError("~ needs an integral operand")
        self._dataType = self.child.dataType
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(self.dataType, c.validity, data=~c.data)


class _Shift(BinaryExpression):
    """Java shift semantics: the amount is masked to the value width
    (x << 33 on int == x << 1), never widened."""

    def _resolve_type(self):
        lt = self.left.dataType
        if not isinstance(lt, (T.IntegerType, T.LongType)):
            from spark_rapids_tpu.expr.cast import Cast

            self.children[0] = Cast(self.left, T.INT).resolve(None)
            lt = T.INT
        self._dataType = lt
        self._nullable = self.left.nullable or self.right.nullable

    def do_columnar_eval(self, ctx, cols):
        l, r = cols
        width_mask = 63 if isinstance(self.dataType, T.LongType) else 31
        amt = (r.data.astype(jnp.int32) & width_mask).astype(l.data.dtype)
        return DeviceColumn(self.dataType, l.validity & r.validity,
                            data=self._fn(l.data, amt))


class ShiftLeft(_Shift):
    def _fn(self, a, amt):
        return a << amt


class ShiftRight(_Shift):
    def _fn(self, a, amt):
        return a >> amt   # arithmetic (sign-propagating)


class ShiftRightUnsigned(_Shift):
    def _fn(self, a, amt):
        udt = jnp.uint64 if a.dtype == jnp.int64 else jnp.uint32
        return jax.lax.shift_right_logical(
            jax.lax.bitcast_convert_type(a, udt),
            jax.lax.bitcast_convert_type(amt, udt)).astype(a.dtype)


class _TryMixin:
    """try_* arithmetic: the ANSI operation with errors becoming NULL
    (Spark's TryEval over the ANSI evaluator).  The child op runs with a
    forked always-ANSI context; its error flags null the result rows
    instead of raising.

    Reference analog: GpuTryAdd/... (sql-plugin arithmetic.scala)."""

    _fn_name = "try_op"

    def sql_string(self):
        return (f"{self._fn_name}({self.left.sql_string()}, "
                f"{self.right.sql_string()})")

    def do_columnar_eval(self, ctx: EvalContext, cols):
        sub = EvalContext(ctx.batch, ansi=True,
                          row_offset=ctx.row_offset)
        out = super().do_columnar_eval(sub, cols)
        bad = None
        for flag, _msg in sub.error_flags:
            bad = flag if bad is None else (bad | flag)
        if bad is None:
            return out
        return DeviceColumn(out.dtype, out.validity & ~bad,
                            data=out.data, chars=out.chars,
                            lengths=out.lengths,
                            elem_valid=out.elem_valid,
                            children=out.children)


class TryAdd(_TryMixin, Add):
    _fn_name = "try_add"


class TrySubtract(_TryMixin, Subtract):
    _fn_name = "try_subtract"


class TryMultiply(_TryMixin, Multiply):
    _fn_name = "try_multiply"


class TryDivide(_TryMixin, Divide):
    _fn_name = "try_divide"


class UnaryPositive(UnaryExpression):
    """(+ e): identity (Spark keeps the node through analysis)."""

    def sql_string(self):
        return f"(+ {self.child.sql_string()})"

    def _resolve_type(self):
        self._dataType = self.child.dataType
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        return cols[0]
