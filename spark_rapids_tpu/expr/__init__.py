from spark_rapids_tpu.expr.base import (  # noqa: F401
    Alias,
    AttributeReference,
    BoundReference,
    EvalContext,
    Expression,
    Literal,
    col,
    lit,
)
from spark_rapids_tpu.expr import arithmetic  # noqa: F401
from spark_rapids_tpu.expr import predicates  # noqa: F401
from spark_rapids_tpu.expr import conditional  # noqa: F401
from spark_rapids_tpu.expr import cast  # noqa: F401
from spark_rapids_tpu.expr import mathfuncs  # noqa: F401
from spark_rapids_tpu.expr import strings  # noqa: F401
from spark_rapids_tpu.expr import datetime as datetime_exprs  # noqa: F401
