"""JSON expressions: get_json_object, json_tuple, from_json, to_json.

Reference analog: GpuGetJsonObject / GpuJsonTuple (spark-rapids-jni
``get_json_object.cu``), GpuJsonToStructs (jni JSON parser), GpuStructsToJson
(SURVEY.md §2.5 JSON row).  The reference runs a CUDA JSON kernel; the TPU
build keeps JSON parsing on the host (SURVEY.md §2.10 item 10: host parse →
device) behind ``jax.pure_callback`` — the byte-level path engine lives in
spark_rapids_tpu/jsonpath.py with a native C++ port (native/host_kernels.cpp)
for throughput; results land back in the jitted stage as padded columns.

Path support mirrors the reference's plan-time reject stance: wildcard
paths fall back to CPU with an explain reason (overrides._check_json_path).
"""
from __future__ import annotations

import json
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (
    BinaryExpression,
    call_host_kernel,
    EvalContext,
    Expression,
    Literal,
    UnaryExpression,
)
from spark_rapids_tpu.jsonpath import (
    PathStep,
    UnsupportedJsonPath,
    get_json_object_bytes,
    parse_json_path,
)


def _null_string_col(cap: int) -> DeviceColumn:
    return DeviceColumn(T.STRING, jnp.zeros(cap, jnp.bool_),
                        chars=jnp.zeros((cap, 8), jnp.uint8),
                        lengths=jnp.zeros(cap, jnp.int32))


def _padded_json_eval(chars: np.ndarray, lengths: np.ndarray,
                      validity: np.ndarray,
                      steps: List[PathStep]):
    """Host kernel: evaluate one path over a padded char matrix."""
    from spark_rapids_tpu import native

    return native.get_json_object_padded(chars, lengths, validity, steps)


def _callback_string_result(c: DeviceColumn, fn):
    """Run fn(chars,lengths,validity) -> (chars,lengths,valid) on host."""
    cap, w = c.capacity, max(c.width, 1)
    shapes = (jax.ShapeDtypeStruct((cap, w), np.uint8),
              jax.ShapeDtypeStruct((cap,), np.int32),
              jax.ShapeDtypeStruct((cap,), np.bool_))
    out_chars, out_lens, out_valid = call_host_kernel(
        fn, shapes, c.chars, c.lengths, c.validity)
    return DeviceColumn(T.STRING, out_valid, chars=out_chars,
                        lengths=out_lens)


class GetJsonObject(BinaryExpression):
    """get_json_object(json, path) — path must be a literal (Spark requires
    foldable); wildcard paths are rejected at plan time."""

    is_host_kernel = True

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True
        self._steps: Optional[List[PathStep]] = None
        p = self.right
        if isinstance(p, Literal) and p.value is not None:
            try:
                self._steps = parse_json_path(p.value)
            except UnsupportedJsonPath:
                self._steps = None  # overrides rejects before we get here

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        if self._steps is None:
            # invalid path or null path literal: Spark yields NULL rows
            return _null_string_col(c.capacity)
        steps = self._steps

        def fn(chars, lengths, validity):
            return _padded_json_eval(np.asarray(chars), np.asarray(lengths),
                                     np.asarray(validity), steps)

        return _callback_string_result(c, fn)


class JsonTuple(Expression):
    """json_tuple(json, k1, ...) — struct of N string fields c0..cN-1.

    Spark plans json_tuple as a generator (one row, N columns); the TPU
    build returns a struct column (same capability; flattened by a
    Project of GetStructField)."""

    is_host_kernel = True

    def __init__(self, children: List[Expression]):
        super().__init__(children)

    def _resolve_type(self):
        nkeys = len(self.children) - 1
        self._dataType = T.StructType(
            [T.StructField(f"c{i}", T.STRING, True) for i in range(nkeys)])
        self._nullable = False
        self._keys: List[Optional[str]] = []
        for k in self.children[1:]:
            if isinstance(k, Literal) and isinstance(k.value, str):
                self._keys.append(k.value)
            else:
                self._keys.append(None)

    def do_columnar_eval(self, ctx: EvalContext, cols):
        from spark_rapids_tpu.jsonpath import json_tuple_bytes

        c = cols[0]
        cap, w = c.capacity, max(c.width, 1)
        keys: List[str] = []
        slot_to_j = {}
        for slot, k in enumerate(self._keys):
            if k is not None:
                slot_to_j[slot] = len(keys)
                keys.append(k)

        def fn(chars, lengths, validity):
            chars = np.asarray(chars)
            lengths = np.asarray(lengths)
            validity = np.asarray(validity)
            k = len(keys)
            out_chars = np.zeros((k, cap, w), np.uint8)
            out_lens = np.zeros((k, cap), np.int32)
            out_valid = np.zeros((k, cap), np.bool_)
            for i in range(cap):
                if not validity[i]:
                    continue
                vals = json_tuple_bytes(bytes(chars[i, :lengths[i]]), keys)
                for j, v in enumerate(vals):
                    if v is None:
                        continue
                    v = v[:w]
                    out_chars[j, i, :len(v)] = np.frombuffer(v, np.uint8)
                    out_lens[j, i] = len(v)
                    out_valid[j, i] = True
            return out_chars, out_lens, out_valid

        shapes = (jax.ShapeDtypeStruct((len(keys), cap, w), np.uint8),
                  jax.ShapeDtypeStruct((len(keys), cap), np.int32),
                  jax.ShapeDtypeStruct((len(keys), cap), np.bool_))
        if keys:
            och, oln, ova = call_host_kernel(fn, shapes, c.chars,
                                              c.lengths, c.validity)
        kids = []
        for slot in range(len(self._keys)):
            if slot in slot_to_j:
                j = slot_to_j[slot]
                kids.append(DeviceColumn(T.STRING, ova[j], chars=och[j],
                                         lengths=oln[j]))
            else:
                kids.append(_null_string_col(cap))
        validity = jnp.ones(cap, jnp.bool_)
        return DeviceColumn(self.dataType, validity, children=tuple(kids))


# ---------------------------------------------------------------------------
# from_json / to_json
# ---------------------------------------------------------------------------

def convert_json_field(v, dt: T.DataType):
    """One parsed JSON value -> storage value for field type dt.

    Returns (ok, value); ok=False means the RECORD fails (PERMISSIVE mode
    nulls every field of the row, like Spark's JacksonParser badRecord)."""
    if v is None:
        return True, None
    if isinstance(dt, T.StringType):
        if isinstance(v, str):
            return True, v
        if isinstance(v, bool):
            return True, "true" if v else "false"
        if isinstance(v, (int, float)):
            return True, json.dumps(v)
        return True, json.dumps(v, separators=(",", ":"),
                                ensure_ascii=False)
    if isinstance(dt, T.BooleanType):
        return (True, bool(v)) if isinstance(v, bool) else (False, None)
    if isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType, T.LongType)):
        if isinstance(v, bool) or not isinstance(v, int):
            return False, None
        lo = {T.ByteType: -(2**7), T.ShortType: -(2**15),
              T.IntegerType: -(2**31), T.LongType: -(2**63)}[type(dt)]
        if not (lo <= v < -lo):
            return False, None
        return True, v
    if isinstance(dt, (T.FloatType, T.DoubleType)):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return False, None
        return True, float(v)
    return False, None


class JsonToStructs(UnaryExpression):
    """from_json(json, schema) for flat structs of primitive/string fields.

    PERMISSIVE semantics: a malformed record (or a field/type mismatch)
    yields a row with every field NULL; a SQL NULL input yields a NULL
    struct."""

    is_host_kernel = True

    def __init__(self, child: Expression, schema: T.StructType):
        super().__init__(child)
        self.schema = schema

    def _resolve_type(self):
        self._dataType = self.schema
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        cap, w = c.capacity, max(c.width, 1)
        fields = self.schema.fields

        def fn(chars, lengths, validity):
            chars = np.asarray(chars)
            lengths = np.asarray(lengths)
            validity = np.asarray(validity)
            n = cap
            records: List[Optional[list]] = []
            for i in range(n):
                if not validity[i]:
                    records.append(None)
                    continue
                raw = bytes(chars[i, :lengths[i]])
                vals: Optional[list] = []
                try:
                    doc = json.loads(raw.decode("utf-8", "replace"))
                except (ValueError, UnicodeDecodeError):
                    doc = None
                if not isinstance(doc, dict):
                    vals = [None] * len(fields)
                else:
                    for f in fields:
                        ok, sv = convert_json_field(doc.get(f.name),
                                                     f.dataType)
                        if not ok:
                            vals = [None] * len(fields)
                            break
                        vals.append(sv)
                records.append(vals)
            outs = []
            for k, f in enumerate(fields):
                col_vals = [r[k] if r is not None else None for r in records]
                fvalid = np.array([v is not None for v in col_vals],
                                  np.bool_)
                if isinstance(f.dataType, T.StringType):
                    fchars = np.zeros((n, w), np.uint8)
                    flens = np.zeros(n, np.int32)
                    for i, v in enumerate(col_vals):
                        if v is None:
                            continue
                        b = v.encode("utf-8")[:w]
                        fchars[i, :len(b)] = np.frombuffer(b, np.uint8)
                        flens[i] = len(b)
                    outs += [fchars, flens, fvalid]
                else:
                    data = np.zeros(n, T.storage_dtype(f.dataType))
                    for i, v in enumerate(col_vals):
                        if v is not None:
                            data[i] = v
                    outs += [data, fvalid]
            outs.append(validity.copy())
            return tuple(outs)

        shapes = []
        for f in fields:
            if isinstance(f.dataType, T.StringType):
                shapes += [jax.ShapeDtypeStruct((cap, w), np.uint8),
                           jax.ShapeDtypeStruct((cap,), np.int32),
                           jax.ShapeDtypeStruct((cap,), np.bool_)]
            else:
                shapes += [jax.ShapeDtypeStruct(
                    (cap,), T.storage_dtype(f.dataType)),
                    jax.ShapeDtypeStruct((cap,), np.bool_)]
        shapes.append(jax.ShapeDtypeStruct((cap,), np.bool_))
        flat = call_host_kernel(fn, tuple(shapes), c.chars, c.lengths,
                                 c.validity)
        kids = []
        pos = 0
        for f in fields:
            if isinstance(f.dataType, T.StringType):
                kids.append(DeviceColumn(T.STRING, flat[pos + 2],
                                         chars=flat[pos],
                                         lengths=flat[pos + 1]))
                pos += 3
            else:
                kids.append(DeviceColumn(f.dataType, flat[pos + 1],
                                         data=flat[pos]))
                pos += 2
        return DeviceColumn(self.schema, flat[pos], children=tuple(kids))


def _json_escape(s: str) -> str:
    return json.dumps(s, ensure_ascii=False)


class StructsToJson(UnaryExpression):
    """to_json(struct) — null fields omitted (Spark ignoreNullFields)."""

    is_host_kernel = True

    def _resolve_type(self):
        if not isinstance(self.child.dataType, T.StructType):
            raise TypeError("to_json expects a struct input")
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        cap = c.capacity
        fields = self.child.dataType.fields
        # static output bound: braces + per-field key/punct + value bound
        bound = 2
        for f, kid in zip(fields, c.children):
            if isinstance(f.dataType, T.StringType):
                vb = 2 + 6 * max(kid.width, 1)
            elif isinstance(f.dataType, T.BooleanType):
                vb = 5
            else:
                vb = 25
            bound += len(f.name.encode()) + 4 + vb
        bound = max(bound, 8)

        def fn(validity, *kid_arrays):
            validity = np.asarray(validity)
            # unpack per-field host views
            host_fields = []
            pos = 0
            for f in fields:
                if isinstance(f.dataType, T.StringType):
                    host_fields.append((np.asarray(kid_arrays[pos]),
                                        np.asarray(kid_arrays[pos + 1]),
                                        np.asarray(kid_arrays[pos + 2])))
                    pos += 3
                else:
                    host_fields.append((np.asarray(kid_arrays[pos]),
                                        np.asarray(kid_arrays[pos + 1])))
                    pos += 2
            out_chars = np.zeros((cap, bound), np.uint8)
            out_lens = np.zeros(cap, np.int32)
            for i in range(cap):
                if not validity[i]:
                    continue
                parts = []
                for f, hf in zip(fields, host_fields):
                    if isinstance(f.dataType, T.StringType):
                        fchars, flens, fvalid = hf
                        if not fvalid[i]:
                            continue
                        v = bytes(fchars[i, :flens[i]]).decode(
                            "utf-8", "replace")
                        parts.append(f"{_json_escape(f.name)}:"
                                     f"{_json_escape(v)}")
                    else:
                        data, fvalid = hf
                        if not fvalid[i]:
                            continue
                        if isinstance(f.dataType, T.BooleanType):
                            txt = "true" if data[i] else "false"
                        elif isinstance(f.dataType,
                                        (T.FloatType, T.DoubleType)):
                            txt = json.dumps(float(data[i]))
                        else:
                            txt = str(int(data[i]))
                        parts.append(f"{_json_escape(f.name)}:{txt}")
                b = ("{" + ",".join(parts) + "}").encode("utf-8")[:bound]
                out_chars[i, :len(b)] = np.frombuffer(b, np.uint8)
                out_lens[i] = len(b)
            return out_chars, out_lens, validity.copy()

        shapes = (jax.ShapeDtypeStruct((cap, bound), np.uint8),
                  jax.ShapeDtypeStruct((cap,), np.int32),
                  jax.ShapeDtypeStruct((cap,), np.bool_))
        args = [c.validity]
        for f, kid in zip(fields, c.children):
            if isinstance(f.dataType, T.StringType):
                args += [kid.chars, kid.lengths, kid.validity & c.validity]
            else:
                args += [kid.data, kid.validity & c.validity]
        out_chars, out_lens, out_valid = call_host_kernel(
            fn, shapes, *args)
        return DeviceColumn(T.STRING, out_valid, chars=out_chars,
                            lengths=out_lens)


class SchemaOfJson(Expression):
    """schema_of_json('literal json') -> DDL schema string (plan-time
    constant fold — Spark requires a foldable argument).

    Reference analog: GpuSchemaOfJson (SURVEY.md §2.5 JSON)."""

    def __init__(self, children):
        super().__init__(list(children))

    def sql_string(self):
        return f"schema_of_json({self.children[0].sql_string()})"

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = False

    @staticmethod
    def _infer(v) -> str:
        if isinstance(v, bool):
            return "BOOLEAN"
        if isinstance(v, int):
            return "BIGINT"
        if isinstance(v, float):
            return "DOUBLE"
        if isinstance(v, str) or v is None:
            return "STRING"
        if isinstance(v, list):
            if not v:
                return "ARRAY<STRING>"
            return f"ARRAY<{SchemaOfJson._infer(v[0])}>"
        if isinstance(v, dict):
            inner = ", ".join(
                f"{k}: {SchemaOfJson._infer(val)}"
                for k, val in sorted(v.items()))
            return f"STRUCT<{inner}>"
        return "STRING"

    def _folded(self) -> str:
        import json as _json

        from spark_rapids_tpu.expr.base import Literal

        lit = self.children[0]
        if not isinstance(lit, Literal) or lit.value is None:
            raise ValueError(
                "schema_of_json requires a foldable string literal")
        return self._infer(_json.loads(str(lit.value)))

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.columnar.column import HostColumn
        from spark_rapids_tpu.columnar.column import DeviceColumn

        cap = ctx.batch.capacity
        s = self._folded()
        host = HostColumn.from_pylist([s] * cap, T.STRING)
        return DeviceColumn.from_host(host, capacity=cap)
