"""Expression tree core — the GpuExpression analog.

Reference analog: com/nvidia/spark/rapids/GpuExpression (columnarEval
returning a GpuColumnVector) plus Spark Catalyst's Expression/BoundReference/
Literal/Alias.  TPU-first difference: ``eval_tpu`` is *traceable* — it runs
under ``jax.jit`` as part of a whole-stage fused program, so an entire
project/filter chain compiles to one XLA executable (the reference needs
GpuTieredProject + cuDF AST fusion to approximate this; XLA gives it to us).

Every expression:
  * knows its resolved ``dataType`` and ``nullable``;
  * evaluates on device via ``eval_tpu(ctx) -> DeviceColumn`` (jnp ops only —
    no host syncs, no data-dependent Python control flow);
  * is independently re-implemented by the CPU oracle
    (spark_rapids_tpu/cpu/oracle.py) which the differential test harness
    treats as golden, mirroring how the reference tests GPU vs CPU Spark.

Spark null semantics: unless an expression overrides ``null_intolerant``
machinery, output validity = AND of input validities (null-propagating).
Three-valued logic (And/Or), Coalesce, IsNull etc. override eval entirely.
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.columnar.batch import ColumnarBatch


class SparkArithmeticException(Exception):
    """ANSI-mode overflow / invalid operation (matches Spark's error class)."""


@dataclasses.dataclass
class EvalContext:
    """Per-batch evaluation context threaded through eval_tpu.

    ansi errors: device-side ops cannot raise, so ANSI violations set flags
    collected here; ``check_errors`` syncs once per batch at the stage
    boundary (the TPU analog of cuDF kernels throwing from device checks).
    """

    batch: ColumnarBatch
    ansi: bool = False
    error_flags: List = dataclasses.field(default_factory=list)
    # absolute row position of this batch's first row (host int; consumed
    # by Rand / monotonically_increasing_id, which force the eager stage
    # path so the value is concrete)
    row_offset: int = 0

    @property
    def num_rows(self) -> int:
        return self.batch.num_rows

    @property
    def row_mask(self) -> jax.Array:
        return self.batch.row_mask

    def add_error(self, flag_per_row: jax.Array, message: str):
        self.error_flags.append((flag_per_row & self.row_mask, message))

    def check_errors(self):
        for flags, message in self.error_flags:
            if bool(jnp.any(flags)):
                raise SparkArithmeticException(message)
        self.error_flags.clear()


def contains_host_kernel(e: "Expression") -> bool:
    """True if any node needs a host callback (cannot be jit-compiled on
    backends without a PJRT host-callback channel, e.g. the axon TPU
    tunnel) — the enclosing stage then runs eagerly."""
    return bool(e.collect(lambda x: getattr(x, "is_host_kernel", False)))


def call_host_kernel(fn, shapes, *args):
    """Run a host kernel over device arrays.

    Under a trace: jax.pure_callback (CPU/test backends compile this fine).
    Concrete arrays: call directly — mandatory on the axon TPU tunnel,
    whose PJRT plugin has no host-callback channel at all (even the eager
    pure_callback impl compiles a program)."""
    import jax.core

    if any(isinstance(a, jax.core.Tracer) for a in args):
        return jax.pure_callback(fn, shapes, *args)
    res = fn(*(np.asarray(a) for a in args))
    return jax.tree_util.tree_map(jnp.asarray, res)


class Expression:
    """Base expression; subclasses set children and implement do_columnar_eval."""

    is_host_kernel = False  # True: evaluates via jax.pure_callback

    def __init__(self, children: Sequence["Expression"] = ()):
        self.children: List[Expression] = list(children)
        self._dataType: Optional[T.DataType] = None
        self._nullable: bool = True
        self.resolved: bool = False

    # -- naming -------------------------------------------------------------
    @property
    def pretty_name(self) -> str:
        return type(self).__name__

    @property
    def name(self) -> str:
        return self.sql_string()

    def sql_string(self) -> str:
        args = ", ".join(c.sql_string() for c in self.children)
        return f"{self.pretty_name.lower()}({args})"

    # -- typing -------------------------------------------------------------
    @property
    def dataType(self) -> T.DataType:
        assert self._dataType is not None, f"{self} not resolved"
        return self._dataType

    @property
    def nullable(self) -> bool:
        return self._nullable

    def resolve(self, schema: T.StructType) -> "Expression":
        """Bind attribute references and compute output types, bottom-up.

        Returns self (mutated) for chaining; mirrors Catalyst analysis enough
        for the harness — real Spark would hand us a resolved tree.
        """
        self.children = [c.resolve(schema) for c in self.children]
        self._resolve_type()
        self.resolved = True
        return self

    def _resolve_type(self):
        """Subclasses compute self._dataType / self._nullable here."""
        raise NotImplementedError(type(self).__name__)

    # -- device evaluation --------------------------------------------------
    def eval_tpu(self, ctx: EvalContext) -> DeviceColumn:
        cols = [c.eval_tpu(ctx) for c in self.children]
        return self.do_columnar_eval(ctx, cols)

    def do_columnar_eval(self, ctx: EvalContext,
                         cols: List[DeviceColumn]) -> DeviceColumn:
        raise NotImplementedError(type(self).__name__)

    # -- helpers ------------------------------------------------------------
    @staticmethod
    def and_validity(cols: Sequence[DeviceColumn]) -> jax.Array:
        v = cols[0].validity
        for c in cols[1:]:
            v = v & c.validity
        return v

    def map_children(self, fn) -> "Expression":
        self.children = [fn(c) for c in self.children]
        return self

    def transform_up(self, fn) -> "Expression":
        self.children = [c.transform_up(fn) for c in self.children]
        return fn(self)

    def collect(self, pred) -> List["Expression"]:
        out = []
        for c in self.children:
            out.extend(c.collect(pred))
        if pred(self):
            out.append(self)
        return out

    def __repr__(self):
        return self.sql_string()

    # -- operator sugar for the DataFrame API -------------------------------
    def _bin(self, other, cls):
        return cls(self, _wrap(other))

    def __add__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Add
        return self._bin(o, Add)

    def __sub__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Subtract
        return self._bin(o, Subtract)

    def __mul__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Multiply
        return self._bin(o, Multiply)

    def __truediv__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Divide
        return self._bin(o, Divide)

    def __mod__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Remainder
        return self._bin(o, Remainder)

    def __neg__(self):
        from spark_rapids_tpu.expr.arithmetic import UnaryMinus
        return UnaryMinus(self)

    def __lt__(self, o):
        from spark_rapids_tpu.expr.predicates import LessThan
        return self._bin(o, LessThan)

    def __le__(self, o):
        from spark_rapids_tpu.expr.predicates import LessThanOrEqual
        return self._bin(o, LessThanOrEqual)

    def __gt__(self, o):
        from spark_rapids_tpu.expr.predicates import GreaterThan
        return self._bin(o, GreaterThan)

    def __ge__(self, o):
        from spark_rapids_tpu.expr.predicates import GreaterThanOrEqual
        return self._bin(o, GreaterThanOrEqual)

    def eq(self, o):
        from spark_rapids_tpu.expr.predicates import EqualTo
        return self._bin(o, EqualTo)

    def __and__(self, o):
        from spark_rapids_tpu.expr.predicates import And
        return self._bin(o, And)

    def __or__(self, o):
        from spark_rapids_tpu.expr.predicates import Or
        return self._bin(o, Or)

    def __invert__(self):
        from spark_rapids_tpu.expr.predicates import Not
        return Not(self)

    def is_null(self):
        from spark_rapids_tpu.expr.predicates import IsNull
        return IsNull(self)

    def is_not_null(self):
        from spark_rapids_tpu.expr.predicates import IsNotNull
        return IsNotNull(self)

    def cast(self, dt: T.DataType):
        from spark_rapids_tpu.expr.cast import Cast
        return Cast(self, dt)

    def alias(self, name: str):
        return Alias(self, name)

    def isin(self, *values):
        from spark_rapids_tpu.expr.predicates import In
        return In(self, [lit(v) for v in values])

    def substr(self, pos, length):
        from spark_rapids_tpu.expr.strings import Substring
        return Substring(self, _wrap(pos), _wrap(length))


def _wrap(v) -> Expression:
    return v if isinstance(v, Expression) else Literal.of(v)


class AttributeReference(Expression):
    """Unresolved column-by-name; resolve() binds it to an ordinal."""

    def __init__(self, colname: str):
        super().__init__()
        self.colname = colname

    def sql_string(self):
        return self.colname

    def resolve(self, schema: T.StructType) -> Expression:
        names = schema.field_names()
        matches = [i for i, n in enumerate(names) if n == self.colname]
        if not matches:
            matches = [i for i, n in enumerate(names)
                       if n.lower() == self.colname.lower()]
        if len(matches) != 1:
            raise KeyError(
                f"cannot resolve column '{self.colname}' in {names}")
        i = matches[0]
        return BoundReference(i, schema.fields[i].dataType,
                              schema.fields[i].nullable, name=self.colname)

    def _resolve_type(self):
        raise AssertionError("AttributeReference must be bound")


class BoundReference(Expression):
    def __init__(self, ordinal: int, dtype: T.DataType, nullable: bool = True,
                 name: Optional[str] = None):
        super().__init__()
        self.ordinal = ordinal
        self._dataType = dtype
        self._nullable = nullable
        self._name = name
        self.resolved = True

    def sql_string(self):
        return self._name or f"input[{self.ordinal}]"

    def resolve(self, schema):
        return self

    def eval_tpu(self, ctx: EvalContext) -> DeviceColumn:
        return ctx.batch.columns[self.ordinal]


class Literal(Expression):
    def __init__(self, value: Any, dtype: T.DataType):
        super().__init__()
        self.value = value
        self._dataType = dtype
        self._nullable = value is None
        self.resolved = True

    @staticmethod
    def of(v) -> "Literal":
        import datetime as _dt
        from decimal import Decimal as _Dec

        if v is None:
            return Literal(None, T.NULL)
        if isinstance(v, bool):
            return Literal(v, T.BOOLEAN)
        if isinstance(v, int):
            return Literal(v, T.INT if -(2**31) <= v < 2**31 else T.LONG)
        if isinstance(v, float):
            return Literal(v, T.DOUBLE)
        if isinstance(v, str):
            return Literal(v, T.STRING)
        if isinstance(v, _Dec):
            sign, digits, exp = v.as_tuple()
            scale = max(0, -exp)
            precision = max(len(digits), scale + 1)
            return Literal(v, T.DecimalType(min(precision, 38), scale))
        if isinstance(v, _dt.datetime):
            epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
            vv = v if v.tzinfo else v.replace(tzinfo=_dt.timezone.utc)
            return Literal(int((vv - epoch).total_seconds() * 1_000_000),
                           T.TIMESTAMP)
        if isinstance(v, _dt.date):
            return Literal((v - _dt.date(1970, 1, 1)).days, T.DATE)
        raise TypeError(f"cannot make literal from {type(v)}")

    def sql_string(self):
        return repr(self.value)

    def resolve(self, schema):
        return self

    def storage_value(self):
        """Value in storage representation (decimal -> unscaled int, etc.)."""
        from decimal import Decimal as _Dec

        v = self.value
        if isinstance(self._dataType, T.DecimalType) and isinstance(v, _Dec):
            return int(v.scaleb(self._dataType.scale).to_integral_value())
        return v

    def eval_tpu(self, ctx: EvalContext) -> DeviceColumn:
        cap = ctx.batch.capacity
        dt = self._dataType
        if self.value is None:
            validity = jnp.zeros(cap, jnp.bool_)
            if isinstance(dt, T.StringType):
                return DeviceColumn(dt, validity,
                                    chars=jnp.zeros((cap, 8), jnp.uint8),
                                    lengths=jnp.zeros(cap, jnp.int32))
            if isinstance(dt, T.DecimalType) and dt.is_128:
                return DeviceColumn(dt, validity,
                                    data=jnp.zeros((cap, 2), jnp.int64))
            sdt = T.storage_dtype(dt) if not isinstance(dt, T.NullType) else np.int32
            return DeviceColumn(dt, validity, data=jnp.zeros(cap, sdt))
        validity = jnp.ones(cap, jnp.bool_)
        if isinstance(dt, T.StringType):
            b = self.value.encode("utf-8")
            width = max(len(b), 1)
            row = np.zeros(width, np.uint8)
            row[: len(b)] = np.frombuffer(b, np.uint8)
            chars = jnp.broadcast_to(jnp.asarray(row), (cap, width))
            return DeviceColumn(dt, validity, chars=chars,
                                lengths=jnp.full(cap, len(b), jnp.int32))
        sdt = T.storage_dtype(dt)
        if isinstance(dt, T.DecimalType) and dt.is_128:
            from spark_rapids_tpu.expr.decimal128 import limbs_of

            hi, lo = limbs_of(int(self.storage_value()))
            return DeviceColumn(dt, validity, data=jnp.broadcast_to(
                jnp.asarray([hi, lo], jnp.int64), (cap, 2)))
        return DeviceColumn(dt, validity,
                            data=jnp.full(cap, self.storage_value(), sdt))


class Alias(Expression):
    def __init__(self, child: Expression, alias_name: str):
        super().__init__([child])
        self.alias_name = alias_name

    def sql_string(self):
        return f"{self.children[0].sql_string()} AS {self.alias_name}"

    @property
    def name(self):
        return self.alias_name

    def _resolve_type(self):
        self._dataType = self.children[0].dataType
        self._nullable = self.children[0].nullable

    def eval_tpu(self, ctx):
        return self.children[0].eval_tpu(ctx)


def col(name: str) -> AttributeReference:
    return AttributeReference(name)


def lit(v) -> Literal:
    return Literal.of(v)


class UnaryExpression(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def child(self):
        return self.children[0]


class BinaryExpression(Expression):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]
