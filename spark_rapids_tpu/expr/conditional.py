"""Conditional / null expressions.

Reference analog: com/nvidia/spark/rapids/conditionalExpressions.scala
(GpuIf, GpuCaseWhen) and nullExpressions.scala (GpuCoalesce, GpuNvl,
GpuNaNvl, GpuAtLeastNNonNulls).  On TPU these are pure `jnp.where` selects —
XLA fuses the full predicate chain into the surrounding stage, so unlike the
reference there is no "lazy side evaluation" optimization to port: both sides
are computed vectorized, which is the right trade on a vector machine.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import EvalContext, Expression


def select_column(pred, pred_valid, a: DeviceColumn, b: DeviceColumn,
                  dtype: T.DataType) -> DeviceColumn:
    """where(pred, a, b) with null-aware pred (null pred -> b per CaseWhen
    fallthrough, callers adjust)."""
    take_a = pred & pred_valid
    validity = jnp.where(take_a, a.validity, b.validity)
    if a.is_string:
        w = max(a.width, b.width)
        from spark_rapids_tpu.expr.predicates import _pad_to

        chars = jnp.where(take_a[:, None], _pad_to(a.chars, w), _pad_to(b.chars, w))
        lengths = jnp.where(take_a, a.lengths, b.lengths)
        return DeviceColumn(dtype, validity, chars=chars, lengths=lengths)
    data = jnp.where(take_a, a.data, b.data)
    return DeviceColumn(dtype, validity, data=data)


def _common_type(ts: List[T.DataType]) -> T.DataType:
    out = ts[0]
    for t in ts[1:]:
        if t == out or isinstance(t, T.NullType):
            continue
        if isinstance(out, T.NullType):
            out = t
        elif out.is_numeric and t.is_numeric and not (
                isinstance(out, T.DecimalType) or isinstance(t, T.DecimalType)):
            out = T.numeric_promote(out, t)
        elif isinstance(out, T.DecimalType) and isinstance(t, T.DecimalType):
            s = max(out.scale, t.scale)
            p = max(out.precision - out.scale, t.precision - t.scale) + s
            out = T.DecimalType(min(p, 38), s)
        else:
            raise TypeError(f"no common type for {out} and {t}")
    return out


class If(Expression):
    def __init__(self, pred: Expression, left: Expression, right: Expression):
        super().__init__([pred, left, right])

    def sql_string(self):
        p, l, r = (c.sql_string() for c in self.children)
        return f"if({p}, {l}, {r})"

    def _resolve_type(self):
        from spark_rapids_tpu.expr.cast import Cast

        common = _common_type([self.children[1].dataType,
                               self.children[2].dataType])
        for i in (1, 2):
            if self.children[i].dataType != common:
                self.children[i] = Cast(self.children[i], common).resolve(None)
        self._dataType = common
        self._nullable = (self.children[0].nullable
                          or self.children[1].nullable
                          or self.children[2].nullable)

    def do_columnar_eval(self, ctx: EvalContext, cols):
        p, a, b = cols
        # null predicate -> else branch (Spark)
        return select_column(p.data, p.validity, a, b, self.dataType)


class CaseWhen(Expression):
    """CASE WHEN c1 THEN v1 [WHEN c2 THEN v2]... [ELSE e] END.

    children = [c1, v1, c2, v2, ..., (else)]; has_else marks the tail.
    """

    def __init__(self, branches, else_value=None):
        kids: List[Expression] = []
        for c, v in branches:
            kids.extend([c, v])
        self.has_else = else_value is not None
        if else_value is not None:
            kids.append(else_value)
        super().__init__(kids)

    def sql_string(self):
        n = (len(self.children) - (1 if self.has_else else 0)) // 2
        parts = []
        for i in range(n):
            parts.append(f"WHEN {self.children[2*i].sql_string()} "
                         f"THEN {self.children[2*i+1].sql_string()}")
        if self.has_else:
            parts.append(f"ELSE {self.children[-1].sql_string()}")
        return "CASE " + " ".join(parts) + " END"

    def _value_children_idx(self):
        n = (len(self.children) - (1 if self.has_else else 0)) // 2
        idx = [2 * i + 1 for i in range(n)]
        if self.has_else:
            idx.append(len(self.children) - 1)
        return idx

    def _resolve_type(self):
        from spark_rapids_tpu.expr.cast import Cast

        vidx = self._value_children_idx()
        common = _common_type([self.children[i].dataType for i in vidx])
        for i in vidx:
            if self.children[i].dataType != common:
                self.children[i] = Cast(self.children[i], common).resolve(None)
        self._dataType = common
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        n = (len(self.children) - (1 if self.has_else else 0)) // 2
        if self.has_else:
            acc = cols[-1]
        else:
            from spark_rapids_tpu.expr.base import Literal

            acc = Literal(None, self.dataType).eval_tpu(ctx)
            if acc.is_string is not cols[1].is_string:
                acc = cols[1]
                acc = DeviceColumn(self.dataType,
                                   jnp.zeros_like(acc.validity),
                                   data=acc.data, chars=acc.chars,
                                   lengths=acc.lengths)
        # fold from the last branch backwards so earlier WHENs win
        for i in reversed(range(n)):
            cond, val = cols[2 * i], cols[2 * i + 1]
            acc = select_column(cond.data, cond.validity, val, acc,
                                self.dataType)
        return acc


class Coalesce(Expression):
    def __init__(self, children: List[Expression]):
        super().__init__(children)

    def _resolve_type(self):
        from spark_rapids_tpu.expr.cast import Cast

        common = _common_type([c.dataType for c in self.children])
        self.children = [c if c.dataType == common else Cast(c, common).resolve(None)
                         for c in self.children]
        self._dataType = common
        self._nullable = all(c.nullable for c in self.children)

    def do_columnar_eval(self, ctx: EvalContext, cols):
        acc = cols[-1]
        for c in reversed(cols[:-1]):
            acc = select_column(c.validity, jnp.ones_like(c.validity), c, acc,
                                self.dataType)
        return acc


class Nvl(Coalesce):
    def __init__(self, a: Expression, b: Expression):
        super().__init__([a, b])


class NaNvl(Expression):
    """nanvl(a, b): a unless a is NaN."""

    def __init__(self, a: Expression, b: Expression):
        super().__init__([a, b])

    def _resolve_type(self):
        self._dataType = self.children[0].dataType
        self._nullable = any(c.nullable for c in self.children)

    def do_columnar_eval(self, ctx, cols):
        a, b = cols
        is_nan = jnp.isnan(a.data) & a.validity
        return select_column(~is_nan, jnp.ones_like(is_nan), a, b, self.dataType)


class Greatest(Expression):
    def __init__(self, children):
        super().__init__(children)

    def _resolve_type(self):
        from spark_rapids_tpu.expr.cast import Cast

        common = _common_type([c.dataType for c in self.children])
        self.children = [c if c.dataType == common else Cast(c, common).resolve(None)
                         for c in self.children]
        self._dataType = common
        self._nullable = all(c.nullable for c in self.children)

    def _pick(self, a, b):
        # NaN is the greatest value in Spark's ordering; jnp.maximum
        # propagates NaN, which is exactly "NaN wins"
        return jnp.maximum(a, b)

    def do_columnar_eval(self, ctx, cols):
        # Spark: skips nulls, null only if ALL null; NaN is greatest
        acc = cols[0]
        data, validity = acc.data, acc.validity
        for c in cols[1:]:
            both = validity & c.validity
            picked = self._pick(data, c.data)
            data = jnp.where(both, picked,
                             jnp.where(c.validity, c.data, data))
            validity = validity | c.validity
        return DeviceColumn(self.dataType, validity, data=data)


class Least(Greatest):
    def _pick(self, a, b):
        # least must IGNORE NaN (NaN is greatest): min(NaN, x) = x
        if jnp.issubdtype(a.dtype, jnp.floating):
            return jnp.where(jnp.isnan(a), b,
                             jnp.where(jnp.isnan(b), a, jnp.minimum(a, b)))
        return jnp.minimum(a, b)


class Nvl2(Expression):
    """nvl2(a, b, c): b when a is not null, else c."""

    def __init__(self, a: Expression, b: Expression, c: Expression):
        super().__init__([a, b, c])

    def _resolve_type(self):
        self._dataType = self.children[1].dataType
        self._nullable = (self.children[1].nullable
                          or self.children[2].nullable)

    def do_columnar_eval(self, ctx, cols):
        a, b, c = cols
        return select_column(a.validity, jnp.ones_like(a.validity), b, c,
                             self.dataType)


class NullIf(Expression):
    """nullif(a, b): null when a == b, else a."""

    def __init__(self, a: Expression, b: Expression):
        super().__init__([a, b])

    def _resolve_type(self):
        self._dataType = self.children[0].dataType
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        a, b = cols
        from spark_rapids_tpu.expr.predicates import EqualTo

        eq = EqualTo(self.children[0], self.children[1])
        eq._dataType = T.BOOLEAN
        eq.resolved = True
        eqc = eq.do_columnar_eval(ctx, [a, b])
        null_out = eqc.data & eqc.validity
        if a.is_string:
            return DeviceColumn(self.dataType, a.validity & ~null_out,
                                chars=a.chars, lengths=a.lengths)
        return DeviceColumn(self.dataType, a.validity & ~null_out,
                            data=a.data, chars=a.chars, lengths=a.lengths,
                            elem_valid=a.elem_valid, children=a.children)
