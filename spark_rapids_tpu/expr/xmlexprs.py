"""from_xml / to_xml — per-row XML record codecs (Spark 4.0 surface).

Reference analog: the reference accelerates from_json/to_json via cuDF's
JSON device parser and leaves XML to CPU connectors; here both row codecs
ride the same host-kernel tier as JsonToStructs (one pure_callback per
batch), with flat primitive/string structs — the tag check restricts.

from_xml is PERMISSIVE: a malformed document yields an all-NULL row.
to_xml emits ``<row><field>value</field>...</row>`` with null fields
omitted, matching Spark's writer defaults.
"""
from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import List, Optional

import jax
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (EvalContext, Expression,
                                        UnaryExpression, call_host_kernel)
from spark_rapids_tpu.expr.jsonexprs import convert_json_field


class XmlToStructs(UnaryExpression):
    """from_xml(xml, schema) for flat structs (child elements by name)."""

    is_host_kernel = True

    def __init__(self, child: Expression, schema: T.StructType):
        super().__init__(child)
        self.schema = schema

    def _resolve_type(self):
        self._dataType = self.schema
        self._nullable = True

    def sql_string(self):
        return f"from_xml({self.child.sql_string()})"

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        cap, w = c.capacity, max(c.width, 1)
        fields = self.schema.fields

        def fn(chars, lengths, validity):
            chars = np.asarray(chars)
            lengths = np.asarray(lengths)
            validity = np.asarray(validity)
            records: List[Optional[list]] = []
            for i in range(cap):
                if not validity[i]:
                    records.append(None)
                    continue
                raw = bytes(chars[i, :lengths[i]])
                vals: Optional[list] = []
                try:
                    root = ET.fromstring(raw.decode("utf-8", "replace"))
                except ET.ParseError:
                    root = None
                if root is None:
                    vals = [None] * len(fields)
                else:
                    for f in fields:
                        el = root.find(f.name)
                        txt = None if el is None else (el.text or "")
                        if txt is None:
                            vals.append(None)
                            continue
                        sv = txt
                        if not isinstance(f.dataType, T.StringType):
                            try:
                                if isinstance(f.dataType, T.BooleanType):
                                    sv = txt.strip().lower() == "true"
                                elif isinstance(f.dataType,
                                                (T.FloatType,
                                                 T.DoubleType)):
                                    sv = float(txt)
                                else:
                                    sv = int(txt.strip())
                            except ValueError:
                                vals = [None] * len(fields)
                                break
                        ok, sv = convert_json_field(sv, f.dataType)
                        if not ok:
                            vals = [None] * len(fields)
                            break
                        vals.append(sv)
                records.append(vals)
            outs = []
            for k, f in enumerate(fields):
                col_vals = [r[k] if r is not None else None
                            for r in records]
                fvalid = np.array([v is not None for v in col_vals],
                                  np.bool_)
                if isinstance(f.dataType, T.StringType):
                    fchars = np.zeros((cap, w), np.uint8)
                    flens = np.zeros(cap, np.int32)
                    for i, v in enumerate(col_vals):
                        if v is None:
                            continue
                        b = v.encode("utf-8")[:w]
                        fchars[i, :len(b)] = np.frombuffer(b, np.uint8)
                        flens[i] = len(b)
                    outs += [fchars, flens, fvalid]
                else:
                    data = np.zeros(cap, T.storage_dtype(f.dataType))
                    for i, v in enumerate(col_vals):
                        if v is not None:
                            data[i] = v
                    outs += [data, fvalid]
            outs.append(validity.copy())
            return tuple(outs)

        shapes = []
        for f in fields:
            if isinstance(f.dataType, T.StringType):
                shapes += [jax.ShapeDtypeStruct((cap, w), np.uint8),
                           jax.ShapeDtypeStruct((cap,), np.int32),
                           jax.ShapeDtypeStruct((cap,), np.bool_)]
            else:
                shapes += [jax.ShapeDtypeStruct(
                    (cap,), T.storage_dtype(f.dataType)),
                    jax.ShapeDtypeStruct((cap,), np.bool_)]
        shapes.append(jax.ShapeDtypeStruct((cap,), np.bool_))
        flat = call_host_kernel(fn, tuple(shapes), c.chars, c.lengths,
                                c.validity)
        kids = []
        pos = 0
        for f in fields:
            if isinstance(f.dataType, T.StringType):
                kids.append(DeviceColumn(T.STRING, flat[pos + 2],
                                         chars=flat[pos],
                                         lengths=flat[pos + 1]))
                pos += 3
            else:
                kids.append(DeviceColumn(f.dataType, flat[pos + 1],
                                         data=flat[pos]))
                pos += 2
        return DeviceColumn(self.schema, flat[pos], children=tuple(kids))


def _xml_escape(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


class StructsToXml(UnaryExpression):
    """to_xml(struct) -> one <row>...</row> document per row."""

    is_host_kernel = True

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def sql_string(self):
        return f"to_xml({self.child.sql_string()})"

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        cap = c.capacity
        st: T.StructType = self.child.dataType
        width = 16
        for f, kid in zip(st.fields, c.children):
            width += len(f.name) * 2 + 5 + (
                kid.chars.shape[1] * 5 if kid.chars is not None else 24)

        flat = [c.validity]
        layout = []
        for kid in c.children:
            flat.append(kid.validity)
            if kid.data is not None and kid.chars is None:
                flat.append(kid.data)
                layout.append(("flat", 2))
            else:
                flat.append(kid.chars)
                flat.append(kid.lengths)
                layout.append(("str", 3))

        def fn(*arrs):
            arrs = [np.asarray(a) for a in arrs]
            validity = arrs[0]
            parts = []
            pos = 1
            for kind, cnt in layout:
                parts.append((kind, arrs[pos:pos + cnt]))
                pos += cnt
            out_chars = np.zeros((cap, width), np.uint8)
            out_lens = np.zeros(cap, np.int32)
            for i in range(cap):
                if not validity[i]:
                    continue
                body = []
                for (kind, ps), f in zip(parts, st.fields):
                    if not ps[0][i]:
                        continue
                    if kind == "str":
                        v = _xml_escape(bytes(
                            ps[1][i, :ps[2][i]]).decode("utf-8", "replace"))
                    else:
                        raw = ps[1][i]
                        if isinstance(f.dataType, T.BooleanType):
                            v = "true" if raw else "false"
                        elif isinstance(f.dataType,
                                        (T.FloatType, T.DoubleType)):
                            v = repr(float(raw))
                        else:
                            v = str(int(raw))
                    body.append(f"<{f.name}>{v}</{f.name}>")
                s = "<row>" + "".join(body) + "</row>"
                b = s.encode("utf-8")[:width]
                out_chars[i, :len(b)] = np.frombuffer(b, np.uint8)
                out_lens[i] = len(b)
            return out_chars, out_lens

        och, oln = call_host_kernel(
            fn, (jax.ShapeDtypeStruct((cap, width), np.uint8),
                 jax.ShapeDtypeStruct((cap,), np.int32)), *flat)
        return DeviceColumn(T.STRING, c.validity, chars=och, lengths=oln)
