"""Math expressions (GpuSqrt, GpuFloor, GpuCeil, GpuRound, GpuExp, GpuLog...).

Reference analog: org/apache/spark/sql/rapids/mathExpressions.scala.
Spark specifics reproduced: log of non-positive -> null; round is HALF_UP
(not banker's); floor/ceil on integral return the input; pow/exp/trig follow
java.lang.Math (IEEE, matches XLA f64).
"""
from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (
    BinaryExpression,
    Expression,
    UnaryExpression,
)
from spark_rapids_tpu.expr.cast import Cast


class _UnaryMathToDouble(UnaryExpression):
    def _resolve_type(self):
        if self.child.dataType != T.DOUBLE:
            self.children = [Cast(self.child, T.DOUBLE).resolve(None)]
        self._dataType = T.DOUBLE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        data, extra_null = self._fn(c.data)
        validity = c.validity if extra_null is None else c.validity & ~extra_null
        return DeviceColumn(T.DOUBLE, validity, data=data)

    def _fn(self, x):
        raise NotImplementedError


class Sqrt(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.sqrt(jnp.where(x < 0, jnp.nan, x)), None


class Exp(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.exp(x), None


class Log(_UnaryMathToDouble):
    """Spark ln(x): null for x <= 0."""

    def _fn(self, x):
        bad = x <= 0
        return jnp.log(jnp.where(bad, 1.0, x)), bad


class Log10(_UnaryMathToDouble):
    def _fn(self, x):
        bad = x <= 0
        return jnp.log10(jnp.where(bad, 1.0, x)), bad


class Sin(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.sin(x), None


class Cos(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.cos(x), None


class Tan(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.tan(x), None


class Asin(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.arcsin(x), None


class Acos(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.arccos(x), None


class Atan(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.arctan(x), None


class Signum(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.sign(x), None


class Pow(BinaryExpression):
    def _resolve_type(self):
        for i in (0, 1):
            if self.children[i].dataType != T.DOUBLE:
                self.children[i] = Cast(self.children[i], T.DOUBLE).resolve(None)
        self._dataType = T.DOUBLE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        l, r = cols
        return DeviceColumn(T.DOUBLE, l.validity & r.validity,
                            data=jnp.power(l.data, r.data))


class Floor(UnaryExpression):
    """floor returns LONG for double input, input type for integral/decimal."""

    def _resolve_type(self):
        ct = self.child.dataType
        if ct.is_integral:
            self._dataType = ct
        elif isinstance(ct, T.DecimalType):
            self._dataType = T.DecimalType(
                min(ct.precision - ct.scale + 1, 38), 0)
        else:
            if ct != T.DOUBLE:
                self.children = [Cast(self.child, T.DOUBLE).resolve(None)]
            self._dataType = T.LONG
        self._nullable = self.child.nullable

    def _round(self, x):
        return jnp.floor(x)

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        ct = self.child.dataType
        if ct.is_integral:
            return c
        if isinstance(ct, T.DecimalType):
            den = 10 ** min(ct.scale, 18)
            q = c.data // den  # jnp floordiv floors: == floor
            if isinstance(self, Ceil):
                rem = c.data - q * den
                q = q + (rem != 0)
            return DeviceColumn(self.dataType, c.validity, data=q)
        return DeviceColumn(T.LONG, c.validity,
                            data=self._round(c.data).astype(jnp.int64))


class Ceil(Floor):
    def _round(self, x):
        return jnp.ceil(x)


class Round(Expression):
    """round(x, scale) HALF_UP (Spark/BigDecimal), not numpy banker's."""

    def __init__(self, child: Expression, scale: Expression):
        super().__init__([child, scale])

    def _resolve_type(self):
        ct = self.children[0].dataType
        if isinstance(ct, T.DecimalType):
            from spark_rapids_tpu.expr.base import Literal

            s = self.children[1]
            assert isinstance(s, Literal), "round scale must be literal"
            new_scale = min(max(int(s.value), 0), ct.scale)
            self._dataType = T.DecimalType(
                min(ct.precision - ct.scale + new_scale + 1, 38), new_scale)
        else:
            self._dataType = ct if ct.is_numeric else T.DOUBLE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c, s = cols
        ct = self.children[0].dataType
        dt = self.dataType
        if isinstance(ct, T.DecimalType):
            from spark_rapids_tpu.expr.cast import _dec_rescale

            data, validity = _dec_rescale(ctx, c.data, c.validity, ct.scale,
                                          dt, ctx.ansi, "round")
            return DeviceColumn(dt, validity, data=data)
        if ct.is_integral:
            return c  # round(int, >=0) is identity; negative scales: later
        scale_f = 10.0 ** s.data.astype(jnp.float64)
        x = c.data * scale_f
        r = jnp.where(x >= 0, jnp.floor(x + 0.5), jnp.ceil(x - 0.5))
        return DeviceColumn(dt, c.validity & s.validity, data=r / scale_f)


class Sinh(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.sinh(x), None


class Cosh(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.cosh(x), None


class Tanh(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.tanh(x), None


class Asinh(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.arcsinh(x), None


class Acosh(_UnaryMathToDouble):
    """java.lang.StrictMath semantics: x < 1 -> NaN."""

    def _fn(self, x):
        return jnp.arccosh(x), None


class Atanh(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.arctanh(x), None


class Cbrt(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.cbrt(x), None


class Log2(_UnaryMathToDouble):
    """Spark log2(x): null for x <= 0."""

    def _fn(self, x):
        bad = x <= 0
        return jnp.log2(jnp.where(bad, 1.0, x)), bad


class Log1p(_UnaryMathToDouble):
    """Spark log1p(x): null for x <= -1."""

    def _fn(self, x):
        bad = x <= -1.0
        return jnp.log1p(jnp.where(bad, 0.0, x)), bad


class Expm1(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.expm1(x), None


class Rint(_UnaryMathToDouble):
    """Math.rint: round half to EVEN (unlike Spark round's HALF_UP)."""

    def _fn(self, x):
        return jnp.round(x), None  # jnp.round is banker's rounding


class Cot(_UnaryMathToDouble):
    def _fn(self, x):
        return 1.0 / jnp.tan(x), None


class Csc(_UnaryMathToDouble):
    def _fn(self, x):
        return 1.0 / jnp.sin(x), None


class Sec(_UnaryMathToDouble):
    def _fn(self, x):
        return 1.0 / jnp.cos(x), None


class ToDegrees(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.degrees(x), None


class ToRadians(_UnaryMathToDouble):
    def _fn(self, x):
        return jnp.radians(x), None


class _BinaryMathToDouble(BinaryExpression):
    def _resolve_type(self):
        new = []
        for c in self.children:
            new.append(c if c.dataType == T.DOUBLE
                       else Cast(c, T.DOUBLE).resolve(None))
        self.children = new
        self._dataType = T.DOUBLE
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        l, r = cols
        return DeviceColumn(T.DOUBLE, l.validity & r.validity,
                            data=self._fn(l.data, r.data))


class Atan2(_BinaryMathToDouble):
    def _fn(self, a, b):
        return jnp.arctan2(a, b)


class Hypot(_BinaryMathToDouble):
    def _fn(self, a, b):
        return jnp.hypot(a, b)


class Logarithm(_BinaryMathToDouble):
    """log(base, x): null when x <= 0 or base <= 0 or base == 1."""

    def do_columnar_eval(self, ctx, cols):
        b, x = cols
        bad = (x.data <= 0) | (b.data <= 0) | (b.data == 1.0)
        out = jnp.log(jnp.where(x.data <= 0, 1.0, x.data)) / jnp.log(
            jnp.where((b.data <= 0) | (b.data == 1.0), 2.0, b.data))
        return DeviceColumn(T.DOUBLE, b.validity & x.validity & ~bad,
                            data=out)


class BRound(Round):
    """bround(x, scale) HALF_EVEN (banker's rounding)."""

    def do_columnar_eval(self, ctx, cols):
        # decimals fall back at tag time (HALF_EVEN decimal rescale TBD)
        c, s = cols
        ct = self.children[0].dataType
        dt = self.dataType
        if ct.is_integral:
            return c
        scale_f = 10.0 ** s.data.astype(jnp.float64)
        x = c.data * scale_f
        # ties to even: numpy/jnp rint IS banker's rounding
        r = jnp.round(x)
        return DeviceColumn(dt, c.validity & s.validity, data=r / scale_f)


class WidthBucket(Expression):
    """width_bucket(v, lo, hi, n) — 1-based bucket; 0 / n+1 outside."""

    def __init__(self, v, lo, hi, n):
        super().__init__([v, lo, hi, n])

    def sql_string(self):
        return ("width_bucket("
                + ", ".join(c.sql_string() for c in self.children) + ")")

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        v, lo, hi, n = (c.data.astype(jnp.float64) for c in cols)
        nb = cols[3].data.astype(jnp.int64)
        ok = ((nb > 0) & jnp.isfinite(v) & jnp.isfinite(lo)
              & jnp.isfinite(hi) & (lo != hi))
        asc = lo < hi
        width = (hi - lo) / nb.astype(jnp.float64)
        b_asc = jnp.floor((v - lo) / width).astype(jnp.int64) + 1
        b_desc = jnp.floor((lo - v) / -width).astype(jnp.int64) + 1
        b = jnp.where(asc, b_asc, b_desc)
        below = jnp.where(asc, v < lo, v > lo)
        above = jnp.where(asc, v >= hi, v <= hi)
        res = jnp.where(below, 0, jnp.where(above, nb + 1, b))
        res = jnp.clip(res, 0, nb + 1)
        validity = ok
        for c in cols:
            validity = validity & c.validity
        return DeviceColumn(T.LONG, validity, data=res)


class Factorial(UnaryExpression):
    """factorial(n) for n in [0, 20]; outside -> null (Spark)."""

    _TABLE = [1]

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        import math as _math

        table = jnp.asarray([_math.factorial(i) for i in range(21)] + [0],
                            jnp.int64)
        v = c.data.astype(jnp.int64)
        ok = (v >= 0) & (v <= 20)
        res = table[jnp.clip(v, 0, 21)]
        return DeviceColumn(T.LONG, c.validity & ok, data=res)


class BitwiseCount(UnaryExpression):
    """bit_count(x) — set bits (bool counts itself)."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        ct = self.child.dataType
        if isinstance(ct, T.BooleanType):
            res = c.data.astype(jnp.int32)
        else:
            # Spark evaluates Long.bitCount on the SIGN-EXTENDED value
            # (Java widening), so bit_count(tinyint -1) is 64, not 8
            v = c.data.astype(jnp.int64)
            x = v.view(jnp.uint64)
            res = jnp.zeros(c.capacity, jnp.int32)
            for shift in range(0, 64, 8):
                byte = ((x >> jnp.uint64(shift))
                        & jnp.uint64(0xFF)).astype(jnp.int32)
                # 8-bit popcount via lookup-free SWAR
                b = byte - ((byte >> 1) & 0x55)
                b = (b & 0x33) + ((b >> 2) & 0x33)
                b = (b + (b >> 4)) & 0x0F
                res = res + b
        return DeviceColumn(T.INT, c.validity, data=res)
