"""Collection expressions over padded list columns.

Reference analog: org/apache/spark/sql/rapids/collectionOperations.scala
(GpuSize, GpuElementAt, GpuGetArrayItem, GpuArrayContains, GpuCreateArray,
SURVEY.md §2.5 Collections).  Device layout: a list column is
``data (cap, ewidth)`` + ``elem_valid (cap, ewidth)`` + ``lengths (cap,)``
(the padded counterpart of cuDF's offsets+child, chosen for XLA static
shapes — columnar/column.py).
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (
    BinaryExpression,
    EvalContext,
    Expression,
    UnaryExpression,
)


def _take_element(arr: DeviceColumn, safe: jax.Array, validity: jax.Array,
                  out_dt: T.DataType) -> DeviceColumn:
    """Per-row element pick (string-array aware)."""
    if arr.is_string_array:
        cap = arr.capacity
        rows = jnp.arange(cap)
        chars = arr.chars[rows, safe]
        lens = arr.data[rows, safe].astype(jnp.int32)
        return DeviceColumn(out_dt, validity, chars=chars, lengths=lens)
    data = jnp.take_along_axis(arr.data, safe[:, None], axis=1)[:, 0]
    return DeviceColumn(out_dt, validity, data=data)


class Size(UnaryExpression):
    """size(array): element count; null input -> -1 (legacy) like Spark's
    default spark.sql.legacy.sizeOfNull=true."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = False

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        data = jnp.where(c.validity, c.lengths, -1)
        return DeviceColumn(T.INT, jnp.ones_like(c.validity), data=data)


class GetArrayItem(BinaryExpression):
    """array[idx]: 0-based; out of bounds -> null (legacy mode)."""

    def _resolve_type(self):
        self._dataType = self.left.dataType.elementType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, idx = cols
        i = idx.data.astype(jnp.int32)
        inb = (i >= 0) & (i < arr.lengths)
        safe = jnp.clip(i, 0, max(arr.ewidth - 1, 0))
        ev = jnp.take_along_axis(arr.elem_valid, safe[:, None], axis=1)[:, 0]
        validity = arr.validity & idx.validity & inb & ev
        return _take_element(arr, safe, validity, self.dataType)


class ElementAt(BinaryExpression):
    """element_at(array, i): 1-based, negative counts from the end;
    out of bounds -> null (legacy mode).  element_at(map, key) is a map
    lookup (delegates to GetMapValue)."""

    def _resolve_type(self):
        lt = self.left.dataType
        if isinstance(lt, T.MapType):
            self._dataType = lt.valueType
        else:
            self._dataType = lt.elementType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        if isinstance(self.left.dataType, T.MapType):
            gm = GetMapValue(self.left, self.right)
            gm._dataType = self._dataType
            return gm.do_columnar_eval(ctx, cols)
        arr, idx = cols
        i = idx.data.astype(jnp.int32)
        n = arr.lengths
        zero = i == 0          # element_at(_, 0) is an error in Spark; null here
        pos = jnp.where(i > 0, i - 1, n + i)
        inb = (pos >= 0) & (pos < n) & ~zero
        safe = jnp.clip(pos, 0, max(arr.ewidth - 1, 0))
        ev = jnp.take_along_axis(arr.elem_valid, safe[:, None], axis=1)[:, 0]
        validity = arr.validity & idx.validity & inb & ev
        return _take_element(arr, safe, validity, self.dataType)


class ArrayContains(BinaryExpression):
    """array_contains(arr, v): Spark null semantics — true if found, null
    if not found but the array has null elements, else false."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, v = cols
        w = arr.ewidth
        in_len = jnp.arange(w)[None, :] < arr.lengths[:, None]
        eq = (arr.data == v.data[:, None]) & arr.elem_valid & in_len
        found = jnp.any(eq, axis=1)
        has_null_elem = jnp.any(~arr.elem_valid & in_len, axis=1)
        validity = arr.validity & v.validity & (found | ~has_null_elem)
        return DeviceColumn(T.BOOLEAN, validity, data=found)


class CreateArray(Expression):
    """array(e1, e2, ...) over flat element expressions."""

    def __init__(self, children: List[Expression]):
        super().__init__(list(children))

    def sql_string(self):
        return "array(" + ", ".join(c.sql_string() for c in self.children) + ")"

    def _resolve_type(self):
        et = self.children[0].dataType
        self._dataType = T.ArrayType(et)
        self._nullable = False

    def do_columnar_eval(self, ctx: EvalContext, cols):
        k = len(cols)
        data = jnp.stack([c.data for c in cols], axis=1)
        ev = jnp.stack([c.validity for c in cols], axis=1)
        cap = cols[0].capacity
        lengths = jnp.full(cap, k, jnp.int32)
        return DeviceColumn(self.dataType, jnp.ones(cap, jnp.bool_),
                            data=data, lengths=lengths, elem_valid=ev)


class ArrayMin(UnaryExpression):
    """array_min: nulls skipped; empty/all-null -> null."""

    _is_min = True

    def _resolve_type(self):
        self._dataType = self.child.dataType.elementType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        w = c.ewidth
        in_len = jnp.arange(w)[None, :] < c.lengths[:, None]
        ok = c.elem_valid & in_len
        dt = self.dataType
        is_f = isinstance(dt, (T.FloatType, T.DoubleType))
        if is_f:
            ident = jnp.asarray(jnp.inf if self._is_min else -jnp.inf,
                                c.data.dtype)
        else:
            info = jnp.iinfo(c.data.dtype)
            ident = jnp.asarray(info.max if self._is_min else info.min,
                                c.data.dtype)
        v = jnp.where(ok, c.data, ident)
        red = jnp.min(v, axis=1) if self._is_min else jnp.max(v, axis=1)
        has = jnp.any(ok, axis=1)
        return DeviceColumn(dt, c.validity & has, data=red)


class ArrayMax(ArrayMin):
    _is_min = False


# ---------------------------------------------------------------------------
# Shared element helpers
# ---------------------------------------------------------------------------

def _in_len(c: DeviceColumn) -> jax.Array:
    return jnp.arange(c.ewidth)[None, :] < c.lengths[:, None]


def _elem_eq(x: jax.Array, y: jax.Array, dtype: T.DataType) -> jax.Array:
    """SQL set-op equality: NaN == NaN for float elements."""
    if isinstance(dtype, (T.FloatType, T.DoubleType)):
        return (x == y) | (jnp.isnan(x) & jnp.isnan(y))
    return x == y


def _dup_map_keys(kdata, live, kt) -> jax.Array:
    """Row mask: any duplicate among the live key elements (pairwise
    equality over the lower triangle) — Spark's EXCEPTION dedup policy."""
    w = max(int(kdata.shape[1]), 1)
    return jnp.any(
        _elem_eq(kdata[:, :, None], kdata[:, None, :], kt)
        & live[:, :, None] & live[:, None, :]
        & jnp.tril(jnp.ones((w, w), jnp.bool_), k=-1)[None],
        axis=(1, 2))


def _compact_elems(data, ev, keep):
    """Per-row stable compaction of kept elements to the front."""
    order = jnp.argsort(~keep, axis=1, stable=True)
    data2 = jnp.take_along_axis(data, order, axis=1)
    ev2 = jnp.take_along_axis(ev, order, axis=1)
    keep2 = jnp.take_along_axis(keep, order, axis=1)
    new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    return jnp.where(keep2, data2, 0), ev2 & keep2, new_len


def _first_occurrence(c: DeviceColumn, et: T.DataType) -> jax.Array:
    """(cap, w) mask: True where this element is its value's first
    appearance within the row (nulls count as one value)."""
    inl = _in_len(c)
    v = c.elem_valid & inl
    nul = ~c.elem_valid & inl
    eq = _elem_eq(c.data[:, :, None], c.data[:, None, :], et)
    same = ((v[:, :, None] & v[:, None, :] & eq)
            | (nul[:, :, None] & nul[:, None, :]))
    w = c.ewidth
    before = jnp.tril(jnp.ones((w, w), jnp.bool_), k=-1)[None, :, :]
    dup = jnp.any(same & before.transpose(0, 2, 1), axis=1)
    return inl & ~dup


def _membership(a: DeviceColumn, b: DeviceColumn, et: T.DataType):
    """(cap, wa) mask: a-element (null-aware) appears among b's elements."""
    inl_b = _in_len(b)
    vb = b.elem_valid & inl_b
    nb = ~b.elem_valid & inl_b
    va = a.elem_valid & _in_len(a)
    na = ~a.elem_valid & _in_len(a)
    eq = _elem_eq(a.data[:, :, None], b.data[:, None, :], et)
    same = ((va[:, :, None] & vb[:, None, :] & eq)
            | (na[:, :, None] & nb[:, None, :]))
    return jnp.any(same, axis=2)


class ArrayPosition(BinaryExpression):
    """array_position(arr, v): 1-based first index, 0 if absent (LONG)."""

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, v = cols
        et = arr.dtype.elementType
        inl = _in_len(arr)
        eq = (_elem_eq(arr.data, v.data[:, None], et)
              & arr.elem_valid & inl)
        found = jnp.any(eq, axis=1)
        pos = jnp.argmax(eq, axis=1) + 1
        data = jnp.where(found, pos, 0).astype(jnp.int64)
        return DeviceColumn(T.LONG, arr.validity & v.validity, data=data)


class ArrayRemove(BinaryExpression):
    """array_remove(arr, v): drop elements equal to v (nulls kept)."""

    def _resolve_type(self):
        self._dataType = self.left.dataType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, v = cols
        et = arr.dtype.elementType
        inl = _in_len(arr)
        drop = (_elem_eq(arr.data, v.data[:, None], et)
                & arr.elem_valid & v.validity[:, None])
        keep = inl & ~drop
        data, ev, lengths = _compact_elems(arr.data, arr.elem_valid, keep)
        return DeviceColumn(self.dataType, arr.validity & v.validity,
                            data=data, lengths=lengths, elem_valid=ev)


class ArrayDistinct(UnaryExpression):
    """array_distinct: first occurrence of each value (one null kept)."""

    def _resolve_type(self):
        self._dataType = self.child.dataType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr = cols[0]
        et = arr.dtype.elementType
        keep = _first_occurrence(arr, et)
        data, ev, lengths = _compact_elems(arr.data, arr.elem_valid, keep)
        return DeviceColumn(self.dataType, arr.validity, data=data,
                            lengths=lengths, elem_valid=ev)


class ArraysOverlap(BinaryExpression):
    """arrays_overlap: true on a shared non-null element; null when no
    overlap but either side contains null (Spark three-valued result)."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        a, b = cols
        et = a.dtype.elementType
        inl_a, inl_b = _in_len(a), _in_len(b)
        va = a.elem_valid & inl_a
        vb = b.elem_valid & inl_b
        eq = (_elem_eq(a.data[:, :, None], b.data[:, None, :], et)
              & va[:, :, None] & vb[:, None, :])
        overlap = jnp.any(eq, axis=(1, 2))
        has_null = (jnp.any(~a.elem_valid & inl_a, axis=1)
                    | jnp.any(~b.elem_valid & inl_b, axis=1))
        nonempty = (a.lengths > 0) & (b.lengths > 0)
        unknown = ~overlap & has_null & nonempty
        validity = a.validity & b.validity & ~unknown
        return DeviceColumn(T.BOOLEAN, validity, data=overlap)


class ArrayUnion(BinaryExpression):
    """array_union: distinct elements of a then b, first-appearance order."""

    def _resolve_type(self):
        self._dataType = self.left.dataType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        a, b = cols
        et = a.dtype.elementType
        # concatenate rows then distinct
        data = jnp.concatenate([a.data, b.data], axis=1)
        ev_raw = jnp.concatenate([a.elem_valid, b.elem_valid], axis=1)
        lengths = a.lengths + b.lengths
        # rebuild a contiguous layout: b's elements start at a.lengths
        wa, wb = a.ewidth, b.ewidth
        w = wa + wb
        pos = jnp.arange(w)[None, :]
        src_b = pos >= wa
        tgt = jnp.where(src_b, a.lengths[:, None] + (pos - wa), pos)
        in_src = jnp.where(src_b, pos - wa < b.lengths[:, None],
                           pos < a.lengths[:, None])
        tgt = jnp.where(in_src, tgt, w)
        cat_data = jnp.zeros_like(data).at[
            jnp.arange(data.shape[0])[:, None], tgt].set(data, mode="drop")
        cat_ev = jnp.zeros_like(ev_raw).at[
            jnp.arange(data.shape[0])[:, None], tgt].set(
            ev_raw, mode="drop")
        cat = DeviceColumn(self.dataType, a.validity, data=cat_data,
                           lengths=lengths.astype(jnp.int32),
                           elem_valid=cat_ev)
        keep = _first_occurrence(cat, et)
        data2, ev2, len2 = _compact_elems(cat_data, cat_ev, keep)
        return DeviceColumn(self.dataType, a.validity & b.validity,
                            data=data2, lengths=len2, elem_valid=ev2)


class ArrayIntersect(BinaryExpression):
    """array_intersect: distinct a-elements that also appear in b."""

    def _resolve_type(self):
        self._dataType = self.left.dataType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        a, b = cols
        et = a.dtype.elementType
        keep = _first_occurrence(a, et) & _membership(a, b, et)
        data, ev, lengths = _compact_elems(a.data, a.elem_valid, keep)
        return DeviceColumn(self.dataType, a.validity & b.validity,
                            data=data, lengths=lengths, elem_valid=ev)


class ArrayExcept(BinaryExpression):
    """array_except: distinct a-elements not appearing in b."""

    def _resolve_type(self):
        self._dataType = self.left.dataType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        a, b = cols
        et = a.dtype.elementType
        keep = _first_occurrence(a, et) & ~_membership(a, b, et)
        data, ev, lengths = _compact_elems(a.data, a.elem_valid, keep)
        return DeviceColumn(self.dataType, a.validity & b.validity,
                            data=data, lengths=lengths, elem_valid=ev)


class Slice(Expression):
    """slice(arr, start, length): 1-based; negative start from the end;
    start=0 or length<0 raises (surfaced via the batch error flags)."""

    def __init__(self, arr: Expression, start: Expression,
                 length: Expression):
        super().__init__([arr, start, length])

    def _resolve_type(self):
        self._dataType = self.children[0].dataType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, st, ln = cols
        n = arr.lengths
        s = st.data.astype(jnp.int32)
        k = ln.data.astype(jnp.int32)
        ok_in = arr.validity & st.validity & ln.validity
        ctx.add_error(ok_in & (s == 0),
                      "Unexpected value for start in function slice: SQL "
                      "array indices start at 1.")
        ctx.add_error(ok_in & (k < 0),
                      "Unexpected value for length in function slice: "
                      "length must be greater than or equal to 0.")
        start0 = jnp.where(s > 0, s - 1, n + s)
        w = arr.ewidth
        pos = jnp.arange(w)[None, :]
        src = start0[:, None] + pos
        take = (pos < k[:, None]) & (src >= 0) & (src < n[:, None])
        safe = jnp.clip(src, 0, max(w - 1, 0))
        data = jnp.where(take, jnp.take_along_axis(arr.data, safe, axis=1), 0)
        ev = jnp.where(take,
                       jnp.take_along_axis(arr.elem_valid, safe, axis=1),
                       False)
        out_len = jnp.sum(take, axis=1).astype(jnp.int32)
        # negative start beyond the head yields an empty array in Spark
        empty = start0 < 0
        out_len = jnp.where(empty, 0, out_len)
        return DeviceColumn(self.dataType, ok_in, data=data,
                            lengths=out_len, elem_valid=ev & ~empty[:, None])


class SortArray(BinaryExpression):
    """sort_array(arr, asc): nulls first when ascending, last descending."""

    def _resolve_type(self):
        self._dataType = self.left.dataType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, asc_col = cols
        from spark_rapids_tpu.expr.base import Literal as _Lit

        asc = True
        if isinstance(self.right, _Lit):
            asc = bool(self.right.value)
        et = arr.dtype.elementType
        inl = _in_len(arr)
        null_in = ~arr.elem_valid & inl
        key = arr.data
        if isinstance(et, (T.FloatType, T.DoubleType)):
            from spark_rapids_tpu.ops.sortkeys import _float_total_order

            # f32 -> f64 is exact and order-preserving, so one bit trick
            # covers both float widths; canonicalize NaN bit patterns
            # (negative-signed NaNs would otherwise sort below -inf) like
            # sortkeys._column_key_words does
            f64 = key.astype(jnp.float64)
            bits = jax.lax.bitcast_convert_type(f64, jnp.int64)
            bits = jnp.where(jnp.isnan(f64),
                             jnp.int64(0x7FF8000000000000), bits)
            key = _float_total_order(bits)
        else:
            key = key.astype(jnp.int64)
        if not asc:
            key = ~key  # monotone reversal without overflow
        # tiers: nulls first (asc) / last (desc); padding always last
        if asc:
            tier = jnp.where(~inl, 2, jnp.where(null_in, 0, 1))
        else:
            tier = jnp.where(~inl, 2, jnp.where(null_in, 1, 0))
        tier32 = tier.astype(jnp.int32)
        s_tier, s_key, s_data, s_ev = jax.lax.sort(
            (tier32, key, arr.data, arr.elem_valid), dimension=1,
            num_keys=2, is_stable=True)
        return DeviceColumn(self.dataType, arr.validity, data=s_data,
                            lengths=arr.lengths, elem_valid=s_ev)


class ArrayRepeat(BinaryExpression):
    """array_repeat(v, n) with a static element-capacity cap."""

    MAX_ELEMENTS = 1024

    def _resolve_type(self):
        self._dataType = T.ArrayType(self.left.dataType)
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        v, n = cols
        cap = v.capacity
        count = jnp.maximum(n.data.astype(jnp.int32), 0)
        ctx.add_error(n.validity & (count > self.MAX_ELEMENTS),
                      f"array_repeat count above the TPU element cap "
                      f"({self.MAX_ELEMENTS})")
        from spark_rapids_tpu.expr.base import Literal as _Lit

        if isinstance(self.right, _Lit) and self.right.value is not None:
            w = max(min(int(self.right.value), self.MAX_ELEMENTS), 1)
        else:
            w = self.MAX_ELEMENTS
        pos = jnp.arange(w)[None, :]
        take = pos < count[:, None]
        data = jnp.where(take, v.data[:, None], 0)
        ev = take & v.validity[:, None]
        return DeviceColumn(self.dataType, n.validity,
                            data=data, lengths=count, elem_valid=ev)


class Sequence(Expression):
    """sequence(start, stop[, step]) with a static element cap (the
    reference errors above MAX_ROUNDED_ARRAY_LENGTH; we error above the
    TPU cap via the batch error flags)."""

    MAX_ELEMENTS = 1024

    def __init__(self, start: Expression, stop: Expression,
                 step: Expression = None):
        kids = [start, stop] + ([step] if step is not None else [])
        super().__init__(kids)

    def _resolve_type(self):
        self._dataType = T.ArrayType(self.children[0].dataType)
        self._nullable = True

    def _static_width(self) -> int:
        """Literal bounds shrink the padded element width (the 1024-wide
        default would cost capacity*8KB per batch otherwise)."""
        from spark_rapids_tpu.expr.base import Literal as _Lit

        kids = self.children
        if all(isinstance(k, _Lit) and k.value is not None for k in kids):
            start, stop = int(kids[0].value), int(kids[1].value)
            step = int(kids[2].value) if len(kids) > 2 else (
                1 if stop >= start else -1)
            if step != 0 and (stop - start) * step >= 0:
                n = abs(stop - start) // abs(step) + 1
                return max(min(n, self.MAX_ELEMENTS), 1)
        return self.MAX_ELEMENTS

    def do_columnar_eval(self, ctx: EvalContext, cols):
        start = cols[0].data.astype(jnp.int64)
        stop = cols[1].data.astype(jnp.int64)
        if len(cols) > 2:
            step = cols[2].data.astype(jnp.int64)
            step_v = cols[2].validity
        else:
            step = jnp.where(stop >= start, 1, -1).astype(jnp.int64)
            step_v = jnp.ones_like(cols[0].validity)
        validity = cols[0].validity & cols[1].validity & step_v
        bad_step = validity & (
            (step == 0) | ((stop > start) & (step < 0))
            | ((stop < start) & (step > 0)))
        ctx.add_error(bad_step,
                      "Illegal sequence boundaries: step must move start "
                      "towards stop")
        safe_step = jnp.where(step == 0, 1, step)
        count = jnp.where(bad_step, 0,
                          (stop - start) // safe_step + 1)
        count = jnp.maximum(count, 0)
        ctx.add_error(validity & (count > self.MAX_ELEMENTS),
                      f"sequence length above the TPU element cap "
                      f"({self.MAX_ELEMENTS})")
        count = jnp.minimum(count, self.MAX_ELEMENTS).astype(jnp.int32)
        w = self._static_width()
        pos = jnp.arange(w, dtype=jnp.int64)[None, :]
        vals = start[:, None] + pos * safe_step[:, None]
        take = pos < count[:, None]
        et = self.children[0].dataType
        data = jnp.where(take, vals, 0).astype(T.storage_dtype(et))
        return DeviceColumn(self.dataType, validity, data=data,
                            lengths=count, elem_valid=take)


# ---------------------------------------------------------------------------
# Maps — device layout: children = (keys ArrayType column, values ArrayType
# column) sharing lengths, the padded counterpart of cuDF MAP (list of
# key/value structs).  Reference: GpuCreateMap / GpuMapKeys / GpuMapValues /
# GpuGetMapValue (collectionOperations.scala).
# ---------------------------------------------------------------------------

class CreateMap(Expression):
    """map(k1, v1, k2, v2, ...); duplicate keys raise (Spark's default
    EXCEPTION dedup policy), surfaced via the batch error flags."""

    def __init__(self, children: List[Expression]):
        assert len(children) % 2 == 0, "map() needs key/value pairs"
        super().__init__(list(children))

    def sql_string(self):
        return "map(" + ", ".join(c.sql_string() for c in self.children) + ")"

    def _resolve_type(self):
        kt = self.children[0].dataType
        vt = self.children[1].dataType
        self._dataType = T.MapType(kt, vt)
        self._nullable = False

    def do_columnar_eval(self, ctx: EvalContext, cols):
        ks = cols[0::2]
        vs = cols[1::2]
        cap = ks[0].capacity
        kdata = jnp.stack([c.data for c in ks], axis=1)
        kvalid = jnp.stack([c.validity for c in ks], axis=1)
        vdata = jnp.stack([c.data for c in vs], axis=1)
        vvalid = jnp.stack([c.validity for c in vs], axis=1)
        # Spark: null keys are invalid; duplicates raise
        ctx.add_error(jnp.any(~kvalid, axis=1),
                      "Cannot use null as map key")
        kt = self.children[0].dataType
        ctx.add_error(
            _dup_map_keys(kdata, jnp.ones_like(kvalid), kt),
            "Duplicate map key was found")
        n = len(ks)
        lengths = jnp.full(cap, n, jnp.int32)
        keys_col = DeviceColumn(T.ArrayType(kt, containsNull=False),
                                jnp.ones(cap, jnp.bool_), data=kdata,
                                lengths=lengths, elem_valid=kvalid)
        vals_col = DeviceColumn(T.ArrayType(self.children[1].dataType),
                                jnp.ones(cap, jnp.bool_), data=vdata,
                                lengths=lengths, elem_valid=vvalid)
        return DeviceColumn(self.dataType, jnp.ones(cap, jnp.bool_),
                            children=(keys_col, vals_col))


class MapKeys(UnaryExpression):
    def _resolve_type(self):
        mt = self.child.dataType
        self._dataType = T.ArrayType(mt.keyType, containsNull=False)
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx: EvalContext, cols):
        m = cols[0]
        k = m.children[0]
        return DeviceColumn(self.dataType, k.validity & m.validity,
                            data=k.data, lengths=k.lengths,
                            elem_valid=k.elem_valid)


class MapValues(UnaryExpression):
    def _resolve_type(self):
        mt = self.child.dataType
        self._dataType = T.ArrayType(mt.valueType)
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx: EvalContext, cols):
        m = cols[0]
        v = m.children[1]
        return DeviceColumn(self.dataType, v.validity & m.validity,
                            data=v.data, lengths=v.lengths,
                            elem_valid=v.elem_valid)


class GetMapValue(BinaryExpression):
    """map[key] — first matching key's value, null when absent."""

    def _resolve_type(self):
        self._dataType = self.left.dataType.valueType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        m, key = cols
        kcol, vcol = m.children
        kt = self.left.dataType.keyType
        inl = _in_len(kcol)
        eq = (_elem_eq(kcol.data, key.data[:, None], kt)
              & kcol.elem_valid & inl)
        found = jnp.any(eq, axis=1)
        pos = jnp.argmax(eq, axis=1)
        safe = jnp.clip(pos, 0, max(kcol.ewidth - 1, 0))
        data = jnp.take_along_axis(vcol.data, safe[:, None], axis=1)[:, 0]
        ev = jnp.take_along_axis(vcol.elem_valid, safe[:, None],
                                 axis=1)[:, 0]
        validity = m.validity & key.validity & found & ev
        return DeviceColumn(self.dataType, validity, data=data)


class MapFromArrays(BinaryExpression):
    """map_from_arrays(keys, values): lengths must match; null/duplicate
    keys raise (Spark EXCEPTION dedup policy) via the error flags."""

    def _resolve_type(self):
        kt = self.left.dataType.elementType
        vt = self.right.dataType.elementType
        self._dataType = T.MapType(kt, vt)
        self._nullable = self.left.nullable or self.right.nullable

    def do_columnar_eval(self, ctx: EvalContext, cols):
        ka, va = cols
        cap = ka.capacity
        valid = ka.validity & va.validity
        ctx.add_error(valid & (ka.lengths != va.lengths),
                      "key and value arrays must have the same length")
        kt = self.left.dataType.elementType
        inl = _in_len(ka)
        live = ka.elem_valid & inl
        ctx.add_error(valid & jnp.any(inl & ~ka.elem_valid, axis=1),
                      "Cannot use null as map key")
        ctx.add_error(valid & _dup_map_keys(ka.data, live, kt),
                      "Duplicate map key was found")
        keys = DeviceColumn(T.ArrayType(kt, containsNull=False),
                            valid, data=ka.data, lengths=ka.lengths,
                            elem_valid=live)
        vals = DeviceColumn(T.ArrayType(self.right.dataType.elementType),
                            valid, data=va.data, lengths=ka.lengths,
                            elem_valid=va.elem_valid & inl)
        return DeviceColumn(self.dataType, valid, children=(keys, vals))


class MapConcat(Expression):
    """map_concat(m1, m2, ...): entry concatenation; duplicate keys across
    inputs raise (Spark EXCEPTION dedup policy)."""

    def __init__(self, children: List[Expression]):
        super().__init__(list(children))

    def sql_string(self):
        return ("map_concat("
                + ", ".join(c.sql_string() for c in self.children) + ")")

    def _resolve_type(self):
        self._dataType = self.children[0].dataType
        self._nullable = any(c.nullable for c in self.children)

    def do_columnar_eval(self, ctx: EvalContext, cols):
        kt = self.dataType.keyType
        valid = cols[0].validity
        for c in cols[1:]:
            valid = valid & c.validity
        kparts, vparts, kevs, vevs = [], [], [], []
        for m in cols:
            kcol, vcol = m.children
            inl = _in_len(kcol)
            kparts.append(kcol.data)
            vparts.append(vcol.data)
            kevs.append(kcol.elem_valid & inl)
            vevs.append(vcol.elem_valid & inl)
        kd = jnp.concatenate(kparts, axis=1)
        vd = jnp.concatenate(vparts, axis=1)
        kev = jnp.concatenate(kevs, axis=1)
        vev = jnp.concatenate(vevs, axis=1)
        # compact the live entries left so lengths/data line up
        kd2, kev2, lengths = _compact_elems(kd, kev, kev)
        vd2, vev2, _ = _compact_elems(vd, vev & kev, kev)
        w = max(kd2.shape[1], 1)
        pos = jnp.arange(w, dtype=jnp.int32)[None, :]
        live = pos < lengths[:, None]
        ctx.add_error(valid & _dup_map_keys(kd2, live, kt),
                      "Duplicate map key was found")
        keys = DeviceColumn(T.ArrayType(kt, containsNull=False), valid,
                            data=kd2, lengths=lengths, elem_valid=kev2)
        vals = DeviceColumn(T.ArrayType(self.dataType.valueType), valid,
                            data=vd2, lengths=lengths, elem_valid=vev2)
        return DeviceColumn(self.dataType, valid, children=(keys, vals))


class MapContainsKey(BinaryExpression):
    """map_contains_key(m, key)."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        m, key = cols
        kcol, _ = m.children
        kt = self.left.dataType.keyType
        inl = _in_len(kcol)
        eq = (_elem_eq(kcol.data, key.data[:, None], kt)
              & kcol.elem_valid & inl)
        return DeviceColumn(T.BOOLEAN, m.validity & key.validity,
                            data=jnp.any(eq, axis=1))


class ArrayCompact(UnaryExpression):
    """array_compact(arr): drops null elements."""

    def _resolve_type(self):
        et = self.child.dataType.elementType
        self._dataType = T.ArrayType(et, containsNull=False)
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr = cols[0]
        keep = arr.elem_valid & _in_len(arr)
        data, ev, lengths = _compact_elems(arr.data, arr.elem_valid, keep)
        return DeviceColumn(self.dataType, arr.validity, data=data,
                            lengths=lengths, elem_valid=ev)


class _ArrayAppendBase(BinaryExpression):
    prepend = False

    def _resolve_type(self):
        self._dataType = T.ArrayType(self.left.dataType.elementType)
        self._nullable = self.left.nullable

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, elem = cols
        cap = arr.capacity
        w = arr.ewidth + 1
        if arr.ewidth == 0:
            sdt = T.storage_dtype(self.dataType.elementType)
            data0 = jnp.zeros((cap, 0), sdt)
            ev0 = jnp.zeros((cap, 0), jnp.bool_)
        else:
            data0, ev0 = arr.data, arr.elem_valid & _in_len(arr)
        pos = jnp.arange(w, dtype=jnp.int32)[None, :]
        if self.prepend:
            data = jnp.concatenate([elem.data[:, None], data0], axis=1)
            ev = jnp.concatenate([elem.validity[:, None], ev0], axis=1)
        else:
            data = jnp.pad(data0, ((0, 0), (0, 1)))
            ev = jnp.pad(ev0, ((0, 0), (0, 1)))
            at = pos == arr.lengths[:, None]
            data = jnp.where(at, elem.data[:, None], data)
            ev = jnp.where(at, elem.validity[:, None], ev)
        return DeviceColumn(self.dataType, arr.validity, data=data,
                            lengths=arr.lengths + 1, elem_valid=ev)


class ArrayAppend(_ArrayAppendBase):
    """array_append(arr, elem) — null elements append as null entries."""


class ArrayPrepend(_ArrayAppendBase):
    """array_prepend(arr, elem)."""

    prepend = True


class Get(BinaryExpression):
    """get(arr, idx) — 0-based, NULL (never an error) out of range
    (Spark 3.4)."""

    def _resolve_type(self):
        self._dataType = self.left.dataType.elementType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, i = cols
        idx = i.data.astype(jnp.int32)
        inb = (idx >= 0) & (idx < arr.lengths)
        safe = jnp.clip(idx, 0, max(arr.ewidth - 1, 0))
        validity = arr.validity & i.validity & inb
        ev = jnp.take_along_axis(arr.elem_valid, safe[:, None],
                                 axis=1)[:, 0] if arr.ewidth else \
            jnp.zeros(arr.capacity, jnp.bool_)
        return _take_element(arr, safe, validity & ev, self.dataType)


class ArraySize(Size):
    """array_size(arr) — Size with legacySizeOfNull=false: NULL input is
    NULL, not -1."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        r = super().do_columnar_eval(ctx, cols)
        return DeviceColumn(T.INT, cols[0].validity, data=r.data)


class ArrayInsert(Expression):
    """array_insert(arr, pos, item) — Spark 3.5 default semantics
    (legacy negativeIndexInArrayInsert=false: -1 appends).  ``pos`` must
    be a foldable non-zero literal (the output width bucket is a static
    shape; the overrides rule tags non-literal positions back to CPU).

    Reference analog: GpuArrayInsert (SURVEY.md §2.5 Collections)."""

    def __init__(self, children: List[Expression]):
        super().__init__(list(children))

    def sql_string(self):
        a, p, v = self.children
        return (f"array_insert({a.sql_string()}, {p.sql_string()}, "
                f"{v.sql_string()})")

    @property
    def pos_literal(self):
        from spark_rapids_tpu.expr.base import Literal

        p = self.children[1]
        return p.value if isinstance(p, Literal) else None

    def _resolve_type(self):
        et = self.children[0].dataType.elementType
        self._dataType = T.ArrayType(et, containsNull=True)
        self._nullable = self.children[0].nullable

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, _posc, val = cols
        pos = int(self.pos_literal)
        cap = arr.capacity
        ew = arr.ewidth
        wout = max(ew + 1, abs(pos))
        lens = arr.lengths.astype(jnp.int32)
        j = jnp.arange(wout, dtype=jnp.int32)[None, :]     # (1, wout)
        if pos > 0:
            idx0 = jnp.full((cap, 1), pos - 1, jnp.int32)
        else:
            # Spark 3.5 default (legacy flag off): -1 appends, so the
            # 0-based insert position is len + pos + 1
            idx0 = (lens[:, None] + pos + 1).astype(jnp.int32)
        neg = idx0 < 0
        # case A (idx0 >= 0): insert at idx0, tail-null pad when past len
        # case B (idx0 < 0): [item, nulls x (-idx0-1), arr...]
        pad = jnp.where(neg, -idx0, 0)
        is_item = jnp.where(neg, j == 0, j == idx0)
        srcA = jnp.where(j < idx0, j, j - 1)
        srcB = j - pad - 1
        src = jnp.where(neg, srcB, srcA)
        src_ok = (~is_item & (src >= 0) & (src < lens[:, None]))
        out_len = jnp.where(
            neg[:, 0], -pos * jnp.ones(cap, jnp.int32),
            jnp.maximum(lens + 1, idx0[:, 0] + 1))
        safe = jnp.clip(src, 0, max(ew - 1, 0))
        item_valid = val.validity[:, None]
        in_out = j < out_len[:, None]
        if arr.is_string_array:
            rows = jnp.arange(cap)[:, None]
            chars = jnp.where(
                is_item[:, :, None],
                _pad_chars_to(val.chars, arr.chars.shape[-1])[:, None, :],
                arr.chars[rows, safe])
            elens = jnp.where(is_item, val.lengths[:, None].astype(
                arr.data.dtype), arr.data[rows, safe])
            ev = jnp.where(is_item, item_valid,
                           src_ok & arr.elem_valid[rows, safe]) & in_out
            return DeviceColumn(self.dataType, arr.validity, chars=chars,
                                data=jnp.where(ev, elens, 0),
                                lengths=out_len, elem_valid=ev)
        data = jnp.where(is_item, val.data[:, None],
                         jnp.take_along_axis(
                             arr.data, safe, axis=1))
        ev = jnp.where(is_item, item_valid,
                       src_ok & jnp.take_along_axis(
                           arr.elem_valid, safe, axis=1)) & in_out
        return DeviceColumn(self.dataType, arr.validity,
                            data=jnp.where(ev, data,
                                           jnp.zeros_like(data)),
                            lengths=out_len, elem_valid=ev)


def _pad_chars_to(chars, w):
    if chars.shape[-1] >= w:
        return chars[..., :w]
    pad = [(0, 0)] * (chars.ndim - 1) + [(0, w - chars.shape[-1])]
    return jnp.pad(chars, pad)


class Flatten(Expression):
    """flatten(array_of_arrays) -> array.

    The padded device layout has no general array<array<T>> column, so
    the supported shape is the one users actually write —
    ``flatten(array(a1, a2, ...))`` over array-typed columns.  The
    CreateArray is ABSORBED at construction (its members become this
    node's children), so no array<array> type ever appears in the tagged
    plan; any other child shape keeps a single child and is tagged back
    to CPU by the overrides rule.  A null member array makes the result
    null (Spark flatten semantics)."""

    def __init__(self, child: Expression):
        members = None
        if isinstance(child, CreateArray) and child.children:
            members = list(child.children)
        self._absorbed = members is not None
        super().__init__(members if members is not None else [child])

    def _resolve_type(self):
        if self._absorbed:
            self._dataType = self.children[0].dataType
        else:
            self._dataType = self.children[0].dataType.elementType
        self._nullable = True

    def sql_string(self):
        if self._absorbed:
            inner = ", ".join(c.sql_string() for c in self.children)
            return f"flatten(array({inner}))"
        return f"flatten({self.children[0].sql_string()})"

    def eval_tpu(self, ctx: EvalContext) -> DeviceColumn:
        members = [m.eval_tpu(ctx) for m in self.children]
        validity = self.and_validity(members)
        lens = sum(m.lengths.astype(jnp.int32) for m in members)
        if members[0].is_string_array:
            w = max(m.chars.shape[-1] for m in members)
            chars = jnp.concatenate(
                [_pad_chars_to(m.chars, w) for m in members], axis=1)
        else:
            chars = None
        elens = jnp.concatenate([m.data for m in members], axis=1)
        # compact each row's PRESENT elements (inside their array's
        # length; null elements count as present) to a prefix with a
        # stable per-row sort by (absent, position)
        present = jnp.concatenate([_in_len(m) for m in members], axis=1)
        wtot = elens.shape[1]
        posm = jnp.broadcast_to(jnp.arange(wtot, dtype=jnp.int32)[None, :],
                                elens.shape[:1] + (wtot,))
        live_idx = jax.lax.sort(((~present).astype(jnp.int32), posm),
                                num_keys=2, dimension=1, is_stable=True)[1]
        gath = jnp.take_along_axis
        elens_c = gath(elens, live_idx, axis=1)
        ev_c = gath(jnp.concatenate(
            [m.elem_valid for m in members], axis=1), live_idx, axis=1)
        in_out = jnp.arange(wtot, dtype=jnp.int32)[None, :] < lens[:, None]
        if chars is not None:
            chars_c = gath(chars, live_idx[:, :, None], axis=1)
            return DeviceColumn(self.dataType, validity, chars=chars_c,
                                data=jnp.where(ev_c & in_out, elens_c, 0),
                                lengths=lens, elem_valid=ev_c & in_out)
        return DeviceColumn(self.dataType, validity,
                            data=elens_c,
                            lengths=lens, elem_valid=ev_c & in_out)


class StrToMap(Expression):
    """str_to_map(text[, pairDelim[, keyValueDelim]]) -> map<string,string>.

    Reference analog: GpuStringToMap (SURVEY.md §2.5 Collections).  Like
    the split family, irregular per-row shapes make this a host kernel;
    delimiters are Java regexes validated at plan time.  Duplicate keys
    follow Spark's default EXCEPTION dedup policy via the error flags."""

    is_host_kernel = True

    def __init__(self, children: List[Expression]):
        super().__init__(list(children))

    def sql_string(self):
        return ("str_to_map("
                + ", ".join(c.sql_string() for c in self.children) + ")")

    def _resolve_type(self):
        from spark_rapids_tpu.expr.base import Literal

        self._dataType = T.MapType(T.STRING, T.STRING)
        self._nullable = True
        self._pair = ","
        self._kv = ":"
        if len(self.children) > 1 and isinstance(self.children[1], Literal):
            self._pair = str(self.children[1].value)
        if len(self.children) > 2 and isinstance(self.children[2], Literal):
            self._kv = str(self.children[2].value)

    def do_columnar_eval(self, ctx: EvalContext, cols):
        import re as _re

        import numpy as np

        from spark_rapids_tpu.columnar.column import HostColumn
        from spark_rapids_tpu.cpu.oracle import _java_regex_to_python

        c = cols[0]
        n = int(ctx.batch.num_rows)
        cap = c.capacity
        vals = c.to_host(n).to_pylist()
        rp = _re.compile(_java_regex_to_python(self._pair))
        rk = _re.compile(_java_regex_to_python(self._kv))
        out = []
        dup = np.zeros(cap, np.bool_)
        for i, s in enumerate(vals):
            if s is None:
                out.append(None)
                continue
            m = {}
            for entry in rp.split(s):
                parts = rk.split(entry, maxsplit=1)
                k = parts[0]
                v = parts[1] if len(parts) > 1 else None
                if k in m:
                    dup[i] = True
                m[k] = v
            out.append(m)
        ctx.add_error(jnp.asarray(dup), "Duplicate map key was found")
        host = HostColumn.from_pylist(out, self.dataType)
        return DeviceColumn.from_host(host, capacity=cap)


class MapEntries(UnaryExpression):
    """map_entries(m) -> array<struct<key, value>> — the map's children
    ARE the entries layout (per-field array columns sharing lengths).

    Reference analog: GpuMapEntries (collectionOperations.scala)."""

    def _resolve_type(self):
        mt = self.child.dataType
        et = T.StructType([T.StructField("key", mt.keyType, False),
                           T.StructField("value", mt.valueType, True)])
        self._dataType = T.ArrayType(et, containsNull=False)
        self._nullable = self.child.nullable

    def sql_string(self):
        return f"map_entries({self.child.sql_string()})"

    def do_columnar_eval(self, ctx: EvalContext, cols):
        m = cols[0]
        kcol, vcol = m.children
        return DeviceColumn(self.dataType, m.validity,
                            lengths=kcol.lengths,
                            children=(kcol, vcol))


class ArraysZip(Expression):
    """arrays_zip(a1, a2, ...) -> array<struct<...>> zipped to the
    LONGEST input; shorter inputs contribute null fields.

    Reference analog: GpuArraysZip (collectionOperations.scala)."""

    def __init__(self, children: List[Expression], names=None):
        super().__init__(list(children))
        self._names = names

    def sql_string(self):
        return ("arrays_zip("
                + ", ".join(c.sql_string() for c in self.children) + ")")

    def _resolve_type(self):
        names = self._names or [str(i) for i in range(len(self.children))]
        fields = [T.StructField(nm, c.dataType.elementType, True)
                  for nm, c in zip(names, self.children)]
        self._dataType = T.ArrayType(T.StructType(fields),
                                     containsNull=False)
        self._nullable = any(c.nullable for c in self.children)

    def do_columnar_eval(self, ctx: EvalContext, cols):
        validity = self.and_validity(cols)
        out_len = cols[0].lengths
        for c in cols[1:]:
            out_len = jnp.maximum(out_len, c.lengths)
        kids = []
        for c in cols:
            # keep each input's own lengths: the entries layout's reader
            # nulls fields past their array's length
            kids.append(DeviceColumn(
                T.ArrayType(c.dtype.elementType, containsNull=True),
                validity, data=c.data, chars=c.chars,
                lengths=c.lengths, elem_valid=c.elem_valid))
        return DeviceColumn(self.dataType, validity, lengths=out_len,
                            children=tuple(kids))


class TryElementAt(ElementAt):
    """try_element_at: element_at that returns NULL instead of erroring on
    0 / out-of-range index (the engine's ElementAt is already null-safe;
    this class pins the ANSI-mode behavior too)."""

    def sql_string(self):
        return (f"try_element_at({self.left.sql_string()}, "
                f"{self.right.sql_string()})")


class Cardinality(UnaryExpression):
    """cardinality(array|map): element count, NULL for null input (unlike
    legacy size() which yields -1)."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def sql_string(self):
        return f"cardinality({self.child.sql_string()})"

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        lens = c.lengths
        if lens is None and c.children is not None:
            lens = c.children[0].lengths    # map layout: key child
        return DeviceColumn(T.INT, c.validity, data=lens)


class MapFromEntries(UnaryExpression):
    """map_from_entries(array<struct<k,v>>) -> map<k,v>.

    The entries layout IS the map layout (per-field element columns
    sharing lengths), so this is a relabel + the Spark error checks:
    null keys error; duplicate keys error under the default
    spark.sql.mapKeyDedupPolicy=EXCEPTION.

    Reference analog: GpuMapFromEntries (collectionOperations.scala,
    SURVEY.md §2.5 Collections)."""

    def _resolve_type(self):
        at = self.child.dataType
        et = at.elementType
        self._dataType = T.MapType(et.fields[0].dataType,
                                   et.fields[1].dataType)
        self._nullable = True

    def sql_string(self):
        return f"map_from_entries({self.child.sql_string()})"

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr = cols[0]
        kcol, vcol = arr.children
        w = max(arr.ewidth, 1)
        in_len = jnp.arange(w)[None, :] < arr.lengths[:, None]
        # null map keys are an error (Spark: "Cannot use null as map key")
        null_key = arr.validity & jnp.any(in_len & ~kcol.elem_valid, axis=1)
        ctx.add_error(null_key, "Cannot use null as map key")
        # duplicate keys: per-row sort + adjacent compare (EXCEPTION policy)
        kd = kcol.data
        if kd is not None and kd.ndim == 2:
            big = jnp.iinfo(jnp.int64).max
            masked = jnp.where(in_len & kcol.elem_valid,
                               kd.astype(jnp.int64), big)
            ks = jnp.sort(masked, axis=1)
            dup = jnp.any((ks[:, 1:] == ks[:, :-1]) & (ks[:, 1:] != big),
                          axis=1)
            ctx.add_error(arr.validity & dup,
                          "Duplicate map key was found")
        keys_out = DeviceColumn(T.ArrayType(self.dataType.keyType, False),
                                arr.validity, data=kcol.data,
                                chars=kcol.chars,
                                lengths=arr.lengths,
                                elem_valid=kcol.elem_valid)
        vals_out = DeviceColumn(T.ArrayType(self.dataType.valueType),
                                arr.validity, data=vcol.data,
                                chars=vcol.chars,
                                lengths=arr.lengths,
                                elem_valid=vcol.elem_valid)
        return DeviceColumn(self.dataType, arr.validity,
                            lengths=arr.lengths,
                            children=(keys_out, vals_out))


class MapSort(UnaryExpression):
    """map_sort-like canonical ordering: entries sorted by key per row
    (Spark 4.0 MapSort; flat orderable keys — the tag check restricts).

    TPU design: one vectorized per-row argsort over the padded entries
    axis (pads sort last), then take_along_axis on keys and values —
    no per-row loops."""

    def _resolve_type(self):
        self._dataType = self.child.dataType
        self._nullable = self.child.nullable

    def sql_string(self):
        return f"map_sort({self.child.sql_string()})"

    def do_columnar_eval(self, ctx: EvalContext, cols):
        m = cols[0]
        kcol, vcol = m.children
        # map columns carry lengths/width on the key child
        lens = kcol.lengths
        w = max(kcol.data.shape[1], 1)
        in_len = jnp.arange(w)[None, :] < lens[:, None]
        big = jnp.iinfo(jnp.int64).max
        masked = jnp.where(in_len, kcol.data.astype(jnp.int64), big)
        order = jnp.argsort(masked, axis=1)
        ks = jnp.take_along_axis(kcol.data, order, axis=1)
        kev = jnp.take_along_axis(kcol.elem_valid, order, axis=1)
        kout = DeviceColumn(kcol.dtype, kcol.validity, data=ks,
                            lengths=lens, elem_valid=kev)
        if vcol.data is not None and vcol.data.ndim == 2:
            vs = jnp.take_along_axis(vcol.data, order, axis=1)
            vev = jnp.take_along_axis(vcol.elem_valid, order, axis=1)
            vout = DeviceColumn(vcol.dtype, vcol.validity, data=vs,
                                lengths=lens, elem_valid=vev)
        else:   # string values: gather the 3-D char tensor by entry
            # string_array layout: data holds per-element byte lengths
            vch = jnp.take_along_axis(vcol.chars, order[:, :, None], axis=1)
            vln = jnp.take_along_axis(vcol.data, order, axis=1)
            vev = jnp.take_along_axis(vcol.elem_valid, order, axis=1)
            vout = DeviceColumn(vcol.dtype, vcol.validity, data=vln,
                                chars=vch, lengths=lens,
                                elem_valid=vev)
        return DeviceColumn(self.dataType, m.validity,
                            children=(kout, vout))


class Shuffle(UnaryExpression):
    """shuffle(array[, seed]): random permutation per row via a
    splitmix-keyed per-row argsort (not Spark's sequence — like GpuRand,
    the stream differs; tests pin determinism per seed)."""

    def __init__(self, child: Expression, seed: int = 0):
        super().__init__(child)
        self._seed = seed

    def _resolve_type(self):
        self._dataType = self.child.dataType
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr = cols[0]
        w = max(arr.ewidth, 1)
        cap = arr.capacity
        # fixed stride so the stream is layout-independent (the oracle
        # computes the same ranks from (row, element) alone)
        idx = (jnp.arange(cap, dtype=jnp.uint64)[:, None]
               * jnp.uint64(1 << 17)
               + jnp.arange(w, dtype=jnp.uint64)[None, :])
        z = idx * jnp.uint64(0x9E3779B97F4A7C15) \
            + jnp.uint64(self._seed * 2654435769 + 11)
        z = (z ^ (z >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> 27)) * jnp.uint64(0x94D049BB133111EB)
        rank = (z ^ (z >> 31)).astype(jnp.int64)
        in_len = jnp.arange(w)[None, :] < arr.lengths[:, None]
        big = jnp.iinfo(jnp.int64).max
        order = jnp.argsort(jnp.where(in_len, rank, big), axis=1)
        data = jnp.take_along_axis(arr.data, order, axis=1)
        ev = jnp.take_along_axis(arr.elem_valid, order, axis=1)
        return DeviceColumn(self.dataType, arr.validity, data=data,
                            lengths=arr.lengths, elem_valid=ev)
