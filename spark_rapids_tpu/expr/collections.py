"""Collection expressions over padded list columns.

Reference analog: org/apache/spark/sql/rapids/collectionOperations.scala
(GpuSize, GpuElementAt, GpuGetArrayItem, GpuArrayContains, GpuCreateArray,
SURVEY.md §2.5 Collections).  Device layout: a list column is
``data (cap, ewidth)`` + ``elem_valid (cap, ewidth)`` + ``lengths (cap,)``
(the padded counterpart of cuDF's offsets+child, chosen for XLA static
shapes — columnar/column.py).
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (
    BinaryExpression,
    EvalContext,
    Expression,
    UnaryExpression,
)


class Size(UnaryExpression):
    """size(array): element count; null input -> -1 (legacy) like Spark's
    default spark.sql.legacy.sizeOfNull=true."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = False

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        data = jnp.where(c.validity, c.lengths, -1)
        return DeviceColumn(T.INT, jnp.ones_like(c.validity), data=data)


class GetArrayItem(BinaryExpression):
    """array[idx]: 0-based; out of bounds -> null (legacy mode)."""

    def _resolve_type(self):
        self._dataType = self.left.dataType.elementType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, idx = cols
        i = idx.data.astype(jnp.int32)
        inb = (i >= 0) & (i < arr.lengths)
        safe = jnp.clip(i, 0, max(arr.ewidth - 1, 0))
        data = jnp.take_along_axis(arr.data, safe[:, None], axis=1)[:, 0]
        ev = jnp.take_along_axis(arr.elem_valid, safe[:, None], axis=1)[:, 0]
        validity = arr.validity & idx.validity & inb & ev
        return DeviceColumn(self.dataType, validity, data=data)


class ElementAt(BinaryExpression):
    """element_at(array, i): 1-based, negative counts from the end;
    out of bounds -> null (legacy mode)."""

    def _resolve_type(self):
        self._dataType = self.left.dataType.elementType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, idx = cols
        i = idx.data.astype(jnp.int32)
        n = arr.lengths
        zero = i == 0          # element_at(_, 0) is an error in Spark; null here
        pos = jnp.where(i > 0, i - 1, n + i)
        inb = (pos >= 0) & (pos < n) & ~zero
        safe = jnp.clip(pos, 0, max(arr.ewidth - 1, 0))
        data = jnp.take_along_axis(arr.data, safe[:, None], axis=1)[:, 0]
        ev = jnp.take_along_axis(arr.elem_valid, safe[:, None], axis=1)[:, 0]
        validity = arr.validity & idx.validity & inb & ev
        return DeviceColumn(self.dataType, validity, data=data)


class ArrayContains(BinaryExpression):
    """array_contains(arr, v): Spark null semantics — true if found, null
    if not found but the array has null elements, else false."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        arr, v = cols
        w = arr.ewidth
        in_len = jnp.arange(w)[None, :] < arr.lengths[:, None]
        eq = (arr.data == v.data[:, None]) & arr.elem_valid & in_len
        found = jnp.any(eq, axis=1)
        has_null_elem = jnp.any(~arr.elem_valid & in_len, axis=1)
        validity = arr.validity & v.validity & (found | ~has_null_elem)
        return DeviceColumn(T.BOOLEAN, validity, data=found)


class CreateArray(Expression):
    """array(e1, e2, ...) over flat element expressions."""

    def __init__(self, children: List[Expression]):
        super().__init__(list(children))

    def sql_string(self):
        return "array(" + ", ".join(c.sql_string() for c in self.children) + ")"

    def _resolve_type(self):
        et = self.children[0].dataType
        self._dataType = T.ArrayType(et)
        self._nullable = False

    def do_columnar_eval(self, ctx: EvalContext, cols):
        k = len(cols)
        data = jnp.stack([c.data for c in cols], axis=1)
        ev = jnp.stack([c.validity for c in cols], axis=1)
        cap = cols[0].capacity
        lengths = jnp.full(cap, k, jnp.int32)
        return DeviceColumn(self.dataType, jnp.ones(cap, jnp.bool_),
                            data=data, lengths=lengths, elem_valid=ev)


class ArrayMin(UnaryExpression):
    """array_min: nulls skipped; empty/all-null -> null."""

    _is_min = True

    def _resolve_type(self):
        self._dataType = self.child.dataType.elementType
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c = cols[0]
        w = c.ewidth
        in_len = jnp.arange(w)[None, :] < c.lengths[:, None]
        ok = c.elem_valid & in_len
        dt = self.dataType
        is_f = isinstance(dt, (T.FloatType, T.DoubleType))
        if is_f:
            ident = jnp.asarray(jnp.inf if self._is_min else -jnp.inf,
                                c.data.dtype)
        else:
            info = jnp.iinfo(c.data.dtype)
            ident = jnp.asarray(info.max if self._is_min else info.min,
                                c.data.dtype)
        v = jnp.where(ok, c.data, ident)
        red = jnp.min(v, axis=1) if self._is_min else jnp.max(v, axis=1)
        has = jnp.any(ok, axis=1)
        return DeviceColumn(dt, c.validity & has, data=red)


class ArrayMax(ArrayMin):
    _is_min = False
