"""Struct expressions over struct device columns.

Reference analog: org/apache/spark/sql/rapids/complexTypeCreator.scala
(GpuCreateNamedStruct) and complexTypeExtractors (GpuGetStructField) —
cuDF STRUCT columns are a validity mask over child columns, and so are
ours (columnar/column.py kind "struct"), so extraction is a child pick
and creation is a bundle: both free at the XLA level.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import Expression, UnaryExpression


class GetStructField(UnaryExpression):
    """struct.field — child column pick, validity AND'd with the struct's."""

    def __init__(self, child: Expression, name: str):
        super().__init__(child)
        self.field_name = name

    def sql_string(self):
        return f"{self.child.sql_string()}.{self.field_name}"

    def _resolve_type(self):
        st = self.child.dataType
        if not isinstance(st, T.StructType):
            raise TypeError(f"GetStructField on {st.simpleString}")
        matches = [f for f in st.fields if f.name == self.field_name]
        if not matches:
            raise KeyError(
                f"no field '{self.field_name}' in {st.simpleString}")
        self._field_ordinal = st.fields.index(matches[0])
        self._dataType = matches[0].dataType
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        kid = c.children[self._field_ordinal]
        validity = kid.validity & c.validity
        return DeviceColumn(kid.dtype, validity, data=kid.data,
                            chars=kid.chars, lengths=kid.lengths,
                            elem_valid=kid.elem_valid, children=kid.children)


class CreateNamedStruct(Expression):
    """named_struct('a', x, 'b', y) — bundle children into a struct column."""

    def __init__(self, names: List[str], values: List[Expression]):
        super().__init__(values)
        self.field_names = list(names)

    def sql_string(self):
        parts = ", ".join(f"'{n}', {v.sql_string()}"
                          for n, v in zip(self.field_names, self.children))
        return f"named_struct({parts})"

    def _resolve_type(self):
        self._dataType = T.StructType(
            [T.StructField(n, c.dataType, c.nullable)
             for n, c in zip(self.field_names, self.children)])
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        validity = jnp.ones(ctx.batch.capacity, jnp.bool_)
        return DeviceColumn(self.dataType, validity, children=tuple(cols))
