"""String expressions over the padded char-matrix layout.

Reference analog: org/apache/spark/sql/rapids/stringFunctions.scala
(GpuSubstring, GpuConcat, GpuUpper/GpuLower, GpuStringTrim, GpuContains,
GpuStartsWith/GpuEndsWith, GpuLength, GpuStringRepeat...).  cuDF implements
these over (chars, offsets); here every op is a dense (rows x width) vector
transform — gathers along the width axis with index arithmetic, which XLA
maps onto the VPU.

Unicode note: Upper/Lower are ASCII-only for now (the reference similarly
documents incompatibilities and hides some behind conf); Length counts UTF-8
*code points* like Spark, computed from the byte patterns.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (
    BinaryExpression,
    EvalContext,
    Expression,
    UnaryExpression,
)
from spark_rapids_tpu.expr.predicates import _pad_to


class Length(UnaryExpression):
    """UTF-8 code-point count (Spark length), not byte count."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        pos = jnp.arange(c.width)[None, :]
        in_str = pos < c.lengths[:, None]
        # count bytes that are NOT utf-8 continuation bytes (0b10xxxxxx)
        is_cont = (c.chars & 0xC0) == 0x80
        n = jnp.sum(in_str & ~is_cont, axis=1)
        return DeviceColumn(T.INT, c.validity, data=n.astype(jnp.int32))


class Upper(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def _tx(self, ch):
        return jnp.where((ch >= ord("a")) & (ch <= ord("z")), ch - 32, ch)

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(T.STRING, c.validity,
                            chars=self._tx(c.chars).astype(jnp.uint8),
                            lengths=c.lengths)


class Lower(Upper):
    def _tx(self, ch):
        return jnp.where((ch >= ord("A")) & (ch <= ord("Z")), ch + 32, ch)


class Substring(Expression):
    """substring(str, pos, len) with Spark 1-based / negative pos semantics.

    Byte-based gather; Spark substring is character-based — for ASCII they
    agree.  Non-ASCII correctness comes with the codepoint-index map
    (later round; tagged incompat until then, like the reference's CSV/regex
    caveats)."""

    def __init__(self, s: Expression, pos: Expression, length: Expression):
        super().__init__([s, pos, length])

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c, p, ln = cols
        n = c.lengths
        pos = p.data.astype(jnp.int32)
        # Spark substringSQL: pos>0 -> 1-based; pos<0 -> from end (may land
        # before the start — the window is [start, start+len) computed on the
        # UNclamped start, then clipped, so a negative start eats length)
        start0 = jnp.where(pos > 0, pos - 1,
                           jnp.where(pos < 0, n + pos, 0))
        want = jnp.maximum(ln.data.astype(jnp.int32), 0)
        end0 = start0 + want
        start = jnp.clip(start0, 0, n)
        out_len = jnp.maximum(jnp.clip(end0, 0, n) - start, 0)
        width = c.width
        idx = start[:, None] + jnp.arange(width)[None, :]
        take = jnp.arange(width)[None, :] < out_len[:, None]
        gathered = jnp.take_along_axis(c.chars, jnp.clip(idx, 0, width - 1),
                                       axis=1)
        chars = jnp.where(take, gathered, 0).astype(jnp.uint8)
        validity = c.validity & p.validity & ln.validity
        return DeviceColumn(T.STRING, validity, chars=chars,
                            lengths=out_len.astype(jnp.int32))


class Concat(Expression):
    """concat(s1, s2, ...): null if any input null (Spark)."""

    def __init__(self, children: List[Expression]):
        super().__init__(children)

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = any(c.nullable for c in self.children)

    def do_columnar_eval(self, ctx, cols):
        total_w = sum(c.width for c in cols)
        n = cols[0].capacity
        out = jnp.zeros((n, total_w), jnp.uint8)
        out_len = jnp.zeros(n, jnp.int32)
        validity = cols[0].validity
        for c in cols[1:]:
            validity = validity & c.validity
        for c in cols:
            # scatter c's chars at position out_len per row
            idx = out_len[:, None] + jnp.arange(c.width)[None, :]
            take = jnp.arange(c.width)[None, :] < c.lengths[:, None]
            # build one-hot-ish scatter via take_along_axis on the source side:
            # for each output col j, find source col j - out_len
            src_idx = jnp.arange(total_w)[None, :] - out_len[:, None]
            in_range = (src_idx >= 0) & (src_idx < c.width)
            src = jnp.take_along_axis(
                _pad_to(c.chars, total_w),
                jnp.clip(src_idx, 0, total_w - 1), axis=1)
            write = in_range & (src_idx < c.lengths[:, None])
            out = jnp.where(write, src, out)
            out_len = out_len + c.lengths
            del idx, take
        return DeviceColumn(T.STRING, validity, chars=out, lengths=out_len)


class _FixedCompare(BinaryExpression):
    """contains/startswith/endswith with arbitrary (usually literal) needle."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True


class StartsWith(_FixedCompare):
    def do_columnar_eval(self, ctx, cols):
        s, pre = cols
        w = max(s.width, pre.width)
        a = _pad_to(s.chars, w)
        b = _pad_to(pre.chars, w)
        pos = jnp.arange(w)[None, :]
        relevant = pos < pre.lengths[:, None]
        eq = jnp.all(~relevant | (a == b), axis=1)
        data = eq & (s.lengths >= pre.lengths)
        return DeviceColumn(T.BOOLEAN, s.validity & pre.validity, data=data)


class EndsWith(_FixedCompare):
    def do_columnar_eval(self, ctx, cols):
        s, suf = cols
        w = s.width
        start = s.lengths - suf.lengths
        idx = start[:, None] + jnp.arange(max(suf.width, 1))[None, :]
        gathered = jnp.take_along_axis(
            s.chars, jnp.clip(idx, 0, max(w - 1, 0)), axis=1)
        pos = jnp.arange(max(suf.width, 1))[None, :]
        relevant = pos < suf.lengths[:, None]
        b = suf.chars if suf.width else jnp.zeros_like(gathered)
        eq = jnp.all(~relevant | (gathered == _pad_to(b, gathered.shape[1])),
                     axis=1)
        data = eq & (s.lengths >= suf.lengths)
        return DeviceColumn(T.BOOLEAN, s.validity & suf.validity, data=data)


class Contains(_FixedCompare):
    def do_columnar_eval(self, ctx, cols):
        s, needle = cols
        # shared first-match scan (also backs instr/locate)
        matches = _first_match_pos(s, needle) > 0
        return DeviceColumn(T.BOOLEAN, s.validity & needle.validity,
                            data=matches)


class StringTrim(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        pos = jnp.arange(c.width)[None, :]
        in_str = pos < c.lengths[:, None]
        is_ws = (c.chars == ord(" ")) & in_str
        nonws = in_str & ~is_ws
        any_nonws = jnp.any(nonws, axis=1)
        first = jnp.where(any_nonws, jnp.argmax(nonws, axis=1), 0)
        last = jnp.where(any_nonws,
                         c.width - 1 - jnp.argmax(nonws[:, ::-1], axis=1), -1)
        out_len = (last - first + 1).astype(jnp.int32)
        idx = first[:, None] + jnp.arange(c.width)[None, :]
        take = jnp.arange(c.width)[None, :] < out_len[:, None]
        gathered = jnp.take_along_axis(c.chars, jnp.clip(idx, 0, c.width - 1),
                                       axis=1)
        chars = jnp.where(take, gathered, 0).astype(jnp.uint8)
        return DeviceColumn(T.STRING, c.validity, chars=chars, lengths=out_len)


class Like(BinaryExpression):
    """SQL LIKE with literal pattern, compiled at plan time to device ops.

    Reference analog: GpuLike; complex patterns fall back at tag time (the
    regex-transpiler-reject path, SURVEY.md §2.5).  Supported here:
    'abc%', '%abc', '%abc%', exact, and patterns without wildcards; others
    are rejected by the overrides layer (try_compile_like)."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        s, _ = cols
        pat = self.right
        assert isinstance(pat, Literal), "LIKE pattern must be literal"
        p: str = pat.value
        simple = "_" not in p and "\\" not in p
        core = p.strip("%")
        if simple and "%" not in core:
            needle = Literal(core, T.STRING).eval_tpu(ctx)
            if p.startswith("%") and p.endswith("%"):
                return Contains(self.left, pat).do_columnar_eval(
                    ctx, [s, needle])
            if p.endswith("%"):
                return StartsWith(self.left, pat).do_columnar_eval(
                    ctx, [s, needle])
            if p.startswith("%"):
                return EndsWith(self.left, pat).do_columnar_eval(
                    ctx, [s, needle])
            from spark_rapids_tpu.expr.predicates import string_compare

            _, eq = string_compare(s, needle)
            return DeviceColumn(T.BOOLEAN, s.validity, data=eq)
        # general patterns (underscores, inner %, escapes): full-match DFA
        from spark_rapids_tpu.regex import compile_regex, like_to_regex

        compiled = getattr(self, "_dfa", None)
        if compiled is None:
            compiled = self._dfa = compile_regex(like_to_regex(p),
                                                 full_match=True)
        return DeviceColumn(T.BOOLEAN, s.validity, data=run_dfa(s, compiled))


def try_compile_like(p):
    """-> (supported, compiled-or-None).  Fast paths (prefix/suffix/
    contains/exact) need no DFA; everything else (underscores, inner %,
    escapes) compiles to a full-match DFA, returned so the tag-time caller
    can stash it on the expression (avoids a second compile at eval)."""
    if p is None:
        return False, None
    if "_" not in p and "\\" not in p:
        core = p.strip("%")
        if "%" not in core:
            return True, None
    from spark_rapids_tpu.regex import (
        RegexUnsupported,
        compile_regex,
        like_to_regex,
    )

    try:
        return True, compile_regex(like_to_regex(p), full_match=True)
    except (RegexUnsupported, ValueError):
        # invalid escape sequences error identically on the CPU path, so
        # letting them fall back surfaces the same Spark-style error there
        return False, None


# ---------------------------------------------------------------------------
# Breadth set: replace/translate/instr/locate/pad/repeat/reverse/initcap/
# ascii/chr/concat_ws.  Reference analog: stringFunctions.scala
# (GpuStringReplace, GpuStringTranslate, GpuStringInstr, GpuStringLocate,
# GpuStringLPad/RPad, GpuStringRepeat, GpuReverse, GpuInitCap, GpuAscii,
# GpuChr, GpuConcatWs).  All are dense (rows x width) vector transforms;
# where the reference requires literal needles/pads at plan time, the
# overrides layer enforces the same restriction here.
# ---------------------------------------------------------------------------


def _literal_bytes(e: Expression) -> bytes:
    from spark_rapids_tpu.expr.base import Literal

    assert isinstance(e, Literal) and e.value is not None
    return e.value.encode("utf-8")


def _match_literal_at(c: DeviceColumn, needle: bytes) -> "jnp.ndarray":
    """(n, w) bool: needle matches starting at byte position i."""
    w = c.width
    ls = len(needle)
    m = jnp.ones((c.capacity, max(w, 1)), jnp.bool_)
    for k, b in enumerate(needle):
        if k >= w:
            m = jnp.zeros_like(m)
            break
        shifted = jnp.concatenate(
            [c.chars[:, k:], jnp.zeros((c.capacity, k), jnp.uint8)], axis=1)
        m = m & (shifted == b)
    pos = jnp.arange(max(w, 1))[None, :]
    return m & (pos + ls <= c.lengths[:, None])


class Reverse(UnaryExpression):
    """Byte-reverse (ASCII-only, like Upper/Lower)."""

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        w = max(c.width, 1)
        idx = c.lengths[:, None] - 1 - jnp.arange(w)[None, :]
        take = jnp.arange(w)[None, :] < c.lengths[:, None]
        src = c.chars if c.width else jnp.zeros((c.capacity, 1), jnp.uint8)
        g = jnp.take_along_axis(src, jnp.clip(idx, 0, w - 1), axis=1)
        return DeviceColumn(T.STRING, c.validity,
                            chars=jnp.where(take, g, 0).astype(jnp.uint8),
                            lengths=c.lengths)


class InitCap(UnaryExpression):
    """First letter of each space-separated word upper, rest lower
    (ASCII-only)."""

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        ch = c.chars
        is_space = ch == ord(" ")
        prev_space = jnp.concatenate(
            [jnp.ones((c.capacity, 1), jnp.bool_), is_space[:, :-1]], axis=1)
        lower = jnp.where((ch >= ord("A")) & (ch <= ord("Z")), ch + 32, ch)
        upper = jnp.where((ch >= ord("a")) & (ch <= ord("z")), ch - 32, ch)
        out = jnp.where(prev_space, upper, lower)
        return DeviceColumn(T.STRING, c.validity,
                            chars=out.astype(jnp.uint8), lengths=c.lengths)


class Ascii(UnaryExpression):
    """ascii(s): code of the first byte; 0 for empty (ASCII-only)."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        if not c.width:
            return DeviceColumn(T.INT, c.validity,
                                data=jnp.zeros(c.capacity, jnp.int32))
        # decode the first UTF-8 code point (Spark: codePointAt(0))
        b = [c.chars[:, k].astype(jnp.int32) if k < c.width
             else jnp.zeros(c.capacity, jnp.int32) for k in range(4)]
        one = b[0] < 0x80
        two = (b[0] >= 0xC0) & (b[0] < 0xE0)
        three = (b[0] >= 0xE0) & (b[0] < 0xF0)
        cp = jnp.where(
            one, b[0],
            jnp.where(two, ((b[0] & 0x1F) << 6) | (b[1] & 0x3F),
                      jnp.where(three,
                                ((b[0] & 0x0F) << 12) | ((b[1] & 0x3F) << 6)
                                | (b[2] & 0x3F),
                                ((b[0] & 0x07) << 18) | ((b[1] & 0x3F) << 12)
                                | ((b[2] & 0x3F) << 6) | (b[3] & 0x3F))))
        out = jnp.where(c.lengths > 0, cp, 0)
        return DeviceColumn(T.INT, c.validity, data=out)


class Chr(UnaryExpression):
    """chr(n): character with code n % 256 (UTF-8 encoded); n<0 -> ''."""

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        lv = c.data.astype(jnp.int64)
        code = (lv % 256).astype(jnp.int32)  # python-style mod: >= 0
        neg = lv < 0
        two_byte = code >= 128
        b0 = jnp.where(two_byte, 0xC0 | (code >> 6), code)
        b1 = jnp.where(two_byte, 0x80 | (code & 0x3F), 0)
        chars = jnp.stack([b0, b1], axis=1).astype(jnp.uint8)
        out_len = jnp.where(neg, 0, jnp.where(two_byte, 2, 1)).astype(jnp.int32)
        chars = jnp.where(jnp.arange(2)[None, :] < out_len[:, None], chars, 0)
        return DeviceColumn(T.STRING, c.validity,
                            chars=chars.astype(jnp.uint8), lengths=out_len)


class StringReplace(Expression):
    """replace(str, search, rep) with literal search/rep: non-overlapping
    left-to-right, like Java String.replace.  Empty search returns str."""

    def __init__(self, s: Expression, search: Expression, rep: Expression):
        super().__init__([s, search, rep])

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        import jax

        c = cols[0]
        validity = self.and_validity(cols)
        search = _literal_bytes(self.children[1])
        rep = _literal_bytes(self.children[2])
        ls, lr = len(search), len(rep)
        if ls == 0 or c.width == 0 or ls > c.width:
            return DeviceColumn(T.STRING, validity, chars=c.chars,
                                lengths=c.lengths)
        n, w = c.capacity, c.width
        m = _match_literal_at(c, search)

        # greedy non-overlap: scan across columns with a per-row skip count
        def step(skip, m_col):
            start = m_col & (skip == 0)
            new_skip = jnp.where(start, ls - 1, jnp.maximum(skip - 1, 0))
            return new_skip, (start, skip > 0)

        _, (starts_t, covered_t) = jax.lax.scan(
            step, jnp.zeros(n, jnp.int32), m.T)
        starts, covered = starts_t.T, covered_t.T
        in_str = jnp.arange(w)[None, :] < c.lengths[:, None]
        contrib = jnp.where(in_str,
                            jnp.where(starts, lr,
                                      jnp.where(covered, 0, 1)), 0)
        off = jnp.cumsum(contrib, axis=1) - contrib  # exclusive
        n_rep_max = w // ls
        out_w = w + n_rep_max * max(lr - ls, 0)
        out_len = jnp.sum(contrib, axis=1).astype(jnp.int32)
        flat = jnp.zeros(n * out_w, jnp.uint8)
        rows = jnp.arange(n)[:, None]
        # plain chars
        tgt = jnp.where(in_str & ~starts & ~covered,
                        rows * out_w + off, n * out_w)
        flat = flat.at[tgt.reshape(-1)].set(c.chars.reshape(-1), mode="drop")
        # replacement bytes
        for k, b in enumerate(rep):
            tgt = jnp.where(in_str & starts, rows * out_w + off + k, n * out_w)
            flat = flat.at[tgt.reshape(-1)].set(
                jnp.uint8(b), mode="drop")
        return DeviceColumn(T.STRING, validity,
                            chars=flat.reshape(n, out_w), lengths=out_len)


class StringTranslate(Expression):
    """translate(str, from, to) with literal from/to; unmatched from-chars
    are deleted (ASCII-only byte mapping)."""

    def __init__(self, s: Expression, frm: Expression, to: Expression):
        super().__init__([s, frm, to])

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        import numpy as np

        c = cols[0]
        validity = self.and_validity(cols)
        frm = _literal_bytes(self.children[1])
        to = _literal_bytes(self.children[2])
        table = np.arange(256, dtype=np.uint8)
        deleted = np.zeros(256, np.bool_)
        seen = set()
        for i, b in enumerate(frm):
            if b in seen:  # first occurrence wins (Java Spark behavior)
                continue
            seen.add(b)
            if i < len(to):
                table[b] = to[i]
            else:
                deleted[b] = True
        if c.width == 0:
            return DeviceColumn(T.STRING, validity, chars=c.chars,
                                lengths=c.lengths)
        mapped = jnp.take(jnp.asarray(table), c.chars.astype(jnp.int32))
        in_str = jnp.arange(c.width)[None, :] < c.lengths[:, None]
        drop = jnp.take(jnp.asarray(deleted), c.chars.astype(jnp.int32))
        keep = in_str & ~drop
        # stable compaction: sort by (dropped-or-padding) ascending
        perm = jnp.argsort(~keep, axis=1, stable=True)
        g = jnp.take_along_axis(mapped, perm, axis=1)
        out_len = jnp.sum(keep, axis=1).astype(jnp.int32)
        mask = jnp.arange(c.width)[None, :] < out_len[:, None]
        return DeviceColumn(T.STRING, validity,
                            chars=jnp.where(mask, g, 0).astype(jnp.uint8),
                            lengths=out_len)


def _first_match_pos(s: DeviceColumn, needle: DeviceColumn,
                     from_idx=None) -> "jnp.ndarray":
    """1-based CHARACTER position of the first needle occurrence at/after
    char index from_idx (0-based), 0 if absent.  Spark's instr/locate count
    code points (UTF8String.indexOf), not bytes: matching is byte-wise over
    the UTF-8 matrix, but reported positions count non-continuation bytes.
    Empty needle -> 1 regardless of start.

    Start positions are scanned in CHUNKS inside a lax.fori_loop — compile
    size is O(1) in the string width (a Python loop over `range(width)`
    unrolled a 2048-step program at the widest bucket: minutes of XLA
    compile — VERDICT r3 weak #4), while each iteration stays a wide
    vectorized gather+compare so the MXU-adjacent VPU lanes stay busy.
    Peak scratch is capped at ~256MB via the chunk size."""
    w = max(s.width, 1)
    nw = max(needle.width, 1)
    cap = s.capacity
    npos = jnp.arange(nw)[None, :]
    relevant = npos < needle.lengths[:, None]
    nchars = (needle.chars if needle.width
              else jnp.zeros((cap, nw), jnp.uint8))
    schars = s.chars if s.width else jnp.zeros((cap, w), jnp.uint8)
    # chars_before[:, j] = number of code points strictly before byte j
    noncont = ((schars < 0x80) | (schars >= 0xC0)).astype(jnp.int32)
    chars_before = jnp.cumsum(noncont, axis=1) - noncont

    chunk = max(1, min(w, (1 << 28) // max(cap * nw, 1)))
    n_chunks = -(-w // chunk)

    def one_chunk(ci, carry):
        found, first = carry
        starts = ci * chunk + jnp.arange(chunk, dtype=jnp.int32)  # (k,)
        idx = jnp.clip(starts[:, None] + jnp.arange(nw)[None, :],
                       0, w - 1)                                   # (k, nw)
        seg = jnp.take(schars, idx.reshape(-1), axis=1).reshape(
            cap, chunk, nw)
        eq = jnp.all(~relevant[:, None, :] | (seg == nchars[:, None, :]),
                     axis=2)                                       # (cap, k)
        in_range = starts[None, :] < w
        hit = (eq & in_range
               & (starts[None, :] + needle.lengths[:, None]
                  <= s.lengths[:, None]))
        cpos = jnp.take(chars_before, jnp.clip(starts, 0, w - 1), axis=1)
        if from_idx is not None:
            fi = from_idx if jnp.ndim(from_idx) == 0 else from_idx[:, None]
            hit = hit & (cpos >= fi)
        has = jnp.any(hit, axis=1)
        j = jnp.argmax(hit, axis=1)                 # first True (ascending)
        cand = jnp.take_along_axis(cpos, j[:, None], axis=1)[:, 0] + 1
        first = jnp.where(has & ~found, cand, first)
        return found | has, first

    found0 = jnp.zeros(cap, jnp.bool_)
    first0 = jnp.zeros(cap, jnp.int32)
    if n_chunks == 1:
        _, first = one_chunk(jnp.int32(0), (found0, first0))
    else:
        _, first = jax.lax.fori_loop(0, n_chunks, one_chunk,
                                     (found0, first0))
    return jnp.where(needle.lengths == 0, 1, first)


class StringInstr(BinaryExpression):
    """instr(str, substr): 1-based first occurrence; 0 if absent."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        s, needle = cols
        return DeviceColumn(T.INT, s.validity & needle.validity,
                            data=_first_match_pos(s, needle))


class StringLocate(Expression):
    """locate(substr, str, start).  Spark semantics: start < 1 -> 0;
    null start -> 0 (valid); empty substr -> 1."""

    def __init__(self, substr: Expression, s: Expression,
                 start: Expression):
        super().__init__([substr, s, start])

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        needle, s, st = cols
        start_val = st.data.astype(jnp.int32)
        first = _first_match_pos(s, needle, jnp.maximum(start_val - 1, 0))
        out = jnp.where(st.validity & (start_val >= 1), first, 0)
        return DeviceColumn(T.INT, s.validity & needle.validity, data=out)


class _PadBase(Expression):
    def __init__(self, s: Expression, ln: Expression, pad: Expression):
        super().__init__([s, ln, pad])

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def _parts(self, cols):
        from spark_rapids_tpu.expr.base import Literal

        c = cols[0]
        assert isinstance(self.children[1], Literal)
        target = max(int(self.children[1].value), 0)
        pad = _literal_bytes(self.children[2])
        return c, target, pad


class StringLPad(_PadBase):
    def do_columnar_eval(self, ctx, cols):
        import numpy as np

        c, target, pad = self._parts(cols)
        validity = self.and_validity(cols)
        if target == 0:
            return DeviceColumn(T.STRING, validity,
                                chars=jnp.zeros((c.capacity, 1), jnp.uint8),
                                lengths=jnp.zeros(c.capacity, jnp.int32))
        w = max(target, 1)
        spaces = jnp.maximum(target - c.lengths, 0)
        pad_np = np.frombuffer(pad, np.uint8)
        pad_cols = jnp.asarray(
            np.resize(pad_np, w) if len(pad) else np.zeros(w, np.uint8))
        j = jnp.arange(w)[None, :]
        src_idx = j - spaces[:, None]
        gw = max(c.width, w)
        src_chars = (_pad_to(c.chars, gw) if c.width
                     else jnp.zeros((c.capacity, gw), jnp.uint8))
        src = jnp.take_along_axis(src_chars,
                                  jnp.clip(src_idx, 0, gw - 1), axis=1)
        out = jnp.where(src_idx < 0, pad_cols[None, :], src)
        out_len = jnp.full(c.capacity, target, jnp.int32)  # always `target`
        mask = j < out_len[:, None]
        return DeviceColumn(T.STRING, validity,
                            chars=jnp.where(mask, out, 0).astype(jnp.uint8),
                            lengths=out_len)


class StringRPad(_PadBase):
    def do_columnar_eval(self, ctx, cols):
        import numpy as np

        c, target, pad = self._parts(cols)
        validity = self.and_validity(cols)
        if target == 0:
            return DeviceColumn(T.STRING, validity,
                                chars=jnp.zeros((c.capacity, 1), jnp.uint8),
                                lengths=jnp.zeros(c.capacity, jnp.int32))
        w = max(target, 1)
        lp = max(len(pad), 1)
        pad_arr = jnp.asarray(np.frombuffer(pad.ljust(1, b"\0"), np.uint8))
        j = jnp.arange(w)[None, :]
        pad_idx = (j - c.lengths[:, None]) % lp
        padded = jnp.take(pad_arr, pad_idx)
        src = (_pad_to(c.chars, w)[:, :w] if c.width
               else jnp.zeros((c.capacity, w), jnp.uint8))
        out = jnp.where(j < c.lengths[:, None], src, padded)
        out_len = jnp.full(c.capacity, target, jnp.int32)
        mask = j < out_len[:, None]
        return DeviceColumn(T.STRING, validity,
                            chars=jnp.where(mask, out, 0).astype(jnp.uint8),
                            lengths=out_len)


class StringRepeat(BinaryExpression):
    """repeat(str, n) with literal n."""

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        c, _ = cols
        validity = self.and_validity(cols)
        assert isinstance(self.right, Literal)
        n_rep = max(int(self.right.value), 0)
        if n_rep == 0 or c.width == 0:
            return DeviceColumn(T.STRING, validity,
                                chars=jnp.zeros((c.capacity, 1), jnp.uint8),
                                lengths=jnp.zeros(c.capacity, jnp.int32))
        w = c.width * n_rep
        j = jnp.arange(w)[None, :]
        safe_len = jnp.maximum(c.lengths, 1)[:, None]
        src_idx = j % safe_len
        out = jnp.take_along_axis(_pad_to(c.chars, w),
                                  jnp.clip(src_idx, 0, w - 1), axis=1)
        out_len = (c.lengths * n_rep).astype(jnp.int32)
        mask = j < out_len[:, None]
        return DeviceColumn(T.STRING, validity,
                            chars=jnp.where(mask, out, 0).astype(jnp.uint8),
                            lengths=out_len)


class ConcatWs(Expression):
    """concat_ws(sep, s1, s2, ...): null inputs are SKIPPED (not null-
    propagating like concat); null only when the separator is null (the
    TPU path requires a non-null literal sep via overrides)."""

    def __init__(self, children: List[Expression]):
        super().__init__(children)

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.children[0].nullable

    def do_columnar_eval(self, ctx, cols):
        sep = cols[0]
        pieces = cols[1:]
        n = sep.capacity
        total_w = (sum(max(c.width, 1) for c in pieces)
                   + max(sep.width, 1) * max(len(pieces) - 1, 0))
        out = jnp.zeros((n, total_w), jnp.uint8)
        out_len = jnp.zeros(n, jnp.int32)
        has_prev = jnp.zeros(n, jnp.bool_)
        for c in pieces:
            include = c.validity
            emit_sep = has_prev & include
            for part, emit, plen in ((sep, emit_sep, sep.lengths),
                                     (c, include, c.lengths)):
                if part.width == 0:
                    continue
                src_idx = jnp.arange(total_w)[None, :] - out_len[:, None]
                in_range = (src_idx >= 0) & (src_idx < part.width)
                src = jnp.take_along_axis(
                    _pad_to(part.chars, total_w),
                    jnp.clip(src_idx, 0, total_w - 1), axis=1)
                write = (in_range & (src_idx < plen[:, None])
                         & emit[:, None])
                out = jnp.where(write, src, out)
                out_len = out_len + jnp.where(emit, plen, 0)
            has_prev = has_prev | include
        return DeviceColumn(T.STRING, jnp.ones(n, jnp.bool_),
                            chars=out, lengths=out_len)


# ---------------------------------------------------------------------------
# Regex: RLike over the plan-time-compiled DFA (regex/transpiler.py).
# ---------------------------------------------------------------------------


def run_dfa(c: DeviceColumn, compiled) -> "jnp.ndarray":
    """Run a compiled DFA over every row; -> (n,) bool matched.

    One lax.scan step per byte column: a single gather into the
    (states x 256) table, vectorized across rows — the TPU replacement for
    cuDF's regex VM."""
    import jax

    table = jnp.asarray(compiled.table.reshape(-1))  # (S*256,)
    accept = jnp.asarray(compiled.accept)
    n = c.capacity
    if c.width == 0:
        state = jnp.zeros(n, jnp.int32)
        return accept[state]
    in_str = jnp.arange(c.width)[None, :] < c.lengths[:, None]

    def step(state, xs):
        ch, live = xs
        nxt = jnp.take(table, state * 256 + ch.astype(jnp.int32))
        return jnp.where(live, nxt, state), None

    state, _ = jax.lax.scan(step, jnp.zeros(n, jnp.int32),
                            (c.chars.T, in_str.T))
    return accept[state]


class RLike(BinaryExpression):
    """str RLIKE pattern (literal).  Pattern is transpiled to a DFA at plan
    time; unsupported patterns are rejected by the overrides layer (the
    reference's CudfRegexTranspiler-reject path)."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True

    def _compiled(self):
        from spark_rapids_tpu.expr.base import Literal
        from spark_rapids_tpu.regex import compile_regex

        cached = getattr(self, "_dfa", None)
        if cached is None:
            assert isinstance(self.right, Literal)
            cached = self._dfa = compile_regex(self.right.value)
        return cached

    def do_columnar_eval(self, ctx, cols):
        s, _ = cols
        return DeviceColumn(T.BOOLEAN, s.validity,
                            data=run_dfa(s, self._compiled()))


class OctetLength(UnaryExpression):
    """octet_length(str): byte count (the padded layout stores it directly)."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(T.INT, c.validity, data=c.lengths)


class BitLength(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(T.INT, c.validity, data=c.lengths * 8)


class _LeftRight(BinaryExpression):
    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c, k = cols
        n = c.lengths
        want = k.data.astype(jnp.int32)
        take_n = jnp.clip(jnp.where(want < 0, 0, want), 0, n)
        start = self._start(n, take_n)
        width = c.width
        idx = start[:, None] + jnp.arange(width)[None, :]
        keep = jnp.arange(width)[None, :] < take_n[:, None]
        gathered = jnp.take_along_axis(c.chars, jnp.clip(idx, 0, width - 1),
                                       axis=1)
        return DeviceColumn(T.STRING, c.validity & k.validity,
                            chars=jnp.where(keep, gathered, 0).astype(jnp.uint8),
                            lengths=take_n)


class StringLeft(_LeftRight):
    """left(str, n): first n bytes (ASCII-exact; see Substring caveat)."""

    def _start(self, n, take_n):
        return jnp.zeros_like(n)


class StringRight(_LeftRight):
    """right(str, n): last n bytes."""

    def _start(self, n, take_n):
        return n - take_n


class SubstringIndex(Expression):
    """substring_index(str, delim, count) with a LITERAL delimiter.

    count > 0: everything before the count-th occurrence (whole string if
    fewer); count < 0: everything after the |count|-th occurrence from the
    right; count = 0 or empty delim -> empty string."""

    def __init__(self, s: Expression, delim: Expression, count: Expression):
        super().__init__([s, delim, count])

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        c, _, k = cols
        delim_expr = self.children[1]
        delim = (str(delim_expr.value).encode("utf-8")
                 if isinstance(delim_expr, Literal)
                 and delim_expr.value is not None else b"")
        width = c.width
        n = c.lengths
        count = k.data.astype(jnp.int32)
        validity = c.validity & cols[1].validity & k.validity
        if len(delim) == 0:
            return DeviceColumn(T.STRING, validity,
                                chars=jnp.zeros_like(c.chars),
                                lengths=jnp.zeros_like(n))
        if len(delim) > width:
            # delimiter longer than every string: no occurrence anywhere ->
            # whole string (count != 0) / empty (count == 0)
            out_len = jnp.where(count == 0, 0, n)
            keep = jnp.arange(width)[None, :] < out_len[:, None]
            return DeviceColumn(T.STRING, validity,
                                chars=jnp.where(keep, c.chars, 0
                                                ).astype(jnp.uint8),
                                lengths=out_len.astype(jnp.int32))
        dl = len(delim)
        # occurrence start positions: delim bytes match AND fully in bounds.
        # Spark counts LEFT-TO-RIGHT NON-OVERLAPPING occurrences for both
        # signs (StringUtils.ordinalIndexOf / lastOrdinalIndexOf are
        # non-overlapping scans).
        hit = jnp.ones((c.capacity, width), jnp.bool_)
        for j, b in enumerate(delim):
            shifted = jnp.roll(c.chars, -j, axis=1) if j else c.chars
            hit = hit & (shifted == b)
        pos_ok = (jnp.arange(width)[None, :] + dl) <= n[:, None]
        hit = hit & pos_ok
        if dl > 1:
            # kill overlapping hits: scan left->right, a hit only counts if
            # no counted hit began in the previous dl-1 positions
            def step(carry, x):
                # carry: distance since last counted hit (>= dl means free)
                free = carry >= dl
                counted = x & free
                nc = jnp.where(counted, 1, carry + 1)
                return nc, counted

            init = jnp.full(c.capacity, dl, jnp.int32)
            _, counted_t = jax.lax.scan(step, init, hit.T)
            hit = counted_t.T
        occ_idx = jnp.cumsum(hit.astype(jnp.int32), axis=1)  # 1-based count
        total = occ_idx[:, -1]
        # forward: cut before count-th occurrence
        is_kth = hit & (occ_idx == jnp.clip(count, 1, None)[:, None])
        kth_pos = jnp.min(jnp.where(
            is_kth, jnp.arange(width)[None, :], width), axis=1)
        fwd_len = jnp.where((count > 0) & (total >= count), kth_pos, n)
        # backward: cut after the (total+count+1)-th occurrence (count < 0)
        wanted = total + count + 1
        is_kth_b = hit & (occ_idx == jnp.clip(wanted, 1, None)[:, None])
        kth_pos_b = jnp.min(jnp.where(
            is_kth_b, jnp.arange(width)[None, :], width), axis=1)
        bwd_start = jnp.where((count < 0) & (total >= -count),
                              kth_pos_b + dl, 0)
        start = jnp.where(count < 0, bwd_start, 0)
        out_len = jnp.where(count == 0, 0,
                            jnp.where(count > 0, fwd_len, n - start))
        out_len = jnp.clip(out_len, 0, n)
        idx = start[:, None] + jnp.arange(width)[None, :]
        keep = jnp.arange(width)[None, :] < out_len[:, None]
        gathered = jnp.take_along_axis(c.chars, jnp.clip(idx, 0, width - 1),
                                       axis=1)
        return DeviceColumn(T.STRING, validity,
                            chars=jnp.where(keep, gathered, 0).astype(jnp.uint8),
                            lengths=out_len.astype(jnp.int32))


class RegExpReplace(Expression):
    """regexp_replace(str, pattern, replacement) — all matches replaced.

    Pattern + replacement are plan-time literals from the span-safe subset
    (regex/spans.py); replacement is literal bytes (no $group refs).
    Reference analog: GpuRegExpReplace via CudfRegexTranspiler."""

    def __init__(self, s: Expression, pattern: Expression,
                 replacement: Expression):
        super().__init__([s, pattern, replacement])
        self._dfa = None  # stashed by the tag-time check

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal
        from spark_rapids_tpu.regex.spans import (
            compile_for_spans,
            greedy_match_starts,
            match_lengths,
        )

        c = cols[0]
        if self._dfa is None:
            self._dfa = compile_for_spans(str(self.children[1].value))
        repl = str(self.children[2].value).encode("utf-8")
        R = len(repl)
        w = c.width
        n = c.lengths
        best = match_lengths(self._dfa, c.chars, n)
        matched, mlen = greedy_match_starts(best, n)
        nz = matched & (mlen > 0)
        # covered[p]: char p consumed by a (non-zero) match — diff array
        cap = c.capacity
        diff = jnp.zeros((cap, w + 2), jnp.int32)
        pcols = jnp.arange(w + 1, dtype=jnp.int32)[None, :]
        starts_idx = jnp.where(nz, pcols, w + 1)
        ends_idx = jnp.where(nz, pcols + mlen, w + 1)
        rows_idx = jnp.arange(cap)[:, None].repeat(w + 1, 1)
        diff = diff.at[rows_idx, starts_idx].add(1, mode="drop")
        diff = diff.at[rows_idx, ends_idx].add(-1, mode="drop")
        covered = jnp.cumsum(diff[:, :w], axis=1) > 0
        keep_char = ~covered & (jnp.arange(w)[None, :] < n[:, None])
        # emissions per position p in [0, w]: R if matched[p], +1 if
        # p < w and keep_char[p]
        emit = matched.astype(jnp.int32) * R
        emit = emit.at[:, :w].add(keep_char.astype(jnp.int32))
        prefix = jnp.cumsum(emit, axis=1) - emit     # exclusive
        out_len = prefix[:, -1] + emit[:, -1]
        out_w = c.width * (R + 1) + R if R else c.width
        from spark_rapids_tpu.columnar.column import (
            DEFAULT_WIDTH_BUCKETS,
            round_up_bucket,
        )

        out_w = round_up_bucket(max(out_w, 1), DEFAULT_WIDTH_BUCKETS)
        out = jnp.zeros((cap, out_w), jnp.uint8)
        # chars land after the (optional) replacement at their position
        char_off = prefix[:, :w] + matched[:, :w].astype(jnp.int32) * R
        char_tgt = jnp.where(keep_char, char_off, out_w)
        rows_w = jnp.arange(cap)[:, None].repeat(w, 1)
        out = out.at[rows_w, char_tgt].set(
            jnp.where(keep_char, c.chars, 0).astype(jnp.uint8), mode="drop")
        # replacement bytes (static unroll over R)
        rows_w1 = jnp.arange(cap)[:, None].repeat(w + 1, 1)
        for r, byte in enumerate(repl):
            tgt = jnp.where(matched, prefix + r, out_w)
            out = out.at[rows_w1, tgt].set(jnp.uint8(byte), mode="drop")
        validity = c.validity & cols[1].validity & cols[2].validity
        return DeviceColumn(T.STRING, validity, chars=out,
                            lengths=out_len.astype(jnp.int32))


class RegExpExtract(Expression):
    """regexp_extract(str, pattern, idx) with idx == 0 (the whole match);
    capture groups need a backtracking engine and fall back.

    No match -> empty string (Spark)."""

    def __init__(self, s: Expression, pattern: Expression,
                 idx: Expression):
        super().__init__([s, pattern, idx])
        self._dfa = None

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.regex.spans import (
            compile_for_spans,
            match_lengths,
        )

        c = cols[0]
        if self._dfa is None:
            self._dfa = compile_for_spans(str(self.children[1].value))
        w = c.width
        n = c.lengths
        best = match_lengths(self._dfa, c.chars, n)
        has = best >= 0
        first = jnp.argmax(has, axis=1).astype(jnp.int32)
        found = jnp.any(has, axis=1)
        mlen = jnp.where(found,
                         jnp.take_along_axis(best, first[:, None],
                                             axis=1)[:, 0], 0)
        idx = first[:, None] + jnp.arange(w)[None, :]
        keep = jnp.arange(w)[None, :] < mlen[:, None]
        gathered = jnp.take_along_axis(c.chars, jnp.clip(idx, 0, w - 1),
                                       axis=1)
        validity = c.validity & cols[1].validity & cols[2].validity
        return DeviceColumn(T.STRING, validity,
                            chars=jnp.where(keep, gathered, 0).astype(jnp.uint8),
                            lengths=jnp.where(found, mlen, 0).astype(jnp.int32))


def _java_split(rx, s: str, limit: int):
    """Java String.split semantics: limit>0 caps the part count (limit=1
    -> no split at all); limit==0 drops TRAILING empty strings; negative
    limits keep them."""
    if limit == 1:
        return [s]
    parts = rx.split(s, maxsplit=(limit - 1 if limit > 0 else 0))
    if limit == 0:
        while parts and parts[-1] == "":
            parts.pop()
    return parts


class StringSplit(Expression):
    """split(str, regex[, limit]) -> array<string> (3-D char tensor).

    Reference analog: GpuStringSplit via the regex transpiler
    (RegexParser.scala consumers).  Irregular per-row output shapes make
    this a host kernel (like the JSON family); the pattern is validated
    at plan time and translated with the same Java-regex rules the oracle
    uses for RLike."""

    is_host_kernel = True

    def __init__(self, s: Expression, pattern: Expression,
                 limit: Expression = None):
        kids = [s, pattern] + ([limit] if limit is not None else [])
        super().__init__(kids)

    def _resolve_type(self):
        self._dataType = T.ArrayType(T.STRING, containsNull=False)
        self._nullable = True
        from spark_rapids_tpu.expr.base import Literal

        self._pattern = None
        self._limit = -1
        if isinstance(self.children[1], Literal) \
                and self.children[1].value is not None:
            self._pattern = str(self.children[1].value)
        if len(self.children) > 2 and isinstance(self.children[2], Literal) \
                and self.children[2].value is not None:
            self._limit = int(self.children[2].value)

    def do_columnar_eval(self, ctx: EvalContext, cols):
        import re as _re

        import numpy as np

        from spark_rapids_tpu.columnar.column import HostColumn

        c = cols[0]
        n = int(ctx.batch.num_rows)  # eager (host kernel) path
        cap = c.capacity
        host = c.to_host(n)
        vals = host.to_pylist()
        from spark_rapids_tpu.cpu.oracle import _java_regex_to_python

        try:
            rx = _re.compile(_java_regex_to_python(self._pattern))
        except _re.error:
            rx = None
        out = []
        for v in vals:
            if v is None or rx is None:
                out.append(None)
                continue
            out.append(_java_split(rx, v, self._limit))
        h = HostColumn.from_pylist(out, self.dataType)
        from spark_rapids_tpu.columnar.column import DeviceColumn

        return DeviceColumn.from_host(h, capacity=cap)


class ArrayJoin(Expression):
    """array_join(arr, delim[, null_replacement])."""

    is_host_kernel = True

    def __init__(self, arr: Expression, delim: Expression,
                 null_replacement: Expression = None):
        kids = [arr, delim] + ([null_replacement]
                               if null_replacement is not None else [])
        super().__init__(kids)

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        from spark_rapids_tpu.columnar.column import DeviceColumn, HostColumn

        arr, delim = cols[0], cols[1]
        nullrep = cols[2] if len(cols) > 2 else None
        n = int(ctx.batch.num_rows)
        cap = arr.capacity
        rows = arr.to_host(n).to_pylist()
        delims = delim.to_host(n).to_pylist()
        reps = nullrep.to_host(n).to_pylist() if nullrep is not None \
            else [None] * n
        out = []
        for row, d, rep in zip(rows, delims, reps):
            if row is None or d is None:
                out.append(None)
                continue
            parts = [e if e is not None else rep for e in row]
            out.append(d.join(p for p in parts if p is not None))
        h = HostColumn.from_pylist(out, T.STRING)
        return DeviceColumn.from_host(h, capacity=cap)


class RegExpExtractAll(Expression):
    """regexp_extract_all(str, pattern[, idx=0]) -> array<string> of all
    non-overlapping leftmost matches.

    Tag-time contract (checked in overrides): span-safe pattern with
    bounded, non-empty match length (min>=1, max<=MAX_MATCH_LEN) so the
    padded element matrix stays static; rows with more than MAX_MATCHES
    matches raise via the error flags instead of truncating silently."""

    MAX_MATCH_LEN = 32
    MAX_MATCHES = 64

    def __init__(self, s: Expression, pattern: Expression,
                 idx: Expression = None):
        from spark_rapids_tpu.expr.base import Literal

        super().__init__([s, pattern]
                         + ([idx] if idx is not None else
                            [Literal(0, T.INT)]))
        self._dfa = None
        self._bounds = None

    def _resolve_type(self):
        self._dataType = T.ArrayType(T.STRING, containsNull=False)
        self._nullable = True

    def sql_string(self):
        return (f"regexp_extract_all({self.children[0].sql_string()}, "
                f"{self.children[1].sql_string()})")

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.regex.spans import (
            compile_for_spans,
            greedy_match_starts,
            match_lengths,
        )

        c = cols[0]
        if self._dfa is None:
            self._dfa = compile_for_spans(str(self.children[1].value))
        cap, w = c.capacity, c.width
        n = c.lengths
        best = match_lengths(self._dfa, c.chars, n)
        matched, mlen = greedy_match_starts(best, n)
        # positions span [0, w] (a zero-length match may sit at the end);
        # bounded non-empty matches only start inside the string
        nz = (matched & (mlen > 0))[:, :w]
        mlen = mlen[:, :w]
        ecount = jnp.sum(nz, axis=1).astype(jnp.int32)
        maxe = min(self.MAX_MATCHES, max(w, 1))
        ctx.add_error(c.validity & (ecount > maxe),
                      f"regexp_extract_all: more than {self.MAX_MATCHES} "
                      f"matches in one string")
        eidx = (jnp.cumsum(nz.astype(jnp.int32), axis=1) - 1)
        rows = jnp.arange(cap)[:, None].repeat(w, 1)
        tgt = jnp.where(nz, jnp.clip(eidx, 0, maxe - 1), maxe)
        pos = jnp.arange(w, dtype=jnp.int32)[None, :].repeat(cap, 0)
        starts_e = jnp.zeros((cap, maxe), jnp.int32).at[rows, tgt].set(
            pos, mode="drop")
        mlen_e = jnp.zeros((cap, maxe), jnp.int32).at[rows, tgt].set(
            jnp.where(nz, mlen, 0), mode="drop")
        ew = min(self.MAX_MATCH_LEN, max(w, 1))
        k = jnp.arange(ew, dtype=jnp.int32)[None, None, :]
        src = jnp.clip(starts_e[:, :, None] + k, 0, w - 1)
        chars3 = jnp.take_along_axis(
            c.chars[:, None, :].repeat(maxe, 1), src, axis=2)
        inlen = k < mlen_e[:, :, None]
        chars3 = jnp.where(inlen, chars3, 0).astype(jnp.uint8)
        elem_valid = (jnp.arange(maxe, dtype=jnp.int32)[None, :]
                      < ecount[:, None])
        validity = c.validity & cols[1].validity
        return DeviceColumn(self.dataType, validity, chars=chars3,
                            data=mlen_e, lengths=jnp.minimum(ecount, maxe),
                            elem_valid=elem_valid)


class Overlay(Expression):
    """overlay(input, replace, pos[, len]) — 1-based; len<0 means
    length(replace) (Spark default)."""

    def __init__(self, s, r, pos, length=None):
        from spark_rapids_tpu.expr.base import Literal

        super().__init__([s, r, pos]
                         + ([length] if length is not None
                            else [Literal(-1, T.INT)]))

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def sql_string(self):
        return ("overlay("
                + ", ".join(c.sql_string() for c in self.children) + ")")

    def do_columnar_eval(self, ctx, cols):
        s, r, p, ln = cols
        cap = s.capacity
        pos0 = (p.data.astype(jnp.int32) - 1)
        rl = r.lengths
        replen = jnp.where(ln.data.astype(jnp.int32) < 0, rl,
                           ln.data.astype(jnp.int32))
        pre_len = jnp.clip(pos0, 0, s.lengths)
        tail_start = jnp.clip(pos0 + replen, 0, s.lengths)
        tail_len = s.lengths - tail_start
        out_len = pre_len + rl + tail_len
        out_w = int(s.width + r.width)
        from spark_rapids_tpu.columnar.column import (
            DEFAULT_WIDTH_BUCKETS,
            round_up_bucket,
        )

        out_w = round_up_bucket(max(out_w, 1), DEFAULT_WIDTH_BUCKETS)
        pos_o = jnp.arange(out_w, dtype=jnp.int32)[None, :]
        # three segments gathered by source index
        in_pre = pos_o < pre_len[:, None]
        in_rep = ~in_pre & (pos_o < (pre_len + rl)[:, None])
        in_tail = ~in_pre & ~in_rep & (pos_o < out_len[:, None])
        src_s = jnp.where(in_pre, pos_o,
                          jnp.where(in_tail,
                                    pos_o - (pre_len + rl)[:, None]
                                    + tail_start[:, None], 0))
        src_r = jnp.where(in_rep, pos_o - pre_len[:, None], 0)
        sw = max(s.width, 1)
        rw = max(r.width, 1)
        g_s = jnp.take_along_axis(
            s.chars if s.width else jnp.zeros((cap, 1), jnp.uint8),
            jnp.clip(src_s, 0, sw - 1), axis=1)
        g_r = jnp.take_along_axis(
            r.chars if r.width else jnp.zeros((cap, 1), jnp.uint8),
            jnp.clip(src_r, 0, rw - 1), axis=1)
        chars = jnp.where(in_rep, g_r,
                          jnp.where(in_pre | in_tail, g_s, 0))
        validity = s.validity & r.validity & p.validity & ln.validity
        return DeviceColumn(T.STRING, validity,
                            chars=chars.astype(jnp.uint8),
                            lengths=out_len.astype(jnp.int32))


class FindInSet(BinaryExpression):
    """find_in_set(s, comma_list) — 1-based index, 0 when absent or when s
    contains a comma."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        s, lst = cols
        cap = s.capacity
        w = max(lst.width, 1)
        pos = jnp.arange(w, dtype=jnp.int32)[None, :]
        in_l = pos < lst.lengths[:, None]
        lch = jnp.where(in_l, lst.chars, 0) if lst.width else \
            jnp.zeros((cap, 1), jnp.uint8)
        is_comma = (lch == ord(",")) & in_l
        # element id per position (elements are the runs between commas)
        elem = jnp.cumsum(is_comma.astype(jnp.int32), axis=1) - \
            is_comma.astype(jnp.int32)
        rows = jnp.arange(cap)[:, None].repeat(w, 1)
        # per-element char count + first position via scatter-reduce
        maxe = w + 1
        one_hot_src = jnp.where(in_l & ~is_comma, elem, maxe)
        counts = jnp.zeros((cap, maxe + 1), jnp.int32).at[
            rows, jnp.clip(one_hot_src, 0, maxe)].add(
            jnp.where(in_l & ~is_comma, 1, 0), mode="drop")
        counts = counts[:, :maxe]
        first_pos = jnp.full((cap, maxe + 1), w, jnp.int32).at[
            rows, jnp.clip(jnp.where(in_l & ~is_comma, elem, maxe),
                           0, maxe)].min(
            jnp.where(in_l & ~is_comma, pos, w), mode="drop")
        first_pos = first_pos[:, :maxe]
        nelem = jnp.sum(is_comma.astype(jnp.int32), axis=1) + 1
        # compare s against each element (element count = comma count + 1)
        slen = s.lengths
        sw = max(s.width, 1)
        sch = s.chars if s.width else jnp.zeros((cap, 1), jnp.uint8)
        s_has_comma = jnp.any((sch == ord(",")) &
                              (jnp.arange(sw)[None, :] < slen[:, None]),
                              axis=1)
        k = jnp.arange(sw, dtype=jnp.int32)[None, None, :]
        src = jnp.clip(first_pos[:, :, None] + k, 0, w - 1)
        echars = jnp.take_along_axis(lch[:, None, :].repeat(maxe, 1), src,
                                     axis=2)
        want = sch[:, None, :]
        cmp_len = jnp.minimum(counts, slen[:, None])
        eq = jnp.all(jnp.where(k < cmp_len[:, :, None], echars == want,
                               True), axis=2)
        match = eq & (counts == slen[:, None]) & \
            (jnp.arange(maxe, dtype=jnp.int32)[None, :] < nelem[:, None])
        found = jnp.any(match, axis=1)
        idx = jnp.argmax(match, axis=1).astype(jnp.int32) + 1
        res = jnp.where(found & ~s_has_comma, idx, 0)
        return DeviceColumn(T.INT, s.validity & lst.validity, data=res)


class Elt(Expression):
    """elt(n, s1, s2, ...) — 1-based pick; out of range -> null."""

    def __init__(self, children):
        super().__init__(list(children))

    def sql_string(self):
        return "elt(" + ", ".join(c.sql_string() for c in self.children) + ")"

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        n = cols[0]
        opts = cols[1:]
        cap = n.capacity
        w = max(max((c.width for c in opts), default=1), 1)
        from spark_rapids_tpu.expr.predicates import _pad_to

        idx = n.data.astype(jnp.int32)
        chars = jnp.zeros((cap, w), jnp.uint8)
        lengths = jnp.zeros(cap, jnp.int32)
        validity = jnp.zeros(cap, jnp.bool_)
        for k, c in enumerate(opts):
            takes = idx == (k + 1)
            chars = jnp.where(takes[:, None], _pad_to(c.chars, w), chars)
            lengths = jnp.where(takes, c.lengths, lengths)
            validity = jnp.where(takes, c.validity, validity)
        return DeviceColumn(T.STRING, n.validity & validity,
                            chars=chars, lengths=lengths)


class StringSpace(UnaryExpression):
    """space(n) — n spaces (n<0 -> empty).  A literal n sizes the char
    matrix exactly; non-literal n pays the MAX_LEN-wide bucket and rows
    above MAX_LEN raise via the error flags."""

    MAX_LEN = 2048

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.columnar.column import (
            DEFAULT_WIDTH_BUCKETS,
            round_up_bucket,
        )
        from spark_rapids_tpu.expr.base import Literal

        c = cols[0]
        n = jnp.maximum(c.data.astype(jnp.int32), 0)
        if isinstance(self.child, Literal) and self.child.value is not None:
            w_static = round_up_bucket(
                min(max(int(self.child.value), 1), self.MAX_LEN),
                DEFAULT_WIDTH_BUCKETS)
        else:
            w_static = round_up_bucket(self.MAX_LEN, DEFAULT_WIDTH_BUCKETS)
        ctx.add_error(c.validity & (n > w_static),
                      f"space(): length above {self.MAX_LEN}")
        n = jnp.minimum(n, w_static)
        pos = jnp.arange(w_static, dtype=jnp.int32)[None, :]
        chars = jnp.where(pos < n[:, None], jnp.uint8(ord(" ")),
                          jnp.uint8(0))
        return DeviceColumn(T.STRING, c.validity, chars=chars, lengths=n)


class StringTrimLeft(UnaryExpression):
    """ltrim(s) — strips leading spaces (Spark trims 0x20 only)."""

    side = "left"

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        pos = jnp.arange(c.width)[None, :]
        in_str = pos < c.lengths[:, None]
        nonws = in_str & (c.chars != ord(" "))
        any_nonws = jnp.any(nonws, axis=1)
        if self.side == "left":
            first = jnp.where(any_nonws, jnp.argmax(nonws, axis=1), 0)
            out_len = jnp.where(any_nonws, c.lengths - first, 0)
        else:
            first = jnp.zeros(c.capacity, jnp.int32)
            last = jnp.where(
                any_nonws,
                c.width - 1 - jnp.argmax(nonws[:, ::-1], axis=1), -1)
            out_len = (last + 1).astype(jnp.int32)
        idx = first[:, None] + jnp.arange(c.width)[None, :]
        take = jnp.arange(c.width)[None, :] < out_len[:, None]
        gathered = jnp.take_along_axis(
            c.chars, jnp.clip(idx, 0, max(c.width - 1, 0)), axis=1)
        return DeviceColumn(T.STRING, c.validity,
                            chars=jnp.where(take, gathered,
                                            0).astype(jnp.uint8),
                            lengths=out_len.astype(jnp.int32))


class StringTrimRight(StringTrimLeft):
    """rtrim(s)."""

    side = "right"


class Mask(Expression):
    """mask(s[, upper[, lower[, digit[, other]]]]) — literal replacement
    chars; NULL keeps the class, '\\0' sentinel not supported."""

    def __init__(self, s, upper=None, lower=None, digit=None, other=None):
        from spark_rapids_tpu.expr.base import Literal

        def lit_or(v, dflt):
            return v if v is not None else Literal(dflt, T.STRING)

        super().__init__([s, lit_or(upper, "X"), lit_or(lower, "x"),
                          lit_or(digit, "n"),
                          other if other is not None
                          else Literal(None, T.STRING)])

    def sql_string(self):
        return "mask(" + ", ".join(c.sql_string() for c in self.children) + ")"

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]

        def rep_of(i):
            e = self.children[i]
            v = getattr(e, "value", None)
            return None if v is None else ord(str(v)[0])

        up, lo, dg, ot = (rep_of(1), rep_of(2), rep_of(3), rep_of(4))
        ch = c.chars
        out = ch
        is_up = (ch >= ord("A")) & (ch <= ord("Z"))
        is_lo = (ch >= ord("a")) & (ch <= ord("z"))
        is_dg = (ch >= ord("0")) & (ch <= ord("9"))
        if up is not None:
            out = jnp.where(is_up, jnp.uint8(up), out)
        if lo is not None:
            out = jnp.where(is_lo, jnp.uint8(lo), out)
        if dg is not None:
            out = jnp.where(is_dg, jnp.uint8(dg), out)
        if ot is not None:
            out = jnp.where(~(is_up | is_lo | is_dg), jnp.uint8(ot), out)
        return DeviceColumn(T.STRING, c.validity,
                            chars=out.astype(jnp.uint8),
                            lengths=c.lengths)


class ILike(Like):
    """ILIKE — case-insensitive LIKE: ascii-lower BOTH the data and the
    pattern, then the same compiled-literal machinery."""

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        s, p = cols
        lower = jnp.where((s.chars >= ord("A")) & (s.chars <= ord("Z")),
                          s.chars + 32, s.chars).astype(jnp.uint8)
        sl = DeviceColumn(T.STRING, s.validity, chars=lower,
                          lengths=s.lengths)
        low = getattr(self, "_low", None)
        if low is None:
            low = Like(self.children[0],
                       Literal(str(self.right.value).lower(), T.STRING))
            low._dataType = T.BOOLEAN
            low.resolved = True
            if getattr(self, "_compiled", None) is not None:
                low._compiled = self._compiled  # tag-time DFA, reused
            self._low = low
        return low.do_columnar_eval(ctx, [sl, p])


class _RegExpSpanBase(Expression):
    """Shared span scan for regexp_count / regexp_instr / regexp_substr."""

    def __init__(self, s, pattern):
        super().__init__([s, pattern])
        self._dfa = None

    def _spans(self, cols):
        from spark_rapids_tpu.regex.spans import (compile_for_spans,
                                                  greedy_match_starts,
                                                  match_lengths)

        c = cols[0]
        if self._dfa is None:
            self._dfa = compile_for_spans(str(self.children[1].value))
        best = match_lengths(self._dfa, c.chars, c.lengths)
        matched, mlen = greedy_match_starts(best, c.lengths)
        return c, matched, mlen


class RegExpCount(_RegExpSpanBase):
    """regexp_count(s, pattern) — non-overlapping match count."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = True

    def sql_string(self):
        return (f"regexp_count({self.children[0].sql_string()}, "
                f"{self.children[1].sql_string()})")

    def do_columnar_eval(self, ctx, cols):
        c, matched, mlen = self._spans(cols)
        n = jnp.sum((matched & (mlen > 0)).astype(jnp.int32), axis=1)
        return DeviceColumn(T.INT, c.validity & cols[1].validity, data=n)


class RegExpInStr(_RegExpSpanBase):
    """regexp_instr(s, pattern) — 1-based position of the first match,
    0 when absent."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = True

    def sql_string(self):
        return (f"regexp_instr({self.children[0].sql_string()}, "
                f"{self.children[1].sql_string()})")

    def do_columnar_eval(self, ctx, cols):
        c, matched, mlen = self._spans(cols)
        nz = matched & (mlen > 0)
        found = jnp.any(nz, axis=1)
        pos = jnp.argmax(nz, axis=1).astype(jnp.int32) + 1
        return DeviceColumn(T.INT, c.validity & cols[1].validity,
                            data=jnp.where(found, pos, 0))


class RegExpSubStr(_RegExpSpanBase):
    """regexp_substr(s, pattern) — first match, NULL when absent."""

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def sql_string(self):
        return (f"regexp_substr({self.children[0].sql_string()}, "
                f"{self.children[1].sql_string()})")

    def do_columnar_eval(self, ctx, cols):
        c, matched, mlen = self._spans(cols)
        nz = matched & (mlen > 0)
        found = jnp.any(nz, axis=1)
        first = jnp.argmax(nz, axis=1).astype(jnp.int32)
        w = max(c.width, 1)
        ln = jnp.take_along_axis(mlen, first[:, None], axis=1)[:, 0]
        idx = first[:, None] + jnp.arange(w)[None, :]
        keep = jnp.arange(w)[None, :] < ln[:, None]
        g = jnp.take_along_axis(
            c.chars if c.width else jnp.zeros((c.capacity, 1), jnp.uint8),
            jnp.clip(idx, 0, w - 1), axis=1)
        validity = c.validity & cols[1].validity & found
        return DeviceColumn(T.STRING, validity,
                            chars=jnp.where(keep, g, 0).astype(jnp.uint8),
                            lengths=jnp.where(found, ln, 0).astype(jnp.int32))


class SplitPart(Expression):
    """split_part(s, delim, n) — 1-based field between literal delimiters;
    negative n counts from the end; out of range -> empty string."""

    def __init__(self, s, delim, n):
        super().__init__([s, delim, n])

    def sql_string(self):
        return ("split_part("
                + ", ".join(c.sql_string() for c in self.children) + ")")

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        s, d, nn = cols
        delim = str(self.children[1].value).encode()
        L = len(delim)
        cap, w = s.capacity, max(s.width, 1)
        ch = s.chars if s.width else jnp.zeros((cap, 1), jnp.uint8)
        pos = jnp.arange(w)[None, :]
        in_str = pos < s.lengths[:, None]
        # delimiter-start mask (non-overlapping, left to right is implied
        # because fields between delim STARTS are what Spark splits on —
        # overlapping delims only arise for self-overlapping literals,
        # which the tag check rejects)
        hit = jnp.ones((cap, w), jnp.bool_)
        for k, byte in enumerate(delim):
            idx = jnp.clip(pos + k, 0, w - 1)
            ok = jnp.take_along_axis(ch, idx, axis=1) == byte
            ok = ok & (pos + k < s.lengths[:, None])
            hit = hit & ok
        hit = hit & in_str
        field = jnp.cumsum(hit.astype(jnp.int32), axis=1)
        # char belongs to field f unless inside a delimiter occurrence
        in_delim = jnp.zeros((cap, w), jnp.bool_)
        for k in range(L):
            src = pos - k
            ok = (src >= 0)
            h = jnp.take_along_axis(hit, jnp.clip(src, 0, w - 1), axis=1)
            in_delim = in_delim | (h & ok)
        nfields = (jnp.max(jnp.where(in_str, field, 0), axis=1) + 1)
        want = nn.data.astype(jnp.int32)
        want = jnp.where(want < 0, nfields + want + 1, want)
        target = want - 1
        fid = field - hit.astype(jnp.int32)  # delim start counts next field
        sel = in_str & ~in_delim & (fid == target[:, None])
        out_len = jnp.sum(sel, axis=1).astype(jnp.int32)
        # compact selected chars to the left
        tgt = jnp.cumsum(sel.astype(jnp.int32), axis=1) - 1
        rows = jnp.arange(cap)[:, None].repeat(w, 1)
        out = jnp.zeros((cap, w), jnp.uint8).at[
            rows, jnp.where(sel, tgt, w)].set(
            jnp.where(sel, ch, 0), mode="drop")
        # out of range -> EMPTY STRING, not null (Spark split_part)
        ok_range = (want >= 1) & (want <= nfields)
        validity = s.validity & d.validity & nn.validity
        return DeviceColumn(T.STRING, validity,
                            chars=jnp.where(ok_range[:, None], out,
                                            0).astype(jnp.uint8),
                            lengths=jnp.where(ok_range, out_len,
                                              0).astype(jnp.int32))


class Luhn(UnaryExpression):
    """luhn_check(s): Luhn mod-10 checksum validity of a digit string.

    Reference analog: GpuLuhnCheck (sql-plugin stringFunctions; SURVEY.md
    §2.5 Strings).  False for empty strings or any non-digit byte."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = self.child.nullable

    def sql_string(self):
        return f"luhn_check({self.child.sql_string()})"

    def do_columnar_eval(self, ctx, cols):
        s = cols[0]
        cap = s.capacity
        if not s.width:
            return DeviceColumn(T.BOOLEAN, s.validity,
                                data=jnp.zeros(cap, jnp.bool_))
        ch = s.chars.astype(jnp.int32)
        w = s.width
        in_str = jnp.arange(w)[None, :] < s.lengths[:, None]
        digit = (ch >= 0x30) & (ch <= 0x39)
        all_digits = jnp.all(digit | ~in_str, axis=1) & (s.lengths > 0)
        d = jnp.where(in_str & digit, ch - 0x30, 0)
        # position from the right (rightmost = 0); double odd positions
        pos_r = s.lengths[:, None] - 1 - jnp.arange(w)[None, :]
        dbl = (pos_r % 2) == 1
        dd = jnp.where(dbl, d * 2, d)
        dd = jnp.where(dd > 9, dd - 9, dd)
        total = jnp.sum(jnp.where(in_str, dd, 0), axis=1)
        ok = all_digits & (total % 10 == 0)
        return DeviceColumn(T.BOOLEAN, s.validity, data=ok)


class Empty2Null(UnaryExpression):
    """empty string -> NULL (Spark inserts this above Hive text writes)."""

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def sql_string(self):
        return f"empty2null({self.child.sql_string()})"

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(T.STRING, c.validity & (c.lengths > 0),
                            chars=c.chars, lengths=c.lengths)
