"""String expressions over the padded char-matrix layout.

Reference analog: org/apache/spark/sql/rapids/stringFunctions.scala
(GpuSubstring, GpuConcat, GpuUpper/GpuLower, GpuStringTrim, GpuContains,
GpuStartsWith/GpuEndsWith, GpuLength, GpuStringRepeat...).  cuDF implements
these over (chars, offsets); here every op is a dense (rows x width) vector
transform — gathers along the width axis with index arithmetic, which XLA
maps onto the VPU.

Unicode note: Upper/Lower are ASCII-only for now (the reference similarly
documents incompatibilities and hides some behind conf); Length counts UTF-8
*code points* like Spark, computed from the byte patterns.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (
    BinaryExpression,
    EvalContext,
    Expression,
    UnaryExpression,
)
from spark_rapids_tpu.expr.predicates import _pad_to


class Length(UnaryExpression):
    """UTF-8 code-point count (Spark length), not byte count."""

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        pos = jnp.arange(c.width)[None, :]
        in_str = pos < c.lengths[:, None]
        # count bytes that are NOT utf-8 continuation bytes (0b10xxxxxx)
        is_cont = (c.chars & 0xC0) == 0x80
        n = jnp.sum(in_str & ~is_cont, axis=1)
        return DeviceColumn(T.INT, c.validity, data=n.astype(jnp.int32))


class Upper(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def _tx(self, ch):
        return jnp.where((ch >= ord("a")) & (ch <= ord("z")), ch - 32, ch)

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(T.STRING, c.validity,
                            chars=self._tx(c.chars).astype(jnp.uint8),
                            lengths=c.lengths)


class Lower(Upper):
    def _tx(self, ch):
        return jnp.where((ch >= ord("A")) & (ch <= ord("Z")), ch + 32, ch)


class Substring(Expression):
    """substring(str, pos, len) with Spark 1-based / negative pos semantics.

    Byte-based gather; Spark substring is character-based — for ASCII they
    agree.  Non-ASCII correctness comes with the codepoint-index map
    (later round; tagged incompat until then, like the reference's CSV/regex
    caveats)."""

    def __init__(self, s: Expression, pos: Expression, length: Expression):
        super().__init__([s, pos, length])

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def do_columnar_eval(self, ctx: EvalContext, cols):
        c, p, ln = cols
        n = c.lengths
        pos = p.data.astype(jnp.int32)
        # Spark substringSQL: pos>0 -> 1-based; pos<0 -> from end (may land
        # before the start — the window is [start, start+len) computed on the
        # UNclamped start, then clipped, so a negative start eats length)
        start0 = jnp.where(pos > 0, pos - 1,
                           jnp.where(pos < 0, n + pos, 0))
        want = jnp.maximum(ln.data.astype(jnp.int32), 0)
        end0 = start0 + want
        start = jnp.clip(start0, 0, n)
        out_len = jnp.maximum(jnp.clip(end0, 0, n) - start, 0)
        width = c.width
        idx = start[:, None] + jnp.arange(width)[None, :]
        take = jnp.arange(width)[None, :] < out_len[:, None]
        gathered = jnp.take_along_axis(c.chars, jnp.clip(idx, 0, width - 1),
                                       axis=1)
        chars = jnp.where(take, gathered, 0).astype(jnp.uint8)
        validity = c.validity & p.validity & ln.validity
        return DeviceColumn(T.STRING, validity, chars=chars,
                            lengths=out_len.astype(jnp.int32))


class Concat(Expression):
    """concat(s1, s2, ...): null if any input null (Spark)."""

    def __init__(self, children: List[Expression]):
        super().__init__(children)

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = any(c.nullable for c in self.children)

    def do_columnar_eval(self, ctx, cols):
        total_w = sum(c.width for c in cols)
        n = cols[0].capacity
        out = jnp.zeros((n, total_w), jnp.uint8)
        out_len = jnp.zeros(n, jnp.int32)
        validity = cols[0].validity
        for c in cols[1:]:
            validity = validity & c.validity
        for c in cols:
            # scatter c's chars at position out_len per row
            idx = out_len[:, None] + jnp.arange(c.width)[None, :]
            take = jnp.arange(c.width)[None, :] < c.lengths[:, None]
            # build one-hot-ish scatter via take_along_axis on the source side:
            # for each output col j, find source col j - out_len
            src_idx = jnp.arange(total_w)[None, :] - out_len[:, None]
            in_range = (src_idx >= 0) & (src_idx < c.width)
            src = jnp.take_along_axis(
                _pad_to(c.chars, total_w),
                jnp.clip(src_idx, 0, total_w - 1), axis=1)
            write = in_range & (src_idx < c.lengths[:, None])
            out = jnp.where(write, src, out)
            out_len = out_len + c.lengths
            del idx, take
        return DeviceColumn(T.STRING, validity, chars=out, lengths=out_len)


class _FixedCompare(BinaryExpression):
    """contains/startswith/endswith with arbitrary (usually literal) needle."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True


class StartsWith(_FixedCompare):
    def do_columnar_eval(self, ctx, cols):
        s, pre = cols
        w = max(s.width, pre.width)
        a = _pad_to(s.chars, w)
        b = _pad_to(pre.chars, w)
        pos = jnp.arange(w)[None, :]
        relevant = pos < pre.lengths[:, None]
        eq = jnp.all(~relevant | (a == b), axis=1)
        data = eq & (s.lengths >= pre.lengths)
        return DeviceColumn(T.BOOLEAN, s.validity & pre.validity, data=data)


class EndsWith(_FixedCompare):
    def do_columnar_eval(self, ctx, cols):
        s, suf = cols
        w = s.width
        start = s.lengths - suf.lengths
        idx = start[:, None] + jnp.arange(max(suf.width, 1))[None, :]
        gathered = jnp.take_along_axis(
            s.chars, jnp.clip(idx, 0, max(w - 1, 0)), axis=1)
        pos = jnp.arange(max(suf.width, 1))[None, :]
        relevant = pos < suf.lengths[:, None]
        b = suf.chars if suf.width else jnp.zeros_like(gathered)
        eq = jnp.all(~relevant | (gathered == _pad_to(b, gathered.shape[1])),
                     axis=1)
        data = eq & (s.lengths >= suf.lengths)
        return DeviceColumn(T.BOOLEAN, s.validity & suf.validity, data=data)


class Contains(_FixedCompare):
    def do_columnar_eval(self, ctx, cols):
        s, needle = cols
        w = s.width
        nw = max(needle.width, 1)
        # compare needle at every start offset: O(w * nw) vector ops
        matches = jnp.zeros((s.capacity,), jnp.bool_)
        npos = jnp.arange(nw)[None, :]
        relevant = npos < needle.lengths[:, None]
        nchars = needle.chars if needle.width else jnp.zeros((s.capacity, nw), jnp.uint8)
        for start in range(w):
            idx = start + jnp.arange(nw)[None, :]
            seg = jnp.take_along_axis(s.chars, jnp.clip(idx, 0, w - 1), axis=1)
            eq = jnp.all(~relevant | (seg == nchars), axis=1)
            fits = start + needle.lengths <= s.lengths
            matches = matches | (eq & fits)
        return DeviceColumn(T.BOOLEAN, s.validity & needle.validity,
                            data=matches)


class StringTrim(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        pos = jnp.arange(c.width)[None, :]
        in_str = pos < c.lengths[:, None]
        is_ws = (c.chars == ord(" ")) & in_str
        nonws = in_str & ~is_ws
        any_nonws = jnp.any(nonws, axis=1)
        first = jnp.where(any_nonws, jnp.argmax(nonws, axis=1), 0)
        last = jnp.where(any_nonws,
                         c.width - 1 - jnp.argmax(nonws[:, ::-1], axis=1), -1)
        out_len = (last - first + 1).astype(jnp.int32)
        idx = first[:, None] + jnp.arange(c.width)[None, :]
        take = jnp.arange(c.width)[None, :] < out_len[:, None]
        gathered = jnp.take_along_axis(c.chars, jnp.clip(idx, 0, c.width - 1),
                                       axis=1)
        chars = jnp.where(take, gathered, 0).astype(jnp.uint8)
        return DeviceColumn(T.STRING, c.validity, chars=chars, lengths=out_len)


class Like(BinaryExpression):
    """SQL LIKE with literal pattern, compiled at plan time to device ops.

    Reference analog: GpuLike; complex patterns fall back at tag time (the
    regex-transpiler-reject path, SURVEY.md §2.5).  Supported here:
    'abc%', '%abc', '%abc%', exact, and patterns without wildcards; others
    are rejected by the overrides layer (like_pattern_supported)."""

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        s, _ = cols
        pat = self.right
        assert isinstance(pat, Literal), "LIKE pattern must be literal"
        p: str = pat.value
        core = p.strip("%")
        lit_expr = Literal(core, T.STRING)
        needle = lit_expr.eval_tpu(ctx)
        if p.startswith("%") and p.endswith("%") and "%" not in core:
            return Contains(self.left, pat).do_columnar_eval(ctx, [s, needle])
        if p.endswith("%") and "%" not in p[:-1]:
            return StartsWith(self.left, pat).do_columnar_eval(ctx, [s, needle])
        if p.startswith("%") and "%" not in p[1:]:
            return EndsWith(self.left, pat).do_columnar_eval(ctx, [s, needle])
        if "%" not in p and "_" not in p:
            from spark_rapids_tpu.expr.predicates import string_compare

            _, eq = string_compare(s, needle)
            return DeviceColumn(T.BOOLEAN, s.validity, data=eq)
        raise TypeError(f"LIKE pattern {p!r} not supported on TPU")


def like_pattern_supported(p: str) -> bool:
    if "_" in p or "\\" in p:
        return False
    core = p.strip("%")
    return "%" not in core
