"""from_avro / to_avro — per-row Avro binary codec expressions.

Reference analog: the spark-avro connector's AvroDataToCatalyst /
CatalystDataToAvro, which the plugin accelerates via GpuAvroScan-adjacent
paths (SURVEY.md §2.5 JSON/Avro row codecs).  TPU design: the record
codec is a host kernel (one pure_callback over the batch — the same tier
as Crc32/Encode); the surrounding plan stays columnar on device.  The
value codec is io/avro.py's own from-scratch implementation — no
third-party avro dependency.

Supported schemas: flat records of primitive fields (int/long, string,
boolean, float/double) with optional ["null", T] unions — the subset the
tag check admits; anything else falls back by rule.
"""
from __future__ import annotations

import json
from typing import Optional

import jax
import numpy as np

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (Expression, UnaryExpression,
                                        call_host_kernel)
from spark_rapids_tpu.io.avro import (_Reader, _decode_value, _encode_value,
                                      avro_schema_to_struct)


def _schema_of(expr) -> Optional[dict]:
    from spark_rapids_tpu.expr.base import Literal

    if len(expr.children) > 1 and isinstance(expr.children[1], Literal) \
            and expr.children[1].value is not None:
        try:
            return json.loads(str(expr.children[1].value))
        except ValueError:
            return None
    return None


class AvroDataToCatalyst(Expression):
    """from_avro(binary, jsonSchema) -> struct (PERMISSIVE: corrupt rows
    null out, matching the connector's default mode)."""

    is_host_kernel = True

    def __init__(self, child: Expression, json_schema: Expression):
        super().__init__([child, json_schema])

    def _resolve_type(self):
        self._avro_schema = _schema_of(self)
        self._dataType = (avro_schema_to_struct(self._avro_schema)
                          if self._avro_schema else
                          T.StructType([]))
        self._nullable = True

    def sql_string(self):
        return f"from_avro({self.children[0].sql_string()})"

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        cap = c.capacity
        schema = self._avro_schema
        st: T.StructType = self.dataType

        STR_W = 64      # fixed decode width for string fields

        def run(chars, lengths, validity):
            chars = np.asarray(chars)
            lengths = np.asarray(lengths)
            validity = np.asarray(validity)
            ok = np.zeros(cap, np.bool_)
            outs = [ok]
            store = []
            for f in st.fields:
                fv = np.zeros(cap, np.bool_)
                if isinstance(f.dataType, T.StringType):
                    store.append((fv, np.zeros((cap, STR_W), np.uint8),
                                  np.zeros(cap, np.int32)))
                else:
                    store.append((fv, np.zeros(
                        cap, T.storage_dtype(f.dataType))))
            for i in range(cap):
                if not validity[i]:
                    continue
                try:
                    r = _Reader(bytes(chars[i, :lengths[i]]))
                    rec = _decode_value(r, schema)
                except Exception:
                    continue
                ok[i] = True
                for f, parts in zip(st.fields, store):
                    v = rec.get(f.name)
                    if v is None:
                        continue
                    parts[0][i] = True
                    if isinstance(f.dataType, T.StringType):
                        b = str(v).encode("utf-8")[:STR_W]
                        parts[1][i, :len(b)] = np.frombuffer(b, np.uint8)
                        parts[2][i] = len(b)
                    else:
                        parts[1][i] = v
            for parts in store:
                outs.extend(parts)
            return tuple(outs)

        shapes = [jax.ShapeDtypeStruct((cap,), np.bool_)]
        for f in st.fields:
            shapes.append(jax.ShapeDtypeStruct((cap,), np.bool_))
            if isinstance(f.dataType, T.StringType):
                shapes.append(jax.ShapeDtypeStruct((cap, STR_W), np.uint8))
                shapes.append(jax.ShapeDtypeStruct((cap,), np.int32))
            else:
                shapes.append(jax.ShapeDtypeStruct(
                    (cap,), T.storage_dtype(f.dataType)))
        res = call_host_kernel(run, tuple(shapes), c.chars, c.lengths,
                               c.validity)
        ok = res[0]
        kids = []
        k = 1
        for f in st.fields:
            fv = res[k]
            k += 1
            if isinstance(f.dataType, T.StringType):
                ch, ln = res[k], res[k + 1]
                k += 2
                kids.append(DeviceColumn(f.dataType, fv, chars=ch,
                                         lengths=ln))
            else:
                d = res[k]
                k += 1
                kids.append(DeviceColumn(f.dataType, fv, data=d))
        return DeviceColumn(st, c.validity & ok, children=tuple(kids))


class CatalystDataToAvro(Expression):
    """to_avro(struct[, jsonSchema]) -> binary (string column)."""

    is_host_kernel = True

    def __init__(self, child: Expression,
                 json_schema: Optional[Expression] = None):
        super().__init__([child] if json_schema is None
                         else [child, json_schema])

    def _resolve_type(self):
        self._avro_schema = _schema_of(self)
        if self._avro_schema is None:
            st = self.children[0].dataType
            self._avro_schema = {
                "type": "record", "name": "topLevelRecord",
                "fields": [{"name": f.name,
                            "type": [_avro_primitive(f.dataType), "null"]
                            if f.nullable else _avro_primitive(f.dataType)}
                           for f in st.fields]}
        self._dataType = T.STRING
        self._nullable = self.children[0].nullable

    def sql_string(self):
        return f"to_avro({self.children[0].sql_string()})"

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        cap = c.capacity
        st: T.StructType = self.children[0].dataType
        schema = self._avro_schema

        flat = [c.validity]
        field_layout = []
        for kid in c.children:
            flat.append(kid.validity)
            if kid.data is not None:
                flat.append(kid.data)
                field_layout.append(("flat", 2))
            else:
                flat.append(kid.chars)
                flat.append(kid.lengths)
                field_layout.append(("str", 3))
        width = 16
        for f, kid in zip(st.fields, c.children):
            width += (kid.chars.shape[1] + 8) if kid.chars is not None else 12

        def run(*arrs):
            arrs = [np.asarray(a) for a in arrs]
            validity = arrs[0]
            out_chars = np.zeros((cap, width), np.uint8)
            out_lens = np.zeros(cap, np.int32)
            pos = 1
            cols_np = []
            for kind, cnt in field_layout:
                cols_np.append((kind, arrs[pos:pos + cnt]))
                pos += cnt
            for i in range(cap):
                if not validity[i]:
                    continue
                rec = {}
                for (kind, parts), f in zip(cols_np, st.fields):
                    if not parts[0][i]:
                        rec[f.name] = None
                    elif kind == "str":
                        rec[f.name] = bytes(
                            parts[1][i, :parts[2][i]]).decode(
                            "utf-8", "replace")
                    else:
                        v = parts[1][i]
                        if isinstance(f.dataType, T.BooleanType):
                            v = bool(v)
                        elif isinstance(f.dataType,
                                        (T.FloatType, T.DoubleType)):
                            v = float(v)
                        else:
                            v = int(v)
                        rec[f.name] = v
                buf = bytearray()
                _encode_value(buf, schema, rec)
                b = bytes(buf)[:width]
                out_chars[i, :len(b)] = np.frombuffer(b, np.uint8)
                out_lens[i] = len(b)
            return out_chars, out_lens

        shapes = (jax.ShapeDtypeStruct((cap, width), np.uint8),
                  jax.ShapeDtypeStruct((cap,), np.int32))
        och, oln = call_host_kernel(run, shapes, *flat)
        return DeviceColumn(T.STRING, c.validity, chars=och, lengths=oln)


def _avro_primitive(dt) -> str:
    if isinstance(dt, T.BooleanType):
        return "boolean"
    if isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType)):
        return "int"
    if isinstance(dt, T.LongType):
        return "long"
    if isinstance(dt, T.FloatType):
        return "float"
    if isinstance(dt, T.DoubleType):
        return "double"
    if isinstance(dt, T.StringType):
        return "string"
    raise TypeError(f"to_avro: unsupported field type {dt}")
