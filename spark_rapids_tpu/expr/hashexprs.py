"""Hash expressions: hash() (Murmur3) and xxhash64().

Reference analog: GpuMurmur3Hash / GpuXxHash64 (HashFunctions.scala,
SURVEY.md §2.5 hash/misc), backed by spark-rapids-jni murmur_hash.cu /
xxhash64.cu.  Here both are vectorized jnp programs over the columnar
layout (ops/hashing.py); seed-chaining across columns matches Spark's
HashExpression: h = hash(col_i, seed=h), null columns pass the seed.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import Expression
from spark_rapids_tpu.ops.hashing import murmur3_columns, xxhash64_columns


class Murmur3Hash(Expression):
    """hash(c1, c2, ...) -> int32, never null (seed 42)."""

    def __init__(self, children: List[Expression], seed: int = 42):
        super().__init__(children)
        self.seed = seed

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        h = murmur3_columns(cols, seed=self.seed)
        return DeviceColumn(T.INT, jnp.ones(cols[0].capacity, jnp.bool_),
                            data=h)


class XxHash64(Expression):
    """xxhash64(c1, c2, ...) -> int64, never null (seed 42)."""

    def __init__(self, children: List[Expression], seed: int = 42):
        super().__init__(children)
        self.seed = seed

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        h = xxhash64_columns(cols, seed=self.seed)
        return DeviceColumn(T.LONG, jnp.ones(cols[0].capacity, jnp.bool_),
                            data=h)


class BloomFilterMightContain(Expression):
    """might_contain(bloom, value) — probes a bloom_filter_agg result.

    Reference analog: GpuBloomFilterMightContain (spark-rapids-jni
    bloom_filter.cu), the runtime-filter join pushdown probe.  The filter
    is an array<long> of words built by bloom_filter_agg with matching
    (num_items, num_bits); double hashing with xxhash64 seeds 42/77 (layout
    documented in exec/aggregate.TpuHashAggregateExec._eval_bloom — NOT
    byte-compatible with Spark's sketch serialization)."""

    def __init__(self, bloom, value, num_items: int = 4096,
                 num_bits: int = 65536):
        super().__init__([bloom, value])
        self.num_items = int(num_items)
        self.num_bits = int(num_bits)

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        import math as _math

        bloom, v = cols
        k = max(1, round(self.num_bits / self.num_items * _math.log(2)))
        cap = v.capacity
        h1 = xxhash64_columns([v], seed=42)
        h2 = xxhash64_columns([v], seed=77)
        hit = jnp.ones(cap, jnp.bool_)
        ew = max(bloom.ewidth, 1)
        for j in range(k):
            bit = jnp.remainder(h1 + j * h2, self.num_bits)
            word_idx = jnp.clip(bit // 64, 0, ew - 1)
            word = jnp.take_along_axis(bloom.data,
                                       word_idx[:, None], axis=1)[:, 0]
            hit = hit & (jnp.bitwise_and(
                jnp.right_shift(word, bit % 64), 1) == 1)
        validity = bloom.validity & v.validity
        return DeviceColumn(T.BOOLEAN, validity, data=hit)


class HiveHash(Expression):
    """hive_hash(c1, c2, ...) -> int32, never null.

    Reference analog: GpuHiveHash (spark-rapids-jni hive_hash.cu,
    SURVEY.md §2.5): h = 31*h + colHash with Hive's per-type hashes."""

    def __init__(self, children: List[Expression]):
        super().__init__(children)

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = False

    def sql_string(self):
        return ("hive_hash("
                + ", ".join(c.sql_string() for c in self.children) + ")")

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.ops.hashing import hive_hash_columns

        return DeviceColumn(T.INT, jnp.ones(cols[0].capacity, jnp.bool_),
                            data=hive_hash_columns(cols))
