"""Hash expressions: hash() (Murmur3) and xxhash64().

Reference analog: GpuMurmur3Hash / GpuXxHash64 (HashFunctions.scala,
SURVEY.md §2.5 hash/misc), backed by spark-rapids-jni murmur_hash.cu /
xxhash64.cu.  Here both are vectorized jnp programs over the columnar
layout (ops/hashing.py); seed-chaining across columns matches Spark's
HashExpression: h = hash(col_i, seed=h), null columns pass the seed.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import Expression
from spark_rapids_tpu.ops.hashing import murmur3_columns, xxhash64_columns


class Murmur3Hash(Expression):
    """hash(c1, c2, ...) -> int32, never null (seed 42)."""

    def __init__(self, children: List[Expression], seed: int = 42):
        super().__init__(children)
        self.seed = seed

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        h = murmur3_columns(cols, seed=self.seed)
        return DeviceColumn(T.INT, jnp.ones(cols[0].capacity, jnp.bool_),
                            data=h)


class XxHash64(Expression):
    """xxhash64(c1, c2, ...) -> int64, never null (seed 42)."""

    def __init__(self, children: List[Expression], seed: int = 42):
        super().__init__(children)
        self.seed = seed

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        h = xxhash64_columns(cols, seed=self.seed)
        return DeviceColumn(T.LONG, jnp.ones(cols[0].capacity, jnp.bool_),
                            data=h)
