"""User-defined functions on TPU — the RapidsUDF hook.

Reference analog: com.nvidia.spark.RapidsUDF (sql-plugin-api, SURVEY.md
§2.8): a UDF author opts into GPU execution by ALSO implementing
``evaluateColumnar(ColumnVector...)``; GpuOverrides detects the interface
and replaces the row-based UDF, otherwise the UDF stays on CPU with an
explain reason.

TPU counterpart: a ``TpuUDF`` implements

  * ``evaluate_columnar(*cols: DeviceColumn) -> DeviceColumn`` — a
    jax-traceable columnar kernel (runs inside the enclosing stage's jitted
    program, so it fuses with the surrounding expressions); and
  * ``__call__(*scalars) -> scalar`` — the original row-based function,
    which is what the CPU oracle (and Spark) executes.

A plain Python function (no ``evaluate_columnar``) is still usable: the
plan tags the expression ``willNotWorkOnTpu`` and the whole stage falls
back to CPU row evaluation, mirroring the reference's behavior for
un-accelerated UDFs.
"""
from __future__ import annotations

from typing import List

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.base import EvalContext, Expression


class TpuUDF:
    """Base class (optional — duck typing suffices) for TPU-enabled UDFs."""

    def evaluate_columnar(self, *cols):
        raise NotImplementedError

    def __call__(self, *args):
        raise NotImplementedError


def supports_columnar(fn) -> bool:
    m = getattr(fn, "evaluate_columnar", None)
    if not callable(m):
        return False
    # a TpuUDF subclass that only implements __call__ inherits the base's
    # raising stub — that is NOT a columnar implementation
    impl = getattr(type(fn), "evaluate_columnar", None)
    return impl is not TpuUDF.evaluate_columnar


class UserDefinedExpression(Expression):
    """ScalaUDF / GpuScalaUDF analog wrapping a python callable."""

    def __init__(self, fn, children: List[Expression],
                 dataType: T.DataType, name: str = "udf"):
        super().__init__(list(children))
        self.fn = fn
        self._dataType = dataType
        self._nullable = True
        self._name = name

    def sql_string(self):
        args = ", ".join(c.sql_string() for c in self.children)
        return f"{self._name}({args})"

    @property
    def name(self):
        return self._name

    def _resolve_type(self):
        pass  # dataType fixed at construction (like ScalaUDF)

    def do_columnar_eval(self, ctx: EvalContext, cols):
        out = self.fn.evaluate_columnar(*cols)
        if out.dtype != self._dataType:
            raise TypeError(
                f"UDF {self._name} returned {out.dtype.simpleString}, "
                f"declared {self._dataType.simpleString}")
        return out


def udf(fn, return_type: T.DataType, name: str = "udf"):
    """pyspark-flavored helper: udf(fn, T.INT)(col("a"), col("b"))."""

    def make(*children):
        from spark_rapids_tpu.expr.base import Expression, Literal

        kids = [c if isinstance(c, Expression) else Literal.of(c)
                for c in children]
        return UserDefinedExpression(fn, kids, return_type, name)

    return make
