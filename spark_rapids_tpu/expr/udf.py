"""User-defined functions on TPU — the RapidsUDF hook.

Reference analog: com.nvidia.spark.RapidsUDF (sql-plugin-api, SURVEY.md
§2.8): a UDF author opts into GPU execution by ALSO implementing
``evaluateColumnar(ColumnVector...)``; GpuOverrides detects the interface
and replaces the row-based UDF, otherwise the UDF stays on CPU with an
explain reason.

TPU counterpart: a ``TpuUDF`` implements

  * ``evaluate_columnar(*cols: DeviceColumn) -> DeviceColumn`` — a
    jax-traceable columnar kernel (runs inside the enclosing stage's jitted
    program, so it fuses with the surrounding expressions); and
  * ``__call__(*scalars) -> scalar`` — the original row-based function,
    which is what the CPU oracle (and Spark) executes.

A plain Python function (no ``evaluate_columnar``) is still usable: the
plan tags the expression ``willNotWorkOnTpu`` and the whole stage falls
back to CPU row evaluation, mirroring the reference's behavior for
un-accelerated UDFs.
"""
from __future__ import annotations

from typing import List

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.base import EvalContext, Expression


class TpuUDF:
    """Base class (optional — duck typing suffices) for TPU-enabled UDFs."""

    def evaluate_columnar(self, *cols):
        raise NotImplementedError

    def __call__(self, *args):
        raise NotImplementedError


def supports_columnar(fn) -> bool:
    m = getattr(fn, "evaluate_columnar", None)
    if not callable(m):
        return False
    # a TpuUDF subclass that only implements __call__ inherits the base's
    # raising stub — that is NOT a columnar implementation
    impl = getattr(type(fn), "evaluate_columnar", None)
    return impl is not TpuUDF.evaluate_columnar


class UserDefinedExpression(Expression):
    """ScalaUDF / GpuScalaUDF analog wrapping a python callable.

    Three execution tiers, mirroring the reference's UDF ladder:
      1. ``evaluate_columnar`` (TpuUDF / RapidsUDF analog): a jax kernel
         that fuses into the stage's compiled program;
      2. plain python function + ``spark.rapids.sql.python.arrowEval``
         (default): runs INSIDE the TPU plan as a host kernel — batches
         transfer to the host, the function evaluates row-by-row (or
         whole-column when ``vectorized``), results upload back.  The
         GpuArrowEvalPythonExec analog, minus the worker daemon (the
         engine is in-process python already);
      3. otherwise the stage falls back to CPU with an explain reason.
    """

    def __init__(self, fn, children: List[Expression],
                 dataType: T.DataType, name: str = "udf",
                 vectorized: bool = False):
        super().__init__(list(children))
        self.fn = fn
        self._dataType = dataType
        self._nullable = True
        self._name = name
        self.vectorized = vectorized

    @property
    def is_host_kernel(self):  # noqa: D401 - property form of the flag
        return not supports_columnar(self.fn)

    def sql_string(self):
        args = ", ".join(c.sql_string() for c in self.children)
        return f"{self._name}({args})"

    @property
    def name(self):
        return self._name

    def _resolve_type(self):
        pass  # dataType fixed at construction (like ScalaUDF)

    def do_columnar_eval(self, ctx: EvalContext, cols):
        if supports_columnar(self.fn):
            out = self.fn.evaluate_columnar(*cols)
            if out.dtype != self._dataType:
                raise TypeError(
                    f"UDF {self._name} returned {out.dtype.simpleString}, "
                    f"declared {self._dataType.simpleString}")
            return out
        return self._eval_python(ctx, cols)

    def _eval_python(self, ctx: EvalContext, cols):
        """Arrow-eval python path — batches cross to the host, the
        function evaluates, results upload.  is_host_kernel routes the
        enclosing stage through the EAGER (non-jit) path, so row counts
        and buffers are concrete here."""
        import numpy as np

        from spark_rapids_tpu.columnar.column import DeviceColumn

        cap = ctx.batch.capacity
        n = int(ctx.batch.num_rows)
        dt = self._dataType
        host_cols = [c.to_host(n) for c in cols]
        pylists = [h.to_pylist() for h in host_cols]
        if self.vectorized:
            # pandas-style: whole columns in storage representation
            ins = [h.data if h.data is not None
                   else np.array(p, dtype=object)
                   for h, p in zip(host_cols, pylists)]
            res = np.asarray(self.fn(*ins))
            valid_mask = np.ones(n, np.bool_)
            for h in host_cols:
                valid_mask &= h.validity
            results = [res[i].item() if valid_mask[i] else None
                       for i in range(n)]
        else:
            # row-based, nulls passed through as None (Spark semantics;
            # identical to the oracle's _h_udf — no exception swallowing)
            from spark_rapids_tpu.udf_compiler import F, _wants_namespace

            if _wants_namespace(self.fn):
                results = [self.fn(*[p[i] for p in pylists], F)
                           for i in range(n)]
            else:
                results = [self.fn(*[p[i] for p in pylists])
                           for i in range(n)]
        from spark_rapids_tpu.cpu.oracle import _clamp_udf_result
        from spark_rapids_tpu.columnar.column import HostColumn

        results = [_clamp_udf_result(v, dt) for v in results]
        out = HostColumn.from_pylist(results, dt)
        return DeviceColumn.from_host(out, capacity=cap)


def udf(fn, return_type: T.DataType, name: str = "udf"):
    """pyspark-flavored helper: udf(fn, T.INT)(col("a"), col("b"))."""

    def make(*children):
        from spark_rapids_tpu.expr.base import Expression, Literal

        kids = [c if isinstance(c, Expression) else Literal.of(c)
                for c in children]
        return UserDefinedExpression(fn, kids, return_type, name)

    return make
