"""XPath expressions over XML strings.

Reference analog: GpuXPathBoolean/Short/Int/Long/Float/Double/String/List
(sql-plugin xpath expressions backed by spark-rapids-jni's XPath kernel,
SURVEY.md §2.5).  Irregular string processing makes these host kernels
here (like the JSON/split families): batches cross to the host, a
python-XML evaluator applies the path, results upload.

Supported path subset (validated at plan time; matches the common Hive
xpath usage):

    /a/b          child steps from the document root
    //b           descendant search
    /a/*          wildcard child
    /a/b/@attr    attribute value extraction
    /a/b/text()   explicit text nodes
    /a[1]/b       positional predicates (1-based)
    /a/b[@x='v']  attribute-equality predicates

Malformed XML or an unsupported path yields null for that row (the CPU
oracle runs this same evaluator, so differential tests stay exact).
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.base import EvalContext, Expression


def xpath_eval(xml: Optional[str], path: str) -> Optional[List[str]]:
    """Evaluate the path subset; None for malformed XML, else the list of
    matched string values (element text / attribute values)."""
    if xml is None:
        return None
    import xml.etree.ElementTree as ET

    try:
        root = ET.fromstring(xml)
    except ET.ParseError:
        return None
    want_text = False
    attr = None
    p = path.strip()
    if p.endswith("/text()"):
        want_text = True
        p = p[: -len("/text()")]
    else:
        last = p.rsplit("/", 1)[-1]
        if last.startswith("@"):
            attr = last[1:]
            p = p[: -(len(last) + 1)]
    nodes = _match(root, p)
    if nodes is None:
        return None
    out = []
    for nd in nodes:
        if attr is not None:
            if attr in nd.attrib:
                out.append(nd.attrib[attr])
        elif want_text:
            if nd.text is not None and nd.text != "":
                out.append(nd.text)
        else:
            out.append("".join(nd.itertext()))
    return out


def _match(root, p: str):
    """Resolve the element-step part of the path against the root."""
    p = p.strip()
    if p in ("", "/"):
        return [root]
    if p.startswith("//"):
        # descendant search including the root itself
        rest = p[2:]
        first, _, tail = rest.partition("/")
        name, pred = _split_pred(first)
        cands = ([root] if _name_ok(root, name) else []) \
            + [e for e in root.iter() if e is not root
               and _name_ok(e, name)]
        cands = _apply_pred(cands, pred)
        if cands is None:
            return None
        return _steps(cands, tail)
    if p.startswith("/"):
        first, _, tail = p[1:].partition("/")
        name, pred = _split_pred(first)
        if not _name_ok(root, name):
            return []
        sel = _apply_pred([root], pred)
        if sel is None:
            return None
        return _steps(sel, tail)
    # relative path: treat as children of root
    return _steps([root], p)


def _steps(nodes, tail: str):
    while tail:
        step, _, tail = tail.partition("/")
        name, pred = _split_pred(step)
        nxt = []
        for nd in nodes:
            nxt.extend(c for c in list(nd) if _name_ok(c, name))
        nodes = _apply_pred(nxt, pred)
        if nodes is None:
            return None
    return nodes


def _name_ok(e, name: str) -> bool:
    return name == "*" or e.tag == name


def _split_pred(step: str):
    if "[" in step and step.endswith("]"):
        name, _, pred = step.partition("[")
        return name, pred[:-1]
    return step, None


def _apply_pred(nodes, pred: Optional[str]):
    if pred is None:
        return nodes
    pred = pred.strip()
    if pred.isdigit():
        i = int(pred)
        return [nodes[i - 1]] if 1 <= i <= len(nodes) else []
    if pred.startswith("@") and "=" in pred:
        attr, _, val = pred[1:].partition("=")
        val = val.strip().strip("'\"")
        return [n for n in nodes if n.attrib.get(attr.strip()) == val]
    return None  # unsupported predicate -> null rows


class _XPathBase(Expression):
    is_host_kernel = True
    _fname = "xpath"

    def __init__(self, children: List[Expression]):
        super().__init__(list(children))

    def sql_string(self):
        args = ", ".join(c.sql_string() for c in self.children)
        return f"{self._fname}({args})"

    def _path(self) -> Optional[str]:
        from spark_rapids_tpu.expr.base import Literal

        p = self.children[1]
        return str(p.value) if isinstance(p, Literal) \
            and p.value is not None else None

    def _convert(self, matches: Optional[List[str]]):
        raise NotImplementedError

    def do_columnar_eval(self, ctx: EvalContext, cols):
        from spark_rapids_tpu.columnar.column import (DeviceColumn,
                                                      HostColumn)

        c = cols[0]
        cap = c.capacity
        n = int(ctx.batch.num_rows)
        path = self._path()
        vals = c.to_host(n).to_pylist()
        out = [self._convert(xpath_eval(v, path)) if path is not None
               else None for v in vals]
        host = HostColumn.from_pylist(out, self.dataType)
        return DeviceColumn.from_host(host, capacity=cap)


class XPathList(_XPathBase):
    _fname = "xpath"

    def _resolve_type(self):
        self._dataType = T.ArrayType(T.STRING, containsNull=False)
        self._nullable = True

    def _convert(self, m):
        return m


class XPathString(_XPathBase):
    _fname = "xpath_string"

    def _resolve_type(self):
        self._dataType = T.STRING
        self._nullable = True

    def _convert(self, m):
        if m is None:
            return None
        return m[0] if m else None


class XPathBoolean(_XPathBase):
    _fname = "xpath_boolean"

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = True

    def _convert(self, m):
        if m is None:
            return None
        return bool(m)


class _XPathNumeric(_XPathBase):
    def _num(self, m):
        if m is None or not m:
            return None
        try:
            return float(m[0])
        except ValueError:
            return None


class XPathShort(_XPathNumeric):
    _fname = "xpath_short"

    def _resolve_type(self):
        self._dataType = T.SHORT
        self._nullable = True

    def _convert(self, m):
        v = self._num(m)
        if v is None:
            return None
        w = int(v)
        return ((w + 2 ** 15) % 2 ** 16) - 2 ** 15


class XPathInt(_XPathNumeric):
    _fname = "xpath_int"

    def _resolve_type(self):
        self._dataType = T.INT
        self._nullable = True

    def _convert(self, m):
        v = self._num(m)
        if v is None:
            return None
        w = int(v)
        return ((w + 2 ** 31) % 2 ** 32) - 2 ** 31


class XPathLong(_XPathNumeric):
    _fname = "xpath_long"

    def _resolve_type(self):
        self._dataType = T.LONG
        self._nullable = True

    def _convert(self, m):
        v = self._num(m)
        if v is None:
            return None
        w = int(v)
        return ((w + 2 ** 63) % 2 ** 64) - 2 ** 63


class XPathFloat(_XPathNumeric):
    _fname = "xpath_float"

    def _resolve_type(self):
        self._dataType = T.FLOAT
        self._nullable = True

    def _convert(self, m):
        return self._num(m)


class XPathDouble(_XPathNumeric):
    _fname = "xpath_double"

    def _resolve_type(self):
        self._dataType = T.DOUBLE
        self._nullable = True

    def _convert(self, m):
        return self._num(m)
