"""Comparison and boolean predicates with Spark null semantics.

Reference analog: com/nvidia/spark/rapids/predicates (GpuEqualTo, GpuLessThan,
GpuAnd/GpuOr with three-valued logic, GpuNot, GpuIsNull/GpuIsNotNull/GpuIsNan,
GpuInSet, GpuEqualNullSafe).

String ordering: Spark compares strings by UTF-8 byte order.  With the padded
char-matrix layout (padding byte 0x00 sorts before every real byte) plain
row-wise byte comparison yields the right order; equality additionally checks
lengths.  Known limitation (documented): strings containing embedded NUL bytes
may order differently than Spark — matched by a tag-time warning.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.base import (
    BinaryExpression,
    EvalContext,
    Expression,
    UnaryExpression,
)


def _pad_to(chars, width):
    w = chars.shape[1]
    if w >= width:
        return chars[:, :width]
    return jnp.pad(chars, ((0, 0), (0, width - w)))


def string_compare(l: DeviceColumn, r: DeviceColumn):
    """Returns (lt, eq) bool vectors for two string columns."""
    w = max(l.width, r.width)
    a = _pad_to(l.chars, w)
    b = _pad_to(r.chars, w)
    diff = a != b
    any_diff = jnp.any(diff, axis=1)
    # first differing byte position; argmax over bool gives first True
    first = jnp.argmax(diff, axis=1)
    rows = jnp.arange(a.shape[0])
    av = a[rows, first]
    bv = b[rows, first]
    lt = any_diff & (av < bv)
    eq = ~any_diff & (l.lengths == r.lengths)
    # embedded-NUL caveat: padded bytes equal but lengths differ -> shorter lt
    lt = lt | (~any_diff & (l.lengths < r.lengths))
    return lt, eq


def _coerce_comparison(left: Expression, right: Expression):
    """Insert casts so both sides share a comparable type; returns (l, r)."""
    from spark_rapids_tpu.expr.cast import Cast

    lt, rt = left.dataType, right.dataType
    if lt == rt:
        return left, right
    if isinstance(lt, T.DecimalType) and isinstance(rt, T.DecimalType):
        s = max(lt.scale, rt.scale)
        p = max(lt.precision - lt.scale, rt.precision - rt.scale) + s
        common = T.DecimalType(min(p, 38), s)
        return (Cast(left, common).resolve(None),
                Cast(right, common).resolve(None))
    if isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType):
        # promote the non-decimal side to decimal
        from spark_rapids_tpu.expr.arithmetic import _int_as_decimal

        ld = lt if isinstance(lt, T.DecimalType) else _int_as_decimal(lt)
        rd = rt if isinstance(rt, T.DecimalType) else _int_as_decimal(rt)
        l2 = left if lt == ld else Cast(left, ld).resolve(None)
        r2 = right if rt == rd else Cast(right, rd).resolve(None)
        return _coerce_comparison(l2, r2)
    if lt.is_numeric and rt.is_numeric:
        common = T.numeric_promote(lt, rt)
        l2 = left if lt == common else Cast(left, common).resolve(None)
        r2 = right if rt == common else Cast(right, common).resolve(None)
        return l2, r2
    if isinstance(lt, T.StringType) and isinstance(rt, T.DateType):
        return left, Cast(right, T.STRING).resolve(None)
    if isinstance(lt, T.DateType) and isinstance(rt, T.StringType):
        return Cast(left, T.STRING).resolve(None), right
    if isinstance(lt, T.NullType):
        return Cast(left, rt).resolve(None), right
    if isinstance(rt, T.NullType):
        return left, Cast(right, lt).resolve(None)
    raise TypeError(f"cannot compare {lt} with {rt}")


class BinaryComparison(BinaryExpression):
    symbol = "?"

    def sql_string(self):
        return f"({self.left.sql_string()} {self.symbol} {self.right.sql_string()})"

    def _resolve_type(self):
        self.children = list(_coerce_comparison(self.left, self.right))
        self._dataType = T.BOOLEAN
        self._nullable = self.left.nullable or self.right.nullable

    def do_columnar_eval(self, ctx: EvalContext, cols: List[DeviceColumn]):
        l, r = cols
        validity = l.validity & r.validity
        if l.is_string:
            lt, eq = string_compare(l, r)
            data = self._from_lt_eq(lt, eq)
        elif l.is_dec128:
            from spark_rapids_tpu.expr import decimal128 as D

            ah, al = D.unpack(l.data)
            bh, bl = D.unpack(r.data)
            data = self._from_lt_eq(D.lt128(ah, al, bh, bl),
                                    D.eq128(ah, al, bh, bl))
        else:
            data = self._cmp(l.data, r.data)
        return DeviceColumn(T.BOOLEAN, validity, data=data)

    def _cmp(self, a, b):
        raise NotImplementedError

    def _from_lt_eq(self, lt, eq):
        raise NotImplementedError


class EqualTo(BinaryComparison):
    symbol = "="

    def _cmp(self, a, b):
        return a == b

    def _from_lt_eq(self, lt, eq):
        return eq


class LessThan(BinaryComparison):
    symbol = "<"

    def _cmp(self, a, b):
        return a < b

    def _from_lt_eq(self, lt, eq):
        return lt


class LessThanOrEqual(BinaryComparison):
    symbol = "<="

    def _cmp(self, a, b):
        return a <= b

    def _from_lt_eq(self, lt, eq):
        return lt | eq


class GreaterThan(BinaryComparison):
    symbol = ">"

    def _cmp(self, a, b):
        return a > b

    def _from_lt_eq(self, lt, eq):
        return ~(lt | eq)


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="

    def _cmp(self, a, b):
        return a >= b

    def _from_lt_eq(self, lt, eq):
        return ~lt


class EqualNullSafe(BinaryComparison):
    """<=> : null <=> null is true; never returns null."""

    symbol = "<=>"

    def _resolve_type(self):
        super()._resolve_type()
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        l, r = cols
        both_valid = l.validity & r.validity
        both_null = ~l.validity & ~r.validity
        if l.is_string:
            _, eq = string_compare(l, r)
        elif l.is_dec128:
            eq = jnp.all(l.data == r.data, axis=-1)
        else:
            eq = l.data == r.data
        data = (both_valid & eq) | both_null
        return DeviceColumn(T.BOOLEAN, jnp.ones_like(data), data=data)


class And(BinaryExpression):
    """Three-valued AND: false AND null = false."""

    def sql_string(self):
        return f"({self.left.sql_string()} AND {self.right.sql_string()})"

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = self.left.nullable or self.right.nullable

    def do_columnar_eval(self, ctx, cols):
        l, r = cols
        lv, rv = l.validity, r.validity
        ld = l.data & lv  # treat null as "unknown", compute definite values
        rd = r.data & rv
        definite_false = (lv & ~l.data) | (rv & ~r.data)
        data = ld & rd
        validity = (lv & rv) | definite_false
        return DeviceColumn(T.BOOLEAN, validity, data=data & ~definite_false)


class Or(BinaryExpression):
    """Three-valued OR: true OR null = true."""

    def sql_string(self):
        return f"({self.left.sql_string()} OR {self.right.sql_string()})"

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = self.left.nullable or self.right.nullable

    def do_columnar_eval(self, ctx, cols):
        l, r = cols
        lv, rv = l.validity, r.validity
        definite_true = (lv & l.data) | (rv & r.data)
        validity = (lv & rv) | definite_true
        data = definite_true | ((l.data & lv) | (r.data & rv))
        return DeviceColumn(T.BOOLEAN, validity, data=data)


class Not(UnaryExpression):
    def sql_string(self):
        return f"(NOT {self.child.sql_string()})"

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = self.child.nullable

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(T.BOOLEAN, c.validity, data=~c.data)


class IsNull(UnaryExpression):
    def sql_string(self):
        return f"({self.child.sql_string()} IS NULL)"

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(T.BOOLEAN, jnp.ones_like(c.validity),
                            data=~c.validity)


class IsNotNull(UnaryExpression):
    def sql_string(self):
        return f"({self.child.sql_string()} IS NOT NULL)"

    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        return DeviceColumn(T.BOOLEAN, jnp.ones_like(c.validity),
                            data=c.validity)


class IsNaN(UnaryExpression):
    def _resolve_type(self):
        self._dataType = T.BOOLEAN
        self._nullable = False

    def do_columnar_eval(self, ctx, cols):
        c = cols[0]
        data = jnp.isnan(c.data) & c.validity
        return DeviceColumn(T.BOOLEAN, jnp.ones_like(c.validity), data=data)


class In(Expression):
    """value IN (list-of-literals); Spark null semantics: null if value is
    null, or if no match and the list contains a null."""

    def __init__(self, value: Expression, candidates: List[Expression]):
        super().__init__([value] + list(candidates))

    def sql_string(self):
        cands = ", ".join(c.sql_string() for c in self.children[1:])
        return f"({self.children[0].sql_string()} IN ({cands}))"

    def _resolve_type(self):
        # coerce every candidate to a common comparable type with the value
        # (Spark's ImplicitTypeCasts; without this a decimal128 column would
        # compare raw unscaled limbs against differently-scaled candidates)
        from spark_rapids_tpu.expr.base import Literal

        value = self.children[0]
        new_cands = []
        for c in self.children[1:]:
            if isinstance(c, Literal) and c.value is None:
                new_cands.append(c)
                continue
            value, c2 = _coerce_comparison(value, c)
            new_cands.append(c2)
        # a late value-side promotion must be re-applied to earlier candidates
        final = []
        for c in new_cands:
            if (isinstance(c, Literal) and c.value is None) \
                    or c.dataType == value.dataType:
                final.append(c)
            else:
                _, c2 = _coerce_comparison(value, c)
                final.append(c2)
        self.children = [value] + final
        self._dataType = T.BOOLEAN
        self._nullable = True

    def do_columnar_eval(self, ctx, cols):
        from spark_rapids_tpu.expr.base import Literal

        v = cols[0]
        cands = cols[1:]
        any_match = jnp.zeros(v.capacity, jnp.bool_)
        # null-ness of candidates is a plan-time fact (literals)
        any_null_cand = any(
            isinstance(c, Literal) and c.value is None
            for c in self.children[1:])
        for expr, c in zip(self.children[1:], cands):
            if isinstance(expr, Literal) and expr.value is None:
                continue
            if v.is_string:
                _, eq = string_compare(v, c)
            elif v.is_dec128:
                eq = jnp.all(v.data == c.data, axis=-1)
            else:
                eq = v.data == c.data
            any_match = any_match | (eq & c.validity)
        validity = v.validity
        if any_null_cand:
            validity = validity & any_match  # no match + null cand -> null
        return DeviceColumn(T.BOOLEAN, validity, data=any_match)
