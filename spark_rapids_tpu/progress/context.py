"""Progress context — the ONLY module the hot paths import.

One piece of ambient state: ``TRACKER`` — the process-wide
:class:`~spark_rapids_tpu.progress.tracker.ProgressTracker` (or None).
Like ``diagnostics.context.RECORDER`` it is deliberately a plain module
attribute, not a contextvar: background pool threads (AOT compile,
scan prefetch, shuffle writers) attribute their wall to the owning
query through it, and a contextvar would silently lose their deltas.
Unlike the diagnostics recorder the tracker is MULTI-query: it holds
one live :class:`QueryProgress` per in-flight lifecycle query, which is
what makes an 8-way stress run legible while it is happening.

Disabled-path contract (the ISSUE 3 pattern): every instrumentation
site performs exactly ONE ambient check — ``if CTX.TRACKER is None``
(an attribute read, not a call) — before doing any other Python work.
tests/test_progress.py pins it with cProfile: a collect with
``spark.rapids.tpu.progress.enabled=false`` makes ZERO calls into any
``progress/`` module.

Written only by ``progress.ensure_tracker`` / ``progress.shutdown``
under ``_TRACKER_LOCK``; read lock-free from hot paths.
"""
from __future__ import annotations

TRACKER = None


def active():
    """The active tracker or None (one ambient check)."""
    return TRACKER
