"""ProgressTracker — the process-global live view of in-flight queries.

One :class:`QueryProgress` exists per lifecycle-managed ``collect()``
whose conf enables ``spark.rapids.tpu.progress.enabled``; the exec
layer's batch-pull wrapper (``exec/base._progress``) advances the
owning operator's row/batch/byte counts on every pull, background pools
(AOT compile, scan prefetch, shuffle writers) attribute their wall to
the owning query by id, and the watchdog's stall scan runs here.

Percent-complete and ETA come from joining the live counts against the
PR 8 cost model at registration time:

* per operator — rows produced / plan-predicted rows
  (``aot_output_rows``) when the plan can predict the output, else
  accumulated pull wall / calibrated predicted self wall
  (``profiling.model.QueryPrediction``), else unknown; a finished
  operator is 1.0 and an unfinished one is capped at 0.99, so progress
  is MONOTONE (counts only grow and the caps only release on finish).
* per query — predicted-wall-weighted mean of the known operator
  percentages; ETA is the predicted remaining wall
  ``sum(predicted_self_wall * (1 - pct))`` when predictions exist,
  else an elapsed-time extrapolation once the query is >5% complete.

Ownership discipline (the cross-attribution contract pinned by
tests/test_progress.py): an operator advance counts ONLY when the
exec node's registration stamp (``_prog_qid``) matches the pulling
thread's ambient ``lifecycle`` QueryContext — a concurrent collect of
a shared cached exec tree, or a stamp left behind by a finished query,
attributes nowhere rather than to the wrong query.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from spark_rapids_tpu.lifecycle.context import CURRENT as _QCTX

# unfinished operators cap below 1.0: only StopIteration proves done
_PCT_CAP = 0.99


class OpProgress:
    """Per-operator live accumulation."""

    __slots__ = ("path", "name", "describe", "batches", "rows", "bytes",
                 "wall_ns", "predicted_rows", "predicted_wall_ns",
                 "started_ns", "last_advance_ns", "finished")

    def __init__(self, path: str, name: str, describe: str):
        self.path = path
        self.name = name
        self.describe = describe
        self.batches = 0
        self.rows = 0
        self.bytes = 0
        self.wall_ns = 0
        self.predicted_rows: Optional[int] = None
        self.predicted_wall_ns = 0.0
        self.started_ns: Optional[int] = None
        self.last_advance_ns: Optional[int] = None
        self.finished = False

    def pct(self) -> Optional[float]:
        if self.finished:
            return 1.0
        if self.predicted_rows:
            return min(self.rows / self.predicted_rows, _PCT_CAP)
        if self.predicted_wall_ns > 0:
            return min(self.wall_ns / self.predicted_wall_ns, _PCT_CAP)
        return None


class QueryProgress:
    """Everything tracked for one live query."""

    __slots__ = ("query_id", "diag_qid", "started_ns", "stall_ms",
                 "ops", "op_order", "pull_stack", "background",
                 "last_activity_ns", "stall_flagged", "stalls",
                 "status", "finished", "finished_ns",
                 "predicted_total_wall_ns", "stamp_lost")

    def __init__(self, query_id: str, stall_ms: float,
                 diag_qid: Optional[str]):
        self.query_id = query_id
        self.diag_qid = diag_qid
        self.started_ns = time.monotonic_ns()
        self.stall_ms = float(stall_ms)
        self.ops: Dict[str, OpProgress] = {}
        self.op_order: List[str] = []
        # innermost in-flight pull last: the operator actually doing
        # the work when the query wedges (the exec chain is driven by
        # ONE thread, so a plain stack is exact)
        self.pull_stack: List[str] = []
        # kind -> {"wall_ns": int, "events": int} for background pools
        self.background: Dict[str, Dict[str, int]] = {}
        self.last_activity_ns: Optional[int] = None
        self.stall_flagged = False
        self.stalls = 0
        self.status = "running"
        self.finished = False
        self.finished_ns: Optional[int] = None
        self.predicted_total_wall_ns = 0.0
        # a LATER register() of the same cached plan root overwrote
        # this query's ownership stamps: its pulls now attribute
        # nowhere (by design), so the stall detector must not misread
        # the frozen activity clock as a wedge
        self.stamp_lost = False

    # caller holds the tracker lock for everything below -----------------
    def pct_locked(self) -> Optional[float]:
        num = den = 0.0
        uniform: List[float] = []
        for st in self.ops.values():
            p = st.pct()
            if p is None:
                continue
            if st.predicted_wall_ns > 0:
                num += st.predicted_wall_ns * p
                den += st.predicted_wall_ns
            uniform.append(p)
        if den > 0:
            return num / den
        if uniform:
            return sum(uniform) / len(uniform)
        return None

    def eta_ns_locked(self, now_ns: int) -> Optional[int]:
        if self.finished:
            return 0
        rem = 0.0
        have_pred = False
        for st in self.ops.values():
            if st.predicted_wall_ns > 0:
                have_pred = True
                rem += st.predicted_wall_ns * (1.0 - (st.pct() or 0.0))
        if have_pred:
            return int(rem)
        pct = self.pct_locked()
        if pct is not None and pct > 0.05:
            elapsed = now_ns - self.started_ns
            return int(elapsed * (1.0 - pct) / pct)
        return None

    def stuck_op_locked(self) -> Optional[OpProgress]:
        if self.pull_stack:
            return self.ops.get(self.pull_stack[-1])
        return None


class ProgressTracker:
    """The process-global registry of live (and recently finished)
    query progress states.  All mutation happens under one lock; the
    per-batch enabled-path cost is two short lock acquisitions per pull
    (begin/end), the same order of cost as the diagnostics recorder's
    span bookkeeping."""

    def __init__(self, max_finished: int = 32):
        self._lock = threading.Lock()
        self._queries: Dict[str, QueryProgress] = {}
        self._finished: deque = deque(maxlen=max(int(max_finished), 1))

    def set_max_finished(self, max_finished: int) -> None:
        """Resize the finished ring to the latest conf (keeps the
        newest entries when shrinking)."""
        n = max(int(max_finished), 1)
        with self._lock:
            if self._finished.maxlen != n:
                self._finished = deque(self._finished, maxlen=n)

    # -- registration ----------------------------------------------------
    def register(self, qctx, root, stall_ms: float = 0.0,
                 prediction=None, diag_qid: Optional[str] = None) -> None:
        """Walk the planned exec tree: stamp every TpuExec with this
        query's ownership (``_prog_qid``/``_prog_path``), create its
        live stat bucket, and join the PR 8 prediction (per-operator
        predicted self wall) plus the plan-side row estimate
        (``aot_output_rows``) for percent/ETA rendering."""
        from spark_rapids_tpu.exec.base import TpuExec

        qp = QueryProgress(qctx.query_id, stall_ms, diag_qid)
        pred_by_path = prediction.by_path() if prediction is not None else {}
        prior_qids = set()

        def walk(node, path):
            prior = getattr(node, "_prog_qid", None)
            if prior is not None and prior != qp.query_id:
                prior_qids.add(prior)
            node._prog_qid = qp.query_id
            node._prog_path = path
            st = OpProgress(path, node.node_name, node.describe())
            try:
                rows = node.aot_output_rows()
                if rows:
                    st.predicted_rows = int(sum(rows))
            except Exception:
                st.predicted_rows = None
            p = pred_by_path.get(path)
            if p is not None and p.matched != "miss":
                st.predicted_wall_ns = float(p.predicted_self_wall_ns)
                qp.predicted_total_wall_ns += st.predicted_wall_ns
            qp.ops[path] = st
            qp.op_order.append(path)
            for i, c in enumerate(node.children):
                if isinstance(c, TpuExec):
                    walk(c, f"{path}.{i}")

        walk(root, "0")
        with self._lock:
            # a concurrent collect of the SAME cached plan root: the
            # earlier query's stamps are gone, so its activity clock
            # freezes — exempt it from stall detection (a false
            # "wedged" alarm for a query making normal progress)
            for prior in prior_qids:
                live = self._queries.get(prior)
                if live is not None and not live.finished:
                    live.stamp_lost = True
            self._queries[qp.query_id] = qp

    def mark_untracked(self, query_id: str) -> None:
        """The query left the tracked execution path but is still
        running (whole-query CPU-oracle fallback): its batch pulls stop
        and the activity clock freezes BY DESIGN, so exempt it from
        stall detection instead of flagging a query that is actively
        completing on the CPU."""
        with self._lock:
            qp = self._queries.get(query_id)
            if qp is not None:
                qp.stamp_lost = True

    def finish_query(self, query_id: str, status: str = "ok") -> None:
        """Move a query to the finished ring and emit the ``progress``
        diagnostics summary event into its own recorder (still open:
        this runs inside the query's diagnostics scope)."""
        now = time.monotonic_ns()
        with self._lock:
            qp = self._queries.pop(query_id, None)
            if qp is None:
                return
            qp.finished = True
            qp.finished_ns = now
            qp.status = status
            for st in qp.ops.values():
                if status == "ok":
                    st.finished = True
            self._finished.append(qp)
            snap = self._snapshot_one_locked(qp, now)
        self._emit_progress_event(qp, snap)

    def _emit_progress_event(self, qp: QueryProgress, snap: Dict) -> None:
        try:
            from spark_rapids_tpu.diagnostics import context as _DIAG

            rec = _DIAG.RECORDER
            if rec is not None and qp.diag_qid is not None \
                    and rec.query_id == qp.diag_qid:
                rec.progress_summary(
                    query_id=qp.query_id,
                    pct=snap.get("pct"),
                    eta_ns=snap.get("eta_ns"),
                    stalls=qp.stalls,
                    background={k: dict(v)
                                for k, v in qp.background.items()})
        except Exception:
            # progress must never fail (or re-order) a finishing query
            pass

    # -- the hot path (exec/base._progress) ------------------------------
    def begin_pull(self, op):
        """Start one batch pull; returns an opaque handle or None when
        the pull must run untracked (no ambient query, or the node's
        stamp belongs to a different query than the pulling thread's —
        the cross-attribution guard)."""
        ctx = _QCTX.get()
        if ctx is None:
            return None
        qid = getattr(op, "_prog_qid", None)
        if qid != ctx.query_id:
            return None
        path = getattr(op, "_prog_path", None)
        t0 = time.monotonic_ns()
        with self._lock:
            qp = self._queries.get(qid)
            if qp is None:
                return None
            st = qp.ops.get(path)
            if st is None:
                return None
            if st.started_ns is None:
                st.started_ns = t0
            qp.pull_stack.append(path)
            return (qp, st, t0)

    def end_pull(self, handle, rows: Optional[int], nbytes: int,
                 finished: bool) -> None:
        qp, st, t0 = handle
        now = time.monotonic_ns()
        with self._lock:
            if qp.pull_stack and qp.pull_stack[-1] == st.path:
                qp.pull_stack.pop()
            elif st.path in qp.pull_stack:
                qp.pull_stack.remove(st.path)
            st.wall_ns += now - t0
            if finished:
                st.finished = True
            elif rows is not None:
                st.batches += 1
                st.rows += rows
                st.bytes += nbytes
            st.last_advance_ns = now
            qp.last_activity_ns = now
            # an advance ends the current stall episode; the detector
            # re-arms and a LATER wedge reports as a fresh stall
            qp.stall_flagged = False

    # -- background attribution ------------------------------------------
    def add_background(self, query_id: Optional[str], kind: str,
                       wall_ns: int, n: int = 1) -> None:
        """Attribute ``wall_ns`` of pool-thread work (AOT compile, scan
        prefetch upload, shuffle-write serialization) to the owning
        query — its wall shows up under that query, not nowhere.  A
        job whose owner already finished attributes to the finished
        snapshot if still retained, else drops silently."""
        if not query_id:
            return
        now = time.monotonic_ns()
        with self._lock:
            qp = self._queries.get(query_id)
            if qp is None:
                qp = next((f for f in reversed(self._finished)
                           if f.query_id == query_id), None)
            if qp is None:
                return
            b = qp.background.setdefault(kind, {"wall_ns": 0, "events": 0})
            b["wall_ns"] += int(wall_ns)
            b["events"] += int(n)
            if not qp.finished:
                qp.last_activity_ns = now
                qp.stall_flagged = False

    # -- stall detection (lifecycle/watchdog.py) -------------------------
    def scan_stalls(self, now_ns: int) -> List[Dict[str, Any]]:
        """One watchdog-period scan: flag every live query whose
        configured ``progress.stallMs`` elapsed with NO operator
        advance (and no background attribution), bump
        ``stalls_detected``, emit the ``query_stall`` diagnostics event
        naming the stuck operator, and trigger a flight-recorder
        post-mortem embedding the live progress snapshot.  Never
        raises: a broken emission path must not kill the watchdog."""
        stalled = []
        with self._lock:
            for qp in self._queries.values():
                if qp.finished or qp.stall_ms <= 0 or qp.stall_flagged \
                        or qp.stamp_lost:
                    continue
                last = qp.last_activity_ns or qp.started_ns
                stalled_ms = (now_ns - last) / 1e6
                if stalled_ms < qp.stall_ms:
                    continue
                qp.stall_flagged = True
                qp.stalls += 1
                stuck = qp.stuck_op_locked()
                stalled.append({
                    "query_id": qp.query_id,
                    "diag_qid": qp.diag_qid,
                    "stalled_ms": stalled_ms,
                    "path": stuck.path if stuck is not None else "",
                    "name": stuck.name if stuck is not None else "",
                })
        for s in stalled:
            self._report_stall(s)
        return stalled

    def _report_stall(self, s: Dict[str, Any]) -> None:
        try:
            from spark_rapids_tpu import perfcounters as PC

            PC.bump("stalls_detected")
            detail = (f"no operator advanced for {s['stalled_ms']:.0f}ms "
                      f"(spark.rapids.tpu.progress.stallMs); stuck in "
                      f"{s['name'] or '(no in-flight operator)'}"
                      + (f" at {s['path']}" if s["path"] else ""))
            from spark_rapids_tpu.diagnostics import context as _DIAG

            rec = _DIAG.RECORDER
            if rec is not None and s["diag_qid"] is not None \
                    and rec.query_id == s["diag_qid"]:
                rec.query_stall(s["query_id"], s["path"], s["name"],
                                s["stalled_ms"], detail)
            from spark_rapids_tpu.telemetry import context as _TEL

            hub = _TEL.HUB
            if hub is not None:
                hub.record_event("query_stall", query_id=s["query_id"],
                                 op=s["name"], path=s["path"],
                                 stalled_ms=round(s["stalled_ms"], 1))
                hub.postmortem("query_stall", query_id=s["query_id"],
                               detail=detail, claim_query=False)
        except Exception:
            # stall REPORTING is best-effort; the watchdog loop (and
            # the query itself) must survive any telemetry failure
            pass

    # -- snapshots --------------------------------------------------------
    def _snapshot_one_locked(self, qp: QueryProgress,
                             now_ns: int) -> Dict[str, Any]:
        end = qp.finished_ns if qp.finished else now_ns
        last = qp.last_activity_ns or qp.started_ns
        stuck = qp.stuck_op_locked()
        eta_ns = qp.eta_ns_locked(now_ns)
        ops = []
        for path in qp.op_order:
            st = qp.ops[path]
            ops.append({
                "path": st.path, "name": st.name,
                "describe": st.describe,
                "batches": st.batches, "rows": st.rows,
                "bytes": st.bytes,
                "wall_ms": round(st.wall_ns / 1e6, 3),
                "pct": st.pct(),
                "predicted_rows": st.predicted_rows,
                "predicted_wall_ms": round(
                    st.predicted_wall_ns / 1e6, 3),
                "finished": st.finished,
                "in_flight": path in qp.pull_stack,
                "last_advance_ms_ago": (
                    None if st.last_advance_ns is None
                    else round((end - st.last_advance_ns) / 1e6, 1)),
            })
        return {
            "query_id": qp.query_id,
            "diag_qid": qp.diag_qid,
            "status": qp.status,
            "elapsed_ms": round((end - qp.started_ns) / 1e6, 3),
            "pct": qp.pct_locked(),
            "eta_ns": eta_ns,
            "eta_ms": None if eta_ns is None else round(eta_ns / 1e6, 1),
            "predicted_wall_ms": round(
                qp.predicted_total_wall_ns / 1e6, 3),
            "stalls": qp.stalls,
            "stalled": qp.stall_flagged,
            "stamp_lost": qp.stamp_lost,
            "last_advance_ms_ago": round((now_ns - last) / 1e6, 1),
            "stuck_op": (None if stuck is None else
                         {"path": stuck.path, "name": stuck.name}),
            "operators": ops,
            "background": {k: dict(v) for k, v in qp.background.items()},
        }

    def snapshot(self, include_finished: bool = True) -> List[Dict]:
        """The live view: one dict per in-flight query (plus recently
        finished ones), newest last.  Counted by ``progress_snapshots``
        — the surface the /progress endpoint and ``session.progress()``
        serve."""
        from spark_rapids_tpu import perfcounters as PC

        PC.bump("progress_snapshots")
        now = time.monotonic_ns()
        with self._lock:
            # newest last by REGISTRATION TIME — unpadded "q<n>" ids
            # sort lexicographically (q10 < q2), not chronologically
            pairs = [(qp.started_ns, self._snapshot_one_locked(qp, now))
                     for qp in self._queries.values()]
            if include_finished:
                pairs.extend((qp.started_ns,
                              self._snapshot_one_locked(qp, now))
                             for qp in self._finished)
        pairs.sort(key=lambda p: p[0])
        return [snap for _, snap in pairs]

    def snapshot_for(self, query_id: str) -> Optional[Dict]:
        """One query's snapshot (live or recently finished) — what the
        flight-recorder bundle embeds."""
        now = time.monotonic_ns()
        with self._lock:
            qp = self._queries.get(query_id)
            if qp is None:
                qp = next((f for f in reversed(self._finished)
                           if f.query_id == query_id), None)
            if qp is None:
                return None
            return self._snapshot_one_locked(qp, now)

    def aggregate_stats(self) -> Dict[str, float]:
        """Peek-only per-tick aggregates for the telemetry sampler:
        queries running, min/median percent-complete, stalled count."""
        with self._lock:
            pcts = []
            stalled = 0
            n = 0
            for qp in self._queries.values():
                if qp.finished:
                    continue
                n += 1
                if qp.stall_flagged:
                    stalled += 1
                p = qp.pct_locked()
                if p is not None:
                    pcts.append(p)
        pcts.sort()
        return {
            "progress_queries_running": float(n),
            "progress_min_pct": pcts[0] if pcts else 0.0,
            "progress_median_pct": (pcts[len(pcts) // 2]
                                    if pcts else 0.0),
            "progress_stalled": float(stalled),
        }

    def clear(self) -> None:
        """Test hook: drop every live and finished state."""
        with self._lock:
            self._queries.clear()
            self._finished.clear()
