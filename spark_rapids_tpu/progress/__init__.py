"""Live query introspection (ISSUE 12): per-operator progress + ETA,
causal attribution of background work, and heartbeat stall detection —
what makes an in-flight query (and an 8-way stress run) legible while
it is happening instead of only after it finishes or the watchdog
kills it.

Reference analog: Spark's UI + history server show live per-stage task
progress for the reference plugin, and scheduler-layer work (Theseus,
arXiv:2508.05029; Presto+GPU, arXiv:2606.24647) consumes exactly these
live per-operator signals.  This package is the substrate:

  context.py — the ambient TRACKER slot (ONE attribute read on every
               hot path; None = disabled = zero progress calls)
  tracker.py — ProgressTracker / QueryProgress / OpProgress: live
               counts, cost-model joins (pct/ETA), background
               attribution, the watchdog stall scan, snapshots

Surfaces: ``TpuSession.progress()`` / ``spark_rapids_tpu.progress.
snapshot()``, live ``df.explain("analyze")`` for an in-flight query,
``GET /progress`` on the telemetry HTTP endpoint, per-tick aggregate
gauges in the telemetry sampler, and ``tools/history.py`` — the query
history server over the rotating diagnostics event logs.

Overhead contract: with ``spark.rapids.tpu.progress.enabled=false``
(the default) a collect makes ZERO calls into this package — every
call site gates on the conf or the ambient ``context.TRACKER``
attribute before importing anything here (tests/test_progress.py pins
it with cProfile, the diagnostics/telemetry/profiling methodology).

This ``__init__`` is deliberately lazy (the diagnostics pattern): the
hot paths import only ``progress.context`` — so this module must not
pull ``tracker`` in at import time; it loads on the first ENABLED
query.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from spark_rapids_tpu.progress import context as CTX

_TRACKER_LOCK = threading.Lock()


def ensure_tracker(max_finished: int = 32):
    """Idempotent process-global install (called by the first enabled
    collect): later queries reuse the tracker for the process's life —
    multi-query by design, unlike the one-recorder diagnostics slot.
    The finished-ring retention honors the LATEST conf (a later
    session's ``progress.maxFinished`` resizes, not silently
    ignores)."""
    with _TRACKER_LOCK:
        if CTX.TRACKER is None:
            from spark_rapids_tpu.progress.tracker import ProgressTracker

            CTX.TRACKER = ProgressTracker(max_finished=max_finished)
        else:
            CTX.TRACKER.set_max_finished(max_finished)
        return CTX.TRACKER


def get_tracker():
    return CTX.TRACKER


def shutdown() -> None:
    """Clear the tracker slot (tests / process teardown); the next
    enabled collect rebuilds."""
    with _TRACKER_LOCK:
        CTX.TRACKER = None


def snapshot(include_finished: bool = True) -> List[Dict]:
    """The live multi-query snapshot ('' when progress is off) — what
    ``session.progress()`` and the /progress endpoint serve."""
    trk = CTX.TRACKER
    return trk.snapshot(include_finished) if trk is not None else []


def snapshot_for(query_id: str) -> Optional[Dict]:
    trk = CTX.TRACKER
    return trk.snapshot_for(query_id) if trk is not None else None


def _fmt_pct(p: Optional[float]) -> str:
    return "   ?%" if p is None else f"{p * 100:4.0f}%"


def render_snapshot(snap: Dict) -> str:
    """One query's snapshot as the live operator table — the text
    ``df.explain("analyze")`` shows for an in-flight query."""
    eta = snap.get("eta_ms")
    lines = [
        f"query {snap['query_id']}"
        + (f" (diagnostics {snap['diag_qid']})" if snap.get("diag_qid")
           else "")
        + f"  status={snap['status']}"
        + f"  elapsed={snap['elapsed_ms']:.0f}ms"
        + f"  pct={_fmt_pct(snap.get('pct')).strip()}"
        + (f"  eta≈{eta:.0f}ms" if eta is not None else "  eta=?")
        + (f"  STALLED (no advance for "
           f"{snap['last_advance_ms_ago']:.0f}ms)"
           if snap.get("stalled") else ""),
    ]
    stuck = snap.get("stuck_op")
    if stuck is not None:
        lines.append(f"  in flight: {stuck['name']} @ {stuck['path']}")
    lines.append("  path     op                              pct  "
                 "batches       rows   wall_ms  last_advance")
    for op in snap.get("operators", []):
        last = op.get("last_advance_ms_ago")
        lines.append(
            f"  {op['path']:<8} {op['name']:<30} "
            f"{_fmt_pct(op.get('pct'))}  "
            f"{op['batches']:>7} {op['rows']:>10} "
            f"{op['wall_ms']:>9.1f}  "
            + ("never" if last is None else f"{last:.0f}ms ago")
            + ("  <- in flight" if op.get("in_flight") else ""))
    bg = snap.get("background") or {}
    if bg:
        lines.append("  background (attributed to this query):")
        for kind in sorted(bg):
            b = bg[kind]
            lines.append(f"    {kind:<18} {b['events']:>5} events  "
                         f"{b['wall_ns'] / 1e6:>9.1f}ms")
    return "\n".join(lines)


__all__ = [
    "ensure_tracker", "get_tracker", "render_snapshot", "shutdown",
    "snapshot", "snapshot_for",
]
