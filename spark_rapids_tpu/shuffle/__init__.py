"""Shuffle subsystem (SURVEY.md §2.7).

Reference analogs: RapidsShuffleInternalManagerBase (the shuffle manager
shell with MULTITHREADED / UCX / CACHE_ONLY modes), GpuColumnarBatchSerializer
+ the Kudo concat-friendly serialization format, ShuffleBufferCatalog, and
GpuShuffleEnv.

TPU mapping:
  * MULTITHREADED — batches are serialized host-side in the concat-friendly
    wire format (serializer.py, the Kudo analog) by a writer thread pool and
    reassembled by the reader with one cheap multi-block concat.  This is
    the mode that works everywhere, like the reference's default.
  * ICI — device-resident all-to-all over the TPU interconnect via XLA
    collectives (parallel/mesh.py) — the UCX-transport replacement: no
    peer-to-peer pull, the pod slice is the network.
  * CACHE_ONLY — batches stay device-resident in the block store (useful for
    single-process pipelines and tests).
"""
from spark_rapids_tpu.shuffle.manager import (
    TpuShuffleManager,
    get_shuffle_manager,
)
from spark_rapids_tpu.shuffle.serializer import (
    ShuffleCorruption,
    deserialize_concat,
    serialize_batch,
)

__all__ = ["TpuShuffleManager", "get_shuffle_manager", "serialize_batch",
           "deserialize_concat", "ShuffleCorruption"]
