"""Concat-friendly columnar wire format — the Kudo serializer analog.

Reference analog: spark-rapids-jni KudoSerializer + GpuColumnarBatchSerializer
(SURVEY.md §2.7): the shuffle write path serializes device batches into a
layout whose whole point is that the *reader* can assemble many partition
blocks into one batch cheaply (one pass, no per-row work), because a shuffle
read concatenates hundreds of small map-side slices.

Layout (little-endian):

    magic  b"TKU1"
    u32    header_len
    bytes  header (msgpack-less: utf-8 JSON {num_rows, cols:[...]})
    buffers back to back, 8-byte aligned, in header order

Per column the header records kind (flat/string), the numpy dtype string,
string width, and each buffer's (offset, length).  Validity is bit-packed
(1 bit/row — this is wire format, where bytes are precious; in HBM validity
is a bool vector, see columnar/column.py).  Padding rows are dropped at
serialize time and re-created at deserialize time, so shuffle bytes scale
with logical rows, not capacity buckets.

The optional codec (zstd/zlib) compresses the whole frame; `lz4` (the
reference's default) aliases to zstd since this image has no lz4 binding.

deserialize_concat() is the Kudo trick: allocates each output column once
across all blocks and fills sequentially — O(total bytes) regardless of how
many blocks the read assembles.
"""
from __future__ import annotations

import json
import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (
    DEFAULT_ROW_BUCKETS,
    DeviceColumn,
    round_up_bucket,
)

MAGIC = b"TKU2"
_MAGIC_V1 = b"TKU1"   # pre-checksum frames (no CRC word)


class ShuffleCorruption(RuntimeError):
    """A shuffle block failed its integrity check (frame CRC32 mismatch,
    bad magic, or a codec that rejected the bytes).  Deterministic by
    classification — re-reading the same block re-derives the same
    corruption, so the fault domain falls the stage back to the CPU
    oracle instead of retrying."""


def _codec_pair(codec: Optional[str]):
    c = (codec or "none").lower()
    if c in ("none", "uncompressed"):
        return (lambda b: b), (lambda b: b)
    if c in ("zstd", "lz4"):  # lz4 aliases to zstd (no lz4 binding in image)
        try:
            import zstandard
        except ImportError:
            # degrade to stdlib zlib instead of failing every exchange at
            # runtime on images without the zstandard wheel (writer and
            # reader resolve the codec through this same gate, so both
            # sides of a shuffle agree within a process)
            import zlib

            return (lambda b: zlib.compress(b, 1)), zlib.decompress

        cctx = zstandard.ZstdCompressor(level=1)
        dctx = zstandard.ZstdDecompressor()
        return cctx.compress, dctx.decompress
    if c == "zlib":
        import zlib

        return (lambda b: zlib.compress(b, 1)), zlib.decompress
    raise ValueError(f"unknown shuffle codec {codec}")


def _align8(n: int) -> int:
    return (n + 7) & ~7


def serialize_batch(batch: ColumnarBatch, codec: Optional[str] = None) -> bytes:
    """Device batch -> wire bytes (host).  Drops capacity padding."""
    import jax

    n = batch.num_rows
    header_cols = []
    buffers: List[bytes] = []
    offset = 0

    def add_buffer(raw: bytes) -> Tuple[int, int]:
        nonlocal offset
        off = offset
        buffers.append(raw)
        pad = _align8(len(raw)) - len(raw)
        if pad:
            buffers.append(b"\0" * pad)
        offset += _align8(len(raw))
        return off, len(raw)

    # one LOGICAL host sync for the whole batch: sync_get routes the
    # pytree fetch through sync_event, so host_syncs counts this as a
    # single round trip instead of one per materialized leaf
    from spark_rapids_tpu.perfcounters import sync_get

    host_cols = sync_get(
        [(c.validity, c.data, c.chars, c.lengths, c.elem_valid)
         for c in batch.columns])
    for c, (validity, data, chars, lengths, elem_valid) in zip(
            batch.columns, host_cols):
        validity = np.asarray(validity)[:n]
        vbuf = add_buffer(np.packbits(validity, bitorder="little").tobytes())
        if c.is_array:
            # padded list column: per-row element counts + ragged element
            # data/validity (padding elements never travel, like strings)
            lengths = np.asarray(lengths)[:n].astype(np.int32)
            ew = int(lengths.max()) if n else 0
            data = np.asarray(data)[:n]
            ev = np.asarray(elem_valid)[:n]
            take = np.arange(data.shape[1])[None, :] < lengths[:, None]
            flat = np.ascontiguousarray(data[take])
            flat_ev = np.packbits(ev[take], bitorder="little")
            lbuf = add_buffer(lengths.tobytes())
            dbuf = add_buffer(flat.tobytes())
            ebuf = add_buffer(flat_ev.tobytes())
            header_cols.append({
                "kind": "array", "dtype": data.dtype.str, "ewidth": ew,
                "validity": vbuf, "lengths": lbuf, "data": dbuf,
                "elem_valid": ebuf})
        elif c.is_string:
            from spark_rapids_tpu.native import padded_to_ragged

            lengths = np.asarray(lengths)[:n]
            width = int(lengths.max()) if n else 0
            chars_np = np.ascontiguousarray(np.asarray(chars)[:n])
            # ragged wire layout (Kudo-style): padding bytes never travel
            packed, _ = padded_to_ragged(chars_np, lengths)
            lbuf = add_buffer(lengths.astype(np.int32).tobytes())
            cbuf = add_buffer(packed.tobytes())
            header_cols.append({
                "kind": "string", "width": width,
                "validity": vbuf, "lengths": lbuf, "chars": cbuf})
        else:
            data = np.ascontiguousarray(np.asarray(data)[:n])
            dbuf = add_buffer(data.tobytes())
            header_cols.append({
                "kind": "flat", "dtype": data.dtype.str,
                "trail": list(data.shape[1:]),
                "validity": vbuf, "data": dbuf})
    header = json.dumps({"num_rows": n, "cols": header_cols}).encode()
    # integrity checksum (ISSUE 4 satellite): the CRC32 of everything
    # after the checksum word rides in the frame; the reader verifies
    # before trusting a single offset, so a flipped bit anywhere —
    # host store, disk overflow file, decompressor — surfaces as a
    # deterministic ShuffleCorruption instead of silent wrong results
    payload = b"".join([struct.pack("<I", len(header)), header] + buffers)
    import zlib

    frame = b"".join([MAGIC, struct.pack("<I", zlib.crc32(payload)),
                      payload])
    comp, _ = _codec_pair(codec)
    return comp(frame)


def _parse(frame: bytes):
    if frame[:4] == _MAGIC_V1:
        # legacy checksum-less frame: parse without verification
        body_off = 4
    elif frame[:4] == MAGIC:
        import zlib

        (want,) = struct.unpack_from("<I", frame, 4)
        got = zlib.crc32(frame[8:])
        if got != want:
            raise ShuffleCorruption(
                f"shuffle frame CRC mismatch: wrote {want:#010x}, "
                f"read {got:#010x} over {len(frame) - 8} bytes")
        body_off = 8
    else:
        raise ShuffleCorruption(
            f"bad shuffle frame magic {frame[:4]!r}")
    (hlen,) = struct.unpack_from("<I", frame, body_off)
    header = json.loads(frame[body_off + 4: body_off + 4 + hlen].decode())
    body = frame[body_off + 4 + hlen:]
    return header, body


def _decode_frame(block: bytes, decomp) -> tuple:
    """Decompress + parse one wire block; codec-level rejections (a
    flipped bit in the compressed stream) surface as the same typed
    corruption error as a CRC mismatch."""
    try:
        frame = decomp(block)
    except Exception as e:
        raise ShuffleCorruption(
            f"shuffle block failed to decompress: "
            f"{type(e).__name__}: {e}") from e
    return _parse(frame)


def deserialize_concat(blocks: Sequence[bytes], schema: T.StructType,
                       codec: Optional[str] = None,
                       row_buckets=DEFAULT_ROW_BUCKETS) -> ColumnarBatch:
    """Assemble many wire blocks into ONE padded device batch.

    The concat-friendly read: per column one output allocation, blocks
    copied in sequentially, a single host->device upload at the end."""
    import jax.numpy as jnp

    _, decomp = _codec_pair(codec)
    parsed = [_decode_frame(b, decomp) for b in blocks]
    total = sum(h["num_rows"] for h, _ in parsed)
    cap = round_up_bucket(max(total, 1), row_buckets)
    out_cols: List[DeviceColumn] = []
    for ci, f in enumerate(schema.fields):
        validity = np.zeros(cap, dtype=np.bool_)
        is_string = isinstance(f.dataType, T.StringType)
        is_array = isinstance(f.dataType, T.ArrayType)
        if is_string:
            width = max([h["cols"][ci]["width"] for h, _ in parsed] + [1])
            chars = np.zeros((cap, width), dtype=np.uint8)
            lengths = np.zeros(cap, dtype=np.int32)
        elif is_array:
            ew = max([h["cols"][ci]["ewidth"] for h, _ in parsed] + [1])
            sdt = np.dtype(T.storage_dtype(f.dataType.elementType))
            data = np.zeros((cap, ew), dtype=sdt)
            ev = np.zeros((cap, ew), dtype=np.bool_)
            lengths = np.zeros(cap, dtype=np.int32)
        else:
            sdt = np.dtype(T.storage_dtype(f.dataType))
            trail = tuple(parsed[0][0]["cols"][ci].get("trail", ())
                          ) if parsed else ()
            data = np.zeros((cap,) + trail, dtype=sdt)
        row = 0
        for h, body in parsed:
            n = h["num_rows"]
            col = h["cols"][ci]
            voff, vlen = col["validity"]
            vbits = np.frombuffer(body, np.uint8, count=vlen, offset=voff)
            validity[row: row + n] = np.unpackbits(
                vbits, count=n, bitorder="little").astype(np.bool_)
            if is_array:
                loff, llen = col["lengths"]
                lens = np.frombuffer(body, np.int32, count=n, offset=loff)
                lengths[row: row + n] = lens
                total_e = int(lens.sum())
                doff, dlen = col["data"]
                flat = np.frombuffer(body, np.dtype(col["dtype"]),
                                     count=total_e, offset=doff)
                eoff, elen = col["elem_valid"]
                ebits = np.frombuffer(body, np.uint8, count=elen,
                                      offset=eoff)
                flat_ev = np.unpackbits(ebits, count=total_e,
                                        bitorder="little").astype(np.bool_)
                take = (np.arange(ew)[None, :]
                        < lens.astype(np.int32)[:, None])
                dview = data[row: row + n]
                evview = ev[row: row + n]
                dview[take] = flat
                evview[take] = flat_ev
                row += n
                continue
            if is_string:
                loff, llen = col["lengths"]
                lens = np.frombuffer(body, np.int32, count=n, offset=loff)
                lengths[row: row + n] = lens
                w = col["width"]
                if w:
                    from spark_rapids_tpu.native import ragged_to_padded

                    coff, clen = col["chars"]
                    packed = np.frombuffer(body, np.uint8, count=clen,
                                           offset=coff)
                    offs = np.zeros(n + 1, np.int64)
                    np.cumsum(lens, out=offs[1:])
                    chars[row: row + n, :w] = ragged_to_padded(
                        packed, offs, w)[:, :w]
            else:
                doff, dlen = col["data"]
                k = int(np.prod(trail)) if trail else 1
                data[row: row + n] = np.frombuffer(
                    body, np.dtype(col["dtype"]), count=n * k, offset=doff
                ).reshape((n,) + trail)
            row += n
        if is_string:
            out_cols.append(DeviceColumn(
                f.dataType, jnp.asarray(validity),
                chars=jnp.asarray(chars), lengths=jnp.asarray(lengths)))
        elif is_array:
            out_cols.append(DeviceColumn(
                f.dataType, jnp.asarray(validity), data=jnp.asarray(data),
                lengths=jnp.asarray(lengths), elem_valid=jnp.asarray(ev)))
        else:
            out_cols.append(DeviceColumn(
                f.dataType, jnp.asarray(validity), data=jnp.asarray(data)))
    return ColumnarBatch(out_cols, total, schema)
