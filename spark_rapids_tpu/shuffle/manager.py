"""TpuShuffleManager — the shuffle manager shell.

Reference analog: RapidsShuffleInternalManagerBase / GpuShuffleEnv /
ShuffleBufferCatalog (SURVEY.md §2.7): per-shuffle registration, a writer
that serializes partition slices (thread pool in MULTITHREADED mode), a
block store mapping (shuffle, map, partition) -> block, and a reader that
fetches a partition's blocks and assembles them into batches.

TPU adaptation: blocks live in a host block store (the netty shuffle file
analog — memory-backed, overflowing to the spill dir); CACHE_ONLY keeps
device batches resident (no serialization); ICI mode is the mesh all-to-all
(parallel/mesh.py) used when executing over a device mesh.
"""
from __future__ import annotations

import concurrent.futures as cf
import itertools
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import (
    SHUFFLE_COMPRESSION_CODEC,
    SHUFFLE_MODE,
    SHUFFLE_MT_WRITER_THREADS,
    SPILL_DIR,
    TpuConf,
    conf,
)
from spark_rapids_tpu.shuffle.serializer import (
    deserialize_concat,
    serialize_batch,
)

SHUFFLE_HOST_STORE_LIMIT = conf(
    "spark.rapids.shuffle.hostStoreSize").doc(
    "Host memory for shuffle blocks before they overflow to disk files "
    "(the netty shuffle-file analog).").bytes_conf(1 << 31)


class _BlockStore:
    """Host block store with disk overflow (ShuffleBufferCatalog analog)."""

    def __init__(self, limit: int, spill_dir: Optional[str]):
        self._blocks: Dict[Tuple[int, int, int], bytes] = {}
        self._files: Dict[Tuple[int, int, int], str] = {}
        self._bytes = 0
        self.limit = limit
        self.spill_dir = spill_dir
        self._lock = threading.Lock()

    def put(self, key: Tuple[int, int, int], blob: bytes) -> None:
        with self._lock:
            if self._bytes + len(blob) > self.limit:
                if self.spill_dir is None:
                    self.spill_dir = tempfile.mkdtemp(prefix="srt_shuffle_")
                os.makedirs(self.spill_dir, exist_ok=True)
                path = os.path.join(
                    self.spill_dir,
                    f"shuffle_{key[0]}_{key[1]}_{key[2]}.blk")
                with open(path, "wb") as f:
                    f.write(blob)
                self._files[key] = path
            else:
                self._blocks[key] = blob
                self._bytes += len(blob)

    def get(self, key: Tuple[int, int, int]) -> Optional[bytes]:
        with self._lock:
            if key in self._blocks:
                return self._blocks[key]
            path = self._files.get(key)
        if path is not None:
            with open(path, "rb") as f:
                return f.read()
        return None

    def keys_for_partition(self, shuffle_id: int,
                           pid: int) -> List[Tuple[int, int, int]]:
        with self._lock:
            ks = [k for k in itertools.chain(self._blocks, self._files)
                  if k[0] == shuffle_id and k[2] == pid]
        return sorted(ks)

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            for k in [k for k in self._blocks if k[0] == shuffle_id]:
                self._bytes -= len(self._blocks.pop(k))
            for k in [k for k in self._files if k[0] == shuffle_id]:
                try:
                    os.unlink(self._files.pop(k))
                except OSError:
                    pass


class TpuShuffleManager:
    def __init__(self, tpu_conf: TpuConf):
        self.mode = tpu_conf.get(SHUFFLE_MODE).upper()
        self.codec = tpu_conf.get(SHUFFLE_COMPRESSION_CODEC)
        self.writer_threads = tpu_conf.get(SHUFFLE_MT_WRITER_THREADS)
        self.store = _BlockStore(tpu_conf.get(SHUFFLE_HOST_STORE_LIMIT),
                                 tpu_conf.get(SPILL_DIR))
        self._device_store: Dict[Tuple[int, int, int], ColumnarBatch] = {}
        # PROCESS-unique ids (ISSUE 14): a shuffle-conf change rebuilds
        # the manager, and a restarted per-instance counter would hand a
        # new query an id an in-flight query (or a remote worker store)
        # still holds — the distributed tier keys cross-process state by
        # these ids, so reuse would mix queries' partitions
        self._next_shuffle = _shuffle_ids
        self._pool: Optional[cf.ThreadPoolExecutor] = None
        self._lock = threading.Lock()
        # lifecycle bookkeeping (ISSUE 4): live shuffle ids + the query
        # that registered each, so query-end cleanup can drop what a
        # mid-batch unwind left behind and the leak gate can see the rest
        self._owners: Dict[int, Optional[str]] = {}
        # metrics
        self.bytes_written = 0
        self.blocks_written = 0

    def _get_pool(self) -> cf.ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = cf.ThreadPoolExecutor(
                    max_workers=self.writer_threads,
                    thread_name_prefix="shuffle-writer")
            return self._pool

    def register_shuffle(self) -> int:
        from spark_rapids_tpu.lifecycle.context import current

        sid = next(self._next_shuffle)
        ctx = current()
        with self._lock:
            self._owners[sid] = ctx.query_id if ctx is not None else None
        return sid

    def active_shuffles(self) -> List[int]:
        with self._lock:
            return sorted(self._owners)

    def unregister_owned(self, query_id: str) -> int:
        """Query-end cleanup: drop every registration the given query
        left behind; returns how many were dropped."""
        with self._lock:
            victims = [sid for sid, q in self._owners.items()
                       if q == query_id]
        for sid in victims:
            self.unregister_shuffle(sid)
        return len(victims)

    # -- write side ------------------------------------------------------
    def write_map_output(self, shuffle_id: int, map_id: int,
                         slices: List[ColumnarBatch]) -> None:
        """Write one map task's partition slices (pid = index)."""
        from spark_rapids_tpu.lifecycle.context import (
            current,
            current_token,
        )
        from spark_rapids_tpu.progress import context as PROG_CTX

        token = current_token()   # captured HERE: pool threads have no
        if token is not None:     # query contextvar of their own
            token.check()
        # progress attribution (ISSUE 12): like the token, the owning
        # query id is captured on the submitting thread so pool-side
        # serialization wall lands under the right query
        ctx = current()
        owner_qid = ctx.query_id if ctx is not None else None
        if self.mode == "CACHE_ONLY":
            for pid, b in enumerate(slices):
                if b is not None and b.num_rows > 0:
                    self._device_store[(shuffle_id, map_id, pid)] = b
            return
        # MULTITHREADED: serialize each non-empty slice on the pool
        pool = self._get_pool()

        def job(pid: int, batch: ColumnarBatch):
            # cooperative cancellation: a cancelled query's queued
            # serialization jobs bail instead of burning the pool
            if token is not None:
                token.check()
            if PROG_CTX.TRACKER is None or owner_qid is None:
                blob = serialize_batch(batch, codec=self.codec)
                self.store.put((shuffle_id, map_id, pid), blob)
                return len(blob)
            t0 = time.perf_counter_ns()
            blob = serialize_batch(batch, codec=self.codec)
            self.store.put((shuffle_id, map_id, pid), blob)
            PROG_CTX.TRACKER.add_background(
                owner_qid, "shuffle_write",
                time.perf_counter_ns() - t0)
            return len(blob)

        futures = [pool.submit(job, pid, b) for pid, b in enumerate(slices)
                   if b is not None and b.num_rows > 0]
        try:
            for f in futures:
                n = f.result()
                # under _lock: concurrent queries share this singleton
                # manager, and += is a non-atomic read-modify-write
                with self._lock:
                    self.bytes_written += n
                    self.blocks_written += 1
        except BaseException:
            for f in futures:
                f.cancel()
            # drain in-flight jobs before unwinding: a straggler's
            # store.put() landing AFTER query-end cleanup unregistered
            # this shuffle would leak its block in the singleton store
            # forever (the id is gone from the owner map, so no leak
            # report would ever see it)
            cf.wait(futures)
            raise

    # -- read side -------------------------------------------------------
    def read_partition(self, shuffle_id: int, pid: int,
                       schema: T.StructType) -> Optional[ColumnarBatch]:
        """Assemble one reduce partition from all map outputs."""
        from spark_rapids_tpu.lifecycle.context import check_cancel

        check_cancel()
        if self.mode == "CACHE_ONLY":
            batches = [b for k, b in sorted(self._device_store.items())
                       if k[0] == shuffle_id and k[2] == pid]
            if not batches:
                return None
            return (batches[0] if len(batches) == 1
                    else ColumnarBatch.concat(batches))
        keys = self.store.keys_for_partition(shuffle_id, pid)
        blocks = [self.store.get(k) for k in keys]
        blocks = [b for b in blocks if b is not None]
        if not blocks:
            return None
        return deserialize_concat(blocks, schema, codec=self.codec)

    def unregister_shuffle(self, shuffle_id: int) -> None:
        self.store.remove_shuffle(shuffle_id)
        for k in [k for k in self._device_store if k[0] == shuffle_id]:
            del self._device_store[k]
        # ISSUE 14: a distributed exchange registered under this id
        # placed partitions on REMOTE workers — unregistering (directly,
        # or via the query-end unregister_owned sweep) must release
        # those too, or the leak outlives the query on another process.
        # Peek only: cleanup must never build a coordinator.
        from spark_rapids_tpu.distributed import peek_coordinator

        coord = peek_coordinator()
        if coord is not None:
            coord.release_exchange(shuffle_id)
        with self._lock:
            self._owners.pop(shuffle_id, None)


_lock = threading.Lock()
_manager: Optional[TpuShuffleManager] = None
_manager_key = None
# shared by every manager generation — see TpuShuffleManager.__init__
_shuffle_ids = itertools.count()


def get_shuffle_manager(tpu_conf: Optional[TpuConf] = None) -> TpuShuffleManager:
    """GpuShuffleEnv analog: process-wide manager, rebuilt when the shuffle
    configs change."""
    global _manager, _manager_key
    with _lock:
        if tpu_conf is None:
            if _manager is None:
                _manager = TpuShuffleManager(TpuConf())
            return _manager
        key = (tpu_conf.get(SHUFFLE_MODE), tpu_conf.get(SHUFFLE_COMPRESSION_CODEC),
               tpu_conf.get(SHUFFLE_MT_WRITER_THREADS))
        if _manager is None or key != _manager_key:
            _manager = TpuShuffleManager(tpu_conf)
            _manager_key = key
        return _manager


def peek_shuffle_manager() -> Optional[TpuShuffleManager]:
    """The singleton if it exists — cleanup/leak paths must never CREATE
    one."""
    return _manager


def reset_shuffle_manager() -> None:
    global _manager, _manager_key
    with _lock:
        _manager = None
        _manager_key = None
