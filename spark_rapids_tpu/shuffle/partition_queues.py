"""Spill-backed exchange partition queues (ISSUE 10).

Reference analog: RapidsShuffleInternalManagerBase's block store plus
SpillableColumnarBatch (SURVEY.md §2.3/§2.7) — but organized the way the
out-of-core exchange consumes them: one queue per reduce partition,
appended map-side slice by slice, drained partition by partition.

Residency discipline: slices up to a conf'd device budget stay resident
as :class:`SpillFramework` handles (the pool's LRU sheds them down-tier
under pressure, so device residency is bounded by the HBM pool no matter
how large the exchange input is); slices beyond the budget cross the
host boundary immediately as CRC-framed serializer blocks
(``shuffle/serializer.py`` — a flipped bit anywhere surfaces as a
deterministic :class:`ShuffleCorruption` instead of silent wrong rows).
Every append/read observes the current query's CancelToken, so a tripped
deadline unwinds a wide exchange instead of finishing it.

Wall inside the queue (serialize / track / materialize) lands in the
``exchange_spill_ns`` counter; host-boundary blocks count into
``exchange_host_blocks`` / ``exchange_host_block_bytes`` — bench.py
decomposes exchange walls from these.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu import types as T
from spark_rapids_tpu.accounting import context as _ACCT
from spark_rapids_tpu.columnar.batch import ColumnarBatch


class SpillBackedPartitionQueues:
    """Per-partition queues of exchange output slices with bounded
    device residency (the spill-backed exchange's block store)."""

    def __init__(self, n_parts: int, schema: T.StructType,
                 device_budget: int, codec: Optional[str] = None,
                 host_budget: int = 0,
                 spill_dir: Optional[str] = None):
        from spark_rapids_tpu.memory.spill import get_spill_framework

        self.n_parts = n_parts
        self.schema = schema
        self.device_budget = max(int(device_budget), 0)
        # host-memory budget for retained CRC blobs (0 = unbounded):
        # past it blobs land as files in the spill dir — the distributed
        # lineage buffer (ISSUE 14) retains a whole exchange until its
        # partitions commit, which must not pin the driver's RAM
        self.host_budget = max(int(host_budget), 0)
        self._spill_dir = spill_dir
        self._made_spill_dir = False
        self.codec = codec
        self._fw = get_spill_framework()
        # per-partition entries:
        #   ("dev", handle) | ("host", crc_blob) | ("hostfile", path)
        self._queues: Dict[int, List[Tuple[str, object]]] = {
            p: [] for p in range(n_parts)}
        self._device_bytes = 0
        self._host_mem_bytes = 0
        self._next_file = 0
        self.host_blocks = 0
        self.host_block_bytes = 0

    @property
    def device_bytes(self) -> int:
        """Device bytes currently queued as resident handles (the
        queue's own budget accounting; the SpillFramework pool bound is
        the second, global, limit)."""
        return self._device_bytes

    def append(self, pid: int, batch: ColumnarBatch) -> None:
        """Queue one map-side slice for reduce partition ``pid``."""
        from spark_rapids_tpu.lifecycle.context import check_cancel

        check_cancel()
        if batch is None or batch.num_rows == 0:
            return
        t0 = time.perf_counter_ns()
        nb = batch.nbytes()
        if self._device_bytes + nb <= self.device_budget:
            if _ACCT.LEDGERS is not None:
                # stamp the reduce partition driving this admission so
                # LRU spills it triggers bill against pid (ISSUE 18)
                tok = _ACCT.PARTITION.set(pid)
                try:
                    handle = self._fw.track(batch)
                finally:
                    _ACCT.PARTITION.reset(tok)
            else:
                handle = self._fw.track(batch)
            self._queues[pid].append(("dev", handle))
            self._device_bytes += nb
        else:
            # host boundary: CRC-framed serializer block (ShuffleCorruption
            # on bit rot — never silent wrong rows); ONE framing site for
            # the ICI/exchange host boundary (exec/ici.ici_host_frame)
            from spark_rapids_tpu.exec.ici import ici_host_frame

            blob = ici_host_frame(batch, codec=self.codec)
            self._queues[pid].append(self._host_entry(blob))
            self.host_blocks += 1
            self.host_block_bytes += len(blob)
            PC.bump("exchange_host_blocks")
            PC.bump("exchange_host_block_bytes", len(blob))
        PC.bump("exchange_spill_ns", time.perf_counter_ns() - t0)

    def _host_entry(self, blob: bytes) -> Tuple[str, object]:
        """One host-tier entry: in memory up to ``host_budget``, past
        it a file in the spill dir (blobs are already CRC-framed, so
        disk rot surfaces at decode time as ShuffleCorruption)."""
        if not self.host_budget \
                or self._host_mem_bytes + len(blob) <= self.host_budget:
            self._host_mem_bytes += len(blob)
            return ("host", blob)
        import os
        import tempfile

        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="srt_exch_lineage_")
            self._made_spill_dir = True
        os.makedirs(self._spill_dir, exist_ok=True)
        path = os.path.join(self._spill_dir,
                            f"lineage_{id(self):x}_{self._next_file}.blk")
        self._next_file += 1
        with open(path, "wb") as f:
            f.write(blob)
        return ("hostfile", path)

    def _release_entry(self, kind: str, x) -> None:
        """Drop one entry's backing resource (host accounting / spill
        file / device handle)."""
        if kind == "host":
            self._host_mem_bytes -= len(x)
        elif kind == "hostfile":
            import os

            try:
                os.unlink(x)
            except OSError:
                pass
        elif kind == "dev":
            self._device_bytes -= x.device_bytes
            x.close()

    def append_framed(self, pid: int, blob: bytes) -> None:
        """Queue one PRE-FRAMED host-boundary block (the distributed
        tier frames each slice once — ``exec/ici.ici_host_frame`` — and
        retains the same bytes here as its lineage copy).  Counted like
        any other host-boundary block."""
        from spark_rapids_tpu.lifecycle.context import check_cancel

        check_cancel()
        if not blob:
            return
        self._queues[pid].append(self._host_entry(blob))
        self.host_blocks += 1
        self.host_block_bytes += len(blob)
        PC.bump("exchange_host_blocks")
        PC.bump("exchange_host_block_bytes", len(blob))

    def peek_blobs(self, pid: int) -> List[bytes]:
        """The partition's retained host-boundary blocks WITHOUT
        draining — the re-drive source after a worker loss (ISSUE 14;
        spilled blobs read back from disk).  Only meaningful for queues
        run at device budget 0 (every entry framed): device-resident
        entries are not wire blocks and are skipped."""
        out: List[bytes] = []
        for kind, x in (self._queues.get(pid) or []):
            if kind == "host":
                out.append(x)
            elif kind == "hostfile":
                with open(x, "rb") as f:
                    out.append(f.read())
        return out

    def snapshot_framed(self, pid: int) -> List[bytes]:
        """EVERY queued entry of one partition as CRC-framed
        host-boundary blocks WITHOUT draining — the stage-checkpoint
        source (ISSUE 16).  Unlike :meth:`peek_blobs` this covers
        device-resident entries too: each handle pins, serializes
        through the one framing site, and unpins with the entry still
        queued (the checkpoint is a copy; the read phase drains the
        queue as usual afterwards)."""
        from spark_rapids_tpu.exec.ici import ici_host_frame

        out: List[bytes] = []
        for kind, x in (self._queues.get(pid) or []):
            if kind == "host":
                out.append(x)
            elif kind == "hostfile":
                with open(x, "rb") as f:
                    out.append(f.read())
            else:
                x.pin()
                try:
                    out.append(ici_host_frame(x.get_batch(),
                                              codec=self.codec))
                finally:
                    x.unpin()
        return out

    def release_partition(self, pid: int) -> None:
        """Commit one partition: the consuming stage fully read it, so
        the lineage copy (resident handles, retained blobs, spill
        files) can go."""
        entries = self._queues.get(pid) or []
        self._queues[pid] = []
        for kind, x in entries:
            self._release_entry(kind, x)

    def read(self, pid: int) -> Optional[ColumnarBatch]:
        """Drain reduce partition ``pid`` into one device batch (the
        chunked ``read_chunks`` is the exchange's streaming path; this
        concat form serves callers that want the whole partition)."""
        chunks = list(self.read_chunks(pid))
        if not chunks:
            return None
        return (chunks[0] if len(chunks) == 1
                else ColumnarBatch.concat(chunks))

    def read_chunks(self, pid: int, target_bytes: int = 0):
        """Drain reduce partition ``pid`` as a stream of device batches,
        each ~``target_bytes`` (0: one chunk per queued entry group of
        unbounded size — callers pass the session batch-size goal).  The
        out-of-core invariant lives here: one CHUNK at a time pins /
        materializes / releases, so the drain's device working set is
        one chunk — never the whole partition (a partition far larger
        than the pool would otherwise re-materialize whole and bust the
        residency bound as a single unspillable batch)."""
        from spark_rapids_tpu.lifecycle.context import check_cancel
        from spark_rapids_tpu.shuffle.serializer import deserialize_concat

        check_cancel()
        entries = self._queues.get(pid) or []
        if not entries:
            return
        self._queues[pid] = []
        group: List[Tuple[str, object]] = []
        group_bytes = 0

        def _entry_bytes(kind, x):
            if kind == "dev":
                return x.device_bytes
            if kind == "hostfile":
                import os

                try:
                    return os.path.getsize(x)
                except OSError:
                    return 0
            return len(x)

        def _drain_group():
            t0 = time.perf_counter_ns()
            # stamp the DRAINING partition: restores its materialization
            # pulls up-tier — and spills that restoring displaces — bill
            # against pid, localizing out-of-core pressure (ISSUE 18)
            _tok = _ACCT.PARTITION.set(pid) \
                if _ACCT.LEDGERS is not None else None
            handles = [h for kind, h in group if kind == "dev"]
            try:
                for h in handles:
                    h.pin()
                parts: List[ColumnarBatch] = []
                host_blobs = []
                for kind, x in group:
                    if kind == "dev":
                        parts.append(x.get_batch())
                    elif kind == "hostfile":
                        with open(x, "rb") as f:
                            host_blobs.append(f.read())
                    else:
                        host_blobs.append(x)
                if host_blobs:
                    # CRC-verified host-boundary decode
                    # (ShuffleCorruption on mismatch), concat-friendly
                    # across the group's blobs at once
                    parts.append(deserialize_concat(
                        host_blobs, self.schema, codec=self.codec))
                out = (parts[0] if len(parts) == 1
                       else ColumnarBatch.concat(parts))
            finally:
                for h in handles:
                    h.unpin()
                if _tok is not None:
                    _ACCT.PARTITION.reset(_tok)
            for kind, x in group:
                self._release_entry(kind, x)
            PC.bump("exchange_spill_ns", time.perf_counter_ns() - t0)
            return out

        for kind, x in entries:
            nb = _entry_bytes(kind, x)
            if group and target_bytes and group_bytes + nb > target_bytes:
                yield _drain_group()
                check_cancel()
                group, group_bytes = [], 0
            group.append((kind, x))
            group_bytes += nb
        if group:
            yield _drain_group()

    def close(self) -> None:
        """Release every remaining entry (the error-unwind path; a clean
        drain already released everything in read())."""
        from spark_rapids_tpu.lifecycle import QueryCancelled

        for pid, entries in self._queues.items():
            for kind, x in entries:
                try:
                    self._release_entry(kind, x)
                except QueryCancelled:
                    raise
                except Exception:
                    pass
            self._queues[pid] = []
        self._device_bytes = 0
        self._host_mem_bytes = 0
        if self._made_spill_dir and self._spill_dir:
            import shutil

            shutil.rmtree(self._spill_dir, ignore_errors=True)
            self._spill_dir = None
            self._made_spill_dir = False


def queue_device_budget(conf) -> int:
    """Resolve the queues' device budget: the conf when set, else a
    pool-derived default (2x one target partition's working set, so the
    next partition's slices can stage while the current one computes)."""
    from spark_rapids_tpu.config import (
        EXCHANGE_DEVICE_RESIDENT_BYTES,
        EXCHANGE_TARGET_PARTITION_FRACTION,
    )
    from spark_rapids_tpu.memory.device_manager import get_device_manager

    fixed = conf.get(EXCHANGE_DEVICE_RESIDENT_BYTES)
    if fixed:
        return int(fixed)
    pool = get_device_manager().pool_bytes
    frac = conf.get(EXCHANGE_TARGET_PARTITION_FRACTION)
    return max(int(pool * frac * 2), 1 << 20)


def host_boundary_codec(conf) -> Optional[str]:
    """Codec for the CRC-framed host-boundary blocks: the ici override
    when set, else the shuffle codec."""
    from spark_rapids_tpu.config import (
        ICI_HOST_BOUNDARY_CODEC,
        SHUFFLE_COMPRESSION_CODEC,
    )

    return conf.get(ICI_HOST_BOUNDARY_CODEC) \
        or conf.get(SHUFFLE_COMPRESSION_CODEC)
