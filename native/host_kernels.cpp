// Host-side native kernels for the TPU runtime's data path.
//
// Reference analog: the reference's runtime hot loops live in C++
// (spark-rapids-jni: Kudo serializer, string kernels, row conversion —
// SURVEY.md §2.10).  The TPU compute path is XLA; the HOST glue around it
// (decode staging, shuffle serialization) is where Python loops would
// dominate, so those run here.  Loaded via ctypes (no pybind11 in the
// image); spark_rapids_tpu/native.py holds the bindings + pure-Python
// fallbacks.
//
// Build: python -m spark_rapids_tpu.native  (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// JSON path engine — C++ port of spark_rapids_tpu/jsonpath.py (the reference
// keeps this in a CUDA kernel, get_json_object.cu; here it is a host kernel
// invoked through jax.pure_callback).  The Python module is the semantic
// spec; keep the two in lockstep.
// ---------------------------------------------------------------------------

struct JsonStep {
    bool is_key;
    std::string key;
    int64_t index;
};

inline bool is_ws(uint8_t c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r';
}

inline bool is_delim(uint8_t c) {
    return c == ',' || c == '}' || c == ']' || is_ws(c);
}

inline int64_t skip_ws(const uint8_t* b, int64_t i, int64_t L) {
    while (i < L && is_ws(b[i])) ++i;
    return i;
}

// b[i]=='"'; one past closing quote, or -1
int64_t string_end(const uint8_t* b, int64_t i, int64_t L) {
    ++i;
    while (i < L) {
        if (b[i] == '\\') { i += 2; continue; }
        if (b[i] == '"') return i + 1;
        ++i;
    }
    return -1;
}

bool unescape(const uint8_t* raw, int64_t len, std::string* out);
bool valid_scalar(const uint8_t* raw, int64_t len);

constexpr int64_t kMaxDepth = 256;

// Validating skip (see jsonpath.py _skip_value): Jackson streaming fails on
// malformed tokens it passes over, so bracket-matching alone would diverge.
int64_t skip_value(const uint8_t* b, int64_t i, int64_t L,
                   int64_t depth = 0) {
    if (depth > kMaxDepth) return -1;
    i = skip_ws(b, i, L);
    if (i >= L) return -1;
    uint8_t c = b[i];
    std::string scratch;
    if (c == '"') {
        int64_t e = string_end(b, i, L);
        if (e < 0 || !unescape(b + i + 1, e - i - 2, &scratch)) return -1;
        return e;
    }
    if (c == '{') {
        i = skip_ws(b, i + 1, L);
        if (i < L && b[i] == '}') return i + 1;
        while (true) {
            i = skip_ws(b, i, L);
            if (i >= L || b[i] != '"') return -1;
            int64_t ke = string_end(b, i, L);
            if (ke < 0 || !unescape(b + i + 1, ke - i - 2, &scratch))
                return -1;
            i = skip_ws(b, ke, L);
            if (i >= L || b[i] != ':') return -1;
            int64_t e = skip_value(b, i + 1, L, depth + 1);
            if (e < 0) return -1;
            i = skip_ws(b, e, L);
            if (i >= L) return -1;
            if (b[i] == ',') { ++i; continue; }
            if (b[i] == '}') return i + 1;
            return -1;
        }
    }
    if (c == '[') {
        i = skip_ws(b, i + 1, L);
        if (i < L && b[i] == ']') return i + 1;
        while (true) {
            int64_t e = skip_value(b, i, L, depth + 1);
            if (e < 0) return -1;
            i = skip_ws(b, e, L);
            if (i >= L) return -1;
            if (b[i] == ',') { ++i; continue; }
            if (b[i] == ']') return i + 1;
            return -1;
        }
    }
    int64_t j = i;
    while (j < L && !is_delim(b[j])) ++j;
    if (j == i || !valid_scalar(b + i, j - i)) return -1;
    return j;
}

// JSON string-body unescape into out; false on bad escape
bool unescape(const uint8_t* raw, int64_t len, std::string* out) {
    out->clear();
    out->reserve(len);
    int64_t i = 0;
    while (i < len) {
        uint8_t c = raw[i];
        if (c != '\\') { out->push_back(static_cast<char>(c)); ++i; continue; }
        if (i + 1 >= len) return false;
        uint8_t e = raw[i + 1];
        i += 2;
        switch (e) {
            case '"': out->push_back('"'); continue;
            case '\\': out->push_back('\\'); continue;
            case '/': out->push_back('/'); continue;
            case 'b': out->push_back('\b'); continue;
            case 'f': out->push_back('\f'); continue;
            case 'n': out->push_back('\n'); continue;
            case 'r': out->push_back('\r'); continue;
            case 't': out->push_back('\t'); continue;
            case 'u': break;
            default: return false;
        }
        if (i + 4 > len) return false;
        auto hex4 = [&](int64_t p, int64_t* v) {
            int64_t acc = 0;
            for (int k = 0; k < 4; ++k) {
                uint8_t h = raw[p + k];
                int64_t d;
                if (h >= '0' && h <= '9') d = h - '0';
                else if (h >= 'a' && h <= 'f') d = h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') d = h - 'A' + 10;
                else return false;
                acc = (acc << 4) | d;
            }
            *v = acc;
            return true;
        };
        int64_t cp;
        if (!hex4(i, &cp)) return false;
        i += 4;
        if (cp >= 0xD800 && cp <= 0xDBFF) {
            // high surrogate MUST pair (python spec: chr() would reject)
            int64_t lo = -1;
            if (i + 6 <= len && raw[i] == '\\' && raw[i + 1] == 'u') {
                hex4(i + 2, &lo);
            }
            if (lo < 0xDC00 || lo > 0xDFFF) return false;
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            i += 6;
        } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
            return false;  // lone low surrogate
        }
        // utf-8 encode
        if (cp < 0x80) {
            out->push_back(static_cast<char>(cp));
        } else if (cp < 0x800) {
            out->push_back(static_cast<char>(0xC0 | (cp >> 6)));
            out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
        } else if (cp < 0x10000) {
            out->push_back(static_cast<char>(0xE0 | (cp >> 12)));
            out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
            out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
        } else if (cp < 0x110000) {
            out->push_back(static_cast<char>(0xF0 | (cp >> 18)));
            out->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
            out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
            out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
        } else {
            return false;
        }
    }
    return true;
}

// strip whitespace outside strings
bool compact(const uint8_t* raw, int64_t len, std::string* out) {
    out->clear();
    out->reserve(len);
    int64_t i = 0;
    while (i < len) {
        uint8_t c = raw[i];
        if (c == '"') {
            int64_t e = string_end(raw, i, len);
            if (e < 0) return false;
            out->append(reinterpret_cast<const char*>(raw + i),
                        static_cast<size_t>(e - i));
            i = e;
            continue;
        }
        if (is_ws(c)) { ++i; continue; }
        out->push_back(static_cast<char>(c));
        ++i;
    }
    return true;
}

bool valid_scalar(const uint8_t* raw, int64_t len) {
    auto eq = [&](const char* s) {
        return static_cast<int64_t>(std::strlen(s)) == len &&
               std::memcmp(raw, s, static_cast<size_t>(len)) == 0;
    };
    if (eq("true") || eq("false") || eq("null")) return true;
    int64_t i = 0;
    if (i < len && raw[i] == '-') ++i;
    int64_t start = i;
    while (i < len && raw[i] >= '0' && raw[i] <= '9') ++i;
    if (i == start) return false;
    if (i < len && raw[i] == '.') {
        ++i;
        start = i;
        while (i < len && raw[i] >= '0' && raw[i] <= '9') ++i;
        if (i == start) return false;
    }
    if (i < len && (raw[i] == 'e' || raw[i] == 'E')) {
        ++i;
        if (i < len && (raw[i] == '+' || raw[i] == '-')) ++i;
        start = i;
        while (i < len && raw[i] >= '0' && raw[i] <= '9') ++i;
        if (i == start) return false;
    }
    return i == len;
}

// span of the value addressed by steps[si:]; false if no match
bool navigate(const uint8_t* b, int64_t i, int64_t L,
              const std::vector<JsonStep>& steps, size_t si,
              int64_t* out_s, int64_t* out_e) {
    i = skip_ws(b, i, L);
    if (si == steps.size()) {
        int64_t e = skip_value(b, i, L);
        if (e < 0) return false;
        *out_s = i;
        *out_e = e;
        return true;
    }
    if (i >= L) return false;
    const JsonStep& step = steps[si];
    if (step.is_key) {
        if (b[i] != '{') return false;
        ++i;
        std::string key;
        while (true) {
            i = skip_ws(b, i, L);
            if (i >= L || b[i] == '}') return false;
            if (b[i] != '"') return false;
            int64_t ke = string_end(b, i, L);
            if (ke < 0) return false;
            if (!unescape(b + i + 1, ke - i - 2, &key)) return false;
            int64_t i2 = skip_ws(b, ke, L);
            if (i2 >= L || b[i2] != ':') return false;
            ++i2;
            if (key == step.key) {
                return navigate(b, i2, L, steps, si + 1, out_s, out_e);
            }
            int64_t e = skip_value(b, i2, L);
            if (e < 0) return false;
            i = skip_ws(b, e, L);
            if (i >= L) return false;
            if (b[i] == ',') ++i;
            else if (b[i] != '}') return false;
        }
    }
    if (b[i] != '[') return false;
    ++i;
    for (int64_t k = 0; k < step.index; ++k) {
        i = skip_ws(b, i, L);
        if (i >= L || b[i] == ']') return false;
        int64_t e = skip_value(b, i, L);
        if (e < 0) return false;
        i = skip_ws(b, e, L);
        if (i >= L || b[i] != ',') return false;
        ++i;
    }
    i = skip_ws(b, i, L);
    if (i >= L || b[i] == ']') return false;
    return navigate(b, i, L, steps, si + 1, out_s, out_e);
}

// result string or not-found
bool eval_json_path(const uint8_t* doc, int64_t L,
                    const std::vector<JsonStep>& steps, std::string* out) {
    int64_t s, e;
    if (!navigate(doc, 0, L, steps, 0, &s, &e)) return false;
    uint8_t c = doc[s];
    if (c == '"') return unescape(doc + s + 1, e - s - 2, out);
    if (c == '{' || c == '[') return compact(doc + s, e - s, out);
    if (e - s == 4 && std::memcmp(doc + s, "null", 4) == 0) return false;
    if (!valid_scalar(doc + s, e - s)) return false;
    out->assign(reinterpret_cast<const char*>(doc + s),
                static_cast<size_t>(e - s));
    return true;
}

// steps blob: repeated ['k'|'i'][u32 LE payload][key bytes if 'k']
std::vector<JsonStep> parse_steps(const uint8_t* blob, int64_t blob_len) {
    std::vector<JsonStep> steps;
    int64_t i = 0;
    while (i + 5 <= blob_len) {
        uint8_t tag = blob[i];
        uint32_t v;
        std::memcpy(&v, blob + i + 1, 4);
        i += 5;
        JsonStep s;
        if (tag == 'k') {
            s.is_key = true;
            s.key.assign(reinterpret_cast<const char*>(blob + i), v);
            i += v;
        } else {
            s.is_key = false;
            s.index = v;
        }
        steps.push_back(std::move(s));
    }
    return steps;
}

}  // namespace

extern "C" {

// get_json_object over a padded (rows, width) char matrix; one path for
// all rows.  out_chars must be zeroed (rows*width); results longer than
// width are truncated (cannot happen: every transform shrinks).
void get_json_object_padded(const uint8_t* chars, const int32_t* lengths,
                            const uint8_t* validity, int64_t rows,
                            int64_t width, const uint8_t* steps_blob,
                            int64_t steps_len, uint8_t* out_chars,
                            int32_t* out_lengths, uint8_t* out_valid) {
    const std::vector<JsonStep> steps = parse_steps(steps_blob, steps_len);
    std::string result;
    for (int64_t i = 0; i < rows; ++i) {
        out_valid[i] = 0;
        out_lengths[i] = 0;
        if (!validity[i]) continue;
        const uint8_t* doc = chars + i * width;
        int64_t L = lengths[i] < width ? lengths[i] : width;
        if (!eval_json_path(doc, L, steps, &result)) continue;
        int64_t n = static_cast<int64_t>(result.size());
        if (n > width) n = width;
        std::memcpy(out_chars + i * width, result.data(),
                    static_cast<size_t>(n));
        out_lengths[i] = static_cast<int32_t>(n);
        out_valid[i] = 1;
    }
}

// Arrow (chars, offsets) -> padded (rows, width) char matrix.
// offsets are int64 arrow offsets relative to buf; lengths[i] must equal
// offsets[i+1]-offsets[i]; out is zero-initialized (rows*width).
void ragged_to_padded(const uint8_t* buf, const int64_t* offsets,
                      int64_t rows, int64_t width, uint8_t* out) {
    for (int64_t i = 0; i < rows; ++i) {
        const int64_t start = offsets[i];
        const int64_t len = offsets[i + 1] - start;
        if (len > 0) {
            std::memcpy(out + i * width, buf + start,
                        static_cast<size_t>(len < width ? len : width));
        }
    }
}

// Padded (rows, width) char matrix -> packed bytes + int32 offsets
// (the serializer's ragged write).  out must hold sum(lengths) bytes;
// out_offsets must hold rows+1 entries.
void padded_to_ragged(const uint8_t* chars, const int32_t* lengths,
                      int64_t rows, int64_t width, uint8_t* out,
                      int64_t* out_offsets) {
    int64_t pos = 0;
    out_offsets[0] = 0;
    for (int64_t i = 0; i < rows; ++i) {
        const int64_t len = lengths[i] < width ? lengths[i] : width;
        if (len > 0) {
            std::memcpy(out + pos, chars + i * width,
                        static_cast<size_t>(len));
            pos += len;
        }
        out_offsets[i + 1] = pos;
    }
}


// Raw snappy block decompression (the default codec of most real parquet
// files; no binding exists in the image so the format is implemented from
// scratch — it is a simple LZ77 variant).  Returns bytes written or -1
// on malformed input / overflow.
int64_t snappy_uncompress(const uint8_t* in, int64_t in_len, uint8_t* out,
                          int64_t out_cap) {
    int64_t ip = 0;
    // varint preamble: uncompressed length
    uint64_t ulen = 0;
    int shift = 0;
    while (ip < in_len) {
        uint8_t b = in[ip++];
        ulen |= static_cast<uint64_t>(b & 0x7F) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
        if (shift > 35) return -1;
    }
    if (static_cast<int64_t>(ulen) > out_cap) return -1;
    int64_t op = 0;
    while (ip < in_len) {
        const uint8_t tag = in[ip++];
        const int type = tag & 3;
        if (type == 0) {                       // literal
            int64_t len = (tag >> 2) + 1;
            if (len > 60) {
                const int nb = static_cast<int>(len - 60);
                if (ip + nb > in_len) return -1;
                len = 0;
                for (int k = 0; k < nb; ++k)
                    len |= static_cast<int64_t>(in[ip + k]) << (8 * k);
                len += 1;
                ip += nb;
            }
            if (ip + len > in_len || op + len > out_cap) return -1;
            std::memcpy(out + op, in + ip, static_cast<size_t>(len));
            ip += len;
            op += len;
            continue;
        }
        int64_t len, offset;
        if (type == 1) {                        // copy, 1-byte offset
            if (ip >= in_len) return -1;
            len = ((tag >> 2) & 0x7) + 4;
            offset = (static_cast<int64_t>(tag >> 5) << 8) | in[ip++];
        } else if (type == 2) {                 // copy, 2-byte offset
            if (ip + 2 > in_len) return -1;
            len = (tag >> 2) + 1;
            offset = in[ip] | (static_cast<int64_t>(in[ip + 1]) << 8);
            ip += 2;
        } else {                                // copy, 4-byte offset
            if (ip + 4 > in_len) return -1;
            len = (tag >> 2) + 1;
            offset = 0;
            for (int k = 0; k < 4; ++k)
                offset |= static_cast<int64_t>(in[ip + k]) << (8 * k);
            ip += 4;
        }
        if (offset <= 0 || offset > op || op + len > out_cap) return -1;
        // overlapping copies are byte-serial by definition
        for (int64_t k = 0; k < len; ++k) {
            out[op + k] = out[op + k - offset];
        }
        op += len;
    }

    return (op == static_cast<int64_t>(ulen)) ? op : -1;
}


// PLAIN BYTE_ARRAY page walk: extract the n per-value lengths from the
// interleaved (4-byte LE length, bytes) layout.  The sequential
// dependency makes this a host walk (C, not python) — the chars then
// upload as one padded matrix.  Returns total string bytes or -1.
int64_t plain_byte_array_lens(const uint8_t* buf, int64_t buf_len,
                              int64_t n, int32_t* lens) {
    int64_t pos = 0;
    int64_t total = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (pos + 4 > buf_len) return -1;
        uint32_t ln = static_cast<uint32_t>(buf[pos])
            | (static_cast<uint32_t>(buf[pos + 1]) << 8)
            | (static_cast<uint32_t>(buf[pos + 2]) << 16)
            | (static_cast<uint32_t>(buf[pos + 3]) << 24);
        pos += 4;
        if (pos + ln > static_cast<uint64_t>(buf_len)) return -1;
        lens[i] = static_cast<int32_t>(ln);
        pos += ln;
        total += ln;
    }
    return total;
}


// Raw snappy block COMPRESSION — the decompressor's twin (device parquet
// ENCODE path, round 5).  Greedy hash-table LZ77 emitting the same
// literal/copy tag stream snappy_uncompress parses; not byte-identical
// to google/snappy's output (any valid stream is), but decompresses
// with it.  Returns bytes written or -1 when out_cap is too small.
int64_t snappy_compress(const uint8_t* in, int64_t in_len, uint8_t* out,
                        int64_t out_cap) {
    int64_t op = 0;
    // varint preamble: uncompressed length
    uint64_t u = static_cast<uint64_t>(in_len);
    do {
        if (op >= out_cap) return -1;
        uint8_t b = u & 0x7F;
        u >>= 7;
        out[op++] = u ? (b | 0x80) : b;
    } while (u);

    auto emit_literal = [&](int64_t from, int64_t len) -> bool {
        while (len > 0) {
            int64_t chunk = len < (1 << 24) ? len : ((1 << 24) - 1);
            if (chunk <= 60) {
                if (op + 1 + chunk > out_cap) return false;
                out[op++] = static_cast<uint8_t>((chunk - 1) << 2);
            } else {
                int nb = chunk < (1 << 8) ? 1 : (chunk < (1 << 16) ? 2 : 3);
                if (op + 1 + nb + chunk > out_cap) return false;
                out[op++] = static_cast<uint8_t>((59 + nb) << 2);
                int64_t v = chunk - 1;
                for (int k = 0; k < nb; ++k) {
                    out[op++] = static_cast<uint8_t>(v & 0xFF);
                    v >>= 8;
                }
            }
            std::memcpy(out + op, in + from, static_cast<size_t>(chunk));
            op += chunk;
            from += chunk;
            len -= chunk;
        }
        return true;
    };
    auto emit_copy = [&](int64_t offset, int64_t len) -> bool {
        // prefer 2-byte-offset copies (1..64 length); split longer runs
        while (len >= 4) {
            int64_t chunk = len < 64 ? len : 64;
            if (len - chunk > 0 && len - chunk < 4) chunk = len - 4;
            if (offset < 2048 && chunk >= 4 && chunk <= 11) {
                if (op + 2 > out_cap) return false;
                out[op++] = static_cast<uint8_t>(
                    1 | ((chunk - 4) << 2) | ((offset >> 8) << 5));
                out[op++] = static_cast<uint8_t>(offset & 0xFF);
            } else if (offset < (1 << 16)) {
                if (op + 3 > out_cap) return false;
                out[op++] = static_cast<uint8_t>(2 | ((chunk - 1) << 2));
                out[op++] = static_cast<uint8_t>(offset & 0xFF);
                out[op++] = static_cast<uint8_t>((offset >> 8) & 0xFF);
            } else {
                if (op + 5 > out_cap) return false;
                out[op++] = static_cast<uint8_t>(3 | ((chunk - 1) << 2));
                int64_t v = offset;
                for (int k = 0; k < 4; ++k) {
                    out[op++] = static_cast<uint8_t>(v & 0xFF);
                    v >>= 8;
                }
            }
            len -= chunk;
        }
        return true;
    };

    const int HASH_BITS = 14;
    const int64_t HSIZE = 1 << HASH_BITS;
    std::vector<int64_t> table(HSIZE, -1);
    auto hash4 = [&](int64_t i) -> uint32_t {
        uint32_t v = static_cast<uint32_t>(in[i])
            | (static_cast<uint32_t>(in[i + 1]) << 8)
            | (static_cast<uint32_t>(in[i + 2]) << 16)
            | (static_cast<uint32_t>(in[i + 3]) << 24);
        return (v * 0x1E35A7BDu) >> (32 - HASH_BITS);
    };

    int64_t ip = 0, lit_start = 0;
    while (ip + 4 <= in_len) {
        uint32_t h = hash4(ip);
        int64_t cand = table[h];
        table[h] = ip;
        if (cand >= 0 && ip - cand < (1 << 16)
            && std::memcmp(in + cand, in + ip, 4) == 0) {
            if (ip > lit_start
                && !emit_literal(lit_start, ip - lit_start)) return -1;
            int64_t len = 4;
            while (ip + len < in_len
                   && in[cand + len] == in[ip + len]) ++len;
            if (!emit_copy(ip - cand, len)) return -1;
            ip += len;
            lit_start = ip;
        } else {
            ++ip;
        }
    }
    if (in_len > lit_start
        && !emit_literal(lit_start, in_len - lit_start)) return -1;
    return op;
}

}  // extern "C"
