// Host-side native kernels for the TPU runtime's data path.
//
// Reference analog: the reference's runtime hot loops live in C++
// (spark-rapids-jni: Kudo serializer, string kernels, row conversion —
// SURVEY.md §2.10).  The TPU compute path is XLA; the HOST glue around it
// (decode staging, shuffle serialization) is where Python loops would
// dominate, so those run here.  Loaded via ctypes (no pybind11 in the
// image); spark_rapids_tpu/native.py holds the bindings + pure-Python
// fallbacks.
//
// Build: python -m spark_rapids_tpu.native  (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>

extern "C" {

// Arrow (chars, offsets) -> padded (rows, width) char matrix.
// offsets are int64 arrow offsets relative to buf; lengths[i] must equal
// offsets[i+1]-offsets[i]; out is zero-initialized (rows*width).
void ragged_to_padded(const uint8_t* buf, const int64_t* offsets,
                      int64_t rows, int64_t width, uint8_t* out) {
    for (int64_t i = 0; i < rows; ++i) {
        const int64_t start = offsets[i];
        const int64_t len = offsets[i + 1] - start;
        if (len > 0) {
            std::memcpy(out + i * width, buf + start,
                        static_cast<size_t>(len < width ? len : width));
        }
    }
}

// Padded (rows, width) char matrix -> packed bytes + int32 offsets
// (the serializer's ragged write).  out must hold sum(lengths) bytes;
// out_offsets must hold rows+1 entries.
void padded_to_ragged(const uint8_t* chars, const int32_t* lengths,
                      int64_t rows, int64_t width, uint8_t* out,
                      int64_t* out_offsets) {
    int64_t pos = 0;
    out_offsets[0] = 0;
    for (int64_t i = 0; i < rows; ++i) {
        const int64_t len = lengths[i] < width ? lengths[i] : width;
        if (len > 0) {
            std::memcpy(out + pos, chars + i * width,
                        static_cast<size_t>(len));
            pos += len;
        }
        out_offsets[i + 1] = pos;
    }
}

}  // extern "C"
