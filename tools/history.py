#!/usr/bin/env python
"""Query history server (ISSUE 12): finished queries stay inspectable
across processes.

The live half of the introspection layer (``session.progress()``, the
telemetry ``/progress`` route) dies with the process; this serves the
ROTATING DIAGNOSTICS EVENT LOGS — one ``query-<id>.jsonl`` per query
under ``spark.rapids.tpu.diagnostics.eventLogDir`` — as a browsable
index, the Spark history-server analog over our event-log format:

* index — one row per query, newest first: status, wall, SLO status
  (deadline trip / cancelled / over ``--slo-target-ms`` / ok), cost
  predicted-vs-actual, stall episodes;
* per-query page — the plan tree, the operator table ranked by SELF
  wall (with batches/rows/host-sync/launch counters), the
  predicted-vs-actual cost record, lifecycle + ``query_stall`` +
  ``progress`` events.

Every request re-reads the directory, so a server left running tracks
the live rotation; queries evicted by ``eventLog.maxFiles`` drop off
the index (that bound is the retention policy).  Localhost by design,
like the telemetry scrape endpoint: fleet exposure belongs to a real
sidecar.

Usage:
    python tools/history.py [LOG_DIR ...] [--port 8098]
    python tools/history.py diag_logs --once            # text index
    python tools/history.py diag_logs --once --json     # machine form
"""
from __future__ import annotations

import argparse
import html
import json
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_SLO_TARGET_MS = 0.0     # 0 = no latency SLO judged


# ---------------------------------------------------------------------------
# index construction (pure functions over parsed logs; tests import these)
# ---------------------------------------------------------------------------

def slo_status(qp, slo_target_ms: float) -> str:
    """One word per query: ``deadline`` / ``cancelled`` beat a latency
    judgment (the query never got to finish), then ``violated`` when a
    target is set and the wall exceeds it, else ``ok`` (or ``error``
    for a non-ok non-cancel status)."""
    for e in qp.events:
        if e.get("ev") == "lifecycle":
            if e.get("kind") == "deadline_trip":
                return "deadline"
            if e.get("kind") == "cancelled":
                return "cancelled"
    if qp.status and qp.status != "ok":
        return "error"
    if slo_target_ms > 0 and qp.wall_ns / 1e6 > slo_target_ms:
        return "violated"
    return "ok"


def _cost_record(qp) -> Optional[Dict[str, Any]]:
    for e in qp.events:
        if e.get("ev") == "cost_model":
            return {
                "hits": e.get("hits", 0),
                "misses": e.get("misses", 0),
                "predicted_wall_ms": round(
                    e.get("predicted_wall_ns", 0) / 1e6, 3),
                "matched_actual_wall_ms": round(
                    e.get("matched_actual_wall_ns", 0) / 1e6, 3),
            }
    return None


def _progress_record(qp) -> Optional[Dict[str, Any]]:
    for e in qp.events:
        if e.get("ev") == "progress":
            return {"pct": e.get("pct"), "eta_ns": e.get("eta_ns"),
                    "stalls": e.get("stalls", 0),
                    "background": e.get("background") or {}}
    return None


def _bill_record(qp) -> Optional[Dict[str, Any]]:
    """The query's resource_bill event (ISSUE 18), compacted for the
    index/detail payloads."""
    for e in qp.events:
        if e.get("ev") == "resource_bill":
            sp = e.get("spill") or {}
            return {
                "device_peak_bytes":
                    int(e.get("device_peak_bytes", 0) or 0),
                "device_byte_seconds":
                    float(e.get("device_byte_seconds", 0) or 0),
                "spilled_bytes": int(sp.get("host_bytes", 0) or 0)
                + int(sp.get("disk_bytes", 0) or 0),
                "restored_bytes": int(sp.get("restore_bytes", 0) or 0),
                "residual_bytes": int(e.get("residual_bytes", 0) or 0),
                "partitions": e.get("partitions") or {},
                "worker_bytes": e.get("worker_bytes") or {},
            }
    return None


def _sentinel_record(qp) -> Optional[Dict[str, Any]]:
    """The sentinel's verdict (ISSUE 18): the regression event when one
    was flagged, else None (= no excursion against the baseline)."""
    for e in qp.events:
        if e.get("ev") == "regression":
            return {
                "dimension": e.get("dimension", ""),
                "observed": e.get("observed", 0),
                "baseline": e.get("baseline", 0),
                "ratio": e.get("ratio", 0),
                "op": f"{e.get('op_path', '')}:{e.get('op_name', '')}",
                "detail": e.get("detail", ""),
            }
    return None


def index_rows(profiles, slo_target_ms: float) -> List[Dict[str, Any]]:
    """One summary dict per query, newest first (the /api/queries
    payload and the index table's rows)."""
    rows = []
    for qp in profiles:
        stalls = [e for e in qp.events if e.get("ev") == "query_stall"]
        prog = _progress_record(qp)
        rows.append({
            "query_id": qp.query_id,
            "started_at": qp.started_at,
            "status": qp.status or "?",
            "slo": slo_status(qp, slo_target_ms),
            "wall_ms": round(qp.wall_ns / 1e6, 3),
            "operators": len(qp.operators),
            "stalls": (prog["stalls"] if prog is not None
                       else len(stalls)),
            "cost": _cost_record(qp),
            "bill": _bill_record(qp),
            "regression": _sentinel_record(qp),
            "incomplete": qp.incomplete,
            "log": qp.path,
        })
    rows.sort(key=lambda r: -r["started_at"])
    return rows


def query_detail(qp, slo_target_ms: float) -> Dict[str, Any]:
    """The /api/query/<id> payload: plan, operators ranked by self
    wall, the cost + progress records, lifecycle/stall events."""
    ops = sorted(qp.operators,
                 key=lambda op: -op.get("self_wall_ns",
                                        op.get("wall_ns", 0)))
    return {
        "query_id": qp.query_id,
        "trace_id": qp.trace_id,
        "started_at": qp.started_at,
        "status": qp.status or "?",
        "slo": slo_status(qp, slo_target_ms),
        "wall_ms": round(qp.wall_ns / 1e6, 3),
        "plan": qp.plan,
        "operators": [{
            "path": op.get("path", ""),
            "name": op.get("name", "?"),
            "describe": op.get("describe", ""),
            "self_wall_ms": round(
                op.get("self_wall_ns", op.get("wall_ns", 0)) / 1e6, 3),
            "wall_ms": round(op.get("wall_ns", 0) / 1e6, 3),
            "batches": op.get("batches", 0),
            "rows": op.get("rows", 0),
            "counters": op.get("counters") or {},
        } for op in ops],
        "cost": _cost_record(qp),
        "progress": _progress_record(qp),
        "bill": _bill_record(qp),
        "regression": _sentinel_record(qp),
        "stall_events": [e for e in qp.events
                         if e.get("ev") == "query_stall"],
        "lifecycle": [e for e in qp.events
                      if e.get("ev") == "lifecycle"],
        "worker_spans": [e for e in qp.events
                         if e.get("ev") == "worker_span"],
        "totals": qp.totals,
        "incomplete": qp.incomplete,
        "log": qp.path,
    }


def load_profiles(log_dirs: List[str]):
    from spark_rapids_tpu.diagnostics.report import load_logs

    return load_logs(log_dirs)


def cluster_rows(profiles) -> List[Dict[str, Any]]:
    """One row per WORKER (ISSUE 15): the cluster page over the merged
    event logs — spans served, bytes moved, recovery traffic, the last
    federated counter snapshot, and which queries each worker touched
    (worker spans merge under their owning query by trace id, so this
    is a pure function of the same logs the index serves)."""
    from spark_rapids_tpu.diagnostics.report import workers_summary

    ws = workers_summary(profiles)
    rows = []
    for wid, a in ws["workers"].items():
        c = a["counters"]
        rows.append({
            "worker_id": wid,
            "spans": a["spans"],
            "bytes": a["bytes"],
            "wall_ms": round(a["wall_ns"] / 1e6, 3),
            "by_kind": a["by_kind"],
            "queries": a["queries"],
            "store_puts": c.get("store_puts", 0),
            "store_redrive_puts": c.get("store_redrive_puts", 0),
            "store_fetches": c.get("store_fetches", 0),
            "store_bytes_served": c.get("store_bytes_served", 0),
            "store_overflow_bytes": c.get("store_overflow_bytes", 0),
        })
    return rows


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

_STYLE = """<style>
body { font-family: monospace; margin: 1.5em; }
table { border-collapse: collapse; }
th, td { border: 1px solid #999; padding: 2px 8px; text-align: left; }
th { background: #eee; }
.slo-ok { color: #070; } .slo-violated, .slo-deadline, .slo-error,
.slo-cancelled { color: #b00; font-weight: bold; }
pre { background: #f6f6f6; padding: 0.5em; }
</style>"""


def _esc(v) -> str:
    return html.escape(str(v))


def render_index_html(rows: List[Dict[str, Any]]) -> str:
    body = [f"<html><head><title>query history</title>{_STYLE}</head>",
            "<body><h2>query history "
            f"({len(rows)} queries)</h2><table>",
            "<tr><th>query</th><th>status</th><th>SLO</th>"
            "<th>wall_ms</th><th>ops</th><th>stalls</th>"
            "<th>predicted_ms</th><th>matched_actual_ms</th>"
            "<th>device_B*s</th><th>spilled</th><th>sentinel</th></tr>"]
    for r in rows:
        cost = r["cost"] or {}
        bill = r.get("bill") or {}
        reg = r.get("regression")
        flag = " (incomplete)" if r["incomplete"] else ""
        sentinel = (f"<span class='slo-error'>"
                    f"REGRESSED[{_esc(reg['dimension'])}]</span>"
                    if reg else
                    ("ok" if bill else ""))
        body.append(
            f"<tr><td><a href='/query/{_esc(r['query_id'])}'>"
            f"{_esc(r['query_id'])}</a>{flag}</td>"
            f"<td>{_esc(r['status'])}</td>"
            f"<td class='slo-{_esc(r['slo'])}'>{_esc(r['slo'])}</td>"
            f"<td>{r['wall_ms']:.1f}</td><td>{r['operators']}</td>"
            f"<td>{r['stalls']}</td>"
            f"<td>{cost.get('predicted_wall_ms', '')}</td>"
            f"<td>{cost.get('matched_actual_wall_ms', '')}</td>"
            f"<td>{bill.get('device_byte_seconds', '')}</td>"
            f"<td>{bill.get('spilled_bytes', '')}</td>"
            f"<td>{sentinel}</td></tr>")
    body.append("</table><p><a href='/cluster'>cluster (per-worker "
                "view)</a></p></body></html>")
    return "\n".join(body)


def render_query_html(d: Dict[str, Any]) -> str:
    body = [f"<html><head><title>{_esc(d['query_id'])}</title>{_STYLE}"
            "</head><body>",
            f"<h2>query {_esc(d['query_id'])}</h2>",
            f"<p>status={_esc(d['status'])} "
            f"<span class='slo-{_esc(d['slo'])}'>SLO={_esc(d['slo'])}"
            f"</span> wall={d['wall_ms']:.1f}ms</p>",
            "<h3>plan</h3><pre>"]
    for n in d["plan"]:
        depth = n.get("path", "").count(".")
        body.append(_esc("  " * depth + n.get("describe",
                                              n.get("name", "?"))))
    body.append("</pre><h3>operators (by self wall)</h3><table>")
    body.append("<tr><th>path</th><th>operator</th><th>self_wall_ms"
                "</th><th>wall_ms</th><th>batches</th><th>rows</th>"
                "<th>counters</th></tr>")
    for op in d["operators"]:
        counters = ", ".join(f"{k}={v}" for k, v in
                             sorted(op["counters"].items())[:6])
        body.append(
            f"<tr><td>{_esc(op['path'])}</td><td>{_esc(op['name'])}</td>"
            f"<td>{op['self_wall_ms']:.1f}</td>"
            f"<td>{op['wall_ms']:.1f}</td><td>{op['batches']}</td>"
            f"<td>{op['rows']}</td><td>{_esc(counters)}</td></tr>")
    body.append("</table>")
    if d["cost"] is not None:
        c = d["cost"]
        body.append(
            f"<h3>cost model</h3><p>predicted "
            f"{c['predicted_wall_ms']:.1f}ms vs matched actual "
            f"{c['matched_actual_wall_ms']:.1f}ms "
            f"({c['hits']} hits / {c['misses']} misses)</p>")
    if d["progress"] is not None:
        p = d["progress"]
        body.append(
            f"<h3>progress</h3><p>final pct={p['pct']} "
            f"stalls={p['stalls']} background="
            f"{_esc(json.dumps(p['background']))}</p>")
    if d.get("bill") is not None:
        b = d["bill"]
        body.append(
            f"<h3>resource bill</h3><p>device peak "
            f"{b['device_peak_bytes']}B, "
            f"{b['device_byte_seconds']:.1f} device-byte-seconds, "
            f"spilled {b['spilled_bytes']}B / restored "
            f"{b['restored_bytes']}B, residual {b['residual_bytes']}B"
            "</p>")
        if b["partitions"]:
            body.append(f"<p>hot partitions: "
                        f"{_esc(json.dumps(b['partitions']))}</p>")
        if b["worker_bytes"]:
            body.append(f"<p>worker store bytes: "
                        f"{_esc(json.dumps(b['worker_bytes']))}</p>")
    if d.get("regression") is not None:
        rr = d["regression"]
        body.append(
            f"<h3>sentinel</h3><p class='slo-error'>REGRESSED "
            f"{_esc(rr['dimension'])} x{rr['ratio']} — worst op "
            f"{_esc(rr['op'])}: {_esc(rr['detail'])}</p>")
    if d["stall_events"]:
        body.append("<h3>stalls</h3><pre>")
        for e in d["stall_events"]:
            body.append(_esc(f"{e.get('stalled_ms', 0):>8}ms stuck in "
                             f"{e.get('name', '?')} at "
                             f"{e.get('path', '?')}: "
                             f"{e.get('detail', '')}"))
        body.append("</pre>")
    body.append("<p><a href='/'>back to index</a></p></body></html>")
    return "\n".join(body)


def render_cluster_html(rows: List[Dict[str, Any]]) -> str:
    body = [f"<html><head><title>cluster</title>{_STYLE}</head>",
            f"<body><h2>cluster — {len(rows)} worker"
            f"{'' if len(rows) == 1 else 's'}</h2><table>",
            "<tr><th>worker</th><th>spans</th><th>bytes</th>"
            "<th>wall_ms</th><th>puts</th><th>redrive</th>"
            "<th>fetches</th><th>served_bytes</th>"
            "<th>overflow_bytes</th><th>queries</th></tr>"]
    for r in rows:
        body.append(
            f"<tr><td>{_esc(r['worker_id'])}</td><td>{r['spans']}</td>"
            f"<td>{r['bytes']}</td><td>{r['wall_ms']:.1f}</td>"
            f"<td>{r['store_puts']}</td>"
            f"<td>{r['store_redrive_puts']}</td>"
            f"<td>{r['store_fetches']}</td>"
            f"<td>{r['store_bytes_served']}</td>"
            f"<td>{r['store_overflow_bytes']}</td>"
            f"<td>{len(r['queries'])}</td></tr>")
    body.append("</table><p><a href='/'>back to index</a></p>"
                "</body></html>")
    return "\n".join(body)


def render_index_text(rows: List[Dict[str, Any]]) -> str:
    lines = [f"query history ({len(rows)} queries)",
             f"{'query':<28} {'status':<10} {'slo':<10} "
             f"{'wall_ms':>10} {'ops':>4} {'stalls':>6} "
             f"{'pred_ms':>9}"]
    for r in rows:
        cost = r["cost"] or {}
        pred = cost.get("predicted_wall_ms")
        lines.append(
            f"{r['query_id']:<28} {r['status']:<10} {r['slo']:<10} "
            f"{r['wall_ms']:>10.1f} {r['operators']:>4} "
            f"{r['stalls']:>6} "
            + (f"{pred:>9.1f}" if pred is not None else f"{'-':>9}"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    log_dirs: List[str] = []
    slo_target_ms: float = 0.0

    def do_GET(self):               # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            profiles = load_profiles(self.log_dirs)
            if path == "/":
                self._ok(render_index_html(index_rows(
                    profiles, self.slo_target_ms)).encode(),
                    "text/html; charset=utf-8")
            elif path == "/api/queries":
                self._ok(json.dumps(index_rows(
                    profiles, self.slo_target_ms)).encode(),
                    "application/json; charset=utf-8")
            elif path == "/cluster":
                self._ok(render_cluster_html(
                    cluster_rows(profiles)).encode(),
                    "text/html; charset=utf-8")
            elif path == "/api/cluster":
                self._ok(json.dumps(cluster_rows(profiles)).encode(),
                         "application/json; charset=utf-8")
            elif path.startswith(("/query/", "/api/query/")):
                qid = path.rsplit("/", 1)[1]
                qp = next((p for p in profiles if p.query_id == qid),
                          None)
                if qp is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                d = query_detail(qp, self.slo_target_ms)
                if path.startswith("/api/"):
                    self._ok(json.dumps(d).encode(),
                             "application/json; charset=utf-8")
                else:
                    self._ok(render_query_html(d).encode(),
                             "text/html; charset=utf-8")
            else:
                self.send_response(404)
                self.end_headers()
        except Exception as e:      # a request must never kill the server
            self.send_response(500)
            self.end_headers()
            self.wfile.write(str(e).encode())

    def _ok(self, body: bytes, ctype: str) -> None:
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):      # no stderr chatter per request
        pass


def start_server(log_dirs: List[str], port: int,
                 slo_target_ms: float = 0.0):
    """Bind on 127.0.0.1 (port 0 = ephemeral, used by tests); returns
    (server, bound_port)."""
    handler = type("_BoundHandler", (_Handler,),
                   {"log_dirs": list(log_dirs),
                    "slo_target_ms": float(slo_target_ms)})
    srv = ThreadingHTTPServer(("127.0.0.1", int(port)), handler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever,
                         name="srt-history-http", daemon=True)
    t.start()
    return srv, srv.server_address[1]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Serve diagnostics event logs as a browsable query "
                    "history index.")
    ap.add_argument("logs", nargs="*", default=["diag_logs"],
                    help="event-log directories or query-*.jsonl files "
                         "(default: diag_logs)")
    ap.add_argument("--port", type=int, default=8098,
                    help="listen port on 127.0.0.1 (default 8098; "
                         "0 = ephemeral)")
    ap.add_argument("--slo-target-ms", type=float,
                    default=DEFAULT_SLO_TARGET_MS,
                    help="judge finished queries against this latency "
                         "target (0 = no SLO judgment)")
    ap.add_argument("--once", action="store_true",
                    help="print the index and exit instead of serving")
    ap.add_argument("--json", action="store_true",
                    help="with --once: machine-readable JSON")
    args = ap.parse_args(argv)
    logs = args.logs or ["diag_logs"]

    if args.once:
        rows = index_rows(load_profiles(logs), args.slo_target_ms)
        if not rows:
            print("no event logs found", file=sys.stderr)
            return 2
        print(json.dumps(rows) if args.json
              else render_index_text(rows))
        return 0

    srv, port = start_server(logs, args.port, args.slo_target_ms)
    print(f"query history server on http://127.0.0.1:{port}/ "
          f"(serving {', '.join(logs)}; Ctrl-C stops)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
