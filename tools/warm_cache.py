"""Populate the compile caches for a query suite WITHOUT executing it.

Plan-time enumeration only: each query is planned (TpuOverrides), the AOT
pipeline (compilecache/aot.py) walks the exec tree and compiles every
predictable (stage function x shape-bucket) program on the background
pool, and — when ``spark.rapids.tpu.compile.cacheDir`` points somewhere
persistent (it does by default) — the resulting executables land in JAX's
on-disk cache, so the NEXT process (bench run, CI job, serving replica)
starts with zero cold compiles.  No query executes; no data leaves the
host beyond the dummy warm-up batches.

    python tools/warm_cache.py                       # bench suite, 20M rows
    python tools/warm_cache.py --queries q6,qa --rows 1000000
    python tools/warm_cache.py --cache-dir /nfs/xla-cache --json
    python tools/warm_cache.py --trace serve_trace.jsonl

Match --rows to the rows the real run will use: programs are keyed per
shape bucket, so warming 1M-row buckets does not help a 20M-row run.

``--trace`` (ISSUE 19) warm-starts a SERVING replica from a recorded
trace instead of the fixed bench suite: a JSONL file whose lines are

    {"op": "scan", "format": "parquet", "paths": ["/data/t.parquet"]}
    {"op": "query", "name": "qa", "rows": 1000000}

``scan`` entries execute once through a hot-table-cache session so the
device-resident table cache is primed; ``query`` entries AOT-compile
that bench query at the recorded row count.  A replica warmed this way
serves its first repeated queries with zero cold compiles and zero
H2D bytes for the traced tables.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_queries(names, rows, cache_dir=None):
    import bench as B
    from spark_rapids_tpu.session import TpuSession

    def session():
        conf = {"spark.rapids.sql.enabled": True,
                "spark.rapids.tpu.scan.cacheDeviceBatches": True}
        if cache_dir:
            # in the session conf, not just pre-applied: every session
            # construction re-resolves the cache dir, and a conf without
            # it would silently re-point jax at the repo default
            conf["spark.rapids.tpu.compile.cacheDir"] = cache_dir
        return TpuSession(conf)

    out = {}
    ss = dd = sr = li = None
    if {"qa", "qb", "qc"} & set(names):
        ss = B.make_store_sales(rows)
    if "q6" in names:
        li = B.make_lineitem(rows)
        out["q6"] = B.build_q6(session(), li)
    if "qa" in names:
        dd = B.make_date_dim()
        out["qa"] = B.build_qa(session(), ss, dd)
    if "qb" in names:
        sr = B.make_store_returns(ss, rows // 10)
        out["qb"] = B.build_qb(session(), ss, sr)
    if "qc" in names:
        out["qc"] = B.build_qc(session(), ss)
    return out


def _warm_scans(scan_entries, cache_dir):
    """Execute each recorded scan once through a hot-table-cache
    session so the device-resident table cache is primed for the
    serving replica's replays (ISSUE 19)."""
    from spark_rapids_tpu.io.hot_cache import get_hot_cache
    from spark_rapids_tpu.session import TpuSession

    conf = {"spark.rapids.sql.enabled": True,
            "spark.rapids.tpu.scan.hotTableCache.enabled": True}
    if cache_dir:
        conf["spark.rapids.tpu.compile.cacheDir"] = cache_dir
    s = TpuSession(conf)
    warmed = 0
    for e in scan_entries:
        fmt = e.get("format", "parquet")
        df = getattr(s.read, fmt)(*e["paths"])
        cols = e.get("columns")
        if cols:
            df = df.select(*cols)
        df.collect()
        warmed += 1
    cache = get_hot_cache()
    st = cache.stats() if cache is not None else {"entries": 0,
                                                  "bytes": 0}
    return {"scans": warmed, "hotCacheEntries": st["entries"],
            "hotCacheBytes": st["bytes"]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--queries", default="q6,qa,qb,qc",
                    help="comma list from {q6,qa,qb,qc}")
    ap.add_argument("--trace", default=None,
                    help="warm from a recorded JSONL trace (scan + "
                         "query entries) instead of --queries/--rows")
    ap.add_argument("--rows", type=int,
                    default=int(os.environ.get("BENCH_ROWS", 20_000_000)),
                    help="row count the real run will use (shape buckets "
                    "are keyed on it)")
    ap.add_argument("--cache-dir", default=None,
                    help="persistent cache dir (default: the conf default)")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON line")
    args = ap.parse_args(argv)

    if args.cache_dir:
        # applied process-wide before any session constructs
        from spark_rapids_tpu.config import TpuConf
        from spark_rapids_tpu.session import _apply_compile_cache

        _apply_compile_cache(TpuConf(
            {"spark.rapids.tpu.compile.cacheDir": args.cache_dir}))

    from spark_rapids_tpu import perfcounters as PC
    from spark_rapids_tpu.compilecache import submit_plan
    from spark_rapids_tpu.exec.base import TpuExec

    scan_report = None
    if args.trace:
        with open(args.trace) as f:
            entries = [json.loads(ln) for ln in f if ln.strip()]
        scans = [e for e in entries if e.get("op") == "scan"]
        if scans:
            scan_report = _warm_scans(scans, args.cache_dir)
            if not args.json:
                print(f"[warm_cache] trace: {scan_report['scans']} scans "
                      f"primed ({scan_report['hotCacheBytes']} cached "
                      f"bytes)", file=sys.stderr, flush=True)
        queries = {}
        for e in entries:
            if e.get("op") != "query":
                continue
            rows = int(e.get("rows", args.rows))
            for n, df in _build_queries([e["name"]], rows,
                                        args.cache_dir).items():
                queries[f"{n}@{rows}"] = df
    else:
        names = [q.strip() for q in args.queries.split(",") if q.strip()]
        queries = _build_queries(names, args.rows, args.cache_dir)
    report = {}
    snap_all = PC.snapshot()
    for name, df in queries.items():
        t0 = time.perf_counter()
        snap = PC.snapshot()
        root, _meta = df._planned()
        if not isinstance(root, TpuExec):
            report[name] = {"programs": 0, "skipped": ["plan is CPU-only"]}
            continue
        sub = submit_plan(root, wait=True)
        d = PC.since(snap)
        report[name] = {
            "programs": len(sub.programs),
            "labels": sub.programs,
            "skipped": sub.skipped,
            "aotCompiles": d["aot_compiles"],
            "compileWall_s": round(d["aot_compile_wall_ns"] / 1e9, 3),
            "wall_s": round(time.perf_counter() - t0, 3),
        }
        if not args.json:
            print(f"[warm_cache] {name}: {sub.summary()} "
                  f"({report[name]['compileWall_s']}s compiling)",
                  file=sys.stderr, flush=True)
    # drain the background pool before exit (daemon compile workers
    # dying mid-XLA at interpreter teardown abort the process)
    from spark_rapids_tpu.compilecache.aot import quiesce_aot

    quiesce_aot(60.0)
    total = PC.since(snap_all)
    payload = {
        "rows": args.rows,
        "queries": report,
        "totalAotCompiles": total["aot_compiles"],
        "totalCompileWall_s": round(total["aot_compile_wall_ns"] / 1e9, 3),
    }
    if scan_report is not None:
        payload["scanWarm"] = scan_report
    if args.json:
        print(json.dumps(payload), flush=True)
    else:
        print(f"[warm_cache] done: {payload['totalAotCompiles']} programs "
              f"compiled in {payload['totalCompileWall_s']}s "
              f"across {len(report)} queries", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
