"""Print the planned exec trees of the bench queries."""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import bench


def show(name, df):
    root, _ = df._planned()
    print(f"===== {name} =====")
    print(root.pretty())
    print()


def main():
    n = 1000
    li = bench.make_lineitem(n)
    ss = bench.make_store_sales(n)
    dd = bench.make_date_dim()
    sr = bench.make_store_returns(ss, n // 10)

    show("q6", bench.build_q6(bench._session(True, True), li))
    show("qa", bench.build_qa(bench._session(True, True), ss, dd))
    show("qb", bench.build_qb(bench._session(True, True), ss, sr))
    show("qc", bench.build_qc(bench._session(True, True), ss))


if __name__ == "__main__":
    main()
