#!/usr/bin/env python
"""Fusion-safety manifest CLI — which exec kernels can be inlined into
a larger traced region?

Classifies every registered exec's kernel functions as ``fusable`` /
``fusable-with-rewrite(<reason>)`` / ``unfusable(<reason>)`` from the
tracelint call graph (see docs/static_analysis.md), keyed by the same
``plan_key`` operator-class identity the calibration store and
``tools/qualify.py`` use.  Output is deterministic: two runs over an
unchanged tree are byte-identical (pinned by tests/test_lint.py).

    python tools/fusibility.py                   # manifest to stdout
    python tools/fusibility.py --out fus.json    # write to a file
    python tools/fusibility.py --summary         # one line per operator
    python tools/fusibility.py --check           # drift gate: exit 1 when
                                                 # the committed manifest
                                                 # is stale
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from spark_rapids_tpu.analysis.fusibility import (  # noqa: E402
    build_manifest,
    manifest_json,
)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fusibility.py",
        description="tracelint fusion-safety manifest")
    ap.add_argument("--out", metavar="PATH",
                    help="write the manifest JSON to PATH "
                         "(default: stdout)")
    ap.add_argument("--summary", action="store_true",
                    help="print a one-line-per-operator summary "
                         "instead of JSON")
    ap.add_argument("--check", nargs="?", metavar="PATH",
                    const=os.path.join(REPO, "tools",
                                       "fusibility_manifest.json"),
                    default=None,
                    help="drift gate: regenerate the manifest and "
                         "byte-compare against PATH (default: the "
                         "committed tools/fusibility_manifest.json); "
                         "exit 1 on any difference")
    args = ap.parse_args(argv)

    manifest = build_manifest(REPO)
    if args.check is not None:
        payload = manifest_json(manifest)
        try:
            with open(args.check, "r", encoding="utf-8") as f:
                committed = f.read()
        except OSError as e:
            print(f"fusibility drift gate: cannot read {args.check}: "
                  f"{e}", file=sys.stderr)
            return 1
        if committed != payload:
            print(f"fusibility drift gate: {args.check} is stale — "
                  f"regenerate with:\n"
                  f"  python tools/fusibility.py --out {args.check}",
                  file=sys.stderr)
            return 1
        print(f"fusibility drift gate: {args.check} is current "
              f"({len(manifest['operators'])} operators)",
              file=sys.stderr)
        return 0
    if args.summary:
        for op, e in sorted(manifest["operators"].items()):
            print(f"{op:<30} {e['classification']}")
        counts = {}
        for e in manifest["operators"].values():
            kind = e["classification"].split("(", 1)[0]
            counts[kind] = counts.get(kind, 0) + 1
        print("--")
        for kind in sorted(counts):
            print(f"{kind:<30} {counts[kind]}")
        return 0
    payload = manifest_json(manifest)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(payload)
        print(f"wrote {args.out} ({len(manifest['operators'])} "
              f"operators, {len(manifest['execs'])} exec classes)",
              file=sys.stderr)
    else:
        sys.stdout.write(payload)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
