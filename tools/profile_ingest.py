#!/usr/bin/env python
"""Replay diagnostics event logs into the operator calibration store.

The offline half of the profiling feedback loop (ISSUE 8): point it at
``query-*.jsonl`` files or directories of them (a bench corpus, the
``spark.rapids.tpu.diagnostics.eventLogDir`` of a production run) and
every operator span with a calibration identity folds into
``<store>/calibration.json`` — byte-identically to what the online
``query_end`` hook would have accumulated, so a store seeded offline
drives the same plan-time predictions.

Usage:
    python tools/profile_ingest.py LOG_OR_DIR [LOG_OR_DIR ...] --store DIR
    python tools/profile_ingest.py diag_logs --store profile_store --json

Truncated/partial trailing lines (query killed mid-write) are skipped
with a counted warning, never raised.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Ingest spark_rapids_tpu diagnostics event logs "
                    "into the operator calibration store.")
    ap.add_argument("logs", nargs="+",
                    help="JSONL event logs or directories of query-*.jsonl")
    ap.add_argument("--store", required=True,
                    help="calibration store directory "
                         "(spark.rapids.tpu.profile.dir)")
    ap.add_argument("--alpha", type=float, default=0.25,
                    help="EWMA decay factor (default 0.25, matches "
                         "spark.rapids.tpu.profile.ewmaAlpha)")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable JSON stats")
    args = ap.parse_args(argv)

    from spark_rapids_tpu.profiling.ingest import ingest_logs

    stats = ingest_logs(args.logs, args.store, alpha=args.alpha)
    if args.json:
        print(json.dumps(stats))
    else:
        print(f"ingested {stats['observations']} operator observations "
              f"from {stats['queries']} queries into {args.store} "
              f"({stats['entries']} store entries)")
        if stats["parse_errors"]:
            print(f"WARNING: skipped {stats['parse_errors']} "
                  f"malformed/truncated lines", file=sys.stderr)
        if stats["incomplete_queries"]:
            print(f"WARNING: {stats['incomplete_queries']} queries had "
                  f"events_dropped > 0 (aggregates incomplete)",
                  file=sys.stderr)
    if stats["queries"] == 0:
        print("no event logs found", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
