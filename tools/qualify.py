#!/usr/bin/env python
"""Qualification / advisor CLI — which operators benefit from the TPU?

The spark-rapids-tools qualification analog (SURVEY §5.1) over this
repo's own profile data: reads a calibration store (and/or ingests
event logs on the fly), rolls it up per operator CLASS, and reports
which classes are **fallback-heavy** (runtime CPU fallbacks dominate —
device placement is wasted work), **sync-bound** (host round-trips per
batch above threshold), or **transport-bound** (scan-transfer wall
dominates).  With ``--advisory-out`` it writes the machine-readable
advisory file that ``overrides/meta.py`` consults at plan time behind
``spark.rapids.tpu.profile.advisor.enabled=true`` — only fallback-heavy
classes get re-routed (device → native); sync/transport flags are
tuning advice.

Usage:
    python tools/qualify.py --store profile_store
    python tools/qualify.py diag_logs --store /tmp/fresh_store \\
        --advisory-out profile_store/advisory.json
    python tools/qualify.py --store profile_store --json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def render(advisory: dict) -> str:
    ops = advisory["operators"]
    out = [f"== qualification report: {len(ops)} operator class"
           f"{'' if len(ops) == 1 else 'es'} =="]
    if not ops:
        out.append("(empty store — run queries with "
                   "spark.rapids.tpu.profile.dir set, or ingest event "
                   "logs with tools/profile_ingest.py)")
    rerouted = {op: e for op, e in ops.items()
                if e["route"] != "device"}
    if rerouted:
        out.append("routing recommendations (advisor file consumers "
                   "re-route these at plan time):")
        for op, e in sorted(rerouted.items()):
            out.append(f"  {op:<28} -> {e['route']}  "
                       f"({'; '.join(e['reasons'])})")
    else:
        out.append("routing: all observed operator classes keep their "
                   "device placement")
    out.append("")
    out.append(f"{'operator':<28} {'obs':>5} {'route':>7} "
               f"{'fb%':>6} {'sync/b':>7} {'xport%':>7} "
               f"{'wall(ms)':>9}  flags")
    for op, e in sorted(ops.items(),
                        key=lambda kv: -kv[1]["stats"]["obs"]):
        st = e["stats"]
        out.append(
            f"{op:<28} {st['obs']:>5} {e['route']:>7} "
            f"{st['fallback_ratio'] * 100:>5.0f}% "
            f"{st['syncs_per_batch']:>7.2f} "
            f"{st['transport_share'] * 100:>6.0f}% "
            f"{st['mean_self_wall_ms']:>9.2f}  "
            + (",".join(e["flags"]
                        + ([f"fus:{e['fusibility'].split('(', 1)[0]}"]
                           if "fusibility" in e else [])) or "-"))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Qualification/advisor report over the operator "
                    "calibration store.")
    ap.add_argument("logs", nargs="*",
                    help="optional event logs/dirs to ingest into "
                         "--store before reporting")
    ap.add_argument("--store", required=True,
                    help="calibration store directory")
    ap.add_argument("--advisory-out", metavar="FILE",
                    help="write the machine-readable advisory JSON here "
                         "(what spark.rapids.tpu.profile.advisor.file "
                         "points at)")
    ap.add_argument("--min-obs", type=int, default=None,
                    help="observations before a class is classified "
                         "(default 2)")
    ap.add_argument("--fallback-ratio", type=float, default=None,
                    help="fallback share that flips routing to native "
                         "(default 0.5)")
    ap.add_argument("--syncs-per-batch", type=float, default=None,
                    help="sync-bound flag threshold (default 4.0)")
    ap.add_argument("--transport-share", type=float, default=None,
                    help="transport-bound flag threshold (default 0.5)")
    ap.add_argument("--alpha", type=float, default=0.25,
                    help="EWMA decay for --logs ingestion")
    ap.add_argument("--json", action="store_true",
                    help="emit the advisory JSON to stdout")
    ap.add_argument("--fusibility", metavar="FILE",
                    help="tools/fusibility.py manifest to join: each "
                         "operator class gains its fusion-safety "
                         "classification (shared op_class identity)")
    args = ap.parse_args(argv)

    from spark_rapids_tpu.profiling import advisor
    from spark_rapids_tpu.profiling.store import CalibrationStore

    if args.logs:
        from spark_rapids_tpu.profiling.ingest import ingest_logs

        # return_store: the ingest already holds the merged state —
        # re-parsing the file it just wrote would be a redundant
        # O(store) load
        stats, store = ingest_logs(args.logs, args.store,
                                   alpha=args.alpha, return_store=True)
        if stats["parse_errors"]:
            print(f"WARNING: skipped {stats['parse_errors']} "
                  f"malformed/truncated lines", file=sys.stderr)
    else:
        store = CalibrationStore.load(args.store, alpha=args.alpha)
    kw = {}
    if args.min_obs is not None:
        kw["min_obs"] = args.min_obs
    if args.fallback_ratio is not None:
        kw["fallback_ratio"] = args.fallback_ratio
    if args.syncs_per_batch is not None:
        kw["syncs_per_batch"] = args.syncs_per_batch
    if args.transport_share is not None:
        kw["transport_share"] = args.transport_share
    advisory = advisor.classify(store, **kw)
    if args.fusibility:
        with open(args.fusibility, encoding="utf-8") as f:
            manifest = json.load(f)
        fus_ops = manifest.get("operators", {})
        for op, e in advisory["operators"].items():
            fe = fus_ops.get(op)
            if fe is not None:
                e["fusibility"] = fe["classification"]
    if args.advisory_out:
        advisor.write_advisory(advisory, args.advisory_out)
        print(f"advisory written: {args.advisory_out}", file=sys.stderr)
    if args.json:
        print(json.dumps(advisory))
    else:
        print(render(advisory))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
