#!/usr/bin/env python
"""Drift check: every perf counter / conf / event must be documented.

Since ISSUE 9 the actual checks live in the tpulint framework as the
``doc-drift`` rule (:mod:`spark_rapids_tpu.analysis.rules_docs`), so
``tools/lint.py`` and the tier-1 lint gate run them too.  This file
remains as a thin shim: the CLI entrypoint (exit 1 on drift) and the
``check()`` function (returns problem strings) keep their historical
contracts — tests/test_diagnostics.py, test_telemetry.py and
test_profiling.py call ``check()`` directly.
"""
from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def check() -> list:
    from spark_rapids_tpu.analysis.rules_docs import doc_drift_problems

    return doc_drift_problems(REPO)


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"DRIFT: {p}", file=sys.stderr)
        return 1
    print("counters/confs/events documentation: in sync")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
